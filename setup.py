"""Build shim: metadata lives in pyproject.toml; this file only adds the
optional native extension (move2kube_tpu/native/_fastgather.c). A failed
compile degrades to the pure-Python fallback instead of failing the
install (Extension(optional=True))."""

from setuptools import Extension, setup

setup(
    ext_modules=[
        Extension(
            "move2kube_tpu.native._fastgather",
            sources=["move2kube_tpu/native/_fastgather.c"],
            extra_compile_args=["-O3"],
            optional=True,
        )
    ]
)
