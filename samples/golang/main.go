package main

import "net/http"

func main() {
	http.ListenAndServe(":8080", nil)
}
