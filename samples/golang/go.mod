module github.com/example/sample-go

go 1.22
