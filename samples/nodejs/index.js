const http = require('http');
http.createServer((req, res) => res.end('hi')).listen(8080);
