print("server")
