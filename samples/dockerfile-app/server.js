require("http").createServer().listen(3000)
