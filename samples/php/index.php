<?php
echo "storefront up";
