package com.example;

public class App {
    public static void main(String[] args) {
        System.out.println("orders service up");
    }
}
