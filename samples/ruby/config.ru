require './app'
run Sinatra::Application
