require 'sinatra'

get '/' do
  'catalog up'
end
