"""Megatron-style GPT-2 pretraining with pipeline parallelism (GPU
source; translation input). Layers are spread across pipeline ranks; a
runtime scheduler pushes microbatches between GPUs over NCCL p2p."""
import argparse

import torch
import torch.distributed as dist
from transformers import GPT2LMHeadModel


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--pipeline-model-parallel-size", type=int, default=2)
    parser.add_argument("--micro-batch-size", type=int, default=2)
    parser.add_argument("--global-batch-size", type=int, default=64)
    args = parser.parse_args()

    dist.init_process_group(backend="nccl")
    torch.cuda.set_device(dist.get_rank() % torch.cuda.device_count())
    model = GPT2LMHeadModel.from_pretrained("gpt2-large").cuda()
    optimizer = torch.optim.AdamW(model.parameters(), lr=5e-5)
    for step in range(1000):
        batch = torch.randint(0, 50257, (args.micro_batch_size, 1024)).cuda()
        loss = model(input_ids=batch, labels=batch).loss
        loss.backward()
        optimizer.step()
        optimizer.zero_grad()


if __name__ == "__main__":
    main()
