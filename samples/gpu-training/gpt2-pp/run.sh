#!/bin/sh
# Classic Megatron GPT pipeline run: no ZeRO, layers spread over stages.
torchrun --nproc_per_node 8 pretrain_gpt2_pp.py \
  --pipeline-model-parallel-size 2 \
  --micro-batch-size 2 \
  --global-batch-size 64
