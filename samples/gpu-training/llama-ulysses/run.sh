#!/bin/sh
# DeepSpeed-Ulysses long-context run: sequence parallelism over 4 GPUs
# per replica, ZeRO-3 for the params.
deepspeed --num_gpus 8 train_long_context.py \
  --ds-sequence-parallel-size 4 \
  --seq-length 65536
