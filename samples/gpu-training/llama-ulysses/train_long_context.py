"""DeepSpeed-Ulysses long-context Llama training (GPU source; translation
input). Attention heads are all-to-all resharded across the sequence-
parallel group so each GPU holds the full sequence for a head subset."""
import argparse

import deepspeed
import torch
import torch.distributed as dist
from transformers import LlamaConfig, LlamaForCausalLM


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--ds-sequence-parallel-size", type=int, default=4)
    parser.add_argument("--seq-length", type=int, default=65536)
    args = parser.parse_args()

    dist.init_process_group(backend="nccl")
    torch.cuda.set_device(dist.get_rank() % torch.cuda.device_count())
    config = LlamaConfig(hidden_size=4096, num_hidden_layers=32,
                         max_position_embeddings=args.seq_length)
    model = LlamaForCausalLM(config).cuda()
    engine, optimizer, _, _ = deepspeed.initialize(
        model=model, config="ds_config.json")
    for step in range(1000):
        batch = torch.randint(0, 32000, (1, args.seq_length)).cuda()
        loss = engine(input_ids=batch, labels=batch).loss
        engine.backward(loss)
        engine.step()


if __name__ == "__main__":
    main()
