#!/bin/sh
# HF GPT-2 fine-tune on a DDP node: pure data parallelism, no model
# parallelism -- the translated trainer keeps the true GPT-2 architecture
# so the GPU checkpoint ports onto it.
torchrun --nproc_per_node 8 finetune_gpt2.py
