"""HF GPT-2 causal-LM fine-tune (GPU source; translation input)."""
import torch
import torch.distributed as dist
from torch.nn.parallel import DistributedDataParallel
from transformers import GPT2LMHeadModel


def main():
    dist.init_process_group(backend="nccl")
    torch.cuda.set_device(dist.get_rank() % torch.cuda.device_count())
    model = GPT2LMHeadModel.from_pretrained("gpt2").cuda()
    model = DistributedDataParallel(model)
    optimizer = torch.optim.AdamW(model.parameters(), lr=5e-5)
    for step in range(1000):
        batch = torch.randint(0, 50257, (8, 1024)).cuda()
        loss = model(input_ids=batch, labels=batch).loss
        loss.backward()
        optimizer.step()
        optimizer.zero_grad()


if __name__ == "__main__":
    main()
