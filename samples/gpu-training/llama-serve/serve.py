"""Llama GPU inference server (FastAPI; translation input)."""
import torch
from fastapi import FastAPI
from transformers import AutoTokenizer, LlamaForCausalLM

app = FastAPI()
tokenizer = AutoTokenizer.from_pretrained("meta-llama/Llama-2-7b-hf")
model = LlamaForCausalLM.from_pretrained(
    "meta-llama/Llama-2-7b-hf", torch_dtype=torch.float16).cuda()
model.eval()


@app.post("/generate")
def generate(body: dict):
    ids = tokenizer(body["prompt"], return_tensors="pt").input_ids.cuda()
    with torch.no_grad():
        out = model.generate(ids, max_new_tokens=body.get("max_new_tokens", 64))
    return {"text": tokenizer.decode(out[0], skip_special_tokens=True)}


if __name__ == "__main__":
    import uvicorn

    uvicorn.run(app, host="0.0.0.0", port=8000)
