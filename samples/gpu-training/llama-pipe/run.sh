#!/bin/sh
# Classic Megatron pipeline run: no ZeRO, model too deep to data-shard.
torchrun --nproc_per_node 8 pretrain_llama.py \
  --pipeline-model-parallel-size 2 \
  --micro-batch-size 1 \
  --global-batch-size 32
