"""Megatron-style Llama pretraining with pipeline parallelism (GPU source;
translation input). Stages are spread across ranks; a runtime scheduler
pushes microbatches between GPUs over NCCL p2p."""
import argparse

import torch
import torch.distributed as dist
from transformers import LlamaConfig, LlamaForCausalLM


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--pipeline-model-parallel-size", type=int, default=2)
    parser.add_argument("--micro-batch-size", type=int, default=1)
    parser.add_argument("--global-batch-size", type=int, default=32)
    args = parser.parse_args()

    dist.init_process_group(backend="nccl")
    torch.cuda.set_device(dist.get_rank() % torch.cuda.device_count())
    config = LlamaConfig(hidden_size=4096, num_hidden_layers=32)
    model = LlamaForCausalLM(config).cuda()
    optimizer = torch.optim.AdamW(model.parameters(), lr=3e-4)
    for step in range(1000):
        batch = torch.randint(0, 32000, (args.micro_batch_size, 2048)).cuda()
        loss = model(input_ids=batch, labels=batch).loss
        loss.backward()
        optimizer.step()
        optimizer.zero_grad()


if __name__ == "__main__":
    main()
