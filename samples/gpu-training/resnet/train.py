"""Sample CUDA training script (detection target for the TPU translator)."""
import torch
import torch.distributed as dist
import torchvision.models as models

def main():
    dist.init_process_group(backend="nccl")
    model = models.resnet50().cuda()
    optimizer = torch.optim.SGD(model.parameters(), lr=0.1)
    model = torch.nn.parallel.DistributedDataParallel(model)
    for step in range(100):
        x = torch.randn(64, 3, 224, 224).cuda()
        y = torch.randint(0, 1000, (64,)).cuda()
        loss = torch.nn.functional.cross_entropy(model(x), y)
        loss.backward()
        optimizer.step()
        optimizer.zero_grad()

if __name__ == "__main__":
    main()
