#!/bin/sh
# Long-context GPT-2 fine-tune with DeepSpeed-Ulysses sequence
# parallelism (sp=4 over 8 GPUs). Translates to the true GPT-2
# architecture with ring attention over the mesh's seq axis.
deepspeed --num_gpus 8 train_gpt2_long.py \
  --ds-sequence-parallel-size 4 \
  --seq-length 8192
