"""DeepSpeed-Ulysses long-context GPT-2 fine-tune (GPU source;
translation input). Sequence parallelism shards the 8k context across
the group; the base checkpoint is stock GPT2LMHeadModel."""
import argparse

import deepspeed
import torch
import torch.distributed as dist
from transformers import GPT2LMHeadModel


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--ds-sequence-parallel-size", type=int, default=4)
    parser.add_argument("--seq-length", type=int, default=8192)
    args = parser.parse_args()

    dist.init_process_group(backend="nccl")
    torch.cuda.set_device(dist.get_rank() % torch.cuda.device_count())
    model = GPT2LMHeadModel.from_pretrained("gpt2-xl").cuda()
    engine, optimizer, _, _ = deepspeed.initialize(
        model=model, config="ds_config.json")
    for step in range(1000):
        batch = torch.randint(0, 50257, (1, args.seq_length)).cuda()
        loss = engine(input_ids=batch, labels=batch).loss
        engine.backward(loss)
        engine.step()


if __name__ == "__main__":
    main()
