#!/bin/sh
# Single-node DDPM UNet training; the diffusion workload translates to
# the TPU DDPM trainer (models/unet.py) with a data/fsdp mesh.
python train_ddpm.py
