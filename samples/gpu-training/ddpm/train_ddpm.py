"""DDPM diffusion-model training on CIFAR-scale images (GPU source;
translation input). Classic noise-prediction objective with a UNet."""
import torch
import torch.nn.functional as F
from diffusers import UNet2DModel, DDPMScheduler


def main():
    device = "cuda"
    model = UNet2DModel(sample_size=32, in_channels=3, out_channels=3).to(device)
    scheduler = DDPMScheduler(num_train_timesteps=1000)
    optimizer = torch.optim.AdamW(model.parameters(), lr=1e-4)
    for step in range(100000):
        clean = torch.rand(64, 3, 32, 32, device=device) * 2 - 1
        noise = torch.randn_like(clean)
        t = torch.randint(0, 1000, (clean.shape[0],), device=device)
        noisy = scheduler.add_noise(clean, noise, t)
        pred = model(noisy, t).sample
        loss = F.mse_loss(pred, noise)
        loss.backward()
        optimizer.step()
        optimizer.zero_grad()


if __name__ == "__main__":
    main()
