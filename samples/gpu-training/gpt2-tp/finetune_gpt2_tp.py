"""GPT-2-XL fine-tune with Megatron-style tensor parallelism (GPU
source; translation input). The model is too wide to be worth pure DDP at
this scale, so each node splits attention/MLP matmuls over 2-way TP."""
import argparse

import torch
import torch.distributed as dist
from transformers import GPT2LMHeadModel


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--tensor-model-parallel-size", type=int, default=1)
    args = parser.parse_args()
    dist.init_process_group(backend="nccl")
    torch.cuda.set_device(dist.get_rank() % torch.cuda.device_count())
    model = GPT2LMHeadModel.from_pretrained("gpt2-xl").cuda()
    optimizer = torch.optim.AdamW(model.parameters(), lr=5e-5)
    for step in range(1000):
        batch = torch.randint(0, 50257, (4, 1024)).cuda()
        loss = model(input_ids=batch, labels=batch).loss
        loss.backward()
        optimizer.step()
        optimizer.zero_grad()


if __name__ == "__main__":
    main()
