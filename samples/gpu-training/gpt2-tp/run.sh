#!/bin/sh
# GPT-2-XL fine-tune with 2-way Megatron tensor parallelism across an
# 8-GPU node: dp=4 x tp=2. The translated trainer keeps the true GPT-2
# architecture (the GPU checkpoint ports onto it) with its attention/MLP
# kernels sharded over the mesh's tensor axis.
torchrun --nproc_per_node 8 finetune_gpt2_tp.py --tensor-model-parallel-size 2
