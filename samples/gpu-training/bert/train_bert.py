"""Sample HF BERT fine-tune over torch.distributed/NCCL (detection target:
BASELINE config 3 — "BERT NCCL fine-tune -> v5e-8 JobSet")."""
import torch
import torch.distributed as dist
from transformers import AutoModelForSequenceClassification, AutoTokenizer


def main():
    dist.init_process_group(backend="nccl")
    rank = dist.get_rank()
    torch.cuda.set_device(rank % torch.cuda.device_count())
    tok = AutoTokenizer.from_pretrained("bert-base-uncased")
    model = AutoModelForSequenceClassification.from_pretrained(
        "bert-base-uncased", num_labels=2).cuda()
    model = torch.nn.parallel.DistributedDataParallel(model)
    optimizer = torch.optim.AdamW(model.parameters(), lr=2e-5)
    texts = ["a fine movie"] * 32
    for step in range(200):
        batch = tok(texts, return_tensors="pt", padding="max_length",
                    max_length=128)
        batch = {k: v.cuda() for k, v in batch.items()}
        labels = torch.randint(0, 2, (len(texts),)).cuda()
        loss = model(**batch, labels=labels).loss
        loss.backward()
        optimizer.step()
        optimizer.zero_grad()


if __name__ == "__main__":
    main()
