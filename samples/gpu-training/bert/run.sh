#!/bin/sh
torchrun --nproc_per_node=8 train_bert.py
