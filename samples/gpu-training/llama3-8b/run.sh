#!/bin/sh
deepspeed --num_gpus 64 train_llama3.py
