"""DeepSpeed ZeRO-3 Llama-3-8B pretraining (GPU source; translation input).

BASELINE config 5: 64 A100s, ZeRO-3 sharded params, NCCL allreduce.
"""
import deepspeed
import torch
import torch.distributed as dist
from transformers import LlamaForCausalLM, LlamaConfig


def main():
    dist.init_process_group(backend="nccl")
    torch.cuda.set_device(dist.get_rank() % torch.cuda.device_count())
    # Llama-3-8B dims
    config = LlamaConfig(
        vocab_size=128256,
        hidden_size=4096,
        num_hidden_layers=32,
        num_attention_heads=32,
        num_key_value_heads=8,
        intermediate_size=14336,
        max_position_embeddings=8192,
        rope_theta=500000.0,
    )
    model = LlamaForCausalLM(config).cuda()
    engine, optimizer, _, _ = deepspeed.initialize(
        model=model, config="ds_config.json")
    for step in range(1000):
        batch = torch.randint(0, config.vocab_size, (1, 8192)).cuda()
        loss = engine(input_ids=batch, labels=batch).loss
        engine.backward(loss)
        engine.step()


if __name__ == "__main__":
    main()
