"""DeepSpeed-MoE Llama pretraining (GPU source; translation input)."""
import deepspeed
import torch
import torch.distributed as dist
from transformers import LlamaForCausalLM, LlamaConfig


def main():
    dist.init_process_group(backend="nccl")
    torch.cuda.set_device(dist.get_rank() % torch.cuda.device_count())
    config = LlamaConfig(hidden_size=4096, num_hidden_layers=32)
    model = LlamaForCausalLM(config).cuda()
    engine, optimizer, _, _ = deepspeed.initialize(
        model=model, config="ds_config.json")
    for step in range(1000):
        batch = torch.randint(0, 32000, (1, 2048)).cuda()
        loss = engine(input_ids=batch, labels=batch).loss
        engine.backward(loss)
        engine.step()


if __name__ == "__main__":
    main()
