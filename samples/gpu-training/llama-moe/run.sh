#!/bin/sh
deepspeed --num_gpus 16 train_llama.py \
  --tensor-model-parallel-size 2 \
  --expert-model-parallel-size 4 \
  --num-experts 8
