from flask import Flask

app = Flask(__name__)

@app.route("/")
def hello():
    return "Hello from move2kube-tpu sample!"

if __name__ == "__main__":
    app.run(host="0.0.0.0", port=8080)
