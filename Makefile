# move2kube-tpu developer targets (parity: reference Makefile:14-110;
# no binary build step — pure-Python package + vendored JAX model zoo).

PY ?= python
CPU_ENV = JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= XLA_FLAGS=--xla_force_host_platform_device_count=8

.PHONY: test test-fast coverage lint ci dist bench dryrun e2e perf-smoke fault-smoke multichip-smoke serve-smoke obs-smoke elastic-smoke trace-smoke mfu-smoke fleet-smoke quant-smoke kernel-smoke trainkernel-smoke slo-smoke chaos-smoke swap-smoke numerics-smoke sched-smoke autoscale-smoke asyncserve-smoke usage-smoke clean

test:
	$(CPU_ENV) $(PY) -m pytest tests/ -q

test-fast:
	$(CPU_ENV) $(PY) -m pytest tests/ -q -m "not slow" -x

# suite + dependency-free line coverage (scripts/cov.py, PEP 669) gated
# at the floor (parity: reference build.yml uploads coverage per push);
# report artifact: coverage-report.txt
COV_MIN ?= 78
coverage:
	$(PY) scripts/cov.py clean
	@$(PY) setup.py build_ext --inplace >/dev/null 2>&1 || \
		echo "WARNING: native extension build failed; coverage exercises the numpy fallback paths"
	$(CPU_ENV) $(PY) -m pytest tests/ -q -p scripts.cov
	$(PY) scripts/cov.py report --min $(COV_MIN) --out coverage-report.txt

# AST linter (scripts/lint.py; parity with the reference's golangci-lint
# gate, Makefile:82-101) + bytecode compile + import smoke
lint:
	$(PY) -m compileall -q -x 'assets/' move2kube_tpu scripts
	$(PY) scripts/lint.py move2kube_tpu tests scripts bench.py __graft_entry__.py
	$(PY) -c "import move2kube_tpu.cli.main"

# what .github/workflows/build.yml runs; the coverage collector needs
# sys.monitoring (3.12+), so the 3.11 matrix leg runs the plain suite
ci: lint ci-test dryrun

.PHONY: ci-test
ci-test:
	@if $(PY) -c "import sys; raise SystemExit(0 if sys.version_info >= (3, 12) else 1)"; then \
		$(MAKE) coverage; \
	else \
		echo "python < 3.12: no sys.monitoring, running suite without coverage"; \
		$(MAKE) test; \
	fi

# wheel + sdist + checksums (parity: reference scripts/builddist.go's
# tar+checksum dist packaging; one pure-Python artifact replaces the
# per-OS gox matrix). Used by .github/workflows/release.yml.
dist:
	rm -rf dist
	$(PY) -m build --wheel --sdist --no-isolation --outdir dist
	cd dist && sha256sum * > SHA256SUMS

bench:
	$(PY) bench.py

dryrun:
	$(CPU_ENV) $(PY) -c "import jax; jax.config.update('jax_platforms', 'cpu'); \
	import __graft_entry__ as g; g.dryrun_multichip(8)"

e2e:
	$(CPU_ENV) $(PY) -m pytest tests/test_e2e_translate.py tests/test_gpu2tpu_e2e.py -q

# hot-path perf units in isolation (all CPU-mode): buffer-donation
# aliasing, device-prefetch overlap, flash block-autotune caching
perf-smoke:
	$(CPU_ENV) $(PY) -m pytest tests/test_donation.py tests/test_autotune.py tests/test_data.py -q -m "not slow"

# topology-aware multichip stack in isolation (8 forced host devices):
# ICI mesh planner goldens, overlapped gradient accumulation vs the
# sequential reference, interleaved-1F1B vs GPipe equivalence, and the
# bench scaling phase (one-line-JSON RESULT discipline like fault-smoke)
multichip-smoke:
	$(CPU_ENV) $(PY) -m pytest tests/test_topology.py -q
	$(CPU_ENV) $(PY) bench.py --model scaling

# serving hot path in isolation (CPU-mode): paged KV cache vs dense
# equivalence, continuous-batching engine invariants, serving emission
# (Knative TPU resources + concurrency), then the bench serving phase
# (decode tok/s + p50/p95 step latency, compile-count bound)
serve-smoke:
	$(CPU_ENV) $(PY) -m pytest tests/test_serving.py -q
	$(CPU_ENV) $(PY) bench.py --model serving

# telemetry plane in isolation (CPU-mode): metrics registry/exposition
# semantics, telemetry HTTP server, scrape-annotation emission, then the
# bench obs phase (per-step recording overhead gated at <= 3% of step
# time + a live well-formedness scrape of the exposition)
obs-smoke:
	$(CPU_ENV) $(PY) -m pytest tests/test_obs.py -q
	$(CPU_ENV) $(PY) bench.py --model obs

# resilience subsystem in isolation (all CPU-mode, deterministic faults):
# kill-at-step-N -> resume-from-N under the supervisor, corrupt-checkpoint
# fallback, JobSet failure-policy YAML, goodput accounting
fault-smoke:
	$(CPU_ENV) $(PY) -m pytest tests/test_resilience.py -q

# elastic multislice drill in isolation (all CPU-mode, 8 forced host
# devices as 2 simulated slices): DCN-aware planner goldens, slice-loss
# at step N -> supervisor re-plans onto the survivor slice -> resume from
# the last checkpoint with the global batch preserved and loss continuity
# against a never-faulted run; plus the elastic JobSet/Helm emission
elastic-smoke:
	$(CPU_ENV) $(PY) -m pytest tests/test_elastic.py -q

# runtime tracing in isolation (all CPU-mode): span-ring semantics,
# Chrome/OTLP export well-formedness, per-request TTFT decomposition,
# straggler scoring, and the forced-host slice-loss minitrain drill
# asserting the crash flight recorder (m2kt-flight.json with the final
# step's spans + the slice-lost classification)
trace-smoke:
	$(CPU_ENV) $(PY) -m pytest tests/test_tracing.py -q

# compiled-program cost model in isolation (all CPU-mode): backend
# fallback tolerance, chip-spec aliasing, roofline/MFU math, plan-report
# round-trip; then the forced-host dryrun must land m2kt-plan-report.json
# with predicted HBM inside the documented 4.0x tolerance of the
# compiled memory_analysis footprint
mfu-smoke:
	$(CPU_ENV) $(PY) -m pytest tests/test_costmodel.py -q
	rm -rf /tmp/m2kt-mfu-smoke && mkdir -p /tmp/m2kt-mfu-smoke
	$(CPU_ENV) M2KT_PLAN_REPORT=/tmp/m2kt-mfu-smoke $(PY) -c "import jax; jax.config.update('jax_platforms', 'cpu'); \
	import json, __graft_entry__ as g; g.dryrun_multichip(8); \
	doc = json.load(open('/tmp/m2kt-mfu-smoke/m2kt-plan-report.json')); \
	assert doc['verdict'] == 'fit', doc['verdict']; \
	assert doc['drift']['within_tolerance'], doc['drift']; \
	print('[mfu-smoke] drift %.2fx, mfu ceiling %s' % (doc['drift']['predicted_over_measured'], doc['estimated_mfu']['roofline_ceiling']))"

# fleet serving in isolation (all CPU-mode): router affinity/failover/
# hedging units, refcount+COW page-sharing invariants, prefix-hit and
# disagg-handoff logit equivalence, per-role fleet manifest emission,
# then the bench fleet phase (router + real engine replicas under a
# zipfian multi-tenant replay; FAILS unless the prefix cache hits and
# improves p95 TTFT over the uncached fleet)
fleet-smoke:
	$(CPU_ENV) $(PY) -m pytest tests/test_fleet.py -q
	$(CPU_ENV) $(PY) bench.py --model fleet

# low-precision serving in isolation (all CPU-mode): quant policy +
# int8 weight/KV round-trips, tiered logit gates, spec-decode greedy
# exactness + acceptance, executable-bound and donation under
# quantization, then the bench quant phase (fp32 vs int8 vs int8-kv vs
# spec-decode decode tok/s; FAILS unless int8 beats fp32, the logit
# gate holds, params shrink, and spec matches greedy exactly)
quant-smoke:
	$(CPU_ENV) $(PY) -m pytest tests/test_quant.py -q
	$(CPU_ENV) $(PY) bench.py --model quant

# serving kernels in isolation (all CPU-mode): interpret-mode kernel
# equivalence tests prove the REAL Pallas kernel bodies (fused int8
# paged-decode, packing/padding/COW/prefix-sharing, collective matmul,
# autotune cache keying), then one kernels microbench trial with the
# roofline assertion (FAILS if the fused path loses to its own
# reference or is invisible to the cost model)
kernel-smoke:
	$(CPU_ENV) $(PY) -m pytest tests/test_kernels.py tests/test_autotune.py -q
	$(CPU_ENV) M2KT_BENCH_KERNELS_TRIALS=1 $(PY) bench.py --model kernels

# training kernels in isolation (all CPU-mode, 8 forced host devices):
# fused chunked lm-head cross-entropy vs the reference loss (loss +
# grads, fp32 exact and bf16 logit-gated), flash-backward autotune cache
# keying, fsdp all-gather prefetch vs the sequential GSPMD reference;
# then the forced-host dryrun asserting the M2KT_FUSED_CE=on ladder
# actually dispatches the fused loss (spy, not just a finite loss)
trainkernel-smoke:
	$(CPU_ENV) $(PY) -m pytest tests/test_crossentropy.py tests/test_autotune.py -q
	$(CPU_ENV) $(PY) -c "import jax; jax.config.update('jax_platforms', 'cpu'); \
	import __graft_entry__ as g; g.dryrun_trainkernels(8)"

# fleet tracing + per-tenant SLO plane in isolation (all CPU-mode):
# traceparent round-trip, cross-role stitching with exact latency
# decomposition, tenant-cardinality caps, burn-rate goldens, SLO rule
# emission/Helm round-trip; then the bench fleet phase (tenant-tagged
# zipfian replay; FAILS unless the stitched disagg trace decomposes
# exactly and the synthetic best-effort flood fires the fast-burn alert)
slo-smoke:
	$(CPU_ENV) $(PY) -m pytest tests/test_fleetview.py -q
	$(CPU_ENV) $(PY) bench.py --model fleet

# serving fault tolerance in isolation (all CPU-mode): chaos injectors,
# token-exact mid-stream resume, drain + deadline shedding, and the
# bench chaos phase (kill a replica mid-stream, drain another —
# recovery must be token-identical and within the latency budget)
chaos-smoke:
	$(CPU_ENV) $(PY) -m pytest tests/test_chaos.py -q
	$(CPU_ENV) $(PY) bench.py --model chaos

swap-smoke:
	$(CPU_ENV) $(PY) -m pytest tests/test_weights.py -q
	$(CPU_ENV) $(PY) bench.py --model swap

# numerics plane in isolation (CPU-mode): in-graph tensor-health
# summaries + non-finite forensics drill + quant-drift auditor + the
# translation numerics-diff harness, then the bench numerics phase
# (in-graph recording overhead gated at <= 3% of step time + one live
# drift audit on a clean int8 engine)
numerics-smoke:
	$(CPU_ENV) $(PY) -m pytest tests/test_numerics.py -q
	$(CPU_ENV) $(PY) bench.py --model numerics

# scheduler plane in isolation (CPU-mode): admission quotas + priority
# preemption with token-exact journal resume + chunked prefill + paged
# multi-LoRA equivalence, then the bench sched phase (best-effort flood
# vs one high-priority tenant; FAILS unless gold p95 TTFT holds the SLO,
# every preempted stream resumes token-exact, and each adapter in the
# multi-LoRA batch matches a dedicated engine)
sched-smoke:
	$(CPU_ENV) $(PY) -m pytest tests/test_sched.py -q
	$(CPU_ENV) $(PY) bench.py --model sched

# autoscaling plane in isolation (CPU-mode): demand forecaster goldens
# + controller hysteresis + the discrete-event fleet simulator + the
# emission dueling-controller guard, then the bench autoscale phase
# (24h million-user sim — predictive must beat the reactive HPA on SLO
# attainment AND replica-hours — plus a live smoke where a forecasted
# ramp scales a real fleet before the fast-burn alert fires and drains
# back down without losing a stream)
autoscale-smoke:
	$(CPU_ENV) $(PY) -m pytest tests/test_autoscale.py -q
	$(CPU_ENV) $(PY) bench.py --model autoscale

# async decode pipeline (PR 19): token-exactness + lag-1 journal tests,
# then the interleaved async-vs-sync bench gate (async must win and the
# dispatch gap must shrink)
asyncserve-smoke:
	$(CPU_ENV) $(PY) -m pytest tests/test_async.py -q
	$(CPU_ENV) $(PY) bench.py --model serving

# usage ledger + capture→replay + auto-diagnostics (PR 20): ledger
# determinism, chargeback identity, capture round-trip, watchdog
# hysteresis/rate-limit units, then the bench usage phase (chargeback
# Σ TPU-seconds ≡ pods×wall within 1%, capture replay within 10% of the
# recorded rate and tenant shares, an induced SLO fast-burn producing
# exactly one rate-limited diag bundle, ledger overhead ≤ 1%)
usage-smoke:
	$(CPU_ENV) $(PY) -m pytest tests/test_usage.py -q
	$(CPU_ENV) $(PY) bench.py --model usage

clean:
	find . -name __pycache__ -type d -exec rm -rf {} + 2>/dev/null; true
