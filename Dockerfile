# Container image for the move2kube-tpu CLI tool.
# Parity: reference Dockerfile:1-30 (2-stage build; builder compiles, the
# runtime stage carries only the installed tool). The Python equivalent
# builds a wheel in the first stage and installs it into a slim runtime.
FROM python:3.11-slim AS build
WORKDIR /src
COPY pyproject.toml README.md ./
COPY move2kube_tpu ./move2kube_tpu
RUN pip install --no-cache-dir build && python -m build --wheel --outdir /dist

FROM python:3.11-slim
LABEL org.opencontainers.image.title="move2kube-tpu" \
      org.opencontainers.image.description="Re-platform apps onto Kubernetes with a TPU-first target"
# kubectl is the only external binary the collectors shell out to; the
# image stays usable without it (collect degrades gracefully)
COPY --from=build /dist/*.whl /tmp/
RUN pip install --no-cache-dir /tmp/*.whl && rm /tmp/*.whl
WORKDIR /workspace
ENTRYPOINT ["m2kt"]
CMD ["--help"]
