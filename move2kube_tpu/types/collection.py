"""Schemas for collector outputs.

Parity with ``types/collection/`` in the reference: ClusterMetadata
(cluster.go:28-120) with version-preference resolution, ImageInfo
(image.go:27-50), CF app schemas (cfinstanceapps.go, cfcontainerizers.go).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

from move2kube_tpu.utils import common

# group preference for kind/version selection (parity: groupOrderPolicy
# clustercollector.go:365): modern named groups beat the deprecated
# "extensions" umbrella; unknown groups rank between those.
_GROUP_ORDER = ["", "apps", "networking.k8s.io", "batch",
                "rbac.authorization.k8s.io", "storage.k8s.io",
                "route.openshift.io", "apps.openshift.io",
                "image.openshift.io", "jobset.x-k8s.io",
                "serving.knative.dev", "tekton.dev",
                "triggers.tekton.dev"]
_VERSION_RE = re.compile(r"^v(\d+)(?:(alpha|beta)(\d+))?$")
_STAGE_RANK = {"": 2, "beta": 1, "alpha": 0}


def _version_key(group_version: str):
    """Sort key: preferred group first, then GA > beta > alpha, then the
    higher major/stage number (parity: sortVersionList
    clustercollector.go:412)."""
    group, _, version = group_version.rpartition("/")
    try:
        group_rank = _GROUP_ORDER.index(group)
    except ValueError:
        group_rank = len(_GROUP_ORDER) if group != "extensions" else len(_GROUP_ORDER) + 1
    m = _VERSION_RE.match(version)
    if m:
        major = int(m.group(1))
        stage = _STAGE_RANK[m.group(2) or ""]
        stage_num = int(m.group(3) or 0)
    else:
        major, stage, stage_num = -1, -1, -1
    return (group_rank, -stage, -major, -stage_num)


def sort_version_list(versions: list[str]) -> list[str]:
    """Order group/versions by preference; callers take index 0."""
    return sorted(versions, key=_version_key)

CLUSTER_METADATA_KIND = "ClusterMetadata"
IMAGES_METADATA_KIND = "ImageMetadata"
CF_APPS_KIND = "CfApps"
CF_CONTAINERIZERS_KIND = "CfContainerizers"


@dataclass
class ClusterMetadataSpec:
    """Supported kinds/versions + storage classes of a target cluster.

    ``api_kind_version_map`` maps Kind -> ordered list of group/version
    strings, most-preferred first (parity: cluster.go:28-60).
    """

    api_kind_version_map: dict[str, list[str]] = field(default_factory=dict)
    storage_classes: list[str] = field(default_factory=list)
    # net-new: TPU capability of the cluster (empty = no TPU node pools)
    tpu_accelerators: list[str] = field(default_factory=list)  # e.g. tpu-v5-lite-podslice
    host_capabilities: dict[str, str] = field(default_factory=dict)

    def get_supported_versions(self, kind: str) -> list[str]:
        """Preferred group/versions for kind, or [] if unsupported
        (parity: GetSupportedVersions cluster.go:107). Preference-sorted
        so callers can take [0]."""
        return sort_version_list(self.api_kind_version_map.get(kind, []))

    def supports_kind(self, kind: str) -> bool:
        return bool(self.api_kind_version_map.get(kind))

    def supports_tpu(self) -> bool:
        return bool(self.tpu_accelerators)

    def merge(self, other: "ClusterMetadataSpec") -> None:
        for kind, versions in other.api_kind_version_map.items():
            mine = self.api_kind_version_map.setdefault(kind, [])
            for v in versions:
                if v not in mine:
                    mine.append(v)
        for sc in other.storage_classes:
            if sc not in self.storage_classes:
                self.storage_classes.append(sc)
        for acc in other.tpu_accelerators:
            if acc not in self.tpu_accelerators:
                self.tpu_accelerators.append(acc)

    def to_dict(self) -> dict:
        d: dict = {"apiKindVersionMap": self.api_kind_version_map}
        if self.storage_classes:
            d["storageClasses"] = self.storage_classes
        if self.tpu_accelerators:
            d["tpuAccelerators"] = self.tpu_accelerators
        if self.host_capabilities:
            d["hostCapabilities"] = self.host_capabilities
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "ClusterMetadataSpec":
        return cls(
            api_kind_version_map={
                k: list(v) for k, v in d.get("apiKindVersionMap", {}).items()
            },
            storage_classes=list(d.get("storageClasses", [])),
            tpu_accelerators=list(d.get("tpuAccelerators", [])),
            host_capabilities=dict(d.get("hostCapabilities", {})),
        )


@dataclass
class ClusterMetadata:
    name: str = ""
    spec: ClusterMetadataSpec = field(default_factory=ClusterMetadataSpec)

    def to_dict(self) -> dict:
        doc = common.new_m2kt_doc(CLUSTER_METADATA_KIND, self.name)
        doc["spec"] = self.spec.to_dict()
        return doc

    @classmethod
    def from_dict(cls, d: dict) -> "ClusterMetadata":
        return cls(
            name=d.get("metadata", {}).get("name", ""),
            spec=ClusterMetadataSpec.from_dict(d.get("spec", {})),
        )


def read_cluster_metadata(path: str) -> ClusterMetadata:
    return ClusterMetadata.from_dict(common.read_m2kt_yaml(path, CLUSTER_METADATA_KIND))


@dataclass
class ImageInfo:
    """Inspected image metadata (parity: types/collection/image.go:27-50)."""

    names: list[str] = field(default_factory=list)
    tags: list[tuple[str, str]] = field(default_factory=list)  # (name, tag)
    user_id: int = -1
    accessed_dirs: list[str] = field(default_factory=list)
    ports_to_expose: list[int] = field(default_factory=list)

    def to_dict(self) -> dict:
        doc = common.new_m2kt_doc(IMAGES_METADATA_KIND)
        doc["spec"] = {
            "tags": [f"{n}:{t}" for n, t in self.tags] or list(self.names),
            "userID": self.user_id,
            "accessedDirs": self.accessed_dirs,
            "portsToExpose": self.ports_to_expose,
        }
        return doc

    @classmethod
    def from_dict(cls, d: dict) -> "ImageInfo":
        spec = d.get("spec", {})
        info = cls(
            user_id=spec.get("userID", -1),
            accessed_dirs=list(spec.get("accessedDirs", [])),
            ports_to_expose=list(spec.get("portsToExpose", [])),
        )
        for t in spec.get("tags", []):
            if ":" in t:
                name, tag = t.rsplit(":", 1)
                info.tags.append((name, tag))
            info.names.append(t)
        return info


@dataclass
class CfApp:
    name: str = ""
    buildpack: str = ""
    detected_buildpack: str = ""
    memory_mb: int = 0
    instances: int = 1
    ports: list[int] = field(default_factory=list)
    env: dict[str, str] = field(default_factory=dict)


@dataclass
class CfContainerizers:
    """Buildpack name -> candidate containerization options
    (parity: types/collection/cfcontainerizers.go:28-50)."""

    buildpack_containerizers: dict[str, list[str]] = field(default_factory=dict)

    def options_for(self, buildpack: str) -> list[str]:
        return list(self.buildpack_containerizers.get(buildpack, []))

    def merge(self, other: "CfContainerizers") -> None:
        for bp, opts in other.buildpack_containerizers.items():
            mine = self.buildpack_containerizers.setdefault(bp, [])
            for o in opts:
                if o not in mine:
                    mine.append(o)

    def to_dict(self) -> dict:
        doc = common.new_m2kt_doc(CF_CONTAINERIZERS_KIND)
        doc["spec"] = {
            "buildpackContainerizers": [
                {"buildpackName": bp, "containerizationOptions": opts}
                for bp, opts in sorted(self.buildpack_containerizers.items())
            ]
        }
        return doc

    @classmethod
    def from_dict(cls, d: dict) -> "CfContainerizers":
        out = cls()
        for entry in d.get("spec", {}).get("buildpackContainerizers", []):
            bp = entry.get("buildpackName", "")
            if bp:
                out.buildpack_containerizers[bp] = list(
                    entry.get("containerizationOptions", [])
                )
        return out


def read_cf_containerizers(path: str) -> CfContainerizers:
    return CfContainerizers.from_dict(
        common.read_m2kt_yaml(path, CF_CONTAINERIZERS_KIND)
    )


@dataclass
class CfInstanceApps:
    apps: list[CfApp] = field(default_factory=list)

    def to_dict(self) -> dict:
        doc = common.new_m2kt_doc(CF_APPS_KIND)
        doc["spec"] = {
            "applications": [
                {
                    "name": a.name,
                    "buildpack": a.buildpack,
                    "detectedBuildpack": a.detected_buildpack,
                    "memoryMB": a.memory_mb,
                    "instances": a.instances,
                    "ports": a.ports,
                    "env": a.env,
                }
                for a in self.apps
            ]
        }
        return doc

    @classmethod
    def from_dict(cls, d: dict) -> "CfInstanceApps":
        apps = []
        for a in d.get("spec", {}).get("applications", []):
            apps.append(
                CfApp(
                    name=a.get("name", ""),
                    buildpack=a.get("buildpack", ""),
                    detected_buildpack=a.get("detectedBuildpack", ""),
                    memory_mb=a.get("memoryMB", 0),
                    instances=a.get("instances", 1),
                    ports=list(a.get("ports", [])),
                    env=dict(a.get("env", {})),
                )
            )
        return cls(apps=apps)
