"""Intermediate representation carried through the translate pipeline.

Parity with the reference's ``internal/types/ir.go``: a single mutable
document holding services, images-to-build, storages, RBAC, target-cluster
spec, cached pre-existing k8s objects, Helm values and Tekton wiring, with
merge semantics for combining per-translator IRs (ir.go:256-278).

The reference embeds ``corev1.PodSpec`` in its Service (ir.go:63-125); we
have no client-go, so pod-level fields live in plain dicts that follow the
k8s schema (they are emitted as YAML verbatim), with typed helpers for the
fields the IR passes manipulate.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

from move2kube_tpu.types.collection import ClusterMetadataSpec
from move2kube_tpu.types.output import HelmValues
from move2kube_tpu.types.plan import (
    AcceleratorInfo,
    ContainerBuildType,
    KubernetesOutput,
    Plan,
    PlanService,
)
from move2kube_tpu.utils import common


# --- Storage (parity: ir.go:295-333) ---------------------------------------

class StorageKind:
    CONFIGMAP = "ConfigMap"
    SECRET = "Secret"
    PULL_SECRET = "PullSecret"
    PVC = "PersistentVolumeClaim"


@dataclass
class Storage:
    name: str
    kind: str = StorageKind.CONFIGMAP
    content: dict[str, bytes] = field(default_factory=dict)
    secret_type: str = ""  # k8s secret type, e.g. kubernetes.io/dockerconfigjson
    pvc_spec: dict = field(default_factory=dict)  # corev1.PersistentVolumeClaimSpec
    annotations: dict[str, str] = field(default_factory=dict)

    def merge(self, other: "Storage") -> bool:
        if self.name != other.name:
            return False
        if other.kind:
            self.kind = other.kind
        self.content.update(other.content)
        if other.secret_type:
            self.secret_type = other.secret_type
        if other.pvc_spec:
            self.pvc_spec = other.pvc_spec
        self.annotations.update(other.annotations)
        return True


# --- Container: an image to build or reuse (parity: ir.go:127-235) ---------

@dataclass
class RepoInfo:
    git_repo_url: str = ""
    git_repo_branch: str = ""
    git_repo_dir: str = ""  # service dir relative to repo root
    target_path: str = ""


@dataclass
class Container:
    image_names: list[str] = field(default_factory=list)
    new: bool = True  # False => image already exists, nothing to build
    build_type: str = ContainerBuildType.NEW_DOCKERFILE
    # generated files (Dockerfile, build scripts, rewritten training code...)
    # keyed by path relative to the output containers/<svc>/ dir
    new_files: dict[str, str] = field(default_factory=dict)
    exposed_ports: list[int] = field(default_factory=list)
    user_id: int = -1
    accessed_dirs: list[str] = field(default_factory=list)
    repo_info: RepoInfo = field(default_factory=RepoInfo)
    # net-new: accelerator requirements the TPU apiresources read
    accelerator: AcceleratorInfo | None = None

    def add_file(self, path: str, contents: str) -> None:
        self.new_files[path] = contents

    def add_exposed_port(self, port: int) -> None:
        if port not in self.exposed_ports:
            self.exposed_ports.append(port)

    def merge(self, other: "Container") -> bool:
        """Dedup-merge: True if other refers to the same image (ir.go:180-235).

        Containers with different build types are never merged (ir.go:170) —
        they stay separate entries even when image names collide.
        """
        if self.build_type != other.build_type:
            return False
        if not set(self.image_names) & set(other.image_names):
            return False
        for n in other.image_names:
            if n not in self.image_names:
                self.image_names.append(n)
        self.new = self.new or other.new
        self.new_files.update(other.new_files)
        for p in other.exposed_ports:
            self.add_exposed_port(p)
        if other.user_id >= 0:
            self.user_id = other.user_id
        for d in other.accessed_dirs:
            if d not in self.accessed_dirs:
                self.accessed_dirs.append(d)
        if other.accelerator is not None:
            self.accelerator = other.accelerator
        return True


def new_container_from_image_info(info) -> Container:
    """Build a non-new Container from collected ImageInfo (ir.go:214-235)."""
    c = Container(new=False, build_type=ContainerBuildType.REUSE)
    c.image_names = [f"{name}:{tag}" for name, tag in info.tags] or list(info.names)
    c.user_id = info.user_id
    c.exposed_ports = list(info.ports_to_expose)
    c.accessed_dirs = list(info.accessed_dirs)
    return c


# --- Service (parity: ir.go:63-125) ----------------------------------------

@dataclass
class PortForwarding:
    service_port: int
    container_port: int
    name: str = ""


@dataclass
class Service:
    name: str
    backend_service_name: str = ""  # when k8s name differs from plan name
    # pod-level fields as corev1-schema dicts (emitted verbatim):
    containers: list[dict] = field(default_factory=list)  # corev1.Container
    init_containers: list[dict] = field(default_factory=list)
    volumes: list[dict] = field(default_factory=list)  # corev1.Volume
    image_pull_secrets: list[str] = field(default_factory=list)
    security_context: dict = field(default_factory=dict)
    restart_policy: str = ""  # Always | OnFailure | Never
    service_account_name: str = ""
    node_selector: dict[str, str] = field(default_factory=dict)
    tolerations: list[dict] = field(default_factory=list)
    subdomain: str = ""
    hostname: str = ""
    # service-level:
    annotations: dict[str, str] = field(default_factory=dict)
    labels: dict[str, str] = field(default_factory=dict)
    replicas: int = 1
    networks: list[str] = field(default_factory=list)
    port_forwardings: list[PortForwarding] = field(default_factory=list)
    service_rel_path: str = ""  # ingress fan-out path, default "/<name>"
    only_ingress: bool = False
    daemon: bool = False
    # net-new TPU fields:
    accelerator: AcceleratorInfo | None = None
    job: bool = False  # run-to-completion workload (training) vs long-running

    def add_port_forwarding(self, service_port: int, container_port: int, name: str = "") -> None:
        for pf in self.port_forwardings:
            if pf.service_port == service_port:
                return
        self.port_forwardings.append(PortForwarding(service_port, container_port, name))

    def add_volume(self, volume: dict) -> None:
        if all(v.get("name") != volume.get("name") for v in self.volumes):
            self.volumes.append(volume)

    def has_valid_annotation(self, annotation: str) -> bool:
        return self.annotations.get(annotation) == "true"

    def pod_spec(self) -> dict:
        """Assemble the corev1.PodSpec dict for emission."""
        spec: dict[str, Any] = {"containers": [dict(c) for c in self.containers]}
        if self.init_containers:
            spec["initContainers"] = [dict(c) for c in self.init_containers]
        if self.volumes:
            spec["volumes"] = self.volumes
        if self.image_pull_secrets:
            spec["imagePullSecrets"] = [{"name": n} for n in self.image_pull_secrets]
        if self.security_context:
            spec["securityContext"] = self.security_context
        if self.restart_policy:
            spec["restartPolicy"] = self.restart_policy
        if self.service_account_name:
            spec["serviceAccountName"] = self.service_account_name
        if self.node_selector:
            spec["nodeSelector"] = self.node_selector
        if self.tolerations:
            spec["tolerations"] = self.tolerations
        if self.hostname:
            spec["hostname"] = self.hostname
        if self.subdomain:
            spec["subdomain"] = self.subdomain
        return spec

    def merge(self, other: "Service") -> None:
        self.containers.extend(c for c in other.containers if c not in self.containers)
        self.init_containers.extend(
            c for c in other.init_containers if c not in self.init_containers
        )
        self.tolerations.extend(t for t in other.tolerations if t not in self.tolerations)
        if other.security_context:
            self.security_context = other.security_context
        if other.service_account_name:
            self.service_account_name = other.service_account_name
        if other.hostname:
            self.hostname = other.hostname
        if other.subdomain:
            self.subdomain = other.subdomain
        for v in other.volumes:
            self.add_volume(v)
        for s in other.image_pull_secrets:
            if s not in self.image_pull_secrets:
                self.image_pull_secrets.append(s)
        self.annotations.update(other.annotations)
        self.labels.update(other.labels)
        self.replicas = max(self.replicas, other.replicas)
        for n in other.networks:
            if n not in self.networks:
                self.networks.append(n)
        for pf in other.port_forwardings:
            self.add_port_forwarding(pf.service_port, pf.container_port, pf.name)
        if other.restart_policy:
            self.restart_policy = other.restart_policy
        self.node_selector.update(other.node_selector)
        self.daemon = self.daemon or other.daemon
        self.job = self.job or other.job
        if other.accelerator is not None:
            self.accelerator = other.accelerator


# --- Tekton wiring (parity: internal/types/tekton/tekton.go) ---------------

@dataclass
class TektonResources:
    event_listeners: list[dict] = field(default_factory=list)
    trigger_bindings: list[dict] = field(default_factory=list)
    trigger_templates: list[dict] = field(default_factory=list)
    pipelines: list[dict] = field(default_factory=list)


# --- IR root (parity: ir.go:36-60, 237-400) --------------------------------

@dataclass
class IR:
    name: str = common.DEFAULT_PROJECT_NAME
    services: dict[str, Service] = field(default_factory=dict)
    containers: list[Container] = field(default_factory=list)
    storages: list[Storage] = field(default_factory=list)
    roles: list[dict] = field(default_factory=list)
    role_bindings: list[dict] = field(default_factory=list)
    service_accounts: list[dict] = field(default_factory=list)
    kubernetes: KubernetesOutput = field(default_factory=KubernetesOutput)
    target_cluster_spec: ClusterMetadataSpec = field(default_factory=ClusterMetadataSpec)
    cached_objects: list[dict] = field(default_factory=list)  # pre-existing k8s yamls
    values: HelmValues = field(default_factory=HelmValues)
    tekton: TektonResources = field(default_factory=TektonResources)
    ingress_tls_secret_name: str = ""

    def add_service(self, svc: Service) -> None:
        if svc.name in self.services:
            self.services[svc.name].merge(svc)
        else:
            self.services[svc.name] = svc

    def add_container(self, container: Container) -> None:
        """Dedup-add by image name (parity: IR.AddContainer ir.go:368)."""
        for existing in self.containers:
            if existing.merge(container):
                return
        self.containers.append(container)

    def add_storage(self, storage: Storage) -> None:
        for existing in self.storages:
            if existing.name == storage.name and existing.kind == storage.kind:
                existing.merge(storage)
                return
        self.storages.append(storage)

    def get_container(self, image_name: str) -> Container | None:
        for c in self.containers:
            if image_name in c.image_names:
                return c
        return None

    def merge(self, other: "IR") -> None:
        """Combine another translator's IR into this one (ir.go:256-278)."""
        for svc in other.services.values():
            self.add_service(svc)
        for c in other.containers:
            self.add_container(c)
        for s in other.storages:
            self.add_storage(s)
        self.roles.extend(r for r in other.roles if r not in self.roles)
        self.role_bindings.extend(r for r in other.role_bindings if r not in self.role_bindings)
        self.service_accounts.extend(
            s for s in other.service_accounts if s not in self.service_accounts
        )
        self.kubernetes.merge(other.kubernetes)
        self.target_cluster_spec.merge(other.target_cluster_spec)
        self.cached_objects.extend(other.cached_objects)
        self.values.merge(other.values)
        if other.ingress_tls_secret_name:
            self.ingress_tls_secret_name = other.ingress_tls_secret_name


def new_ir(plan: Plan) -> IR:
    import copy

    ir = IR(name=plan.name)
    # Deep copy: Go copies KubernetesOutput by value (ir.go:245); sharing the
    # object here would leak translate-phase mutations back into the plan file.
    ir.kubernetes = copy.deepcopy(plan.kubernetes)
    return ir


def service_from_plan(plan_svc: PlanService) -> Service:
    svc = Service(name=common.make_dns_label(plan_svc.service_name))
    svc.service_rel_path = plan_svc.service_rel_path or "/" + svc.name
    if plan_svc.accelerator is not None:
        svc.accelerator = plan_svc.accelerator
    return svc
