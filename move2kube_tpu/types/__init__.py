from move2kube_tpu.types import plan, ir, collection, output  # noqa: F401
