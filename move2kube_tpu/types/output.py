"""Helm values.yaml schema (parity: types/output/helmvaluesoutput.go:31-80)."""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class HelmValues:
    registry_url: str = ""
    registry_namespace: str = ""
    ingress_host: str = ""
    # service name -> container name -> image (registry/ns/name:tag)
    services: dict[str, dict[str, str]] = field(default_factory=dict)
    storage_class: str = ""
    global_variables: dict[str, str] = field(default_factory=dict)

    def merge(self, other: "HelmValues") -> None:
        if other.registry_url:
            self.registry_url = other.registry_url
        if other.registry_namespace:
            self.registry_namespace = other.registry_namespace
        if other.ingress_host:
            self.ingress_host = other.ingress_host
        for svc, containers in other.services.items():
            self.services.setdefault(svc, {}).update(containers)
        if other.storage_class:
            self.storage_class = other.storage_class
        self.global_variables.update(other.global_variables)

    def set_image(self, service: str, container: str, image: str) -> None:
        self.services.setdefault(service, {})[container] = image

    def to_dict(self) -> dict:
        d: dict = {
            "registryurl": self.registry_url,
            "registrynamespace": self.registry_namespace,
        }
        if self.ingress_host:
            d["ingresshost"] = self.ingress_host
        if self.services:
            d["services"] = {
                svc: {"containers": dict(containers)}
                for svc, containers in self.services.items()
            }
        if self.storage_class:
            d["storageclass"] = self.storage_class
        if self.global_variables:
            d["globalvariables"] = dict(self.global_variables)
        return d
