"""Plan schema: the serialized state between the plan and translate phases.

Parity with the reference's ``types/plan/plan.go:52-233`` (Plan/PlanSpec/
Service + enums) and ``types/plan/planutils.go:30-270`` (path
relativization). The reference walks struct tags with reflection; we keep
the same behavior — absolute paths in memory, root-relative paths on disk —
with explicit conversion code per field, as SURVEY.md §7 recommends.

Net-new for the TPU north star: the ``Gpu2Tpu`` translation type, the
``JaxXla`` container build type, and per-service ``accelerator`` metadata
(detected GPU topology that the TPU emitters size slices from).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any

from move2kube_tpu import API_VERSION
from move2kube_tpu.utils import common

PLAN_KIND = "Plan"


# --- Enums (parity: types/plan/plan.go:52-131) -----------------------------

class TranslationType:
    COMPOSE2KUBE = "Compose2Kube"
    CFMANIFEST2KUBE = "Cfmanifest2Kube"
    ANY2KUBE = "Any2Kube"
    KUBE2KUBE = "Kube2Kube"
    KNATIVE2KUBE = "Knative2Kube"
    DOCKERFILE2KUBE = "Dockerfile2Kube"
    GPU2TPU = "Gpu2Tpu"  # net-new: GPU training workload -> TPU deployment


class SourceType:
    DIRECTORY = "Directory"
    COMPOSE = "DockerCompose"
    CFMANIFEST = "CfManifest"
    K8S = "Kubernetes"
    KNATIVE = "Knative"
    DOCKERFILE = "Dockerfile"
    GPU_TRAINING = "GpuTraining"  # net-new: CUDA/NCCL/DeepSpeed source tree


class ContainerBuildType:
    NEW_DOCKERFILE = "NewDockerfile"
    REUSE_DOCKERFILE = "ReuseDockerfile"
    REUSE = "Reuse"
    CNB = "CNB"
    S2I = "S2I"
    MANUAL = "Manual"
    JAX_XLA = "JaxXla"  # net-new: rewrite GPU training code into a JAX TPU image


class TargetArtifactType:
    YAMLS = "Yamls"
    HELM = "Helm"
    KNATIVE = "Knative"


class TargetClusterType:  # how plan.targetCluster is specified
    BY_TYPE = "type"  # built-in profile name
    BY_PATH = "path"  # collected ClusterMetadata yaml


# --- Accelerator metadata (net-new) ----------------------------------------

@dataclass
class AcceleratorInfo:
    """Detected GPU requirements of a service, and the TPU mapping for them.

    Filled by the GPU detector (source/gputranslator.py); consumed by the
    jax-xla containerizer and the TPU apiresources to size pod slices.
    """

    gpu_count: int = 0
    gpu_vendor: str = ""  # e.g. "nvidia.com/gpu"
    frameworks: list[str] = field(default_factory=list)  # torch, tf, deepspeed...
    distributed_backend: str = ""  # nccl | gloo | mpi | ""
    parallelism: dict[str, int] = field(default_factory=dict)  # dp/tp/pp/sp/zero_stage
    model_family: str = ""  # resnet | bert | llama | generic
    entrypoint: str = ""  # detected training script, abs path in memory
    tpu_accelerator: str = ""  # e.g. tpu-v5-lite-podslice
    tpu_topology: str = ""  # e.g. 2x4 (per slice)
    num_hosts: int = 1  # hosts per slice
    num_slices: int = 1  # >1 = multi-slice (DCN-connected pod slices)
    serving: bool = False  # inference server (HTTP) vs run-to-completion
    serving_port: int = 0  # detected listen port of the serving workload

    _CAMEL = {
        "gpu_count": "gpuCount",
        "gpu_vendor": "gpuVendor",
        "frameworks": "frameworks",
        "distributed_backend": "distributedBackend",
        "parallelism": "parallelism",
        "model_family": "modelFamily",
        "entrypoint": "entrypoint",
        "tpu_accelerator": "tpuAccelerator",
        "tpu_topology": "tpuTopology",
        "num_hosts": "numHosts",
        "num_slices": "numSlices",
        "serving": "serving",
        "serving_port": "servingPort",
    }

    def to_dict(self) -> dict:
        d: dict[str, Any] = {}
        for attr, key in self._CAMEL.items():
            v = getattr(self, attr)
            if v:
                d[key] = v
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "AcceleratorInfo":
        obj = cls()
        camel_to_attr = {key: attr for attr, key in cls._CAMEL.items()}
        for k, v in d.items():
            attr = camel_to_attr.get(k, k)
            if hasattr(obj, attr):
                setattr(obj, attr, v)
        return obj


# --- Plan service (parity: types/plan/plan.go:194-233) ---------------------

@dataclass
class PlanService:
    service_name: str = ""
    image: str = ""
    translation_type: str = TranslationType.ANY2KUBE
    container_build_type: str = ContainerBuildType.NEW_DOCKERFILE
    source_types: list[str] = field(default_factory=list)
    # containerization target options: per build type, e.g. the detected
    # stack's template path (dockerfile), builder image (s2i/cnb), or the
    # detected model family (jax-xla).
    containerization_target_options: list[str] = field(default_factory=list)
    # source artifacts: artifact-type -> list of paths (abs in memory)
    source_artifacts: dict[str, list[str]] = field(default_factory=dict)
    build_artifacts: dict[str, list[str]] = field(default_factory=dict)
    update_container_build_pipeline: bool = True
    update_deploy_pipeline: bool = True
    service_rel_path: str = ""
    accelerator: AcceleratorInfo | None = None

    # Artifact type keys used inside source_artifacts/build_artifacts
    SOURCE_DIR_ARTIFACT = "SourceDirectories"
    DOCKERFILE_ARTIFACT = "Dockerfile"
    COMPOSE_ARTIFACT = "DockerCompose"
    CFMANIFEST_ARTIFACT = "CfManifest"
    CFRUNNING_ARTIFACT = "CfRunningManifest"
    K8S_ARTIFACT = "Kubernetes"
    KNATIVE_ARTIFACT = "Knative"
    IMAGEINFO_ARTIFACT = "ImageInfo"
    GPU_ENTRYPOINT_ARTIFACT = "GpuTrainingEntrypoint"  # net-new

    def add_source_artifact(self, artifact_type: str, path: str) -> None:
        self.source_artifacts.setdefault(artifact_type, []).append(path)

    def add_build_artifact(self, artifact_type: str, path: str) -> None:
        self.build_artifacts.setdefault(artifact_type, []).append(path)

    def to_dict(self) -> dict:
        d: dict[str, Any] = {
            "serviceName": self.service_name,
            "translationType": self.translation_type,
            "containerBuildType": self.container_build_type,
        }
        if self.image:
            d["image"] = self.image
        if self.source_types:
            d["sourceTypes"] = list(self.source_types)
        if self.containerization_target_options:
            d["containerizationTargetOptions"] = list(self.containerization_target_options)
        if self.source_artifacts:
            d["sourceArtifacts"] = {k: list(v) for k, v in self.source_artifacts.items()}
        if self.build_artifacts:
            d["buildArtifacts"] = {k: list(v) for k, v in self.build_artifacts.items()}
        d["updateContainerBuildPipeline"] = self.update_container_build_pipeline
        d["updateDeployPipeline"] = self.update_deploy_pipeline
        if self.service_rel_path:
            d["serviceRelPath"] = self.service_rel_path
        if self.accelerator is not None:
            d["accelerator"] = self.accelerator.to_dict()
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "PlanService":
        svc = cls(
            service_name=d.get("serviceName", ""),
            image=d.get("image", ""),
            translation_type=d.get("translationType", TranslationType.ANY2KUBE),
            container_build_type=d.get("containerBuildType", ContainerBuildType.NEW_DOCKERFILE),
            source_types=list(d.get("sourceTypes", [])),
            containerization_target_options=list(d.get("containerizationTargetOptions", [])),
            source_artifacts={k: list(v) for k, v in d.get("sourceArtifacts", {}).items()},
            build_artifacts={k: list(v) for k, v in d.get("buildArtifacts", {}).items()},
            update_container_build_pipeline=d.get("updateContainerBuildPipeline", True),
            update_deploy_pipeline=d.get("updateDeployPipeline", True),
            service_rel_path=d.get("serviceRelPath", ""),
        )
        if "accelerator" in d and d["accelerator"]:
            svc.accelerator = AcceleratorInfo.from_dict(d["accelerator"])
        return svc


# --- Target cluster --------------------------------------------------------

@dataclass
class TargetCluster:
    type: str = ""  # built-in profile name (e.g. "Kubernetes", "GCP-GKE-TPU")
    path: str = ""  # or path to a collected ClusterMetadata yaml (abs in memory)

    def to_dict(self) -> dict:
        d: dict[str, Any] = {}
        if self.type:
            d["type"] = self.type
        if self.path:
            d["path"] = self.path
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "TargetCluster":
        return cls(type=d.get("type", ""), path=d.get("path", ""))


# --- Kubernetes output spec (parity: plan.go:134-192) ----------------------

@dataclass
class KubernetesOutput:
    registry_url: str = ""
    registry_namespace: str = ""
    # "" means unset (parity with Go's zero-struct guard, plan.go:169);
    # consumers resolve via effective_artifact_type().
    artifact_type: str = ""
    target_cluster: TargetCluster = field(default_factory=TargetCluster)
    ignore_unsupported_kinds: bool = False

    def effective_artifact_type(self) -> str:
        return self.artifact_type or TargetArtifactType.YAMLS

    def merge(self, other: "KubernetesOutput") -> None:
        import copy

        if other.registry_url:
            self.registry_url = other.registry_url
        if other.registry_namespace:
            self.registry_namespace = other.registry_namespace
        if other.artifact_type:
            self.artifact_type = other.artifact_type
        if other.target_cluster.type or other.target_cluster.path:
            self.target_cluster = copy.deepcopy(other.target_cluster)
        self.ignore_unsupported_kinds = (
            self.ignore_unsupported_kinds or other.ignore_unsupported_kinds
        )

    def to_dict(self) -> dict:
        d: dict[str, Any] = {}
        if self.registry_url:
            d["registryURL"] = self.registry_url
        if self.registry_namespace:
            d["registryNamespace"] = self.registry_namespace
        if self.artifact_type:
            d["artifactType"] = self.artifact_type
        tc = self.target_cluster.to_dict()
        if tc:
            d["targetCluster"] = tc
        if self.ignore_unsupported_kinds:
            d["ignoreUnsupportedKinds"] = True
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "KubernetesOutput":
        return cls(
            registry_url=d.get("registryURL", ""),
            registry_namespace=d.get("registryNamespace", ""),
            artifact_type=d.get("artifactType", ""),
            target_cluster=TargetCluster.from_dict(d.get("targetCluster", {})),
            ignore_unsupported_kinds=d.get("ignoreUnsupportedKinds", False),
        )


# --- Plan ------------------------------------------------------------------

@dataclass
class Plan:
    name: str = common.DEFAULT_PROJECT_NAME
    root_dir: str = ""
    services: dict[str, list[PlanService]] = field(default_factory=dict)
    k8s_files: list[str] = field(default_factory=list)
    qa_caches: list[str] = field(default_factory=list)
    target_info_artifacts: dict[str, list[str]] = field(default_factory=dict)
    kubernetes: KubernetesOutput = field(default_factory=KubernetesOutput)

    TARGET_CLUSTERS_ARTIFACT = "KubernetesCluster"

    def add_service(self, svc: PlanService) -> None:
        self.services.setdefault(svc.service_name, []).append(svc)

    # -- path relativization (parity: planutils.go:30-270) ------------------

    def _service_path_fields(self, svc: PlanService):
        """Yield (container, key) pairs whose values are path lists."""
        for artifacts in (svc.source_artifacts, svc.build_artifacts):
            for k in artifacts:
                yield artifacts, k

    def _convert_paths(self, conv) -> None:
        self.k8s_files = [conv(p) for p in self.k8s_files]
        self.qa_caches = [conv(p) for p in self.qa_caches]
        for k in self.target_info_artifacts:
            self.target_info_artifacts[k] = [conv(p) for p in self.target_info_artifacts[k]]
        if self.kubernetes.target_cluster.path:
            self.kubernetes.target_cluster.path = conv(self.kubernetes.target_cluster.path)
        for svcs in self.services.values():
            for svc in svcs:
                for artifacts, k in self._service_path_fields(svc):
                    artifacts[k] = [conv(p) for p in artifacts[k]]
                if svc.accelerator and svc.accelerator.entrypoint:
                    svc.accelerator.entrypoint = conv(svc.accelerator.entrypoint)

    def _to_relative(self) -> None:
        root = self.root_dir

        def conv(p: str) -> str:
            rel = common.relpath_under(p, root)
            return rel if rel is not None else p

        self._convert_paths(conv)

    def _to_absolute(self) -> None:
        root = self.root_dir

        def conv(p: str) -> str:
            return p if os.path.isabs(p) else os.path.normpath(os.path.join(root, p))

        self._convert_paths(conv)

    def set_root_dir(self, new_root: str) -> None:
        """Re-root all paths (parity: Plan.SetRootDir planutils.go:214)."""
        self._to_relative()
        self.root_dir = os.path.abspath(new_root)
        self._to_absolute()

    # -- serialization ------------------------------------------------------

    def to_dict(self) -> dict:
        self._to_relative()
        try:
            d = {
                "apiVersion": API_VERSION,
                "kind": PLAN_KIND,
                "metadata": {"name": self.name},
                "spec": {
                    "inputs": {
                        "rootDir": self.root_dir,
                        "services": {
                            name: [s.to_dict() for s in svcs]
                            for name, svcs in sorted(self.services.items())
                        },
                    },
                    "outputs": {"kubernetes": self.kubernetes.to_dict()},
                },
            }
            inputs = d["spec"]["inputs"]
            if self.k8s_files:
                inputs["k8sFiles"] = list(self.k8s_files)
            if self.qa_caches:
                inputs["qaCaches"] = list(self.qa_caches)
            if self.target_info_artifacts:
                inputs["targetInfoArtifacts"] = {
                    k: list(v) for k, v in self.target_info_artifacts.items()
                }
            return d
        finally:
            self._to_absolute()

    @classmethod
    def from_dict(cls, d: dict) -> "Plan":
        spec = d.get("spec", {})
        inputs = spec.get("inputs", {})
        outputs = spec.get("outputs", {})
        plan = cls(
            name=d.get("metadata", {}).get("name", common.DEFAULT_PROJECT_NAME),
            root_dir=inputs.get("rootDir", ""),
            k8s_files=list(inputs.get("k8sFiles", [])),
            qa_caches=list(inputs.get("qaCaches", [])),
            target_info_artifacts={
                k: list(v) for k, v in inputs.get("targetInfoArtifacts", {}).items()
            },
            kubernetes=KubernetesOutput.from_dict(outputs.get("kubernetes", {})),
        )
        for name, svcs in inputs.get("services", {}).items():
            plan.services[name] = [PlanService.from_dict(s) for s in svcs]
        plan._to_absolute()
        return plan


def new_plan(name: str = common.DEFAULT_PROJECT_NAME) -> Plan:
    plan = Plan(name=common.make_dns_label(name))
    plan.kubernetes.registry_url = common.DEFAULT_REGISTRY_URL
    plan.kubernetes.registry_namespace = plan.name
    return plan


def read_plan(path: str) -> Plan:
    """Read and path-absolutize a plan file (parity: ReadPlan planutils.go:165)."""
    doc = common.read_m2kt_yaml(path, PLAN_KIND)
    return Plan.from_dict(doc)


def write_plan(path: str, plan: Plan) -> None:
    """Path-relativize and write (parity: WritePlan planutils.go:191)."""
    common.write_yaml(path, plan.to_dict())
