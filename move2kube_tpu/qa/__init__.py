from move2kube_tpu.qa.problem import Problem, SolutionForm  # noqa: F401
from move2kube_tpu.qa.engine import (  # noqa: F401
    fetch_answer,
    fetch_bool,
    fetch_input,
    fetch_multi_select,
    fetch_select,
    start_engine,
    reset_engines,
    add_cache_engine,
    set_write_cache,
)
