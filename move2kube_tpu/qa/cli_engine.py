"""Interactive terminal QA engine.

Parity: ``internal/qaengine/cliengine.go:44-175`` (survey prompts per
solution form) using stdlib input()/getpass.
"""

from __future__ import annotations

import getpass
import sys

from move2kube_tpu.qa.engine import Engine
from move2kube_tpu.qa.problem import Problem, SolutionForm


class CliEngine(Engine):
    def is_interactive(self) -> bool:
        return True

    def fetch_answer(self, problem: Problem) -> Problem:
        print("", file=sys.stderr)
        for line in problem.context:
            print(f"  [{line}]", file=sys.stderr)
        if problem.form == SolutionForm.SELECT:
            self._ask_select(problem)
        elif problem.form == SolutionForm.MULTI_SELECT:
            self._ask_multi_select(problem)
        elif problem.form == SolutionForm.CONFIRM:
            default = "Y/n" if problem.default else "y/N"
            raw = input(f"{problem.desc} [{default}] : ").strip()
            problem.set_answer(raw if raw else bool(problem.default))
        elif problem.form == SolutionForm.PASSWORD:
            problem.set_answer(getpass.getpass(f"{problem.desc} : "))
        elif problem.form == SolutionForm.MULTI_LINE:
            print(f"{problem.desc} (end with a line containing only '.'):", file=sys.stderr)
            lines = []
            while True:
                line = input()
                if line == ".":
                    break
                lines.append(line)
            problem.set_answer("\n".join(lines) or (problem.default or ""))
        else:  # INPUT
            raw = input(f"{problem.desc} [{problem.default or ''}] : ").strip()
            problem.set_answer(raw if raw else (problem.default or ""))
        return problem

    def _ask_select(self, problem: Problem) -> None:
        print(problem.desc, file=sys.stderr)
        for i, opt in enumerate(problem.options, 1):
            marker = "*" if opt == problem.default else " "
            print(f" {marker}{i}. {opt}", file=sys.stderr)
        raw = input(f"choose [1-{len(problem.options)}] : ").strip()
        if not raw:
            problem.set_default_answer()
            return
        if raw.isdigit() and 1 <= int(raw) <= len(problem.options):
            problem.set_answer(problem.options[int(raw) - 1])
        else:
            problem.set_answer(raw)

    def _ask_multi_select(self, problem: Problem) -> None:
        print(problem.desc, file=sys.stderr)
        defaults = set(problem.default or [])
        for i, opt in enumerate(problem.options, 1):
            marker = "*" if opt in defaults else " "
            print(f" {marker}{i}. {opt}", file=sys.stderr)
        raw = input("choose (comma-separated numbers, empty = defaults) : ").strip()
        if not raw:
            problem.set_default_answer()
            return
        picked = []
        for tok in raw.split(","):
            tok = tok.strip()
            if tok.isdigit() and 1 <= int(tok) <= len(problem.options):
                picked.append(problem.options[int(tok) - 1])
            elif tok:
                picked.append(tok)
        problem.set_answer(picked)
