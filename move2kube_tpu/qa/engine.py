"""QA engine dispatcher.

Parity: ``internal/qaengine/engine.go:29-118`` — an ordered chain of
engines (cache engines first, interactive last); ``fetch_answer`` walks the
chain until a problem resolves, retrying the last engine, and appends every
answer to the write cache. Convenience wrappers mirror the reference's
typed fetch helpers.
"""

from __future__ import annotations


from move2kube_tpu.qa.cache import Cache
from move2kube_tpu.qa.problem import Problem
from move2kube_tpu.utils.log import get_logger

log = get_logger("qa")


class Engine:
    """Interface: resolve a problem or leave it unresolved."""

    def start(self) -> None:  # pragma: no cover - trivial
        pass

    def fetch_answer(self, problem: Problem) -> Problem:
        raise NotImplementedError

    def is_interactive(self) -> bool:
        return False


class DefaultEngine(Engine):
    """Accept defaults for everything (parity: defaultengine.go:39)."""

    def fetch_answer(self, problem: Problem) -> Problem:
        problem.set_default_answer()
        return problem


class CacheEngine(Engine):
    """Replay answers from a previous run's cache (cacheengine.go:41)."""

    def __init__(self, cache_path: str) -> None:
        self.cache = Cache(path=cache_path)

    def start(self) -> None:
        self.cache.load()

    def fetch_answer(self, problem: Problem) -> Problem:
        self.cache.get_solution(problem)
        return problem


_engines: list[Engine] = []
_write_cache: Cache | None = None


def reset_engines() -> None:
    global _engines, _write_cache
    _engines = []
    _write_cache = None


def start_engine(interactive: bool = False, qa_skip: bool = False,
                 qa_port: int = 0, qa_disable_cli: bool = False) -> None:
    """Install the interactive (or default) engine (engine.go:40-66).

    ``qa_disable_cli`` (parity: --qadisablecli, cmd translate.go) forces
    REST even without an explicit port: port 0 binds an OS-assigned one
    (reference: freeport), logged by the engine at startup.
    """
    if qa_skip or not interactive:
        add_engine(DefaultEngine())
    elif qa_port or qa_disable_cli:
        from move2kube_tpu.qa.rest_engine import HTTPRESTEngine

        add_engine(HTTPRESTEngine(qa_port))
    else:
        from move2kube_tpu.qa.cli_engine import CliEngine

        add_engine(CliEngine())


def add_engine(engine: Engine) -> None:
    engine.start()
    _engines.append(engine)


def add_cache_engine(cache_path: str) -> None:
    """Cache engines resolve before interactive ones (engine.go:69-80)."""
    e = CacheEngine(cache_path)
    e.start()
    # insert before the first non-cache engine
    idx = 0
    for idx, existing in enumerate(_engines):  # noqa: B007
        if not isinstance(existing, CacheEngine):
            break
    else:
        idx = len(_engines)
    _engines.insert(idx, e)


def set_write_cache(cache_path: str) -> None:
    global _write_cache
    _write_cache = Cache(path=cache_path)
    _write_cache.write()


def fetch_answer(problem: Problem) -> Problem:
    """Resolve a problem through the engine chain (engine.go:84-118)."""
    if not _engines:
        add_engine(DefaultEngine())
    for engine in _engines:
        try:
            engine.fetch_answer(problem)
        except Exception as e:  # noqa: BLE001 - plugin tolerance
            log.debug("qa engine %s failed on %s: %s", type(engine).__name__, problem.id, e)
        if problem.resolved:
            break
    retries = 0
    while not problem.resolved and retries < 3:
        retries += 1
        try:
            _engines[-1].fetch_answer(problem)
        except Exception as e:  # noqa: BLE001
            log.warning("failed to fetch answer for %s: %s", problem.id, e)
    if not problem.resolved:
        problem.set_default_answer()
    if _write_cache is not None:
        _write_cache.add_solution(problem)
    return problem


# -- typed helpers (parity: qaengine convenience fetchers) -------------------

def fetch_select(id: str, desc: str, context: list[str], default: str,
                 options: list[str]) -> str:
    return fetch_answer(Problem.select(id, desc, context, default, options)).answer


def fetch_multi_select(id: str, desc: str, context: list[str],
                       default: list[str], options: list[str]) -> list[str]:
    return fetch_answer(Problem.multi_select(id, desc, context, default, options)).answer


def fetch_input(id: str, desc: str, context: list[str], default: str = "") -> str:
    return fetch_answer(Problem.input(id, desc, context, default)).answer


def fetch_bool(id: str, desc: str, context: list[str], default: bool = True) -> bool:
    return fetch_answer(Problem.confirm(id, desc, context, default)).answer


def fetch_password(id: str, desc: str, context: list[str]) -> str:
    return fetch_answer(Problem.password(id, desc, context)).answer


def fetch_multiline(id: str, desc: str, context: list[str], default: str = "") -> str:
    return fetch_answer(Problem.multiline(id, desc, context, default)).answer
