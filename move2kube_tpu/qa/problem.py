"""QA problem schema.

Parity with the reference's ``types/qaengine/problem.go:30-280``: a Problem
has an id, description, context lines and a typed Solution in one of six
forms (Select, MultiSelect, Input, MultiLine, Password, Confirm), with
answer validation/coercion and fuzzy matching of cached problems against
new ones (the cache-replay contract keys on description text;
problem.go:151-170).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any

from move2kube_tpu.utils import common


class SolutionForm:
    SELECT = "Select"
    MULTI_SELECT = "MultiSelect"
    INPUT = "Input"
    MULTI_LINE = "MultiLine"
    PASSWORD = "Password"
    CONFIRM = "Confirm"


@dataclass
class Problem:
    id: str
    desc: str
    form: str
    context: list[str] = field(default_factory=list)
    options: list[str] = field(default_factory=list)
    default: Any = None
    answer: Any = None
    resolved: bool = False

    # -- constructors (parity: NewSelectProblem etc., problem.go:190-280) ---

    @classmethod
    def select(cls, id: str, desc: str, context: list[str], default: str,
               options: list[str]) -> "Problem":
        if default and default not in options:
            default = options[0] if options else ""
        return cls(id=id, desc=desc, form=SolutionForm.SELECT, context=context,
                   options=options, default=default)

    @classmethod
    def multi_select(cls, id: str, desc: str, context: list[str],
                     default: list[str], options: list[str]) -> "Problem":
        default = [d for d in default if d in options]
        return cls(id=id, desc=desc, form=SolutionForm.MULTI_SELECT,
                   context=context, options=options, default=default)

    @classmethod
    def input(cls, id: str, desc: str, context: list[str], default: str = "") -> "Problem":
        return cls(id=id, desc=desc, form=SolutionForm.INPUT, context=context,
                   default=default)

    @classmethod
    def multiline(cls, id: str, desc: str, context: list[str], default: str = "") -> "Problem":
        return cls(id=id, desc=desc, form=SolutionForm.MULTI_LINE, context=context,
                   default=default)

    @classmethod
    def password(cls, id: str, desc: str, context: list[str]) -> "Problem":
        return cls(id=id, desc=desc, form=SolutionForm.PASSWORD, context=context)

    @classmethod
    def confirm(cls, id: str, desc: str, context: list[str], default: bool = True) -> "Problem":
        return cls(id=id, desc=desc, form=SolutionForm.CONFIRM, context=context,
                   default=default)

    # -- answer handling ----------------------------------------------------

    def set_answer(self, answer: Any) -> None:
        """Validate/coerce an answer and mark resolved (problem.go:60-140)."""
        if self.form == SolutionForm.SELECT:
            answer = str(answer)
            if answer not in self.options:
                match = common.closest_matching_string(answer, self.options)
                if not match:
                    raise ValueError(f"{self.id}: no options to select from")
                answer = match
        elif self.form == SolutionForm.MULTI_SELECT:
            if isinstance(answer, str):
                answer = [a.strip() for a in answer.split(",") if a.strip()]
            answer = [a for a in answer if a in self.options]
        elif self.form == SolutionForm.CONFIRM:
            if isinstance(answer, str):
                answer = answer.strip().lower() in ("y", "yes", "true", "1")
            else:
                answer = bool(answer)
        else:  # Input / MultiLine / Password
            answer = str(answer)
        self.answer = answer
        self.resolved = True

    def set_default_answer(self) -> None:
        if self.form == SolutionForm.CONFIRM:
            self.set_answer(bool(self.default))
        elif self.form == SolutionForm.MULTI_SELECT:
            self.answer = list(self.default or [])
            self.resolved = True
        elif self.form == SolutionForm.SELECT:
            if self.default:
                self.set_answer(self.default)
            elif self.options:
                self.set_answer(self.options[0])
            else:
                raise ValueError(f"{self.id}: select problem with no options")
        else:
            self.set_answer(self.default if self.default is not None else "")

    # -- cache matching (parity: matches/matchString problem.go:151-185) ----

    def matches(self, other: "Problem") -> bool:
        """True if a cached problem (self) answers a new problem (other).

        Descriptions may contain [wildcard] segments that match anything —
        the reference turns bracketed segments into regex wildcards.
        """
        if self.form != other.form:
            return False
        return _match_desc(self.desc, other.desc)

    def to_dict(self) -> dict:
        d: dict[str, Any] = {
            "id": self.id,
            "description": self.desc,
            "solution": {"type": self.form},
        }
        if self.context:
            d["context"] = list(self.context)
        sol = d["solution"]
        if self.options:
            sol["options"] = list(self.options)
        if self.default not in (None, "", []):
            sol["default"] = self.default
        if self.resolved:
            sol["answer"] = self.answer
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Problem":
        sol = d.get("solution", {})
        p = cls(
            id=d.get("id", ""),
            desc=d.get("description", ""),
            form=sol.get("type", SolutionForm.INPUT),
            context=list(d.get("context", [])),
            options=list(sol.get("options", [])),
            default=sol.get("default"),
        )
        if "answer" in sol:
            p.answer = sol["answer"]
            p.resolved = True
        return p


def _match_desc(cached_desc: str, new_desc: str) -> bool:
    if cached_desc == new_desc:
        return True
    # Bracketed segments are wildcards: "Select port for [svc]" matches any svc.
    pattern = re.escape(cached_desc)
    pattern = re.sub(r"\\\[.*?\\\]", ".*", pattern)
    return re.fullmatch(pattern, new_desc) is not None
