"""QA answer cache: YAML persistence of solved problems.

Parity: ``types/qaengine/cache.go:32-135`` — every answered problem is
appended; ``get_solution`` fuzzy-matches new problems against stored ones
so a previous run's answers replay headlessly.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field

from move2kube_tpu.utils import common
from move2kube_tpu.qa.problem import Problem

QA_CACHE_KIND = "QACache"


@dataclass
class Cache:
    path: str = ""
    problems: list[Problem] = field(default_factory=list)
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def load(self) -> None:
        if not self.path or not os.path.exists(self.path):
            return
        doc = common.read_m2kt_yaml(self.path, QA_CACHE_KIND)
        self.problems = [
            Problem.from_dict(p) for p in doc.get("spec", {}).get("solutions", [])
        ]

    def write(self) -> None:
        if not self.path:
            return
        doc = common.new_m2kt_doc(QA_CACHE_KIND)
        doc["spec"] = {"solutions": [p.to_dict() for p in self.problems]}
        common.write_yaml(self.path, doc)

    def add_solution(self, problem: Problem) -> None:
        """Persist a solved problem (cache.go:84)."""
        if not problem.resolved:
            return
        with self._lock:
            self.problems.append(problem)
            self.write()

    def get_solution(self, problem: Problem) -> Problem | None:
        """Answer a new problem from the cache if a stored one matches
        (cache.go:114)."""
        for cached in self.problems:
            if cached.resolved and cached.matches(problem):
                try:
                    problem.set_answer(cached.answer)
                except ValueError:
                    continue
                return problem
        return None
