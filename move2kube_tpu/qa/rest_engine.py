"""REST QA engine: lets a UI drive the pipeline over HTTP.

Parity: ``internal/qaengine/httprestengine.go:58-160`` — the pipeline
thread publishes the current problem and blocks; a client GETs
``/problems/current`` and POSTs ``/problems/current/solution``.
"""

from __future__ import annotations

import json
import queue
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from move2kube_tpu.qa.engine import Engine
from move2kube_tpu.qa.problem import Problem
from move2kube_tpu.utils.log import get_logger

log = get_logger("qa.rest")

API_PREFIX = "/api/v1"


class HTTPRESTEngine(Engine):
    def __init__(self, port: int = 0) -> None:
        self.port = port
        self._current: Problem | None = None
        self._lock = threading.Lock()
        self._answers: queue.Queue = queue.Queue()
        self._server: ThreadingHTTPServer | None = None

    def is_interactive(self) -> bool:
        return True

    def start(self) -> None:
        if self._server is not None:
            return
        engine = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: A003
                log.debug("rest: " + fmt, *args)

            def _send(self, code: int, body: dict) -> None:
                data = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):  # noqa: N802
                if self.path == API_PREFIX + "/problems/current":
                    with engine._lock:
                        p = engine._current
                    if p is None:
                        self._send(204, {})
                    else:
                        self._send(200, p.to_dict())
                else:
                    self._send(404, {"error": "not found"})

            def do_POST(self):  # noqa: N802
                if self.path == API_PREFIX + "/problems/current/solution":
                    length = int(self.headers.get("Content-Length", 0))
                    try:
                        body = json.loads(self.rfile.read(length) or b"{}")
                    except json.JSONDecodeError:
                        self._send(400, {"error": "invalid json"})
                        return
                    if "solution" not in body:
                        self._send(400, {"error": "missing 'solution'"})
                        return
                    engine._answers.put(body["solution"])
                    self._send(200, {"status": "accepted"})
                else:
                    self._send(404, {"error": "not found"})

        self._server = ThreadingHTTPServer(("127.0.0.1", self.port), Handler)
        self.port = self._server.server_address[1]
        t = threading.Thread(target=self._server.serve_forever, daemon=True)
        t.start()
        log.info("QA REST engine listening on 127.0.0.1:%d%s", self.port, API_PREFIX)

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server = None

    def fetch_answer(self, problem: Problem) -> Problem:
        with self._lock:
            self._current = problem
        try:
            answer = self._answers.get(timeout=600)
            problem.set_answer(answer)
        finally:
            with self._lock:
                self._current = None
        return problem
