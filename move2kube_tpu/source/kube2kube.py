"""Kube2Kube: re-target existing Kubernetes yamls.

Parity: ``internal/source/kube2kube.go`` — planning is handled by the
K8sFilesLoader metadata loader; translate re-reads the plan's k8s yamls
into ``ir.cached_objects`` so the apiresource engine converts them to
cluster-supported kinds/versions at write time.
"""

from __future__ import annotations

from move2kube_tpu.source.base import Translator
from move2kube_tpu.types import ir as irtypes
from move2kube_tpu.types.plan import Plan, PlanService, TranslationType
from move2kube_tpu.utils import common
from move2kube_tpu.utils.log import get_logger

log = get_logger("source.kube2kube")


def load_k8s_yamls(paths: list[str]) -> list[dict]:
    objs = []
    for path in paths:
        try:
            with open(path, encoding="utf-8") as f:
                import yaml

                for doc in yaml.safe_load_all(f):
                    if isinstance(doc, dict) and doc.get("kind") and doc.get("apiVersion"):
                        objs.append(doc)
        except Exception as e:  # noqa: BLE001
            log.warning("cannot load k8s yaml %s: %s", path, e)
    return objs


class KubeTranslator(Translator):
    def get_translation_type(self) -> str:
        return TranslationType.KUBE2KUBE

    def get_service_options(self, plan: Plan) -> list[PlanService]:
        return []  # planning handled by metadata loader (kube2kube.go:35-38)

    def translate(self, services: list[PlanService], plan: Plan) -> irtypes.IR:
        ir = irtypes.IR(name=plan.name)
        paths = []
        for svc in services:
            paths.extend(svc.source_artifacts.get(PlanService.K8S_ARTIFACT, []))
        if not paths:
            paths = plan.k8s_files
        ir.cached_objects.extend(load_k8s_yamls(paths))
        return ir
