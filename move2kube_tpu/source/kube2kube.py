"""Kube2Kube: re-target existing Kubernetes yamls.

Parity: ``internal/source/kube2kube.go`` — planning is handled by the
K8sFilesLoader metadata loader; translate re-reads the plan's k8s yamls
into ``ir.cached_objects`` so the apiresource engine converts them to
cluster-supported kinds/versions at write time (the reference's kube
planner/translator seam is ``k8sapiresourceset.go:81-115``).

Net-new (north star): workloads whose pod spec requests ``nvidia.com/gpu``
are *not* passed through — they are lifted into IR services carrying
AcceleratorInfo so the deployment apiresource re-emits them as TPU
JobSets with ``google.com/tpu`` resources, exactly like GPU compose
services (compose2kube.py) and detected CUDA sources.
"""

from __future__ import annotations

from move2kube_tpu.source.base import Translator
from move2kube_tpu.types import ir as irtypes
from move2kube_tpu.types.plan import AcceleratorInfo, Plan, PlanService, TranslationType
from move2kube_tpu.utils import common
from move2kube_tpu.utils.log import get_logger

log = get_logger("source.kube2kube")

# kinds whose spec.template holds the pod spec
_TEMPLATED_KINDS = {"Deployment", "StatefulSet", "ReplicaSet", "DaemonSet",
                    "Job", "ReplicationController", "DeploymentConfig"}
# GPU-machine node-selector/toleration keys that must not survive the move
# to TPU node pools
_GPU_NODE_KEYS = ("nvidia.com", "gke-accelerator", "gpu")


def _pod_template(obj: dict) -> dict | None:
    kind = obj.get("kind")
    if kind == "Pod":
        return {"metadata": obj.get("metadata", {}), "spec": obj.get("spec", {})}
    if kind == "CronJob":
        return (obj.get("spec", {}).get("jobTemplate", {})
                .get("spec", {}).get("template"))
    if kind in _TEMPLATED_KINDS:
        return obj.get("spec", {}).get("template")
    return None


def _strip_gpu_resources(container: dict) -> dict:
    c = dict(container)
    resources = dict(c.get("resources") or {})
    for section in ("limits", "requests"):
        vals = {k: v for k, v in (resources.get(section) or {}).items()
                if "gpu" not in k.lower()}
        if vals:
            resources[section] = vals
        else:
            resources.pop(section, None)
    if resources:
        c["resources"] = resources
    else:
        c.pop("resources", None)
    return c


def _pod_count(obj: dict) -> int:
    """Concurrent pods a workload runs: replicas for replicated kinds,
    parallelism for (Cron)Jobs."""
    spec = obj.get("spec", {}) or {}
    if obj.get("kind") == "CronJob":
        spec = spec.get("jobTemplate", {}).get("spec", {}) or {}
    if obj.get("kind") in ("Job", "CronJob"):
        return int(spec.get("parallelism") or 1)
    return int(spec.get("replicas") or 1)


def k8s_doc_gpu_count(obj: dict) -> int:
    """Total GPUs a k8s workload requests (per-pod GPUs x concurrent pods)."""
    from move2kube_tpu.source import gpu_detect

    template = _pod_template(obj)
    if not template:
        return 0
    containers = (template.get("spec") or {}).get("containers") or []
    per_pod = sum(
        gpu_detect.gpu_resources_from_k8s_container(c) for c in containers)
    return per_pod * max(1, _pod_count(obj))


def tpu_service_from_gpu_workload(obj: dict) -> irtypes.Service | None:
    """Lift a GPU-requesting k8s workload into a TPU-bound IR service.

    Returns None when the object has no pod template or requests no GPUs.
    The returned service carries AcceleratorInfo + job=True, which the
    deployment apiresource turns into a JobSet with google.com/tpu.
    """
    from move2kube_tpu.source import gpu_detect

    total_gpus = k8s_doc_gpu_count(obj)
    if not total_gpus:
        return None
    template = _pod_template(obj)
    pod = template.get("spec", {}) or {}
    containers = pod.get("containers") or []
    acc_type, topology, hosts, num_slices = (
        gpu_detect.map_gpu_to_tpu_multislice(total_gpus))

    name = common.make_dns_label(
        obj.get("metadata", {}).get("name") or "gpu-workload")
    svc = irtypes.Service(name=name)
    # pod-template labels too: Services in the same yaml select on them
    # and pass through via cached_objects expecting pods to still match
    svc.labels = {**(obj.get("metadata", {}).get("labels") or {}),
                  **(template.get("metadata", {}).get("labels") or {})}
    svc.annotations = dict(obj.get("metadata", {}).get("annotations") or {})
    svc.containers = [_strip_gpu_resources(c) for c in containers]
    svc.init_containers = list(pod.get("initContainers") or [])
    svc.volumes = list(pod.get("volumes") or [])
    svc.service_account_name = pod.get("serviceAccountName", "")
    svc.image_pull_secrets = [
        s.get("name", "") for s in pod.get("imagePullSecrets") or []]
    svc.security_context = dict(pod.get("securityContext") or {})
    svc.node_selector = {
        k: v for k, v in (pod.get("nodeSelector") or {}).items()
        if not any(g in k.lower() for g in _GPU_NODE_KEYS)}
    svc.tolerations = [
        t for t in pod.get("tolerations") or []
        if not any(g in (t.get("key") or "").lower() for g in _GPU_NODE_KEYS)]
    svc.accelerator = AcceleratorInfo(
        gpu_count=total_gpus,
        gpu_vendor="nvidia.com/gpu",
        distributed_backend="nccl" if total_gpus > 1 else "",
        tpu_accelerator=acc_type,
        tpu_topology=topology,
        num_hosts=hosts,
        num_slices=num_slices,
    )
    svc.job = True
    svc.restart_policy = "Never"
    log.info("k8s %s %s requests %d GPU(s) -> TPU %s %s x%d slice(s)",
             obj.get("kind"), name, total_gpus, acc_type, topology, num_slices)
    return svc


def load_k8s_yamls(paths: list[str]) -> list[dict]:
    objs = []
    for path in paths:
        try:
            with open(path, encoding="utf-8") as f:
                import yaml

                for doc in yaml.safe_load_all(f):
                    if isinstance(doc, dict) and doc.get("kind") and doc.get("apiVersion"):
                        objs.append(doc)
        except Exception as e:  # noqa: BLE001
            log.warning("cannot load k8s yaml %s: %s", path, e)
    return objs


class KubeTranslator(Translator):
    def get_translation_type(self) -> str:
        return TranslationType.KUBE2KUBE

    def get_service_options(self, plan: Plan) -> list[PlanService]:
        return []  # planning handled by metadata loader (kube2kube.go:35-38)

    def translate(self, services: list[PlanService], plan: Plan) -> irtypes.IR:
        ir = irtypes.IR(name=plan.name)
        paths = []
        for svc in services:
            paths.extend(svc.source_artifacts.get(PlanService.K8S_ARTIFACT, []))
        if not paths:
            paths = plan.k8s_files
        for obj in load_k8s_yamls(paths):
            svc = tpu_service_from_gpu_workload(obj)
            if svc is not None:
                ir.add_service(svc)  # re-emitted as a TPU JobSet
            else:
                ir.cached_objects.append(obj)
        return ir
