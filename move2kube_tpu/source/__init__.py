from move2kube_tpu.source.base import (  # noqa: F401
    Translator,
    get_source_loaders,
    translate_sources,
)
