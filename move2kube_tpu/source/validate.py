"""Numerics-diff validation harness for gpu2tpu translations.

ROADMAP item 4's trust gate: a translated trainer is only believable if
its *numbers* match the source's declared semantics — CASS (2505.16968)
and the GPU-to-CPU construct transpiler (2207.00257) both make the case
that diff-testing against the source is what separates a transpiler
from a text generator. This module runs the two sides on identical
synthetic batches and gates on their deltas:

- the **translated side** is the real emitted-trainer machinery: the
  tiny zoo model under the translation's precision policy (bf16 compute
  over fp32 master weights), ``make_lm_train_step``'s jitted/donated
  step, and ``instrument_optimizer``'s recorders — exactly what the
  emitted ``train_tpu.py`` executes, shrunk to a CPU-sized config;
- the **reference side** replays the *declared source semantics*: fp32
  math, eager-shape jit, and the optimizer/learning-rate parsed from
  the source tree (``gpu_detect``'s ``lr_hint`` + the optimizer name in
  the entrypoint). Both sides share the translated side's initial
  parameters, so every delta is execution semantics, not init luck.

Gates: initial-logit max-rel error (``serving/quant.logit_gate``'s
row-span normalization), first-step gradient-norm delta, per-step
loss-trajectory delta, and finiteness of both trajectories. The
``perturb`` hook chains a corruption into the translated optimizer —
how the tests prove a deliberately broken translation FAILS. Results
land in ``m2kt-numerics-report.{json,md}``.

Source-tree analysis stays importable without jax; the harness itself
is translate-time tooling (this package is NOT vendored into images).
"""

from __future__ import annotations

import json
import os
import re

DEFAULT_STEPS = 4
# gate envs: M2KT_NUMERICS_<NAME>; the defaults absorb bf16-vs-fp32
# rounding on the tiny configs with ~5x headroom while failing hard on
# a wrong optimizer mapping, a double-applied loss scale, or corrupted
# updates
DEFAULT_GATES = {
    "logit_max_rel": 0.05,      # initial logits, row-span normalized
    "grad_norm_max_rel": 0.15,  # first-step global grad norm delta
    "loss_max_rel": 0.10,       # per-step loss trajectory delta
}

_OPTIMIZERS = ("adamw", "adam", "sgd")


def gates_from_env(overrides: dict | None = None) -> dict:
    out = dict(DEFAULT_GATES)
    for key in out:
        raw = os.environ.get(f"M2KT_NUMERICS_{key.upper()}", "")
        if raw:
            try:
                out[key] = float(raw)
            except ValueError:
                pass
    out.update(overrides or {})
    return out


def declared_semantics(src_dir: str) -> dict:
    """What the source tree says it trains with: model family (from
    ``gpu_detect``'s framework/module votes), optimizer name (regexed
    out of the entrypoint — ``torch.optim.AdamW`` and
    ``optim.SGD(...)`` style call sites), and learning rate
    (``lr_hint``). Falls back to AdamW @ 5e-5 — the HF fine-tune
    default — when the tree is silent."""
    from move2kube_tpu.source import gpu_detect

    sem = {"family": "llama", "optimizer": "adamw", "lr": 5e-5,
           "entrypoint": "", "evidence": []}
    report = gpu_detect.analyze_directory(src_dir)
    if report is None:
        return sem
    if report.model_family:
        sem["family"] = report.model_family
    if report.lr_hint:
        sem["lr"] = float(report.lr_hint)
    sem["entrypoint"] = report.entrypoint
    if report.entrypoint:
        try:
            with open(os.path.join(src_dir, report.entrypoint),
                      encoding="utf-8") as fh:
                src = fh.read()
            hits = re.findall(
                r"optim(?:izers)?\.(\w+)\s*\(|torch\.optim\.(\w+)\s*\(",
                src)
            for a, b in hits:
                name = (a or b).lower()
                if name in _OPTIMIZERS:
                    sem["optimizer"] = name
                    sem["evidence"].append(
                        f"{report.entrypoint}: optimizer {a or b}")
                    break
        except OSError:
            pass
    return sem


def _build_optimizer(name: str, lr: float):
    import optax

    if name == "sgd":
        # torch.optim.SGD's default momentum is 0; the samples pass 0.9
        # explicitly, but the trajectory gate tolerates either — the
        # SAME transform drives both sides, so the choice cancels out
        return optax.sgd(lr, momentum=0.9)
    if name == "adam":
        return optax.adam(lr)
    return optax.adamw(lr)


def _tiny_model(family: str):
    """(model, vocab, proxy) for a source family. LM families get their
    own tiny config; everything else (resnet, bert, generic, ...) runs
    the llama proxy — the precision/step/optimizer semantics under test
    are family-independent, and the report labels the proxy honestly."""
    import dataclasses

    import jax.numpy as jnp

    if family in ("gpt2", "gpt"):
        from move2kube_tpu.models.gpt2 import GPT2, gpt2_tiny

        cfg = gpt2_tiny()
        return (lambda dtype: GPT2(dataclasses.replace(cfg, dtype=dtype)),
                cfg.vocab_size, False)
    from move2kube_tpu.models.llama import Llama, llama_tiny

    cfg = llama_tiny()
    return (lambda dtype: Llama(dataclasses.replace(cfg, dtype=dtype)),
            cfg.vocab_size, family not in ("llama",))


def _perturbing(perturb):
    """Identity-state optax transform applying ``perturb`` to the final
    updates — chained LAST so the corruption lands on what the optimizer
    actually applies (an Adam-class transform would normalize away a
    mere gradient scaling)."""
    import optax

    def init(params):
        del params
        return optax.EmptyState()

    def update(updates, state, params=None):
        del params
        return perturb(updates), state

    return optax.GradientTransformation(init, update)


def validate_translation(src_dir: str | None = None,
                         family: str | None = None,
                         steps: int = DEFAULT_STEPS,
                         batch: int = 2, seq: int = 16, seed: int = 0,
                         gates: dict | None = None,
                         perturb=None,
                         out_dir: str | None = None) -> dict:
    """Run the numerics diff and return the report dict (``verdict``:
    ``"pass"``/``"fail"``, per-check entries, both loss trajectories).
    ``src_dir`` supplies the declared semantics; ``family`` overrides
    the detected one; ``perturb`` corrupts the translated side's
    updates (tests prove the gate has teeth with it); ``out_dir`` also
    writes ``m2kt-numerics-report.{json,md}``."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from move2kube_tpu.models import precision as precisionlib
    from move2kube_tpu.models import train as m2kt_train
    from move2kube_tpu.parallel.mesh import MeshConfig, make_mesh
    from move2kube_tpu.serving.quant import logit_gate

    sem = declared_semantics(src_dir) if src_dir else {
        "family": "llama", "optimizer": "adamw", "lr": 5e-5,
        "entrypoint": "", "evidence": []}
    fam = family or sem["family"]
    gate = gates_from_env(gates)
    make_model, vocab, proxy = _tiny_model(fam)

    gen = np.random.default_rng(seed)
    batches = [jnp.asarray(gen.integers(0, vocab, (batch, seq)), jnp.int32)
               for _ in range(steps)]
    ids0 = batches[0]

    # --- translated side: the emitted-trainer machinery, tiny-sized ---
    policy = precisionlib.from_env(default="bf16")
    model_t = make_model(policy.jnp_compute_dtype)
    base_tx = _build_optimizer(sem["optimizer"], sem["lr"])
    if perturb is not None:
        base_tx = optax.chain(base_tx, _perturbing(perturb))
    mesh = make_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
    state = m2kt_train.create_sharded_state(
        jax.random.PRNGKey(seed), model_t, {"input_ids": ids0},
        m2kt_train.instrument_optimizer(policy.wrap_optimizer(base_tx)),
        mesh)
    # both sides start from THESE fp32 master weights (copied before the
    # donated translated step consumes its buffers)
    params0 = jax.tree_util.tree_map(jnp.copy, state.params)
    step_t = m2kt_train.make_lm_train_step(mesh, remat=False,
                                           precision=policy)

    # --- reference side: declared source semantics, fp32 throughout ---
    model_r = make_model(jnp.float32)
    tx_r = _build_optimizer(sem["optimizer"], sem["lr"])
    opt_r = tx_r.init(params0)

    @jax.jit
    def step_r(params, opt_state, ids):
        def loss_fn(p):
            logits = model_r.apply({"params": p}, ids)
            return m2kt_train.lm_loss(logits, ids)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx_r.update(grads, opt_state, params)
        return (optax.apply_updates(params, updates), opt_state, loss,
                optax.global_norm(grads))

    # initial logits: same params, translated (compute-dtype) vs fp32
    logits_t = model_t.apply({"params": policy.cast_params(params0)}, ids0)
    logits_r = model_r.apply({"params": params0}, ids0)
    logit_stats = logit_gate(np.asarray(logits_r, np.float32),
                             np.asarray(logits_t, np.float32))

    loss_t, loss_r, gnorm_t, gnorm_r = [], [], None, None
    for i, ids in enumerate(batches):
        state, lt = step_t(state, {"input_ids": ids})
        loss_t.append(float(jax.block_until_ready(lt)))
        if i == 0:
            gnorm_t = m2kt_train.grad_norm_from_state(state)
        params0, opt_r, lr_, gn = step_r(params0, opt_r, ids)
        loss_r.append(float(jax.block_until_ready(lr_)))
        if i == 0:
            gnorm_r = float(gn)

    eps = 1e-9
    grad_rel = (abs(gnorm_t - gnorm_r) / max(abs(gnorm_r), eps)
                if gnorm_t is not None else 0.0)
    loss_rel = max(abs(a - b) / max(abs(b), eps)
                   for a, b in zip(loss_t, loss_r))
    finite = all(np.isfinite(loss_t)) and all(np.isfinite(loss_r))
    checks = [
        {"name": "logit_max_rel", "value": logit_stats["max_rel_err"],
         "limit": gate["logit_max_rel"],
         "ok": logit_stats["max_rel_err"] <= gate["logit_max_rel"]},
        {"name": "grad_norm_max_rel", "value": grad_rel,
         "limit": gate["grad_norm_max_rel"],
         "ok": grad_rel <= gate["grad_norm_max_rel"]},
        {"name": "loss_max_rel", "value": loss_rel,
         "limit": gate["loss_max_rel"],
         "ok": loss_rel <= gate["loss_max_rel"]},
        {"name": "trajectories_finite", "value": float(finite),
         "limit": 1.0, "ok": finite},
    ]
    report = {
        "verdict": "pass" if all(c["ok"] for c in checks) else "fail",
        "family": fam,
        "proxy_model": proxy,
        "precision_policy": policy.name,
        "source": {"dir": src_dir or "", "entrypoint": sem["entrypoint"],
                   "optimizer": sem["optimizer"], "lr": sem["lr"],
                   "evidence": sem["evidence"]},
        "steps": steps,
        "checks": checks,
        "logit_gate": logit_stats,
        "loss_translated": loss_t,
        "loss_reference": loss_r,
        "grad_norm": {"translated": gnorm_t, "reference": gnorm_r},
    }
    if out_dir:
        write_report(report, out_dir)
    return report


def write_report(report: dict, out_dir: str) -> tuple[str, str]:
    """``m2kt-numerics-report.json`` (machine) + ``.md`` (review) —
    same artifact pairing as the plan report."""
    os.makedirs(out_dir, exist_ok=True)
    jpath = os.path.join(out_dir, "m2kt-numerics-report.json")
    with open(jpath, "w", encoding="utf-8") as fh:
        json.dump(report, fh, indent=2, sort_keys=True)
        fh.write("\n")
    lines = [
        "# Numerics validation report",
        "",
        f"**Verdict: {report['verdict'].upper()}**",
        "",
        f"- family: `{report['family']}`"
        + (" (proxy model)" if report.get("proxy_model") else ""),
        f"- precision policy: `{report['precision_policy']}`",
        f"- source optimizer: `{report['source']['optimizer']}` @ "
        f"lr={report['source']['lr']}",
        f"- steps compared: {report['steps']}",
        "",
        "| check | value | limit | ok |",
        "|---|---|---|---|",
    ]
    for c in report["checks"]:
        lines.append(f"| {c['name']} | {c['value']:.6g} | "
                     f"{c['limit']:.6g} | {'yes' if c['ok'] else 'NO'} |")
    lines += [
        "",
        f"- loss (translated): "
        f"{[round(x, 4) for x in report['loss_translated']]}",
        f"- loss (reference):  "
        f"{[round(x, 4) for x in report['loss_reference']]}",
        "",
    ]
    mpath = os.path.join(out_dir, "m2kt-numerics-report.md")
    with open(mpath, "w", encoding="utf-8") as fh:
        fh.write("\n".join(lines))
    return jpath, mpath


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        description="diff a translated sample against its declared "
                    "source semantics on identical synthetic batches")
    parser.add_argument("src_dir", help="source tree (e.g. a samples/ dir)")
    parser.add_argument("--out", default=".",
                        help="where m2kt-numerics-report.{json,md} land")
    parser.add_argument("--steps", type=int, default=DEFAULT_STEPS)
    args = parser.parse_args(argv)
    report = validate_translation(src_dir=args.src_dir, steps=args.steps,
                                  out_dir=args.out)
    print(f"[m2kt-numerics] {report['verdict']}: " + ", ".join(
        f"{c['name']}={c['value']:.4g}/{c['limit']:.4g}"
        for c in report["checks"]))
    return 0 if report["verdict"] == "pass" else 1


if __name__ == "__main__":
    raise SystemExit(main())
