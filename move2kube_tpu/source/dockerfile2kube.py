"""Dockerfile translator: source trees that already carry Dockerfiles.

Parity: ``internal/source/dockerfile2kube.go`` — finds files parseable as
Dockerfiles (must contain a FROM instruction; isDockerFile :117-144),
buckets multiple Dockerfiles into services by path (bucketDFs :214-280) and
routes each to the ReuseDockerfile containerizer.
"""

from __future__ import annotations

import os
import re

from move2kube_tpu import containerizer
from move2kube_tpu.source.base import Translator
from move2kube_tpu.source.ignores import IgnoreRules
from move2kube_tpu.types import ir as irtypes
from move2kube_tpu.types.plan import (
    ContainerBuildType,
    Plan,
    PlanService,
    SourceType,
    TranslationType,
)
from move2kube_tpu.utils import common
from move2kube_tpu.utils.log import get_logger

log = get_logger("source.dockerfile")

_INSTRUCTION = re.compile(
    r"^\s*(FROM|RUN|CMD|LABEL|MAINTAINER|EXPOSE|ENV|ADD|COPY|ENTRYPOINT"
    r"|VOLUME|USER|WORKDIR|ARG|ONBUILD|STOPSIGNAL|HEALTHCHECK|SHELL)\b",
    re.IGNORECASE,
)


def is_dockerfile(path: str) -> bool:
    """A file is a Dockerfile if it parses as instructions incl. FROM
    (dockerfile2kube.go:117-144)."""
    try:
        with open(path, encoding="utf-8", errors="ignore") as f:
            text = f.read(65536)
    except OSError:
        return False
    has_from = False
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if (not _INSTRUCTION.match(line) and not raw.startswith((" ", "\t"))
                and not raw.rstrip().endswith("\\")):
            # allow continuation lines; anything else disqualifies
            if not has_from:
                return False
        if line.upper().startswith("FROM"):
            has_from = True
    return has_from


def find_dockerfiles(root: str) -> list[str]:
    ignores = IgnoreRules(root)
    out = []
    candidates = common.get_files_by_name(root, ["Dockerfile", "Containerfile"])
    candidates += [
        p for p in common.get_files_by_ext(root, [".dockerfile"]) if p not in candidates
    ]
    for p in candidates:
        if not ignores.is_ignored(p) and is_dockerfile(p):
            out.append(p)
    return out


def bucket_dockerfiles(dockerfiles: list[str], root: str) -> dict[str, str]:
    """service name -> dockerfile path, named by containing dir
    (bucketDFs dockerfile2kube.go:214-280)."""
    buckets: dict[str, str] = {}
    for df in dockerfiles:
        d = os.path.dirname(df)
        rel = common.relpath_under(d, root)
        if rel in (None, "."):
            name = common.make_dns_label(os.path.basename(root.rstrip(os.sep)) or "app")
        else:
            name = common.make_dns_label(rel.replace(os.sep, "-"))
        name = common.unique_name(name, buckets.keys())
        buckets[name] = df
    return buckets


class DockerfileTranslator(Translator):
    def get_translation_type(self) -> str:
        return TranslationType.DOCKERFILE2KUBE

    def get_service_options(self, plan: Plan) -> list[PlanService]:
        dockerfiles = find_dockerfiles(plan.root_dir)
        services = []
        for name, df in bucket_dockerfiles(dockerfiles, plan.root_dir).items():
            svc = PlanService(
                service_name=name,
                translation_type=TranslationType.DOCKERFILE2KUBE,
                container_build_type=ContainerBuildType.REUSE_DOCKERFILE,
                source_types=[SourceType.DOCKERFILE],
                containerization_target_options=[df],
            )
            svc.add_source_artifact(PlanService.DOCKERFILE_ARTIFACT, df)
            svc.add_source_artifact(PlanService.SOURCE_DIR_ARTIFACT, os.path.dirname(df))
            services.append(svc)
        return services

    def translate(self, services: list[PlanService], plan: Plan) -> irtypes.IR:
        ir = irtypes.IR(name=plan.name)
        for plan_svc in services:
            try:
                container = containerizer.get_container(plan, plan_svc)
            except Exception as e:  # noqa: BLE001
                log.warning("dockerfile containerization failed for %s: %s",
                            plan_svc.service_name, e)
                continue
            # ports from the user's Dockerfile EXPOSE lines
            dockerfiles = plan_svc.source_artifacts.get(PlanService.DOCKERFILE_ARTIFACT, [])
            for df in dockerfiles:
                for port in _exposed_ports(df):
                    container.add_exposed_port(port)
            ir.add_container(container)
            svc = irtypes.service_from_plan(plan_svc)
            image = container.image_names[0] if container.image_names else svc.name + ":latest"
            k8s_container: dict = {"name": svc.name, "image": image}
            if container.exposed_ports:
                k8s_container["ports"] = [{"containerPort": p} for p in container.exposed_ports]
                for p in container.exposed_ports:
                    svc.add_port_forwarding(p, p)
            svc.containers.append(k8s_container)
            ir.add_service(svc)
        return ir


def _exposed_ports(dockerfile: str) -> list[int]:
    ports = []
    try:
        for line in open(dockerfile, encoding="utf-8", errors="ignore"):
            m = re.match(r"\s*EXPOSE\s+(.+)", line, re.IGNORECASE)
            if m:
                for tok in m.group(1).split():
                    tok = tok.split("/")[0]
                    if tok.isdigit():
                        ports.append(int(tok))
    except OSError:
        pass
    return ports
