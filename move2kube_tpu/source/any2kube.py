"""Any2Kube: the fallback directory-walker translator.

Parity: ``internal/source/any2kube.go:43-141`` — walks every directory not
claimed by other services (honoring ignore files), asks the containerizer
registry for options, and emits one plan service per (dir x build type).
At translate time it asks the chosen containerizer for the Container and
builds the IR service with its exposed ports.
"""

from __future__ import annotations

import os

from move2kube_tpu import containerizer
from move2kube_tpu.source.base import Translator
from move2kube_tpu.source.ignores import IgnoreRules
from move2kube_tpu.types import ir as irtypes
from move2kube_tpu.types.plan import (
    Plan,
    PlanService,
    SourceType,
    TranslationType,
)
from move2kube_tpu.utils import common
from move2kube_tpu.utils.log import get_logger

log = get_logger("source.any2kube")

_SKIP_DIR_NAMES = {".git", "node_modules", "__pycache__", ".venv", "venv", "vendor"}


def claimed_directories(plan: Plan) -> list[str]:
    """Directories already owned by existing plan services (any2kube.go:58)."""
    dirs = []
    for svcs in plan.services.values():
        for svc in svcs:
            for paths in svc.source_artifacts.values():
                for p in paths:
                    if os.path.isdir(p):
                        dirs.append(os.path.abspath(p))
                    elif os.path.isfile(p):
                        dirs.append(os.path.dirname(os.path.abspath(p)))
    return dirs


class Any2KubeTranslator(Translator):
    def get_translation_type(self) -> str:
        return TranslationType.ANY2KUBE

    def get_service_options(self, plan: Plan) -> list[PlanService]:
        root = plan.root_dir
        ignores = IgnoreRules(root)
        claimed = claimed_directories(plan)
        services: list[PlanService] = []
        taken_names = set(plan.services.keys())

        for dirpath, dirnames, _filenames in os.walk(root):
            dirnames[:] = sorted(
                d for d in dirnames
                if d not in _SKIP_DIR_NAMES and not ignores.is_ignored(os.path.join(dirpath, d))
            )
            absdir = os.path.abspath(dirpath)
            if any(common.is_parent(absdir, c) or common.is_parent(c, absdir) for c in claimed):
                continue
            options = containerizer.get_containerization_options(plan, absdir)
            if not options:
                continue
            base = common.make_dns_label(
                os.path.basename(absdir.rstrip(os.sep)) or plan.name
            )
            name = common.unique_name(base, taken_names)
            taken_names.add(name)
            for build_type, target_options in options.items():
                svc = PlanService(
                    service_name=name,
                    translation_type=TranslationType.ANY2KUBE,
                    container_build_type=build_type,
                    source_types=[SourceType.DIRECTORY],
                    containerization_target_options=list(target_options),
                )
                svc.add_source_artifact(PlanService.SOURCE_DIR_ARTIFACT, absdir)
                svc.service_rel_path = "/" + name
                services.append(svc)
            # a containerizable dir claims its subtree (any2kube.go:98)
            claimed.append(absdir)
            dirnames[:] = []
        return services

    def translate(self, services: list[PlanService], plan: Plan) -> irtypes.IR:
        ir = irtypes.IR(name=plan.name)
        for plan_svc in services:
            try:
                container = containerizer.get_container(plan, plan_svc)
            except Exception as e:  # noqa: BLE001 - plugin tolerance
                log.warning("containerization failed for %s: %s", plan_svc.service_name, e)
                continue
            ir.add_container(container)
            svc = irtypes.service_from_plan(plan_svc)
            image = container.image_names[0] if container.image_names else svc.name + ":latest"
            k8s_container: dict = {"name": svc.name, "image": image}
            if container.exposed_ports:
                k8s_container["ports"] = [
                    {"containerPort": p} for p in container.exposed_ports
                ]
                for p in container.exposed_ports:
                    svc.add_port_forwarding(p, p)
            svc.containers.append(k8s_container)
            if container.accelerator is not None:
                svc.accelerator = container.accelerator
            ir.add_service(svc)
        return ir
