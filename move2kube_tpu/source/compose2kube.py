"""docker-compose translator.

Parity: ``internal/source/compose2kube.go`` + ``internal/source/compose/``
(v1/v2 loader v1v2.go, v3 loader v3.go, utils.go): find compose files by
extension + ``services:`` key, offer Reuse vs ReuseDockerfile per service
(build section present -> both), and convert full service semantics to IR:
image/entrypoint/args/env (with interpolation honoring IGNORE_ENVIRONMENT),
port syntaxes, expose, privileged/user/caps -> SecurityContext,
stop_grace_period, mem_limit, restart policy, deploy.replicas, healthcheck
-> readiness probe, networks -> NetworkPolicy annotations, tmpfs ->
emptyDir, named volumes -> PVC + Storage, bind mounts -> hostPath,
secrets/configs -> Storage.

Net-new: GPU services (``runtime: nvidia``, ``deploy.resources.
reservations.devices`` with gpu capability, count) get AcceleratorInfo so
the TPU emitters turn them into pod-slice workloads (BASELINE config 4).
"""

from __future__ import annotations

import os
import re

from move2kube_tpu.source import gpu_detect
from move2kube_tpu.source.base import Translator
from move2kube_tpu.types import ir as irtypes
from move2kube_tpu.types.plan import (
    ContainerBuildType,
    Plan,
    PlanService,
    SourceType,
    TranslationType,
)
from move2kube_tpu.utils import common
from move2kube_tpu.utils.log import get_logger

log = get_logger("source.compose")

COMPOSE_NETWORK_ANNOTATION = "move2kube-tpu.io/networks"


def _normalize_compose_doc(doc: dict) -> dict | None:
    """Return a doc with a ``services`` mapping, handling the v1 format
    where service names are top-level keys (parity: libcompose ParseV2
    accepts v1; v1v2.go:93). None if the doc isn't compose-shaped."""
    if not isinstance(doc, dict):
        return None
    if isinstance(doc.get("services"), dict):
        return doc
    if "services" in doc or "version" in doc:
        return None
    # v1: every top-level value is a service dict with image/build/etc.
    vals = [v for v in doc.values() if v is not None]
    if vals and all(
        isinstance(v, dict) and ({"image", "build", "ports", "command",
                                  "environment", "volumes", "links"} & v.keys())
        for v in vals
    ):
        return {"services": doc}
    return None


def find_compose_files(root: str) -> list[str]:
    """Compose files = yaml with a services mapping, or the v1 bare-service
    format in a compose-named file (compose2kube.go:122-150)."""
    out = []
    for path in common.get_files_by_ext(root, [".yaml", ".yml"]):
        base = os.path.basename(path).lower()
        looks_like = "compose" in base or base in ("docker-compose.yaml", "docker-compose.yml")
        try:
            doc = common.read_yaml(path)
        except Exception:  # noqa: BLE001
            continue
        norm = _normalize_compose_doc(doc)
        if norm is None:
            continue
        is_v1 = norm is not doc
        if looks_like or (not is_v1 and "version" in doc):
            out.append(path)
    return out


def _interpolate(value: str, env_map: dict[str, str]) -> str:
    """${VAR}, ${VAR:-default}, $VAR interpolation (v3.go via docker/cli;
    environment honored only when IGNORE_ENVIRONMENT is False)."""

    def repl(m: re.Match) -> str:
        if m.group(0) == "$$":  # compose-spec escape for a literal $
            return "$"
        var = m.group("braced") or m.group("plain")
        default = m.group("default") or ""
        if var in env_map:
            return env_map[var]
        if not common.IGNORE_ENVIRONMENT and var in os.environ:
            return os.environ[var]
        return default

    return re.sub(
        r"\$(?:\$|\{(?P<braced>\w+)(?::?-(?P<default>[^}]*))?\}|(?P<plain>\w+))",
        repl,
        value,
    )


def _load_env_file(path: str) -> dict[str, str]:
    env = {}
    try:
        for line in open(path, encoding="utf-8"):
            line = line.strip()
            if not line or line.startswith("#") or "=" not in line:
                continue
            k, v = line.split("=", 1)
            env[k.strip()] = v.strip().strip("'\"")
    except OSError:
        pass
    return env


def parse_ports(raw_ports: list, expose: list) -> list[tuple[int, int]]:
    """-> [(published, target)] covering short/long syntax
    (v1v2.go getPorts:350, parseContainerPort:406)."""
    out: list[tuple[int, int]] = []

    def add(published: int, target: int) -> None:
        if all(p[0] != published for p in out):
            out.append((published, target))

    for p in raw_ports or []:
        if isinstance(p, dict):  # long syntax
            target = int(p.get("target", 0) or 0)
            published = int(p.get("published", target) or target)
            if target:
                add(published, target)
            continue
        s = str(p)
        s = s.split("/")[0]  # strip protocol
        parts = s.split(":")
        try:
            if len(parts) == 1:
                port = int(parts[0])
                add(port, port)
            elif len(parts) == 2:
                add(int(parts[0]), int(parts[1]))
            else:  # ip:published:target
                add(int(parts[-2]), int(parts[-1]))
        except ValueError:
            log.warning("unparseable port %r", p)
    for e in expose or []:
        try:
            port = int(str(e).split("/")[0])
            add(port, port)
        except ValueError:
            continue
    return out


def _parse_env(svc_def: dict, compose_dir: str) -> dict[str, str]:
    env: dict[str, str] = {}
    env_files = svc_def.get("env_file", [])
    if isinstance(env_files, str):
        env_files = [env_files]
    for ef in env_files:
        env.update(_load_env_file(os.path.join(compose_dir, ef)))
    raw = svc_def.get("environment", {})
    if isinstance(raw, list):
        for item in raw:
            if "=" in str(item):
                k, v = str(item).split("=", 1)
                env[k] = v
            elif not common.IGNORE_ENVIRONMENT and str(item) in os.environ:
                env[str(item)] = os.environ[str(item)]
    elif isinstance(raw, dict):
        for k, v in raw.items():
            env[str(k)] = "" if v is None else str(v)
    return env


def _parse_memory(val) -> str | None:
    """compose mem_limit ('512m', '2g', bytes) -> k8s quantity."""
    if val is None:
        return None
    s = str(val).strip().lower()
    m = re.fullmatch(r"(\d+)([bkmg]?)b?", s)
    if not m:
        return None
    n, unit = int(m.group(1)), m.group(2)
    return {"": str(n), "b": str(n), "k": f"{n}Ki", "m": f"{n}Mi", "g": f"{n}Gi"}[unit]


def _gpu_info_from_service(svc_def: dict) -> int:
    """GPU count requested by a compose service (runtime: nvidia /
    deploy.resources.reservations.devices)."""
    count = 0
    if str(svc_def.get("runtime", "")).lower() == "nvidia":
        count = 1
    deploy = svc_def.get("deploy", {}) or {}
    devices = (((deploy.get("resources") or {}).get("reservations") or {}).get("devices")) or []
    for dev in devices:
        caps = [str(c).lower() for c in (dev.get("capabilities") or [])]
        if "gpu" in caps or "nvidia" in str(dev.get("driver", "")).lower():
            c = dev.get("count", 1)
            count = max(count, 999 if str(c) == "all" else int(c or 1))
    env = svc_def.get("environment") or {}
    if isinstance(env, dict) and "NVIDIA_VISIBLE_DEVICES" in env:
        count = max(count, 1)
    return count


def _healthcheck_to_probe(hc: dict) -> dict | None:
    """compose healthcheck -> readiness probe (v3.go getHealthCheck:574)."""
    if not hc or hc.get("disable"):
        return None
    test = hc.get("test", [])
    if isinstance(test, str):
        command = ["CMD-SHELL", test]
    else:
        command = [str(t) for t in test]
    if not command:
        return None
    if command[0] == "NONE":
        return None
    if command[0] in ("CMD", "CMD-SHELL"):
        exec_cmd = command[1:] if command[0] == "CMD" else ["sh", "-c", *command[1:]]
    else:
        exec_cmd = command
    probe: dict = {"exec": {"command": exec_cmd}}

    def seconds(val) -> int | None:
        if val is None:
            return None
        m = re.fullmatch(r"(\d+(?:\.\d+)?)(ms|s|m|h)?", str(val).strip())
        if not m:
            return None
        n = float(m.group(1))
        mult = {"ms": 0.001, "s": 1, "m": 60, "h": 3600, None: 1}[m.group(2)]
        return max(1, int(n * mult))

    if seconds(hc.get("interval")):
        probe["periodSeconds"] = seconds(hc.get("interval"))
    if seconds(hc.get("timeout")):
        probe["timeoutSeconds"] = seconds(hc.get("timeout"))
    if seconds(hc.get("start_period")):
        probe["initialDelaySeconds"] = seconds(hc.get("start_period"))
    if hc.get("retries"):
        probe["failureThreshold"] = int(hc["retries"])
    return probe


class ComposeTranslator(Translator):
    def get_translation_type(self) -> str:
        return TranslationType.COMPOSE2KUBE

    def get_service_options(self, plan: Plan) -> list[PlanService]:
        services: list[PlanService] = []
        for compose_file in find_compose_files(plan.root_dir):
            try:
                doc = _normalize_compose_doc(common.read_yaml(compose_file))
            except Exception as e:  # noqa: BLE001
                log.warning("cannot parse %s: %s", compose_file, e)
                continue
            if doc is None:
                continue
            for svc_name, svc_def in (doc.get("services") or {}).items():
                if not isinstance(svc_def, dict):
                    continue
                name = common.make_dns_label(svc_name)
                has_build = "build" in svc_def
                build_types = (
                    [ContainerBuildType.REUSE_DOCKERFILE, ContainerBuildType.REUSE]
                    if has_build else [ContainerBuildType.REUSE]
                )
                for bt in build_types:
                    svc = PlanService(
                        service_name=name,
                        image=str(svc_def.get("image", "") or f"{name}:latest"),
                        translation_type=TranslationType.COMPOSE2KUBE,
                        container_build_type=bt,
                        source_types=[SourceType.COMPOSE],
                    )
                    svc.add_source_artifact(PlanService.COMPOSE_ARTIFACT, compose_file)
                    if has_build:
                        build = svc_def["build"]
                        ctx = build if isinstance(build, str) else build.get("context", ".")
                        dockerfile = (
                            "Dockerfile" if isinstance(build, str)
                            else build.get("dockerfile", "Dockerfile")
                        )
                        build_dir = os.path.normpath(
                            os.path.join(os.path.dirname(compose_file), ctx)
                        )
                        svc.add_source_artifact(PlanService.SOURCE_DIR_ARTIFACT, build_dir)
                        if bt == ContainerBuildType.REUSE_DOCKERFILE:
                            svc.add_source_artifact(
                                PlanService.DOCKERFILE_ARTIFACT,
                                os.path.join(build_dir, dockerfile),
                            )
                    services.append(svc)
        return services

    def translate(self, services: list[PlanService], plan: Plan) -> irtypes.IR:
        ir = irtypes.IR(name=plan.name)
        # group chosen services by compose file
        by_file: dict[str, list[PlanService]] = {}
        for svc in services:
            for f in svc.source_artifacts.get(PlanService.COMPOSE_ARTIFACT, []):
                by_file.setdefault(f, []).append(svc)
        for compose_file, plan_svcs in by_file.items():
            try:
                self._convert_file(ir, compose_file, plan_svcs, plan)
            except Exception as e:  # noqa: BLE001
                log.warning("compose translate failed for %s: %s", compose_file, e)
        return ir

    def _convert_file(self, ir: irtypes.IR, compose_file: str,
                      plan_svcs: list[PlanService], plan: Plan) -> None:
        doc = _normalize_compose_doc(common.read_yaml(compose_file)) or {}
        compose_dir = os.path.dirname(compose_file)
        wanted = {s.service_name: s for s in plan_svcs}
        top_volumes = doc.get("volumes") or {}
        for svc_name, svc_def in (doc.get("services") or {}).items():
            name = common.make_dns_label(svc_name)
            if name not in wanted:
                continue
            plan_svc = wanted[name]
            if not isinstance(svc_def, dict):
                continue
            self._convert_service(
                ir, name, svc_def, plan_svc, compose_dir, top_volumes, doc
            )
        # secrets/configs -> Storage (v3.go:432-478)
        for sec_name, sec_def in (doc.get("secrets") or {}).items():
            self._add_file_storage(ir, sec_name, sec_def, compose_dir,
                                   irtypes.StorageKind.SECRET)
        for cfg_name, cfg_def in (doc.get("configs") or {}).items():
            self._add_file_storage(ir, cfg_name, cfg_def, compose_dir,
                                   irtypes.StorageKind.CONFIGMAP)

    def _add_file_storage(self, ir: irtypes.IR, name: str, definition: dict,
                          compose_dir: str, kind: str) -> None:
        name = common.make_dns_label(name)
        storage = irtypes.Storage(name=name, kind=kind)
        if isinstance(definition, dict) and definition.get("file"):
            path = os.path.join(compose_dir, definition["file"])
            try:
                storage.content[os.path.basename(path)] = open(path, "rb").read()
            except OSError as e:
                log.warning("cannot read %s content %s: %s", kind, path, e)
        ir.add_storage(storage)

    def _convert_service(self, ir: irtypes.IR, name: str, svc_def: dict,
                         plan_svc: PlanService, compose_dir: str,
                         top_volumes: dict, doc: dict) -> None:
        svc = irtypes.service_from_plan(plan_svc)
        env_map = _parse_env(svc_def, compose_dir)

        image = _interpolate(
            str(svc_def.get("image", "") or plan_svc.image or f"{name}:latest"),
            env_map)
        container: dict = {"name": name, "image": image}

        # entrypoint/command (compose entrypoint->k8s command, command->args)
        ep = svc_def.get("entrypoint")
        if ep:
            container["command"] = [ep] if isinstance(ep, str) else [str(x) for x in ep]
        cmd = svc_def.get("command")
        if cmd:
            container["args"] = (
                ["sh", "-c", cmd] if isinstance(cmd, str) else [str(x) for x in cmd]
            )
        if env_map:
            container["env"] = [
                {"name": k, "value": _interpolate(v, env_map)} for k, v in env_map.items()
            ]

        ports = parse_ports(svc_def.get("ports"), svc_def.get("expose"))
        if ports:
            container["ports"] = [{"containerPort": t} for _, t in ports]
            for published, target in ports:
                svc.add_port_forwarding(published, target)

        # security context (privileged/user/cap_add/cap_drop/read_only)
        sec: dict = {}
        if svc_def.get("privileged"):
            sec["privileged"] = True
        if svc_def.get("read_only"):
            sec["readOnlyRootFilesystem"] = True
        user = svc_def.get("user")
        if user is not None:
            m = re.match(r"^(\d+)", str(user))
            if m:
                sec["runAsUser"] = int(m.group(1))
        caps: dict = {}
        if svc_def.get("cap_add"):
            caps["add"] = [str(c) for c in svc_def["cap_add"]]
        if svc_def.get("cap_drop"):
            caps["drop"] = [str(c) for c in svc_def["cap_drop"]]
        if caps:
            sec["capabilities"] = caps
        if sec:
            container["securityContext"] = sec
        group_add = svc_def.get("group_add")
        if group_add:
            svc.security_context.setdefault("supplementalGroups", []).extend(
                int(g) for g in group_add if str(g).isdigit()
            )

        # resources
        mem = _parse_memory(svc_def.get("mem_limit")
                            or (svc_def.get("deploy", {}).get("resources", {})
                                .get("limits", {}) or {}).get("memory"))
        if mem:
            container.setdefault("resources", {}).setdefault("limits", {})["memory"] = mem

        # healthcheck -> readiness probe
        probe = _healthcheck_to_probe(svc_def.get("healthcheck") or {})
        if probe:
            container["readinessProbe"] = probe

        # restart policy (v1v2.go: restart / deploy.restart_policy)
        restart = str(svc_def.get("restart", "")
                      or ((svc_def.get("deploy", {}).get("restart_policy", {})
                           or {}).get("condition", "")))
        if restart in ("no", "none"):
            svc.restart_policy = "Never"
        elif restart.startswith("on-failure"):
            svc.restart_policy = "OnFailure"
        elif restart in ("always", "any", "unless-stopped"):
            svc.restart_policy = "Always"

        if svc_def.get("stop_grace_period"):
            m = re.match(r"(\d+)", str(svc_def["stop_grace_period"]))
            if m:
                svc.annotations["move2kube-tpu.io/stop-grace-period"] = m.group(1)

        # replicas
        deploy = svc_def.get("deploy") or {}
        if deploy.get("replicas"):
            svc.replicas = int(deploy["replicas"])

        # networks -> annotation consumed by the NetworkPolicy apiresource
        networks = svc_def.get("networks")
        if isinstance(networks, dict):
            svc.networks = [common.make_dns_label(n) for n in networks]
        elif isinstance(networks, list):
            svc.networks = [common.make_dns_label(str(n)) for n in networks]

        # tmpfs -> emptyDir (utils.go tmpfs fabrication)
        tmpfs = svc_def.get("tmpfs")
        if isinstance(tmpfs, str):
            tmpfs = [tmpfs]
        for i, mount in enumerate(tmpfs or []):
            vol_name = f"{name}-tmpfs-{i}"
            svc.add_volume({"name": vol_name, "emptyDir": {"medium": "Memory"}})
            container.setdefault("volumeMounts", []).append(
                {"name": vol_name, "mountPath": str(mount).split(":")[0]}
            )

        # volumes: named -> PVC, path -> hostPath (v1v2.go:269-320)
        for i, vol in enumerate(svc_def.get("volumes") or []):
            if isinstance(vol, dict):  # long syntax
                vtype = vol.get("type", "volume")
                src, target = vol.get("source", ""), vol.get("target", "")
                read_only = bool(vol.get("read_only"))
            else:
                parts = str(vol).split(":")
                if len(parts) == 1:
                    src, target, read_only = "", parts[0], False
                else:
                    src, target = parts[0], parts[1]
                    read_only = len(parts) > 2 and parts[2] == "ro"
                vtype = "bind" if src.startswith((".", "/", "~")) else "volume"
            if not target:
                continue
            if vtype == "tmpfs":
                vol_name = f"{name}-tmpfs-l{i}"
                svc.add_volume({"name": vol_name, "emptyDir": {"medium": "Memory"}})
            elif vtype == "bind" or (src and src.startswith((".", "/", "~"))):
                vol_name = common.make_dns_label(f"{name}-hostpath-{i}")
                host_path = (os.path.normpath(os.path.join(compose_dir, src))
                             if src.startswith(".") else src)
                svc.add_volume({"name": vol_name, "hostPath": {"path": host_path}})
            else:
                vol_name = common.make_dns_label(src or f"{name}-vol-{i}")
                svc.add_volume({
                    "name": vol_name,
                    "persistentVolumeClaim": {"claimName": vol_name},
                })
                pvc = irtypes.Storage(
                    name=vol_name, kind=irtypes.StorageKind.PVC,
                    pvc_spec={
                        "accessModes": ["ReadWriteOnce"],
                        "resources": {"requests": {"storage": common.DEFAULT_PVC_SIZE}},
                    },
                )
                ir.add_storage(pvc)
            mount: dict = {"name": vol_name, "mountPath": target}
            if read_only:
                mount["readOnly"] = True
            container.setdefault("volumeMounts", []).append(mount)

        # secrets/configs mounts
        for sec in svc_def.get("secrets") or []:
            sec_name = common.make_dns_label(sec if isinstance(sec, str) else sec.get("source", ""))
            vol_name = f"secret-{sec_name}"
            svc.add_volume({"name": vol_name, "secret": {"secretName": sec_name}})
            container.setdefault("volumeMounts", []).append(
                {"name": vol_name, "mountPath": f"/run/secrets/{sec_name}", "readOnly": True}
            )
        for cfg in svc_def.get("configs") or []:
            cfg_name = common.make_dns_label(cfg if isinstance(cfg, str) else cfg.get("source", ""))
            vol_name = f"config-{cfg_name}"
            svc.add_volume({"name": vol_name, "configMap": {"name": cfg_name}})
            target = cfg.get("target", f"/{cfg_name}") if isinstance(cfg, dict) else f"/{cfg_name}"
            container.setdefault("volumeMounts", []).append(
                {"name": vol_name, "mountPath": target}
            )

        # net-new: GPU service -> TPU accelerator info (BASELINE config 4)
        gpu_count = _gpu_info_from_service(svc_def)
        if gpu_count:
            acc_type, topology, hosts, num_slices = (
                gpu_detect.map_gpu_to_tpu_multislice(gpu_count))
            from move2kube_tpu.types.plan import AcceleratorInfo

            svc.accelerator = AcceleratorInfo(
                gpu_count=gpu_count,
                gpu_vendor="nvidia.com/gpu",
                distributed_backend="nccl" if gpu_count > 1 else "",
                tpu_accelerator=acc_type,
                tpu_topology=topology,
                num_hosts=hosts,
                num_slices=num_slices,
            )
            # GPU compose services become TPU pod-slice workloads (JobSet)
            svc.job = True
            svc.restart_policy = "Never"

        svc.containers.append(container)
        ir.add_service(svc)

        # the image itself: reuse or rebuild
        if plan_svc.container_build_type == ContainerBuildType.REUSE:
            ir.add_container(irtypes.Container(
                image_names=[image], new=False, build_type=ContainerBuildType.REUSE,
            ))
        else:
            from move2kube_tpu import containerizer as czr

            try:
                ir.add_container(czr.get_container(plan, plan_svc))
            except Exception as e:  # noqa: BLE001
                log.warning("compose build for %s failed: %s", name, e)
