"""Gpu2Tpu translator: claim GPU training directories for the TPU target.

Net-new vs the reference (the north star, BASELINE.json): walks the source
tree like Any2Kube but only claims directories whose Python sources are GPU
training workloads (CUDA / NCCL / DeepSpeed — see ``gpu_detect``). Each
claimed dir becomes a plan service with ``JaxXla`` build type and
AcceleratorInfo recording detected GPU topology and the chosen TPU slice.

At translate time the jax-xla containerizer rewrites the entrypoint into a
JAX program from the model zoo and the IR service is marked as a
run-to-completion Job with TPU resources — the TPU apiresources emit a
JobSet instead of a Deployment for it.
"""

from __future__ import annotations

import os

from move2kube_tpu import containerizer
from move2kube_tpu.source import gpu_detect
from move2kube_tpu.source.base import Translator
from move2kube_tpu.source.ignores import IgnoreRules
from move2kube_tpu.types import ir as irtypes
from move2kube_tpu.types.plan import (
    ContainerBuildType,
    Plan,
    PlanService,
    SourceType,
    TranslationType,
)
from move2kube_tpu.utils import common
from move2kube_tpu.utils.log import get_logger

log = get_logger("source.gpu2tpu")

_SKIP_DIR_NAMES = {".git", "node_modules", "__pycache__", ".venv", "venv", "vendor"}

_COMPOSE_NAMES = ("docker-compose.yaml", "docker-compose.yml",
                  "compose.yaml", "compose.yml")


def source_restart_policy(src_dir: str) -> str:
    """K8s restart policy declared by a compose file in the claimed GPU
    training directory, "" when none is declared.

    The workload author's operational intent survives translation: a
    trainer they ran with ``restart: on-failure`` keeps kubelet-level
    in-place restarts (the cheapest recovery — no pod reschedule, warm
    page cache); ``restart: "no"`` stays Never. ``always`` class policies
    map to OnFailure — a run-to-completion Job has no Always. When the
    compose file has several services, the one with a GPU reservation
    wins; else a single restart-declaring service wins; else ambiguous
    declarations are ignored (logged)."""
    import yaml

    path = next((os.path.join(src_dir, n) for n in _COMPOSE_NAMES
                 if os.path.isfile(os.path.join(src_dir, n))), None)
    if path is None:
        return ""
    try:
        with open(path, encoding="utf-8") as f:
            doc = yaml.safe_load(f) or {}
    except (OSError, yaml.YAMLError) as e:
        log.warning("unreadable compose file %s: %s", path, e)
        return ""
    services = doc.get("services") or {}
    if not isinstance(services, dict):
        return ""

    def _restart_of(svc_def: dict) -> str:
        deploy = svc_def.get("deploy") or {}
        raw = str(svc_def.get("restart", "")
                  or (deploy.get("restart_policy") or {}).get("condition", ""))
        if raw in ("no", "none"):
            return "Never"
        if raw.startswith("on-failure"):
            return "OnFailure"
        if raw in ("always", "any", "unless-stopped"):
            log.info("compose restart %r maps to OnFailure for the "
                     "run-to-completion training Job", raw)
            return "OnFailure"
        return ""

    def _has_gpu(svc_def: dict) -> bool:
        if svc_def.get("runtime") == "nvidia":
            return True
        devices = ((svc_def.get("deploy") or {}).get("resources", {})
                   .get("reservations", {}).get("devices", []))
        return any("gpu" in (d.get("capabilities") or [])
                   for d in devices if isinstance(d, dict))

    declared = {n: _restart_of(s) for n, s in services.items()
                if isinstance(s, dict) and _restart_of(s)}
    if not declared:
        return ""
    gpu_declared = [p for n, p in declared.items()
                    if isinstance(services.get(n), dict)
                    and _has_gpu(services[n])]
    if gpu_declared:
        return gpu_declared[0]
    if len(declared) == 1:
        return next(iter(declared.values()))
    log.info("compose file %s declares %d differing restart policies and "
             "no GPU service; ignoring", path, len(declared))
    return ""


class Gpu2TpuTranslator(Translator):
    def get_translation_type(self) -> str:
        return TranslationType.GPU2TPU

    def get_service_options(self, plan: Plan) -> list[PlanService]:
        from move2kube_tpu.source.any2kube import claimed_directories

        root = plan.root_dir
        ignores = IgnoreRules(root)
        claimed = claimed_directories(plan)
        services: list[PlanService] = []
        taken_names = set(plan.services.keys())

        for dirpath, dirnames, _filenames in os.walk(root):
            dirnames[:] = sorted(
                d for d in dirnames
                if d not in _SKIP_DIR_NAMES and not ignores.is_ignored(os.path.join(dirpath, d))
            )
            absdir = os.path.abspath(dirpath)
            if any(common.is_parent(absdir, c) or common.is_parent(c, absdir) for c in claimed):
                continue
            report = gpu_detect.analyze_directory(absdir)
            if report is None:
                continue
            scripts = report.training_scripts or report.serving_scripts
            # claim the smallest directory containing the workload code: if
            # everything lives under one child, keep walking into it instead
            script_home = common.find_common_directory(scripts)
            if script_home and os.path.abspath(script_home) != absdir:
                if os.path.isfile(script_home):
                    script_home = os.path.dirname(script_home)
                if os.path.abspath(script_home) != absdir:
                    continue
            # scripts spread over several children: when each child is an
            # independently valid GPU workload, descend so sibling
            # trainings become separate services instead of one merged one
            if not any(os.path.dirname(os.path.abspath(s)) == absdir
                       for s in scripts):
                kids = {
                    os.path.join(absdir, os.path.relpath(
                        os.path.abspath(s), absdir).split(os.sep)[0])
                    for s in scripts
                }
                if len(kids) > 1 and all(
                    gpu_detect.analyze_directory(k) is not None for k in kids
                ):
                    continue
            base = common.make_dns_label(
                os.path.basename(absdir.rstrip(os.sep)) or plan.name
            )
            name = common.unique_name(base, taken_names)
            taken_names.add(name)
            acc = gpu_detect.report_to_accelerator(report)
            svc = PlanService(
                service_name=name,
                translation_type=TranslationType.GPU2TPU,
                container_build_type=ContainerBuildType.JAX_XLA,
                source_types=[SourceType.GPU_TRAINING],
                containerization_target_options=[report.model_family or "generic"],
                accelerator=acc,
            )
            svc.add_source_artifact(PlanService.SOURCE_DIR_ARTIFACT, absdir)
            if report.entrypoint:
                svc.add_source_artifact(
                    PlanService.GPU_ENTRYPOINT_ARTIFACT, report.entrypoint
                )
            for ev in report.evidence[:5]:
                log.info("gpu2tpu %s: %s", name, ev)
            services.append(svc)
            claimed.append(absdir)
            dirnames[:] = []
        return services

    def translate(self, services: list[PlanService], plan: Plan) -> irtypes.IR:
        ir = irtypes.IR(name=plan.name)
        for plan_svc in services:
            try:
                container = containerizer.get_container(plan, plan_svc)
            except Exception as e:  # noqa: BLE001
                log.warning("jax-xla containerization failed for %s: %s",
                            plan_svc.service_name, e)
                continue
            if container.accelerator is None:
                container.accelerator = plan_svc.accelerator
            ir.add_container(container)
            svc = irtypes.service_from_plan(plan_svc)
            acc = plan_svc.accelerator
            serving = bool(acc is not None and acc.serving)
            svc.accelerator = acc
            image = container.image_names[0] if container.image_names else svc.name + ":latest"
            container_def = {"name": svc.name, "image": image}
            if serving:
                # inference server: long-running Knative Service, not a
                # run-to-completion Job
                svc.job = False
                svc.restart_policy = "Always"
                port = acc.serving_port or 8080
                svc.add_port_forwarding(80, port)
                container_def["ports"] = [{"containerPort": port}]
            else:
                svc.job = True  # run-to-completion training workload
                # a compose file next to the training code states the
                # author's restart intent; default Never when undeclared
                src_dirs = plan_svc.source_artifacts.get(
                    PlanService.SOURCE_DIR_ARTIFACT, [])
                declared = source_restart_policy(src_dirs[0]) if src_dirs else ""
                svc.restart_policy = declared or "Never"
            svc.containers.append(container_def)
            ir.add_service(svc)
            if not serving:
                self._maybe_validate_numerics(plan_svc)
        return ir

    @staticmethod
    def _maybe_validate_numerics(plan_svc: PlanService) -> None:
        """Opt-in (``M2KT_NUMERICS_VALIDATE=1``) translate-time numerics
        diff: run the translated trainer semantics against the source's
        declared ones on synthetic batches and drop
        ``m2kt-numerics-report.{json,md}`` next to the source. Best
        effort — a translate box without jax skips, it never blocks the
        translation itself (the report is the trust artifact, the gate
        is the harness CLI / CI)."""
        if os.environ.get("M2KT_NUMERICS_VALIDATE", "0") != "1":
            return
        src_dirs = plan_svc.source_artifacts.get(
            PlanService.SOURCE_DIR_ARTIFACT, [])
        if not src_dirs:
            return
        try:
            from move2kube_tpu.source import validate

            report = validate.validate_translation(
                src_dir=src_dirs[0], out_dir=src_dirs[0])
            log.info("gpu2tpu %s: numerics validation %s",
                     plan_svc.service_name, report["verdict"])
        except Exception as e:  # noqa: BLE001
            log.warning("gpu2tpu %s: numerics validation skipped: %s",
                        plan_svc.service_name, e)
