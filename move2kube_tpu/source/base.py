"""Source translator interface and registry.

Parity: ``internal/source/translator.go:27-52`` — translators offer plan
services at plan time (``get_service_options``) and convert selected
services into IR at translate time. Registry order matters: Any2Kube is the
fallback and must be last; the first plan service matching a translator's
type wins at translate time.
"""

from __future__ import annotations

from move2kube_tpu.types.ir import IR, new_ir
from move2kube_tpu.types.plan import Plan, PlanService
from move2kube_tpu.utils.log import get_logger

log = get_logger("source")


class Translator:
    def get_translation_type(self) -> str:
        raise NotImplementedError

    def get_service_options(self, plan: Plan) -> list[PlanService]:
        """Plan phase: detect services this translator can handle."""
        raise NotImplementedError

    def translate(self, services: list[PlanService], plan: Plan) -> IR:
        """Translate phase: convert chosen services into IR."""
        raise NotImplementedError


def get_source_loaders() -> list[Translator]:
    """Ordered registry (translator.go:35-40). Any2Kube must stay last."""
    from move2kube_tpu.source.any2kube import Any2KubeTranslator
    from move2kube_tpu.source.cfmanifest2kube import CfManifestTranslator
    from move2kube_tpu.source.compose2kube import ComposeTranslator
    from move2kube_tpu.source.dockerfile2kube import DockerfileTranslator
    from move2kube_tpu.source.gpu2tpu import Gpu2TpuTranslator
    from move2kube_tpu.source.kube2kube import KubeTranslator
    from move2kube_tpu.source.knative2kube import KnativeTranslator

    return [
        ComposeTranslator(),
        CfManifestTranslator(),
        # before Dockerfile2Kube: a GPU source tree's CUDA Dockerfile must
        # not be reused verbatim (it pins the workload to GPU nodes) — the
        # GPU2TPU option has to be the default for such dirs, and the
        # Dockerfile option stays available as an alternative answer
        Gpu2TpuTranslator(),
        DockerfileTranslator(),
        KubeTranslator(),
        KnativeTranslator(),
        Any2KubeTranslator(),
    ]


def translate_sources(plan: Plan) -> IR:
    """Run every translator over its services and merge the IRs
    (translator.go:42-52)."""
    ir = new_ir(plan)
    translators = {t.get_translation_type(): t for t in get_source_loaders()}
    by_type: dict[str, list[PlanService]] = {}
    for svcs in plan.services.values():
        for svc in svcs:
            by_type.setdefault(svc.translation_type, []).append(svc)
    for ttype, translator in translators.items():
        services = by_type.get(ttype, [])
        if not services:
            continue
        try:
            sub_ir = translator.translate(services, plan)
        except Exception as e:  # noqa: BLE001 - plugin tolerance
            log.warning("translator %s failed: %s", ttype, e)
            continue
        ir.merge(sub_ir)
    return ir
