"""Cloud Foundry manifest translator.

Parity: ``internal/source/cfmanifest2kube.go`` — finds CF ``manifest.yml``
files, matches apps against collected running-instance data
(``m2kt_collect`` CfApps yamls referenced by the plan), offers every
containerizer's options per app, and at translate time builds IR services
with env vars, instance counts, and the PORT convention.
"""

from __future__ import annotations

import os
import re

from move2kube_tpu import containerizer
from move2kube_tpu.source.base import Translator
from move2kube_tpu.types import collection as collecttypes
from move2kube_tpu.types import ir as irtypes
from move2kube_tpu.types.plan import (
    Plan,
    PlanService,
    SourceType,
    TranslationType,
)
from move2kube_tpu.utils import common
from move2kube_tpu.utils.log import get_logger

log = get_logger("source.cfmanifest")

CF_MANIFEST_NAMES = ["manifest.yml", "manifest.yaml"]

# bosh-style manifest variables: ((var)), ((var.subfield)), ((var-name))
_CF_VAR_RE = re.compile(r"\(\(([\w.\-]+)\)\)")


def interpolate_cf_variables(node, artifact_type, found: set[str]):
    """Rewrite ``((var))`` placeholders inside the parsed manifest tree.

    Parity: ``cfmanifest2kube.go:422-470`` (ReadApplicationManifest) —
    unresolved manifest variables become Helm-resolvable template refs
    (``{{ index .Values "globalvariables" "var" }}`` for Helm output,
    ``{{ $var }}`` otherwise) and are collected so the translator can
    register them as Helm global values. Operates on the YAML tree, not
    the raw text: a text substitution would turn unquoted scalars like
    ``instances: ((count))`` into invalid YAML."""
    from move2kube_tpu.types.plan import TargetArtifactType

    def placeholder(var: str) -> str:
        if artifact_type == TargetArtifactType.HELM:
            return '{{ index .Values "globalvariables" "%s" }}' % var
        return "{{ $%s }}" % var

    def walk(n):
        if isinstance(n, str):
            def sub(m):
                found.add(m.group(1))
                return placeholder(m.group(1))
            return _CF_VAR_RE.sub(sub, n)
        if isinstance(n, dict):
            return {walk(k): walk(v) for k, v in n.items()}
        if isinstance(n, list):
            return [walk(x) for x in n]
        return n

    return walk(node)


def find_cf_manifests(root: str) -> list[tuple[str, list[dict]]]:
    """-> [(path, applications)] for files that parse as CF manifests."""
    out = []
    for path in common.get_files_by_name(root, CF_MANIFEST_NAMES):
        try:
            doc = common.read_yaml(path)
        except Exception:  # noqa: BLE001
            continue
        if isinstance(doc, dict) and isinstance(doc.get("applications"), list):
            apps = [a for a in doc["applications"] if isinstance(a, dict) and a.get("name")]
            if apps:
                out.append((path, apps))
    return out


def _load_collected_apps(plan: Plan) -> dict[str, collecttypes.CfApp]:
    apps: dict[str, collecttypes.CfApp] = {}
    for path in plan.target_info_artifacts.get("CfApps", []):
        try:
            doc = common.read_m2kt_yaml(path, collecttypes.CF_APPS_KIND)
            for app in collecttypes.CfInstanceApps.from_dict(doc).apps:
                apps[app.name] = app
        except Exception as e:  # noqa: BLE001
            log.warning("cannot load collected cf apps %s: %s", path, e)
    return apps


def _buildpack_options(buildpack: str) -> list[str]:
    """Build types the collected CfContainerizers mapping offers for a
    buildpack (cfcontainertypescollector.go output consumed at plan time).
    Empty when nothing was collected — we don't guess."""
    from move2kube_tpu.containerizer.manual import ManualContainerizer

    for c in containerizer.get_containerizers():
        if isinstance(c, ManualContainerizer):
            return c.options_for_buildpack(buildpack) if \
                c.cf_containerizers.buildpack_containerizers else []
    return []


class CfManifestTranslator(Translator):
    def get_translation_type(self) -> str:
        return TranslationType.CFMANIFEST2KUBE

    @staticmethod
    def _app_buildpacks(app: dict) -> list[str]:
        bps = [str(b) for b in (app.get("buildpacks") or []) if b]
        if app.get("buildpack"):
            bps.append(str(app["buildpack"]))
        return bps

    def get_service_options(self, plan: Plan) -> list[PlanService]:
        services: list[PlanService] = []
        for manifest_path, apps in find_cf_manifests(plan.root_dir):
            app_dir = os.path.dirname(manifest_path)
            for app in apps:
                name = common.make_dns_label(str(app["name"]))
                src_dir = os.path.normpath(os.path.join(app_dir, str(app.get("path", "."))))
                if not os.path.isdir(src_dir):
                    src_dir = app_dir
                options = containerizer.get_containerization_options(plan, src_dir)
                # collected buildpack->containerizer mapping
                # (cfcontainertypescollector output) widens the options:
                # e.g. a 'binary' buildpack maps to Manual even though no
                # scanner claims the directory
                for bp in self._app_buildpacks(app):
                    for build_type in _buildpack_options(bp):
                        options.setdefault(build_type, [name])
                for build_type, target_options in options.items():
                    svc = PlanService(
                        service_name=name,
                        translation_type=TranslationType.CFMANIFEST2KUBE,
                        container_build_type=build_type,
                        source_types=[SourceType.CFMANIFEST],
                        containerization_target_options=list(target_options),
                    )
                    svc.add_source_artifact(PlanService.CFMANIFEST_ARTIFACT, manifest_path)
                    svc.add_source_artifact(PlanService.SOURCE_DIR_ARTIFACT, src_dir)
                    services.append(svc)
        return services

    def translate(self, services: list[PlanService], plan: Plan) -> irtypes.IR:
        ir = irtypes.IR(name=plan.name)
        collected = _load_collected_apps(plan)
        artifact_type = plan.kubernetes.effective_artifact_type()
        for plan_svc in services:
            manifests = plan_svc.source_artifacts.get(PlanService.CFMANIFEST_ARTIFACT, [])
            app_def: dict = {}
            manifest_vars: set[str] = set()
            for m in manifests:
                try:
                    doc = common.read_yaml(m)
                    doc = interpolate_cf_variables(doc, artifact_type,
                                                   manifest_vars)
                    for a in doc.get("applications", []):
                        if common.make_dns_label(str(a.get("name", ""))) == plan_svc.service_name:
                            app_def = a
                            break
                except Exception:  # noqa: BLE001
                    continue
            # unresolved ((var)) placeholders become Helm globals the
            # user fills in values.yaml (cfmanifest2kube.go:304-307)
            for var in sorted(manifest_vars):
                ir.values.global_variables[var] = var
            try:
                container = containerizer.get_container(plan, plan_svc)
            except Exception as e:  # noqa: BLE001
                log.warning("cf containerization failed for %s: %s",
                            plan_svc.service_name, e)
                continue
            ir.add_container(container)
            svc = irtypes.service_from_plan(plan_svc)
            running = collected.get(str(app_def.get("name", "")))
            # port: running instance > containerizer detect > default 8080
            # (cfmanifest2kube.go:265-412)
            if running and running.ports:
                port = running.ports[0]
            elif container.exposed_ports:
                port = container.exposed_ports[0]
            else:
                port = common.DEFAULT_SERVICE_PORT
            image = container.image_names[0] if container.image_names else svc.name + ":latest"
            env = [{"name": "PORT", "value": str(port)}]
            for k, v in (app_def.get("env") or {}).items():
                env.append({"name": str(k), "value": str(v)})
            if running:
                for k, v in running.env.items():
                    if all(e["name"] != k for e in env):
                        env.append({"name": k, "value": v})
                svc.replicas = max(1, running.instances)
            if app_def.get("instances"):
                try:
                    svc.replicas = max(1, int(app_def["instances"]))
                except (TypeError, ValueError):
                    # an interpolated ((var)) placeholder — keep default;
                    # the value rides values.yaml globalvariables instead
                    pass
            svc.containers.append({
                "name": svc.name,
                "image": image,
                "ports": [{"containerPort": port}],
                "env": env,
            })
            svc.add_port_forwarding(port, port)
            ir.add_service(svc)
        return ir
