""".m2ktignore handling: gitignore-like exclusion for the directory walkers.

Parity: ``internal/source/any2kube.go:151`` (getIgnorePaths) — ignore files
anywhere in the tree exclude paths relative to their own directory.
Supported syntax: one pattern per line, ``#`` comments, ``*`` wildcards
(fnmatch), trailing ``/`` to match directories only.
"""

from __future__ import annotations

import fnmatch
import os

from move2kube_tpu.utils import common

IGNORE_FILES = (common.IGNORE_FILENAME, *common.LEGACY_IGNORE_FILENAMES)


class IgnoreRules:
    def __init__(self, root: str) -> None:
        self.root = os.path.abspath(root)
        # dir -> list of patterns (relative to that dir)
        self.rules: dict[str, list[str]] = {}
        for name in IGNORE_FILES:
            for path in common.get_files_by_name(self.root, [name]):
                patterns = []
                try:
                    for line in open(path, encoding="utf-8"):
                        line = line.strip()
                        if line and not line.startswith("#"):
                            patterns.append(line)
                except OSError:
                    continue
                if patterns:
                    self.rules.setdefault(os.path.dirname(path), []).extend(patterns)

    def is_ignored(self, path: str) -> bool:
        path = os.path.abspath(path)
        for rule_dir, patterns in self.rules.items():
            rel = common.relpath_under(path, rule_dir)
            if rel is None or rel == ".":
                continue
            rel_posix = rel.replace(os.sep, "/")
            for pat in patterns:
                pat = pat.rstrip("/")
                if not pat:
                    continue
                # match full relative path or any leading component
                if fnmatch.fnmatch(rel_posix, pat) or fnmatch.fnmatch(
                    os.path.basename(rel_posix), pat
                ):
                    return True
                parts = rel_posix.split("/")
                for i in range(1, len(parts)):
                    if fnmatch.fnmatch("/".join(parts[:i]), pat):
                        return True
        return False
