"""Static detection of GPU training workloads in user source trees.

The north-star detection layer (net-new vs the reference; BASELINE.json):
AST + pattern analysis of Python sources recognising

- CUDA usage: ``torch.cuda``, ``.cuda()``, ``.to('cuda')``, cupy, numba.cuda
- distributed backends: ``dist.init_process_group('nccl'|'gloo'|'mpi')``,
  ``torchrun``/``torch.distributed.launch``, horovod
- DeepSpeed: imports + ``ds_config`` JSON (ZeRO stage, pipeline/tensor
  parallel sizes)
- TF GPU: ``tf.config...'GPU'``, ``MirroredStrategy``
- model family (resnet / bert / llama / generic) from imports and symbols

and GPU resource requests in compose / k8s inputs (``nvidia.com/gpu``,
``runtime: nvidia``) — handled by the compose/k8s translators calling
:func:`gpu_resources_from_k8s_container`.

The result feeds ``AcceleratorInfo`` on plan services; the jax-xla
containerizer and the TPU apiresources size slices from it (see
:func:`map_gpu_to_tpu`).

Analysis degrades gracefully: unparseable files fall back to text-pattern
scanning, mirroring how the reference tolerates undetectable stacks by
falling back to Manual containerization.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field

from move2kube_tpu.types.plan import AcceleratorInfo
from move2kube_tpu.utils import common
from move2kube_tpu.utils.log import get_logger

log = get_logger("gpu_detect")

_SKIP_DIRS = {".git", "node_modules", "__pycache__", ".venv", "venv"}

# Import roots that signal each framework
_FRAMEWORK_IMPORTS = {
    "torch": "torch",
    "tensorflow": "tf",
    "deepspeed": "deepspeed",
    "horovod": "horovod",
    "cupy": "cupy",
    "jax": "jax",  # already-ported code: no translation needed
}

_MODEL_FAMILY_PATTERNS = [
    ("llama", re.compile(r"llama|LlamaForCausalLM|mistral|decoder_layer|rotary", re.I)),
    ("bert", re.compile(r"\bbert\b|BertModel|BertForSequenceClassification"
                        r"|AutoModelForSequenceClassification", re.I)),
    ("resnet", re.compile(r"resnet|torchvision\.models", re.I)),
    ("gpt", re.compile(r"\bgpt2?\b|GPT2LMHeadModel|causal_lm|CausalLM", re.I)),
    ("unet", re.compile(r"\bunet\b|diffusion", re.I)),
]

_CUDA_TEXT = re.compile(
    r"torch\.cuda|\.cuda\(\)|to\(['\"]cuda|device\s*=\s*['\"]cuda|cupy|numba\.cuda"
    r"|tf\.config[^\n]*GPU|nvidia-smi|CUDA_VISIBLE_DEVICES"
)
_NCCL_TEXT = re.compile(r"['\"]nccl['\"]|init_process_group"
                        r"|DistributedDataParallel|torchrun|torch\.distributed")

# Inference servers: an HTTP framework plus generate/forward-only usage
# marks a script as serving rather than training.
_SERVING_IMPORTS = {"flask", "fastapi", "uvicorn", "gunicorn", "starlette",
                    "sanic", "aiohttp", "tritonclient", "ts"}
_SERVING_TEXT = re.compile(
    r"@app\.(?:route|get|post)|FastAPI\(|Flask\(|uvicorn\.run\("
    r"|app\.run\(|\.generate\(|torchserve|triton"
)
_PORT_TEXT = re.compile(r"port\s*[=:]\s*(\d{2,5})")


@dataclass
class GpuReport:
    """What the analyzer found for one directory."""

    frameworks: list[str] = field(default_factory=list)
    uses_cuda: bool = False
    distributed_backend: str = ""  # nccl | gloo | mpi | horovod | ""
    world_size_hint: int = 0  # e.g. from --nproc_per_node or ds_config
    zero_stage: int = 0
    tensor_parallel: int = 1
    pipeline_parallel: int = 1
    expert_parallel: int = 1
    seq_parallel: int = 1  # DeepSpeed-Ulysses / Megatron context parallel
    num_experts: int = 0  # MoE expert count (DeepSpeed-MoE / Megatron)
    batch_size_hint: int = 0   # per-device batch from source args/config
    lr_hint: float = 0.0
    steps_hint: int = 0
    model_family: str = ""
    entrypoint: str = ""  # training script path
    training_scripts: list[str] = field(default_factory=list)
    serving_frameworks: list[str] = field(default_factory=list)
    serving_scripts: list[str] = field(default_factory=list)
    serving_port: int = 0  # detected HTTP listen port (0 = unknown)
    evidence: list[str] = field(default_factory=list)  # human-readable findings

    @property
    def is_serving(self) -> bool:
        """Inference server, not a run-to-completion trainer. A tree with
        both training and serving scripts stays a trainer (fine-tune repos
        ship demo servers; the training job is the migration target)."""
        return bool(self.serving_scripts) and not self.training_scripts


def _iter_py_files(directory: str, max_files: int = 500):
    n = 0
    for dirpath, dirnames, filenames in os.walk(directory):
        dirnames[:] = [d for d in dirnames if d not in _SKIP_DIRS]
        for f in sorted(filenames):
            if f.endswith(".py"):
                yield os.path.join(dirpath, f)
                n += 1
                if n >= max_files:
                    return


class _PyVisitor(ast.NodeVisitor):
    def __init__(self) -> None:
        self.imports: set[str] = set()
        self.backend: str = ""
        self.is_training = False
        self.nproc_hint = 0

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            self.imports.add(a.name.split(".")[0])
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module:
            self.imports.add(node.module.split(".")[0])
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        # dist.init_process_group("nccl") / backend="nccl"
        fname = ""
        if isinstance(node.func, ast.Attribute):
            fname = node.func.attr
        elif isinstance(node.func, ast.Name):
            fname = node.func.id
        if fname == "init_process_group":
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                    if arg.value in ("nccl", "gloo", "mpi"):
                        self.backend = arg.value
                        break
        if fname in ("backward", "step") or fname in ("fit", "train"):
            self.is_training = True
        self.generic_visit(node)


def analyze_file(path: str) -> tuple[_PyVisitor | None, str]:
    try:
        text = open(path, encoding="utf-8", errors="ignore").read()
    except OSError:
        return None, ""
    try:
        tree = ast.parse(text)
        v = _PyVisitor()
        v.visit(tree)
    except SyntaxError:
        v = None
    return v, text


_analysis_cache: dict[str, GpuReport | None] = {}


def clear_cache() -> None:
    _analysis_cache.clear()


def analyze_directory(directory: str) -> GpuReport | None:
    """Analyze a directory; None if it is not a GPU training workload.

    Memoised per absolute path: the plan walker and the jax-xla
    containerizer both probe the same directories (and the walker probes
    every ancestor), so uncached analysis would re-read subtrees
    O(dirs x files) times.
    """
    directory = os.path.abspath(directory)
    if directory in _analysis_cache:
        return _analysis_cache[directory]
    report = _analyze_directory_uncached(directory)
    _analysis_cache[directory] = report
    return report


def _analyze_directory_uncached(directory: str) -> GpuReport | None:
    report = GpuReport()
    family_votes: dict[str, int] = {}
    for path in _iter_py_files(directory):
        v, text = analyze_file(path)
        rel = os.path.relpath(path, directory)
        imports = v.imports if v else set()
        if v is None and text:
            # fall back to text heuristics on unparseable files
            for root in _FRAMEWORK_IMPORTS:
                if re.search(rf"\bimport {root}\b|\bfrom {root}\b", text):
                    imports.add(root)
        for root in imports & set(_FRAMEWORK_IMPORTS):
            if root not in report.frameworks:
                report.frameworks.append(root)
        uses_cuda = bool(_CUDA_TEXT.search(text))
        if uses_cuda:
            report.uses_cuda = True
            report.evidence.append(f"{rel}: CUDA usage")
        if v and v.backend and not report.distributed_backend:
            report.distributed_backend = v.backend
            report.evidence.append(f"{rel}: init_process_group({v.backend!r})")
        elif not report.distributed_backend and _NCCL_TEXT.search(text) and "nccl" in text:
            report.distributed_backend = "nccl"
            report.evidence.append(f"{rel}: nccl reference")
        if "horovod" in imports and not report.distributed_backend:
            report.distributed_backend = "horovod"
        for fam, pat in _MODEL_FAMILY_PATTERNS:
            if pat.search(text):
                family_votes[fam] = family_votes.get(fam, 0) + len(pat.findall(text))
        is_trainingish = (v and v.is_training) or bool(
            re.search(r"\.backward\(\)|optimizer\.step|loss|train_loop|model\.fit", text)
        )
        if is_trainingish and (uses_cuda or imports & {
                "torch", "tensorflow", "deepspeed", "horovod"}):
            report.training_scripts.append(path)
        serving_imports = imports & _SERVING_IMPORTS
        is_servingish = bool(serving_imports) or bool(_SERVING_TEXT.search(text))
        if is_servingish and not is_trainingish and (
                uses_cuda or imports & {"torch", "tensorflow"}):
            report.serving_scripts.append(path)
            for root in sorted(serving_imports):
                if root not in report.serving_frameworks:
                    report.serving_frameworks.append(root)
            report.evidence.append(f"{rel}: GPU inference server")
            if not report.serving_port:
                m = _PORT_TEXT.search(text)
                if m and 1 <= int(m.group(1)) <= 65535:
                    report.serving_port = int(m.group(1))

    # DeepSpeed config JSON (ZeRO stage, micro batch, parallel sizes)
    for cfg in common.get_files_by_ext(directory, [".json"]):
        try:
            doc = common.read_json(cfg)
        except (OSError, json.JSONDecodeError):
            continue
        if not isinstance(doc, dict):
            continue
        if "zero_optimization" in doc or "train_micro_batch_size_per_gpu" in doc:
            if "deepspeed" not in report.frameworks:
                report.frameworks.append("deepspeed")
            zo = doc.get("zero_optimization", {})
            if isinstance(zo, dict):
                report.zero_stage = int(zo.get("stage", 0) or 0)
            report.tensor_parallel = int(
                doc.get("tensor_parallel", {}).get("tp_size", 1)
                if isinstance(doc.get("tensor_parallel"), dict) else 1
            )
            # DeepSpeed-Ulysses sequence parallelism
            report.seq_parallel = max(
                report.seq_parallel,
                int(doc.get("sequence_parallel_size",
                            doc.get("ds_sequence_parallel_size", 1)) or 1))
            # DeepSpeed-MoE config block
            moe = doc.get("moe")
            if isinstance(moe, dict):
                report.num_experts = int(moe.get("num_experts", 0) or 0)
                report.expert_parallel = max(
                    report.expert_parallel,
                    int(moe.get("expert_parallel_size",
                                moe.get("ep_size", 1)) or 1))
            report.evidence.append(
                f"{os.path.relpath(cfg, directory)}: deepspeed config (ZeRO-{report.zero_stage})"
            )
            if not report.distributed_backend:
                report.distributed_backend = "nccl"

    # torchrun / launch hints in shell scripts
    for sh in common.get_files_by_ext(directory, [".sh"]):
        try:
            text = open(sh, encoding="utf-8", errors="ignore").read()
        except OSError:
            continue
        m = re.search(r"--nproc[_-]per[_-]node[=\s]+(\d+)", text)
        if m:
            report.world_size_hint = max(report.world_size_hint, int(m.group(1)))
            if not report.distributed_backend:
                report.distributed_backend = "nccl"
            report.evidence.append(
                f"{os.path.relpath(sh, directory)}: torchrun nproc_per_node={m.group(1)}"
            )
        m = re.search(r"--num[_-]gpus[=\s]+(\d+)", text)
        if m:
            report.world_size_hint = max(report.world_size_hint, int(m.group(1)))
        # Megatron-style parallelism args in launch scripts
        for pat, attr in (
            (r"--tensor[_-]model[_-]parallel[_-]size[=\s]+(\d+)", "tensor_parallel"),
            (r"--pipeline[_-]model[_-]parallel[_-]size[=\s]+(\d+)", "pipeline_parallel"),
            (r"--expert[_-]model[_-]parallel[_-]size[=\s]+(\d+)", "expert_parallel"),
            (r"--num[_-]experts[=\s]+(\d+)", "num_experts"),
            # DeepSpeed-Ulysses / Megatron context parallelism
            (r"--ds[_-]sequence[_-]parallel[_-]size[=\s]+(\d+)", "seq_parallel"),
            (r"--context[_-]parallel[_-]size[=\s]+(\d+)", "seq_parallel"),
            (r"--sequence[_-]parallel[_-]size[=\s]+(\d+)", "seq_parallel"),
        ):
            m = re.search(pat, text)
            if m:
                setattr(report, attr, max(getattr(report, attr), int(m.group(1))))
                report.evidence.append(
                    f"{os.path.relpath(sh, directory)}: {attr}={m.group(1)}")

    # serving port: Dockerfile EXPOSE beats an in-source port= literal
    for name in ("Dockerfile", "dockerfile"):
        dpath = os.path.join(directory, name)
        if os.path.isfile(dpath):
            try:
                text = open(dpath, encoding="utf-8", errors="ignore").read()
            except OSError:
                continue
            m = re.search(r"^\s*EXPOSE\s+(\d+)", text, re.M)
            if m:
                report.serving_port = int(m.group(1))
                report.evidence.append(f"{name}: EXPOSE {m.group(1)}")
            break

    # decide: is this a GPU workload (training or serving)?
    gpu_frameworks = set(report.frameworks) & {
        "torch", "tensorflow", "deepspeed", "horovod", "cupy"}
    if not gpu_frameworks:
        return None
    if not (report.uses_cuda or report.distributed_backend or "deepspeed" in report.frameworks):
        return None
    if not report.training_scripts and not report.serving_scripts:
        return None

    report.model_family = max(family_votes, key=family_votes.get) if family_votes else "generic"
    report.entrypoint = _pick_entrypoint(
        report.training_scripts or report.serving_scripts)
    return report


def _pick_entrypoint(scripts: list[str]) -> str:
    def score(p: str) -> tuple:
        base = os.path.basename(p).lower()
        named = ("train" in base or "serve" in base or "server" in base
                 or base == "app.py")
        return (
            0 if named else (1 if base in ("main.py", "run.py") else 2),
            p.count(os.sep),
            p,
        )

    return sorted(scripts, key=score)[0] if scripts else ""


# --- GPU -> TPU topology mapping -------------------------------------------

# (accelerator type, chips per host) — v5e hosts have 4 or 8 chips depending
# on topology; we use 4 (the 2x2 sub-slice host) for small counts and 2x4
# hosts for v5e-8 and above. v5p hosts have 4 chips.
_V5E = "tpu-v5-lite-podslice"
_V5P = "tpu-v5p-slice"

# gpu_count -> (accelerator, topology, num_hosts)
_TOPOLOGY_TABLE = [
    (1, (_V5E, "1x1", 1)),
    (4, (_V5E, "2x2", 1)),
    (8, (_V5E, "2x4", 2)),
    (16, (_V5E, "4x4", 4)),
    (32, (_V5E, "4x8", 8)),
    (64, (_V5P, "4x4x4", 16)),
    (128, (_V5P, "4x4x8", 32)),
    (256, (_V5P, "4x8x8", 64)),
]


def map_gpu_to_tpu(gpu_count: int, zero_stage: int = 0) -> tuple[str, str, int]:
    """Choose a TPU slice for a GPU chip count.

    ZeRO-3 / model-parallel workloads (sharded params) prefer v5p for HBM
    capacity and 3D torus ICI; everything else maps to v5e pod slices.
    Counts are clamped to [1, 256] (the largest supported topology).
    """
    gpu_count = min(max(gpu_count, 1), 256)
    for threshold, (acc, topo, hosts) in _TOPOLOGY_TABLE:
        if gpu_count <= threshold:
            if zero_stage >= 3 and threshold >= 8:
                # large sharded model: v5p host groups of 4 chips
                chips = max(threshold, 8)
                if chips <= 16:
                    return (_V5P, "2x2x4", max(1, chips // 4))
                return (_V5P, "4x4x4", 16)
            return (acc, topo, hosts)
    return (_V5P, "4x8x8", 64)


MAX_SLICE_CHIPS = 256  # largest single-slice topology in the table
MAX_SLICES = 8
# default host granularity for topologies outside the table (all table
# entries today use 4-chip hosts; single owner for that assumption)
CHIPS_PER_HOST = 4


def topology_chip_count(topology: str) -> int:
    """Chip count of an NxM[xK] topology string; raises ValueError when
    malformed (incl. non-positive dims). Single owner of topology parsing
    (used by the apiresource sizing and the QA slice override)."""
    chips = 1
    for dim_str in str(topology).split("x"):
        dim = int(dim_str)
        if dim <= 0:
            raise ValueError(f"non-positive topology dim {dim} in {topology!r}")
        chips *= dim
    return chips


def map_gpu_to_tpu_multislice(
    gpu_count: int, zero_stage: int = 0,
) -> tuple[str, str, int, int]:
    """-> (accelerator, per-slice topology, hosts per slice, num_slices).

    Workloads beyond the largest single slice span multiple
    DCN-connected slices (SURVEY §5: megascale/DCN emission obligation):
    data parallelism rides DCN between slices, everything else stays on
    ICI within a slice.
    """
    gpu_count = max(1, gpu_count)
    if gpu_count <= MAX_SLICE_CHIPS:
        acc, topo, hosts = map_gpu_to_tpu(gpu_count, zero_stage)
        return acc, topo, hosts, 1
    slices_needed = -(-gpu_count // MAX_SLICE_CHIPS)
    num_slices = min(slices_needed, MAX_SLICES)
    if slices_needed > MAX_SLICES:
        log.warning(
            "detected %d GPUs needs %d slices of %d chips but the emitter "
            "caps at %d slices (%d chips total); scale the JobSet replicas "
            "up manually for the full footprint",
            gpu_count, slices_needed, MAX_SLICE_CHIPS, MAX_SLICES,
            MAX_SLICES * MAX_SLICE_CHIPS)
    acc, topo, hosts = map_gpu_to_tpu(MAX_SLICE_CHIPS, zero_stage)
    return acc, topo, hosts, num_slices


def report_to_accelerator(report: GpuReport, gpu_count: int = 0) -> AcceleratorInfo:
    """Convert an analysis report into plan AcceleratorInfo."""
    count = gpu_count or report.world_size_hint or 1
    acc_type, topology, hosts, num_slices = map_gpu_to_tpu_multislice(
        count, report.zero_stage)
    parallelism: dict[str, int] = {}
    if report.zero_stage:
        parallelism["zero_stage"] = report.zero_stage
    if report.tensor_parallel > 1:
        parallelism["tp"] = report.tensor_parallel
    if report.pipeline_parallel > 1:
        parallelism["pp"] = report.pipeline_parallel
    if report.expert_parallel > 1:
        parallelism["ep"] = report.expert_parallel
    if report.seq_parallel > 1:
        parallelism["sp"] = report.seq_parallel
    if report.num_experts:
        parallelism["experts"] = report.num_experts
    if count > 1:
        parallelism.setdefault("dp", count)
    return AcceleratorInfo(
        gpu_count=count,
        gpu_vendor="nvidia.com/gpu",
        frameworks=list(report.frameworks),
        distributed_backend=report.distributed_backend,
        parallelism=parallelism,
        model_family=report.model_family,
        entrypoint=report.entrypoint,
        tpu_accelerator=acc_type,
        tpu_topology=topology,
        num_hosts=hosts,
        num_slices=num_slices,
        serving=report.is_serving,
        serving_port=report.serving_port if report.is_serving else 0,
    )


def gpu_resources_from_k8s_container(container: dict) -> int:
    """GPU count requested by a k8s container spec (nvidia.com/gpu et al)."""
    total = 0
    resources = container.get("resources", {}) or {}
    for section in ("limits", "requests"):
        for key, val in (resources.get(section) or {}).items():
            if "gpu" in key.lower():
                try:
                    total = max(total, int(val))
                except (TypeError, ValueError):
                    total = max(total, 1)
    return total
