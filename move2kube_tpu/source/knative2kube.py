"""Knative translator: existing Knative yamls round-trip.

Parity: ``internal/source/knative2kube.go`` — delegates to the Knative
apiresource set; existing Knative Services are cached and re-emitted
against the target cluster.
"""

from __future__ import annotations

from move2kube_tpu.source.base import Translator
from move2kube_tpu.source.kube2kube import load_k8s_yamls
from move2kube_tpu.types import ir as irtypes
from move2kube_tpu.types.plan import Plan, PlanService, TranslationType


class KnativeTranslator(Translator):
    def get_translation_type(self) -> str:
        return TranslationType.KNATIVE2KUBE

    def get_service_options(self, plan: Plan) -> list[PlanService]:
        return []  # planning handled by metadata loader

    def translate(self, services: list[PlanService], plan: Plan) -> irtypes.IR:
        ir = irtypes.IR(name=plan.name)
        paths = []
        for svc in services:
            paths.extend(svc.source_artifacts.get(PlanService.KNATIVE_ARTIFACT, []))
        ir.cached_objects.extend(
            o for o in load_k8s_yamls(paths)
            if str(o.get("apiVersion", "")).startswith("serving.knative.dev")
        )
        return ir
