"""Predictive SLO-driven autoscaler: forecast demand -> target replicas.

The control loop the telemetry plane was built for (ROADMAP item 2):

1. a :class:`~move2kube_tpu.serving.fleet.forecast.DemandForecaster`
   predicts the admitted-token rate at ``now + lead``, where the lead
   is the measured cold-join time of a new replica — the PR-14 prewarm
   speedup is spent here as scale-up reaction time;
2. the forecast divides by per-replica capacity (measured decode
   tok/s from the engine's own stats, an env override, or the
   costmodel's roofline tok/s for the compiled executable) at a target
   utilization to give the replica count;
3. hysteresis keeps the answer calm: scale-up applies immediately
   (late capacity is an SLO burn, early capacity is only money),
   scale-down waits for the target to hold below the current size for
   a delay window, and shrink goes through the PR-13 ``drain()`` path
   so no stream is ever dropped by a scaling decision.

Two actuation backends share the controller: :class:`FleetActuator`
grows/shrinks an in-process fleet (tests, bench live smoke), and
:func:`run_controller` is the emitted controller Deployment's main
loop — it scrapes the router's ``/metrics`` page, exports the
``m2kt_autoscale_*`` gauges, and (when RBAC allows and the knob is on)
patches the decode Deployment's scale subresource. The emission side
is deliberately observe-first: with actuation off it is a shadow
controller whose gauges can be compared against the reactive HPA
before anyone hands it the keys. ``fleet_wiring`` suppresses the
reactive HPAs whenever this controller is enabled so the two loops
never duel over the same Deployment.

Stdlib-only imports at module top (vendored into emitted images);
jax-touching pieces stay behind the in-process actuator's factory.
"""

from __future__ import annotations

import json
import logging
import math
import os
import time
from dataclasses import dataclass

from move2kube_tpu.obs.metrics import Registry, default_registry
from move2kube_tpu.serving.fleet.forecast import (
    CounterDemand, DemandForecaster, TenantCounterDemand,
    TenantDemandForecaster)

log = logging.getLogger("move2kube_tpu.autoscaler")

ENABLE_ENV = "M2KT_AUTOSCALE"
INTERVAL_ENV = "M2KT_AUTOSCALE_INTERVAL_S"
MIN_ENV = "M2KT_AUTOSCALE_MIN"
MAX_ENV = "M2KT_AUTOSCALE_MAX"
UTIL_ENV = "M2KT_AUTOSCALE_TARGET_UTIL"
LEAD_ENV = "M2KT_AUTOSCALE_LEAD_S"
DOWN_DELAY_ENV = "M2KT_AUTOSCALE_DOWN_DELAY_S"
REPLICA_TPS_ENV = "M2KT_AUTOSCALE_REPLICA_TPS"
# controller-Deployment wiring (emission role only)
METRICS_URL_ENV = "M2KT_AUTOSCALE_METRICS_URL"
TARGET_ENV = "M2KT_AUTOSCALE_TARGET"
ACTUATE_ENV = "M2KT_AUTOSCALE_ACTUATE"

ADMITTED_COUNTER = "m2kt_router_admitted_tokens_total"
UNUSED_COUNTER = "m2kt_router_admitted_tokens_unused_total"


def _float_env(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        log.warning("%s=%r is not a number; using %g", name, raw, default)
        return default


def _int_env(name: str, default: int) -> int:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        log.warning("%s=%r is not an integer; using %d", name, raw, default)
        return default


@dataclass(frozen=True)
class AutoscaleConfig:
    """Controller knobs; every field has an ``M2KT_AUTOSCALE_*`` env
    override with tolerant parsing (warn + default, never crash — the
    fleet_wiring contract)."""

    interval_s: float = 15.0      # control-loop period
    min_replicas: int = 1
    max_replicas: int = 8
    target_util: float = 0.7      # fraction of capacity demand may fill
    lead_time_s: float = 120.0    # forecast horizon = cold-join time
    down_delay_s: float = 120.0   # target must hold low this long

    @classmethod
    def from_env(cls) -> "AutoscaleConfig":
        return cls(
            interval_s=max(0.1, _float_env(INTERVAL_ENV, cls.interval_s)),
            min_replicas=max(1, _int_env(MIN_ENV, cls.min_replicas)),
            max_replicas=max(1, _int_env(MAX_ENV, cls.max_replicas)),
            target_util=min(1.0, max(
                0.05, _float_env(UTIL_ENV, cls.target_util))),
            lead_time_s=max(0.0, _float_env(LEAD_ENV, cls.lead_time_s)),
            down_delay_s=max(
                0.0, _float_env(DOWN_DELAY_ENV, cls.down_delay_s)),
        )


def autoscale_enabled() -> bool:
    return os.environ.get(ENABLE_ENV, "").strip() in ("1", "true", "on")


# ---------------------------------------------------------------------------
# per-replica capacity
# ---------------------------------------------------------------------------

def capacity_from_cost_report(report, spec, tokens_per_step: float,
                              util: float = 1.0) -> float | None:
    """Roofline tok/s of one replica from the costmodel's per-executable
    numbers: the decode step can go no faster than both the compute time
    (flops / peak) and the HBM time (bytes / bandwidth), so the
    achievable step rate is 1 / max(...) and tok/s follows from the
    tokens one step advances. Returns None when the report is degraded
    (CPU backends often report no cost analysis)."""
    flops = getattr(report, "flops", None)
    bytes_accessed = getattr(report, "bytes_accessed", None)
    if not flops or not bytes_accessed or tokens_per_step <= 0:
        return None
    step_s = max(flops / spec.peak_bf16_flops,
                 bytes_accessed / spec.hbm_bandwidth)
    if step_s <= 0:
        return None
    return (tokens_per_step / step_s) * min(1.0, max(0.0, util))


def replica_capacity_tps(engine=None, default: float = 100.0) -> float:
    """Sustainable decode tok/s of ONE replica, best source first:
    the ``M2KT_AUTOSCALE_REPLICA_TPS`` override, the engine's own
    measured ``decode_throughput_tokens_s``, then the default. Always
    positive — a zero capacity would divide the controller by it."""
    override = _float_env(REPLICA_TPS_ENV, 0.0)
    if override > 0:
        return override
    if engine is not None:
        try:
            measured = float(
                engine.stats().get("decode_throughput_tokens_s") or 0.0)
            if measured > 0:
                return measured
        except Exception:  # noqa: BLE001 - stats are advisory
            pass
    return max(1e-6, default)


# ---------------------------------------------------------------------------
# controller
# ---------------------------------------------------------------------------

class PredictiveAutoscaler:
    """Forecast -> target-replica controller with asymmetric hysteresis.

    Pure decision logic plus gauges; actuation is the caller's problem
    (FleetActuator in-process, the scale subresource in emission, a
    capacity-change event in the simulator). ``capacity_tps`` may be a
    number or a zero-arg callable re-read every decision, so a live
    fleet's measured throughput keeps the controller honest."""

    def __init__(self, forecaster: DemandForecaster, capacity_tps,
                 config: AutoscaleConfig | None = None,
                 clock=time.monotonic,
                 registry: Registry | None = None) -> None:
        self.forecaster = forecaster
        self._capacity = capacity_tps
        self.config = config or AutoscaleConfig.from_env()
        self._clock = clock
        self._below_since: float | None = None
        reg = registry or default_registry()
        self._g_target = reg.gauge(
            "m2kt_autoscale_target_replicas",
            "Replica count the predictive controller wants right now")
        self._g_forecast = reg.gauge(
            "m2kt_autoscale_forecast_tps",
            "Forecast admitted-token demand (tokens/s) at now + lead")
        self._g_lead = reg.gauge(
            "m2kt_autoscale_lead_time_s",
            "Forecast horizon = measured replica cold-join time")
        self._g_actual = reg.gauge(
            "m2kt_autoscale_actual_replicas",
            "Replica count the controller last observed (the "
            "ActuationStalled alert compares this to the target)")
        self._events = reg.counter(
            "m2kt_autoscale_events_total",
            "Scaling decisions applied, by direction",
            labels=("direction",), max_series=4)
        self._g_lead.set(self.config.lead_time_s)

    def capacity_tps(self) -> float:
        cap = self._capacity() if callable(self._capacity) else \
            float(self._capacity)
        return max(1e-6, cap)

    def desired(self, now: float | None = None) -> int:
        """Raw target: forecast demand at now+lead over usable capacity
        per replica, clamped to [min, max]. No hysteresis here."""
        cfg = self.config
        tps = self.forecaster.forecast(cfg.lead_time_s, now=now)
        self._g_forecast.set(tps)
        usable = self.capacity_tps() * cfg.target_util
        want = math.ceil(tps / usable) if tps > 0 else cfg.min_replicas
        return max(cfg.min_replicas, min(cfg.max_replicas, want))

    def decide(self, current: int, now: float | None = None) -> int:
        """The hysteresis step: returns the replica count to actuate.
        Up moves apply immediately; a down move needs the raw target to
        have stayed below ``current`` for ``down_delay_s`` continuously
        (one higher sample resets the timer), and then shrinks by at
        most one replica per decision so a forecast undershoot never
        cliffs the fleet."""
        now = self._clock() if now is None else float(now)
        target = self.desired(now=now)
        self._g_actual.set(float(current))
        if target > current:
            self._below_since = None
            self._g_target.set(float(target))
            self._events.labels(direction="up").inc()
            return target
        if target < current:
            if self._below_since is None:
                self._below_since = now
            if now - self._below_since >= self.config.down_delay_s:
                self._below_since = now  # re-arm for the next step down
                new = current - 1
                self._g_target.set(float(new))
                self._events.labels(direction="down").inc()
                return new
        else:
            self._below_since = None
        self._g_target.set(float(current))
        return current


# ---------------------------------------------------------------------------
# in-process actuation
# ---------------------------------------------------------------------------

class FleetActuator:
    """Grow/shrink an in-process fleet (``build_fleet`` Router) to the
    controller's target. Grow appends factory-built replicas (the
    factory returns a STARTED ``InProcessReplica``); shrink marks the
    tail replica down first — no new placements — then drains it
    through the PR-13 path and closes it, so a scale-down by
    construction never drops a stream. ``lost_streams`` counts drains
    that timed out with work still in flight (their waiters got the
    retryable ``ReplicaDraining``, so even then the router resumes
    them — the counter is the bench gate's evidence, not a leak)."""

    def __init__(self, router, replica_factory,
                 drain_grace_s: float = 30.0) -> None:
        self.router = router
        self._factory = replica_factory
        self.drain_grace_s = float(drain_grace_s)
        self._seq = len(router.replicas)
        self.lost_streams = 0

    def replicas(self) -> int:
        return len(self.router.replicas)

    def scale_to(self, target: int) -> int:
        target = max(0, int(target))
        while len(self.router.replicas) < target:
            name = f"replica-{self._seq}"
            self._seq += 1
            replica = self._factory(name)
            self.router.replicas.append(replica)
            self.router._up[replica.name] = True
            self.router._replica_up.labels(replica=replica.name).set(1.0)
        while len(self.router.replicas) > target:
            replica = self.router.replicas[-1]
            self.router._mark_down(replica, reason="scale-down")
            clean = True
            try:
                clean = replica.drain(self.drain_grace_s)
            finally:
                replica.close()
                self.router.replicas.remove(replica)
                self.router._up.pop(replica.name, None)
            if not clean:
                self.lost_streams += 1
        return len(self.router.replicas)


# ---------------------------------------------------------------------------
# emitted controller Deployment main loop
# ---------------------------------------------------------------------------

def _split_labels(line: str, name: str) -> tuple[str, str] | None:
    """Split one exposition line of family ``name`` into
    ``(label_section, rest)``. Quote-aware: a ``}`` inside a quoted
    label value (tenants are untrusted header strings) does not end the
    label section. Returns None when the line is not this family or is
    malformed — the caller warns and moves on, never raises."""
    if line.startswith(name + "{"):
        i = len(name) + 1
        in_quotes = False
        escaped = False
        while i < len(line):
            c = line[i]
            if escaped:
                escaped = False
            elif c == "\\":
                escaped = True
            elif c == '"':
                in_quotes = not in_quotes
            elif c == "}" and not in_quotes:
                return line[len(name) + 1:i], line[i + 1:].strip()
            i += 1
        return None  # unterminated label section
    if line.startswith(name + " ") or line.startswith(name + "\t"):
        return "", line[len(name):].strip()
    return None


def parse_counter_total(text: str, name: str) -> float:
    """Sum every sample of ``name`` (all label sets) in a Prometheus
    text exposition page. Tolerant of anything that is not the metric,
    of labeled families (quote-aware — a ``}`` inside a tenant label
    value does not truncate the parse), and of exposition lines with
    trailing timestamps (``name value timestamp``: the VALUE is the
    first token after the labels, not the last token on the line).
    Malformed samples warn and are skipped — this runs inside the
    emitted controller loop and must fail open, never crash it."""
    total = 0.0
    bad = 0
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        if not line.startswith(name):
            continue
        parts = _split_labels(line, name)
        if parts is None:
            if line.startswith(name + "{"):
                bad += 1  # this family, unterminated labels
            continue
        _, rest = parts
        fields = rest.split()
        try:
            total += float(fields[0])
        except (IndexError, ValueError):
            bad += 1
            continue
    if bad:
        log.warning("%d malformed exposition line(s) for %s skipped",
                    bad, name)
    return total


def parse_counter_by_label(text: str, name: str,
                           label: str) -> dict[str, float]:
    """Per-label-value sums of ``name`` — the per-tenant split of the
    same page :func:`parse_counter_total` aggregates. Samples missing
    the label fold into ``""``; malformed samples warn and are skipped
    (same fail-open contract)."""
    import re

    out: dict[str, float] = {}
    bad = 0
    pat = re.compile(label + r'="((?:[^"\\]|\\.)*)"')
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#") or not line.startswith(name):
            continue
        parts = _split_labels(line, name)
        if parts is None:
            if line.startswith(name + "{"):
                bad += 1
            continue
        labels_raw, rest = parts
        fields = rest.split()
        try:
            value = float(fields[0])
        except (IndexError, ValueError):
            bad += 1
            continue
        m = pat.search(labels_raw)
        key = ""
        if m:
            key = (m.group(1).replace('\\"', '"')
                   .replace("\\n", "\n").replace("\\\\", "\\"))
        out[key] = out.get(key, 0.0) + value
    if bad:
        log.warning("%d malformed exposition line(s) for %s skipped",
                    bad, name)
    return out


def scrape_admitted_tokens(url: str, timeout_s: float = 5.0) -> float | None:
    """Net admitted-token counter from the router's /metrics page, or
    None on any failure (the loop skips the sample rather than feeding
    the forecaster a zero that reads as demand collapse)."""
    import urllib.request
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            text = resp.read().decode("utf-8", "replace")
        return (parse_counter_total(text, ADMITTED_COUNTER)
                - parse_counter_total(text, UNUSED_COUNTER))
    except Exception as err:  # noqa: BLE001 - scrape is best-effort
        log.warning("metrics scrape %s failed: %s", url, err)
        return None


def scrape_tenant_admitted_tokens(
        url: str, timeout_s: float = 5.0) -> dict[str, float] | None:
    """Per-tenant net admitted-token counters from the router's
    /metrics page (admitted minus the unused corrections), or None on
    any failure. Negative per-tenant nets clamp to 0 — a correction
    outpacing admissions is not negative demand."""
    import urllib.request
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            text = resp.read().decode("utf-8", "replace")
        admitted = parse_counter_by_label(text, ADMITTED_COUNTER, "tenant")
        unused = parse_counter_by_label(text, UNUSED_COUNTER, "tenant")
        return {tenant: max(0.0, value - unused.get(tenant, 0.0))
                for tenant, value in admitted.items()}
    except Exception as err:  # noqa: BLE001 - scrape is best-effort
        log.warning("tenant metrics scrape %s failed: %s", url, err)
        return None


class KubeScaleActuator:
    """PATCH the target Deployment's scale subresource through the
    in-cluster API (service-account token + CA bundle). Fail-open:
    any API error logs and returns False — the controller keeps
    forecasting and exporting gauges, which is its observe-only mode
    anyway. Only engaged when ``M2KT_AUTOSCALE_ACTUATE=1``."""

    TOKEN = "/var/run/secrets/kubernetes.io/serviceaccount/token"  # noqa: S105
    CA = "/var/run/secrets/kubernetes.io/serviceaccount/ca.crt"
    NS = "/var/run/secrets/kubernetes.io/serviceaccount/namespace"

    def __init__(self, deployment: str, namespace: str | None = None):
        self.deployment = deployment
        self.namespace = namespace or self._default_ns()

    def _default_ns(self) -> str:
        try:
            with open(self.NS, encoding="utf-8") as fh:
                return fh.read().strip() or "default"
        except OSError:
            return "default"

    def scale_to(self, target: int) -> bool:
        import ssl
        import urllib.request
        host = os.environ.get("KUBERNETES_SERVICE_HOST", "")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        if not host:
            log.warning("no KUBERNETES_SERVICE_HOST; cannot actuate")
            return False
        try:
            with open(self.TOKEN, encoding="utf-8") as fh:
                token = fh.read().strip()
            ctx = ssl.create_default_context(cafile=self.CA)
            url = (f"https://{host}:{port}/apis/apps/v1/namespaces/"
                   f"{self.namespace}/deployments/{self.deployment}/scale")
            body = json.dumps(
                {"spec": {"replicas": int(target)}}).encode("utf-8")
            req = urllib.request.Request(
                url, data=body, method="PATCH",
                headers={
                    "Authorization": f"Bearer {token}",
                    "Content-Type": "application/merge-patch+json",
                })
            with urllib.request.urlopen(req, timeout=10, context=ctx):
                return True
        except Exception as err:  # noqa: BLE001 - observe-only fallback
            log.warning("scale patch %s/%s -> %d failed: %s",
                        self.namespace, self.deployment, target, err)
            return False


def run_controller(loops: int | None = None,
                   registry: Registry | None = None,
                   clock=time.monotonic, sleep=time.sleep) -> int:
    """Main loop of the emitted autoscaler Deployment: scrape the
    router counters, forecast, decide, export gauges, optionally patch
    the decode Deployment's scale. Runs forever in the pod (``loops``
    bounds it for tests). Returns the last target.

    The forecast is per-tenant (closing the ROADMAP item-2 leftover):
    each tenant's net admitted-token counter feeds its own
    Holt-Winters forecaster, the controller scales on the sum, and the
    split exports as ``m2kt_autoscale_tenant_forecast_tps{tenant}``.
    When the page carries no tenant labels the whole rate lands on the
    ``default`` tenant, which degrades to exactly the old aggregate
    behavior."""
    from move2kube_tpu.obs.slo import DEFAULT_TENANT, max_tenants

    cfg = AutoscaleConfig.from_env()
    url = os.environ.get(METRICS_URL_ENV, "").strip()
    target_deploy = os.environ.get(TARGET_ENV, "").strip()
    if not url:
        raise SystemExit(f"{METRICS_URL_ENV} is required for the "
                         "autoscaler role")
    reg = registry or default_registry()
    window_s = max(30.0, 2 * cfg.interval_s)
    forecaster = TenantDemandForecaster(clock=clock,
                                        max_tenants=max_tenants())
    demand = TenantCounterDemand(forecaster, clock=clock,
                                 window_s=window_s)
    scaler = PredictiveAutoscaler(
        forecaster, lambda: replica_capacity_tps(default=100.0),
        config=cfg, clock=clock, registry=reg)
    g_tenant_forecast = reg.gauge(
        "m2kt_autoscale_tenant_forecast_tps",
        "Forecast admitted-token demand per tenant at now + lead",
        labels=("tenant",), max_series=max_tenants() + 1)
    actuator = None
    if target_deploy and os.environ.get(ACTUATE_ENV, "").strip() == "1":
        actuator = KubeScaleActuator(target_deploy)
    current = cfg.min_replicas
    n = 0
    while loops is None or n < loops:
        n += 1
        per_tenant = scrape_tenant_admitted_tokens(url)
        if per_tenant is None:
            # labeled scrape failed outright; the aggregate fallback
            # keeps the controller fed through a degraded page
            value = scrape_admitted_tokens(url)
            per_tenant = None if value is None else {DEFAULT_TENANT: value}
        if per_tenant is not None:
            if not per_tenant:
                per_tenant = {DEFAULT_TENANT: 0.0}
            demand.tick(per_tenant)
            for tenant, tps in forecaster.forecast_by_tenant(
                    cfg.lead_time_s).items():
                g_tenant_forecast.labels(tenant=tenant).set(tps)
            new = scaler.decide(current)
            if new != current and actuator is not None:
                if actuator.scale_to(new):
                    current = new
            elif actuator is None:
                current = new  # shadow mode tracks its own decision
        if loops is None or n < loops:
            sleep(cfg.interval_s)
    return current
