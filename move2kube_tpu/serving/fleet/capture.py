"""Fleet usage aggregation: chargeback and capture→replay.

This is the consumer side of the pod-local usage ledger
(``obs/ledger.py``). The aggregator scrapes ``GET /usage`` on every
fleet role (or reads the ``m2kt-usage.jsonl`` flight-recorder flushes
of pods that died between scrapes) and turns the snapshot rings into
the two artifacts the ledger exists for:

**Chargeback** (:func:`chargeback`): per-tenant TPU-seconds and a
$-proxy cost per token. Allocation is deliberately simple and stated:
each pod's wall time is split across tenants by their share of that
pod's *net* tokens (admitted minus unused corrections on routers,
prompt+decode histogram mass on engines); a pod with zero attributable
tokens bills to ``unattributed`` — so the raw TPU-seconds column sums
to exactly ``pods × wall`` and the bench gate can check the identity to
1%. A second, attainment-weighted column discounts each tenant's
seconds by its measured SLO attainment (capacity burned while missing
the SLO is the *operator's* cost, not the tenant's) — that column is
what ``m2kt_tenant_tpu_seconds_total`` exports. Dollar figures join the
``obs/costmodel`` chip table with public on-demand list prices; they
are a *proxy* for relative cost, not a bill.

**Capture** (:func:`build_capture`): the same snapshot deltas re-binned
into a versioned trace schema (``m2kt-capture/v1``): per-tenant
arrival/token counts per time bin plus prompt/output length and
latency histogram snapshots. :class:`CapturedTrace` replays a capture
as a drop-in for the simulator's synthetic diurnal
:class:`~move2kube_tpu.serving.fleet.sim.Trace` — arrivals placed in
their recorded bins, lengths drawn from the recorded per-tenant
histograms, service times from the recorded latency shape — which
closes the loop the simulator left open: policies are judged on the
traffic the fleet actually saw, and :func:`fidelity` proves the replay
reproduces the measured aggregate rate and per-tenant token shares
before anyone trusts a verdict from it.
"""

from __future__ import annotations

import json
import logging
import math
import os
import threading
import time
import urllib.request
from dataclasses import dataclass

from move2kube_tpu.obs.costmodel import chip_spec
from move2kube_tpu.obs.ledger import hist_from_doc
from move2kube_tpu.obs.metrics import Registry, default_registry

log = logging.getLogger("m2kt.fleet.capture")

CAPTURE_SCHEMA = "m2kt-capture/v1"
UNATTRIBUTED = "unattributed"

# public on-demand list prices, $/chip-hour (us-central, mid-2025) —
# a relative-cost proxy keyed on ChipSpec.name, not a bill
DOLLARS_PER_CHIP_HOUR = {
    "v4": 3.22,
    "v5e": 1.20,
    "v5p": 4.20,
    "v6e": 2.70,
}


def scrape_usage(url: str, timeout_s: float = 5.0) -> dict | None:
    """Fetch one pod's ``/usage`` document. Fail-open: any failure
    (refused, timeout, bad JSON) warns and returns None — a missing pod
    must degrade the report, never crash the aggregator."""
    try:
        if not url.rstrip("/").endswith("/usage"):
            url = url.rstrip("/") + "/usage"
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            doc = json.loads(resp.read().decode("utf-8"))
        return doc if isinstance(doc, dict) else None
    except Exception as e:  # noqa: BLE001 - aggregator is best-effort
        log.warning("usage scrape of %s failed: %s", url, e)
        return None


# ---------------------------------------------------------------------------
# snapshot arithmetic
# ---------------------------------------------------------------------------


def _hist_count(field) -> float:
    if isinstance(field, dict):
        return float(field.get("count", 0))
    return 0.0


def _hist_sum(field) -> float:
    if isinstance(field, dict):
        return float(field.get("sum", 0.0))
    return 0.0


def _tenant_tokens(fields: dict) -> float | None:
    """Cumulative net-token reading for one tenant in one snapshot, by
    source priority: router net admission (admitted − unused
    corrections), else engine request-shape histogram mass
    (prompt + generated tokens of completed requests)."""
    if "admitted_tokens" in fields:
        return max(0.0, float(fields.get("admitted_tokens", 0.0))
                   - float(fields.get("unused_tokens", 0.0)))
    if "prompt_tokens" in fields or "decode_tokens" in fields:
        return (_hist_sum(fields.get("prompt_tokens"))
                + _hist_sum(fields.get("decode_tokens")))
    return None


def pod_summary(doc: dict) -> dict:
    """Reduce one pod's snapshot ring to what chargeback and capture
    need: wall span, cumulative per-tenant tokens/requests at first and
    last snapshot, last-seen attainment, last-seen histograms."""
    snaps = [s for s in doc.get("snapshots", []) if isinstance(s, dict)]
    out = {
        "host": doc.get("host", "?"),
        "role": doc.get("role", "?"),
        "pid": doc.get("pid", 0),
        "wall_s": 0.0,
        "snapshots": len(snaps),
        "tenants": {},
    }
    if not snaps:
        return out
    first, last = snaps[0], snaps[-1]
    out["wall_s"] = max(0.0, float(last.get("t_mono", 0.0))
                        - float(first.get("t_mono", 0.0)))
    names = set(first.get("tenants", {})) | set(last.get("tenants", {}))
    for name in names:
        f0 = first.get("tenants", {}).get(name, {})
        f1 = last.get("tenants", {}).get(name, {})
        tok0, tok1 = _tenant_tokens(f0), _tenant_tokens(f1)
        requests = max(0.0, _hist_count(f1.get("decode_tokens"))
                       - _hist_count(f0.get("decode_tokens"))) or \
            max(0.0, float(f1.get("requests", 0.0))
                - float(f0.get("requests", 0.0)))
        out["tenants"][name] = {
            "tokens": max(0.0, (tok1 or 0.0) - (tok0 or 0.0)),
            "requests": requests,
            "attainment": float(f1.get("attainment", 1.0)),
            "hists": {k: f1[k] for k in ("prompt_tokens", "decode_tokens",
                                         "ttft", "token_latency")
                      if isinstance(f1.get(k), dict)},
        }
    return out


# ---------------------------------------------------------------------------
# chargeback
# ---------------------------------------------------------------------------


def chargeback(docs: list[dict], accelerator: str = "",
               chips_per_replica: int = 1) -> dict:
    """Join scraped usage docs with the chip cost table into the
    per-tenant chargeback report.

    Invariant the bench gates: the raw ``tpu_seconds`` column sums to
    exactly Σ pod walls (each pod's wall is fully allocated — tenants
    by token share, the remainder to ``unattributed``)."""
    spec, assumed = chip_spec(accelerator)
    price = DOLLARS_PER_CHIP_HOUR.get(spec.name, 0.0)
    pods = [pod_summary(d) for d in docs if isinstance(d, dict)]
    tenants: dict[str, dict] = {}

    def row(name: str) -> dict:
        return tenants.setdefault(name, {
            "tokens": 0.0, "requests": 0.0, "tpu_seconds": 0.0,
            "tpu_seconds_weighted": 0.0, "_att_wsum": 0.0})

    total_wall = 0.0
    for pod in pods:
        wall = pod["wall_s"]
        total_wall += wall
        toks = {n: t["tokens"] for n, t in pod["tenants"].items()}
        total = sum(toks.values())
        if total <= 0:
            row(UNATTRIBUTED)["tpu_seconds"] += wall
            row(UNATTRIBUTED)["tpu_seconds_weighted"] += wall
            continue
        for name, t in pod["tenants"].items():
            share = t["tokens"] / total
            r = row(name)
            seconds = share * wall
            r["tokens"] += t["tokens"]
            r["requests"] += t["requests"]
            r["tpu_seconds"] += seconds
            r["tpu_seconds_weighted"] += seconds * t["attainment"]
            r["_att_wsum"] += t["attainment"] * t["tokens"]
    for name, r in tenants.items():
        r["attainment"] = (r.pop("_att_wsum") / r["tokens"]
                           if r["tokens"] > 0 else 1.0)
        r["dollars"] = (r["tpu_seconds"] / 3600.0) * price \
            * max(1, int(chips_per_replica))
        r["dollars_per_mtok"] = (r["dollars"] / (r["tokens"] / 1e6)
                                 if r["tokens"] > 0 else 0.0)
    return {
        "schema": "m2kt-chargeback/v1",
        "generated_unix": time.time(),
        "accelerator": spec.name,
        "accelerator_assumed": assumed,
        "dollars_per_chip_hour": price,
        "chips_per_replica": max(1, int(chips_per_replica)),
        "pods": [{k: p[k] for k in ("host", "role", "pid", "wall_s",
                                    "snapshots")} for p in pods],
        "total_wall_s": total_wall,
        "total_tpu_seconds": sum(r["tpu_seconds"]
                                 for r in tenants.values()),
        "tenants": tenants,
    }


def export_tenant_seconds(report: dict,
                          registry: Registry | None = None) -> None:
    """Publish the attainment-weighted per-tenant TPU-seconds as
    ``m2kt_tenant_tpu_seconds_total`` (counter: each aggregation round
    adds the interval it just accounted)."""
    reg = registry if registry is not None else default_registry()
    fam = reg.counter(
        "m2kt_tenant_tpu_seconds_total",
        "Attainment-weighted TPU-seconds attributed to each tenant by "
        "the usage aggregator", labels=("tenant",))
    for name, r in report.get("tenants", {}).items():
        fam.labels(tenant=name).inc(max(0.0, r["tpu_seconds_weighted"]))


def render_report_markdown(report: dict) -> str:
    lines = [
        "# m2kt usage / chargeback report",
        "",
        f"- accelerator: **{report['accelerator']}**"
        + (" (assumed)" if report.get("accelerator_assumed") else "")
        + f" at ${report['dollars_per_chip_hour']:.2f}/chip-hour"
        + f" × {report['chips_per_replica']} chip(s)/replica",
        f"- pods: {len(report.get('pods', []))}, total wall "
        f"{report['total_wall_s']:.1f}s, allocated TPU-seconds "
        f"{report['total_tpu_seconds']:.1f}",
        "",
        "| tenant | tokens | requests | TPU-seconds | attainment-"
        "weighted | attainment | $ | $/Mtok |",
        "|---|---|---|---|---|---|---|---|",
    ]
    tenants = report.get("tenants", {})
    for name in sorted(tenants,
                       key=lambda n: -tenants[n]["tpu_seconds"]):
        r = tenants[name]
        lines.append(
            f"| {name} | {r['tokens']:.0f} | {r['requests']:.0f} "
            f"| {r['tpu_seconds']:.2f} | {r['tpu_seconds_weighted']:.2f} "
            f"| {r['attainment']:.3f} | {r['dollars']:.4f} "
            f"| {r['dollars_per_mtok']:.3f} |")
    return "\n".join(lines) + "\n"


def write_report(report: dict, out_dir: str) -> dict:
    """Write ``m2kt-usage-report.{json,md}`` (atomic, best-effort)."""
    paths = {}
    os.makedirs(out_dir, exist_ok=True)
    for ext, body in (("json", json.dumps(report, indent=1,
                                          sort_keys=True) + "\n"),
                      ("md", render_report_markdown(report))):
        path = os.path.join(out_dir, f"m2kt-usage-report.{ext}")
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(body)
        os.replace(tmp, path)
        paths[ext] = path
    return paths


# ---------------------------------------------------------------------------
# capture: snapshot rings -> versioned trace
# ---------------------------------------------------------------------------


def _merge_hist(into: dict | None, doc: dict | None) -> dict | None:
    """Sum two hist docs bucket-wise (same edges — same code version);
    on an edge mismatch keep the heavier one rather than corrupt."""
    if doc is None:
        return into
    if into is None:
        return dict(doc)
    if list(into.get("buckets", ())) != list(doc.get("buckets", ())):
        return into if _hist_count(into) >= _hist_count(doc) else dict(doc)
    return {
        "buckets": list(into["buckets"]),
        "counts": [a + b for a, b in zip(into["counts"], doc["counts"])],
        "sum": into["sum"] + doc["sum"],
        "count": into["count"] + doc["count"],
    }


def build_capture(docs: list[dict], bin_s: float = 60.0) -> dict:
    """Re-bin the fleet's snapshot rings into the replayable capture.

    Per tenant: arrivals and net tokens per ``bin_s`` wall-clock bin
    (consecutive-snapshot deltas, credited to the later snapshot's
    bin), plus the last-seen prompt/output length histograms merged
    across pods. Fleet-level: merged TTFT and per-token latency
    histograms, so the replay draws service times from the measured
    latency shape."""
    bin_s = float(bin_s)
    stamps = [float(s["t_unix"])
              for d in docs if isinstance(d, dict)
              for s in d.get("snapshots", []) if "t_unix" in s]
    if not stamps:
        return {"schema": CAPTURE_SCHEMA, "bin_s": bin_s,
                "duration_s": 0.0, "captured_unix": time.time(),
                "tenants": {}, "latency": {}}
    t0 = min(stamps)
    n_bins = max(1, int(math.ceil((max(stamps) - t0) / bin_s)) or 1)
    tenants: dict[str, dict] = {}
    latency: dict[str, dict | None] = {"ttft": None, "token_latency": None}

    def trow(name: str) -> dict:
        return tenants.setdefault(name, {
            "arrivals_per_bin": [0.0] * n_bins,
            "tokens_per_bin": [0.0] * n_bins,
            "prompt_tokens": None, "decode_tokens": None})

    for doc in docs:
        if not isinstance(doc, dict):
            continue
        snaps = [s for s in doc.get("snapshots", [])
                 if isinstance(s, dict) and "t_unix" in s]
        for prev, cur in zip(snaps, snaps[1:]):
            b = min(n_bins - 1,
                    max(0, int((float(cur["t_unix"]) - t0) / bin_s)))
            pt, ct = prev.get("tenants", {}), cur.get("tenants", {})
            for name in set(pt) | set(ct):
                f0, f1 = pt.get(name, {}), ct.get(name, {})
                tok0, tok1 = _tenant_tokens(f0), _tenant_tokens(f1)
                if tok1 is not None:
                    trow(name)["tokens_per_bin"][b] += max(
                        0.0, tok1 - (tok0 or 0.0))
                arr = max(0.0, _hist_count(f1.get("decode_tokens"))
                          - _hist_count(f0.get("decode_tokens"))) or \
                    max(0.0, float(f1.get("requests", 0.0))
                        - float(f0.get("requests", 0.0)))
                trow(name)["arrivals_per_bin"][b] += arr
        if snaps:
            for name, fields in snaps[-1].get("tenants", {}).items():
                for key in ("prompt_tokens", "decode_tokens"):
                    if isinstance(fields.get(key), dict):
                        trow(name)[key] = _merge_hist(
                            tenants[name][key], fields[key])
                for key in ("ttft", "token_latency"):
                    if isinstance(fields.get(key), dict):
                        latency[key] = _merge_hist(
                            latency[key], fields[key])
    # drop tenants with no recorded traffic at all
    tenants = {n: t for n, t in tenants.items()
               if sum(t["tokens_per_bin"]) > 0
               or sum(t["arrivals_per_bin"]) > 0}
    return {
        "schema": CAPTURE_SCHEMA,
        "captured_unix": time.time(),
        "t0_unix": t0,
        "bin_s": bin_s,
        "duration_s": n_bins * bin_s,
        "tenants": tenants,
        "latency": {k: v for k, v in latency.items() if v is not None},
    }


# ---------------------------------------------------------------------------
# replay: capture -> simulator trace
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class CapturedTraceConfig:
    """The slice of TraceConfig the event loop reads, sourced from a
    capture instead of synthetic knobs."""

    duration_s: float
    tick_s: float
    tenants: int
    seed: int = 0
    requests_total: int = 0


class CapturedTrace:
    """A production capture replayed as a simulator trace (duck-types
    :class:`~move2kube_tpu.serving.fleet.sim.Trace`).

    Arrivals land uniformly inside their recorded wall-clock bin with
    their recorded per-tenant counts — the empirical rate curve, not a
    fitted sinusoid. Request shapes are drawn per tenant from the
    recorded length histograms; service times from the recorded latency
    shape (TTFT histogram as the prefill proxy — it includes queue
    wait, a stated conservative bias) unless an explicit ``latency``
    model is passed. One seed fixes every sample.
    """

    def __init__(self, capture: dict, latency=None, seed: int = 0,
                 rate_scale: float = 1.0) -> None:
        import numpy as np

        from move2kube_tpu.serving.fleet import sim

        if capture.get("schema") != CAPTURE_SCHEMA:
            raise ValueError(
                f"unsupported capture schema {capture.get('schema')!r} "
                f"(want {CAPTURE_SCHEMA})")
        bin_s = float(capture["bin_s"])
        duration = float(capture["duration_s"])
        n_bins = max(1, int(round(duration / bin_s)))
        # tenant index order: heaviest first, matching the simulator's
        # zipf convention so tenant-0 is always the big one
        items = sorted(capture.get("tenants", {}).items(),
                       key=lambda kv: -sum(kv[1]["tokens_per_bin"]))
        self.tenant_names = [name for name, _ in items]
        rng = np.random.default_rng(seed)
        arrival, tenant_ix, prompt, decode = [], [], [], []
        agg_tokens_per_bin = np.zeros(n_bins)
        for ti, (name, rec) in enumerate(items):
            arrs = np.asarray(rec["arrivals_per_bin"], dtype=np.float64)
            toks = np.asarray(rec["tokens_per_bin"], dtype=np.float64)
            arrs = arrs[:n_bins]
            agg_tokens_per_bin[:len(toks[:n_bins])] += toks[:n_bins]
            p_snap = (hist_from_doc(rec["prompt_tokens"])
                      if rec.get("prompt_tokens") else None)
            d_snap = (hist_from_doc(rec["decode_tokens"])
                      if rec.get("decode_tokens") else None)
            p_sample = sim._snapshot_sampler(p_snap) if p_snap else None
            d_sample = sim._snapshot_sampler(d_snap) if d_snap else None
            # mean lengths as fallback when a tenant recorded tokens
            # but no shape histogram (router-only fleets)
            total_arr = arrs.sum()
            mean_tok = (toks.sum() / total_arr) if total_arr > 0 else 0.0
            t_prompt, t_decode = [], []
            for b in range(len(arrs)):
                k = int(round(arrs[b] * rate_scale))
                if k <= 0:
                    continue
                arrival.append(b * bin_s + rng.random(k) * bin_s)
                tenant_ix.append(np.full(k, ti, dtype=np.int64))
                if p_sample is not None:
                    t_prompt.append(np.maximum(1.0, p_sample(k, rng)))
                else:
                    t_prompt.append(np.full(k, max(1.0, mean_tok / 2.0)))
                if d_sample is not None:
                    t_decode.append(np.maximum(1.0, d_sample(k, rng)))
                else:
                    t_decode.append(np.full(k, max(1.0, mean_tok / 2.0)))
            if not t_prompt:
                continue
            tp = np.concatenate(t_prompt)
            td = np.concatenate(t_decode)
            # the histograms supply the length SHAPE; the counter deltas
            # supply the token MASS. Rescale so this tenant's replayed
            # total matches its recorded total exactly — inverse-CDF
            # sampling alone drifts the mean by the in-bucket
            # interpolation error, which the 10% rate gate would eat.
            recorded = toks.sum() * rate_scale
            sampled = tp.sum() + td.sum()
            if recorded > 0 and sampled > 0:
                scale = recorded / sampled
                tp *= scale
                td *= scale
            prompt.append(tp)
            decode.append(td)
        if not arrival:
            raise ValueError("capture contains no replayable arrivals")
        arrival = np.concatenate(arrival)
        order = np.argsort(arrival, kind="stable")
        self.arrival_s = arrival[order]
        self.tenant = np.concatenate(tenant_ix)[order]
        prompt = np.concatenate(prompt)[order]
        decode = np.concatenate(decode)[order]
        self.tokens = (prompt + decode).astype(np.float64)
        self.n = int(self.arrival_s.size)
        self.distinct_users = self.n  # capture carries no user ids
        if latency is None:
            lat = capture.get("latency", {})
            if lat.get("ttft") and lat.get("token_latency"):
                latency = sim.LatencyModel.from_histograms(
                    hist_from_doc(lat["ttft"]),
                    hist_from_doc(lat["token_latency"]))
            else:
                latency = sim.LatencyModel.synthetic()
        prefill_s, per_token_s = latency.sample(self.n, rng)
        self.prefill_s = prefill_s
        self.service_s = prefill_s + decode * per_token_s
        self.cfg = CapturedTraceConfig(
            duration_s=duration, tick_s=bin_s,
            tenants=len(self.tenant_names), seed=seed,
            requests_total=self.n)
        bins = np.minimum((self.arrival_s / bin_s).astype(np.int64),
                          n_bins - 1)
        self.tokens_per_tick = np.bincount(
            bins, weights=self.tokens, minlength=n_bins)
        self.mean_slot_tps = float(
            self.tokens.mean() / max(1e-9, self.service_s.mean()))
        self._shape_t = (np.arange(n_bins) + 0.5) * bin_s
        shape = agg_tokens_per_bin / max(1e-9, agg_tokens_per_bin.mean())
        self._shape = np.maximum(0.05, shape)

    def rate_shape(self, t):
        """Empirical relative rate: the recorded per-bin token curve,
        interpolated (and periodically extended — the predictive
        policy's warm-up asks about yesterday)."""
        import numpy as np

        t = np.asarray(t, dtype=np.float64) % max(
            1e-9, self.cfg.duration_s)
        return np.interp(t, self._shape_t, self._shape)


def fidelity(capture: dict, trace) -> dict:
    """Replay-fidelity check the bench gates: relative error of the
    aggregate token rate plus the max absolute per-tenant token-share
    error between the capture and a (replayed) trace."""
    rec_tokens = {name: float(sum(rec["tokens_per_bin"]))
                  for name, rec in capture.get("tenants", {}).items()}
    rec_total = sum(rec_tokens.values())
    duration = max(1e-9, float(capture.get("duration_s", 0.0)))
    rep_total = float(trace.tokens.sum())
    rate_err = abs(rep_total - rec_total) / max(1e-9, rec_total)
    names = getattr(trace, "tenant_names",
                    [f"tenant-{i}" for i in range(trace.cfg.tenants)])
    rep_tokens = {}
    for ti, name in enumerate(names):
        mask = trace.tenant == ti
        rep_tokens[name] = float(trace.tokens[mask].sum())
    share_err = {}
    for name in set(rec_tokens) | set(rep_tokens):
        rec_share = rec_tokens.get(name, 0.0) / max(1e-9, rec_total)
        rep_share = rep_tokens.get(name, 0.0) / max(1e-9, rep_total)
        share_err[name] = abs(rec_share - rep_share)
    return {
        "recorded_tokens": rec_total,
        "replayed_tokens": rep_total,
        "recorded_tps": rec_total / duration,
        "replayed_tps": rep_total / duration,
        "rate_err": rate_err,
        "share_err": share_err,
        "max_share_err": max(share_err.values()) if share_err else 0.0,
    }


# ---------------------------------------------------------------------------
# aggregator: the scrape loop the autoscaler role runs
# ---------------------------------------------------------------------------

USAGE_SCRAPE_URLS_ENV = "M2KT_USAGE_SCRAPE_URLS"
USAGE_SCRAPE_INTERVAL_ENV = "M2KT_USAGE_SCRAPE_INTERVAL_S"
USAGE_OUT_DIR_ENV = "M2KT_USAGE_OUT_DIR"
DEFAULT_SCRAPE_INTERVAL_S = 60.0


def write_capture(capture: dict, out_dir: str) -> str:
    """Write ``m2kt-capture.json`` (atomic)."""
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, "m2kt-capture.json")
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(capture, f, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


def load_capture(path: str) -> dict:
    """Read a capture doc back; raises ValueError on a schema mismatch
    (an old aggregator's file must not silently replay wrong)."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if doc.get("schema") != CAPTURE_SCHEMA:
        raise ValueError(
            f"capture schema {doc.get('schema')!r} != {CAPTURE_SCHEMA!r}")
    return doc


class UsageAggregator:
    """Scrape every fleet role's ``/usage``, keep the last good doc per
    pod (a restarting pod degrades to its previous ring, never to a
    hole), and refresh the chargeback report + replay capture on disk
    each cycle. Runs inside the autoscaler role — the one fleet pod
    that already holds the scrape-and-decide loop."""

    def __init__(self, urls, out_dir: str | None = None,
                 accelerator: str = "", chips_per_replica: int = 1,
                 bin_s: float = 60.0, interval_s: float | None = None,
                 registry: Registry | None = None,
                 clock=time.monotonic) -> None:
        self.urls = [u for u in urls if u]
        self.out_dir = out_dir or os.environ.get(
            USAGE_OUT_DIR_ENV,
            os.environ.get("M2KT_METRICS_DIR", "/tmp/m2kt-metrics"))
        self.accelerator = accelerator
        self.chips_per_replica = max(1, int(chips_per_replica))
        self.bin_s = float(bin_s)
        if interval_s is None:
            try:
                interval_s = float(os.environ.get(
                    USAGE_SCRAPE_INTERVAL_ENV, DEFAULT_SCRAPE_INTERVAL_S))
            except ValueError:
                interval_s = DEFAULT_SCRAPE_INTERVAL_S
        self.interval_s = max(1.0, float(interval_s))
        self._registry = (registry if registry is not None
                          else default_registry())
        self._clock = clock
        self._last: dict[str, dict] = {}
        self._stop = threading.Event()
        self._thread = None
        self._scrapes = self._registry.counter(
            "m2kt_usage_scrapes_total",
            "Usage-aggregator scrape attempts", labels=("outcome",))
        self.report: dict | None = None
        self.capture: dict | None = None

    @classmethod
    def from_env(cls, registry: Registry | None = None):
        """Build from ``M2KT_USAGE_SCRAPE_URLS`` (comma-separated pod
        base URLs); None when unset — the aggregator is opt-in per
        deployment because it needs the pod list."""
        spec = os.environ.get(USAGE_SCRAPE_URLS_ENV, "").strip()
        if not spec:
            return None
        return cls([u.strip() for u in spec.split(",") if u.strip()],
                   registry=registry)

    def poll(self) -> dict | None:
        """One scrape+publish cycle; returns the refreshed report."""
        for url in self.urls:
            doc = scrape_usage(url)
            if doc is not None:
                self._last[url] = doc
                self._scrapes.labels("ok").inc()
            else:
                self._scrapes.labels("error").inc()
        docs = list(self._last.values())
        if not docs:
            return None
        self.report = chargeback(docs, accelerator=self.accelerator,
                                 chips_per_replica=self.chips_per_replica)
        export_tenant_seconds(self.report, self._registry)
        self.capture = build_capture(docs, bin_s=self.bin_s)
        try:
            write_report(self.report, self.out_dir)
            write_capture(self.capture, self.out_dir)
        except OSError as e:
            log.warning("usage artifact write failed: %s", e)
        return self.report

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="m2kt-usage-agg")
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.poll()
            except Exception as e:  # noqa: BLE001 - loop must survive
                log.warning("usage aggregation cycle failed: %s", e)

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
