"""Fleet weight plane: streamed P2P checkpoint fan-out.

A replica joining a serving fleet (scale-up, reschedule, spot
replacement) used to pay a full object-store checkpoint read before its
first token. But N identical copies of those exact bytes are already
resident in the peers it is joining — so the weight plane turns every
serving replica into a shard server and every cold replica into a
digest-verifying fetcher:

- :class:`WeightManifest` — the versioned table of contents (per-shard
  sha256 / dtype / shape, keyed by the "/"-joined parameter tree path),
  the same npz+json wire framing as the disagg ``KVHandoff`` so both
  planes share one malformation contract: EVERY bad byte surfaces as
  ``ValueError``, never a zipfile/OS error from a worker thread;
- :func:`encode_shard` / :func:`decode_shard` — one parameter leaf per
  wire message. Quantized leaves (``{"q8","scale"}`` — serving/quant.py)
  flatten into two shards, so what streams between peers is the int8
  payload plus its float scales, not the fp32 original;
- :func:`fetch_from_peers` — the joining side: manifest from the first
  healthy peer, then every shard digest-verified on arrival; a
  corrupted or truncated shard is re-fetched from a *different* peer
  (bounded attempts), a dead peer is dropped for the rest of the fetch,
  and the whole operation is deadline-aware via the router's
  ``X-M2KT-Deadline`` budget. Returns ``None`` when no peer can serve a
  complete verified set — the caller falls back to checkpoint restore
  (``models/checkpoint.restore_variables``).

Fetch outcomes land in ``m2kt_weights_fetch_total{source,reason}``
(source ``peer`` here; the store-fallback caller stamps ``store``) and
the installed version in the engine's ``m2kt_weights_version`` gauge.
"""

from __future__ import annotations

import dataclasses
import hashlib
import io
import json
import time
import urllib.parse
import urllib.request

import numpy as np

from move2kube_tpu.serving.fleet.chaos import ChaosKill

_WIRE_VERSION = 1

# the router's per-hop remaining-seconds budget header; redeclared here
# (string-equal, asserted by tests) so the weight plane never imports
# the router module — serve_tpu's weights listener runs router-free
DEADLINE_HEADER = "X-M2KT-Deadline"

FETCH_REASONS = ("ok", "digest_mismatch", "malformed", "connection",
                 "deadline", "no_peer", "stale", "exhausted", "fallback",
                 "error")


def weights_fetch_counter(registry):
    """The shared fetch-outcome counter — one helper so the peer fetcher
    and the store-fallback caller cannot disagree on name or labels."""
    return registry.counter(
        "m2kt_weights_fetch_total",
        "Weight-plane fetch outcomes by source and reason",
        labels=("source", "reason"), max_series=2 * len(FETCH_REASONS))


def flatten_variables(variables) -> dict[str, np.ndarray]:
    """Flatten a variables pytree (plain nested dicts in this repo) into
    ``{"/".join(path): ndarray}`` shards. Quantized leaves — the
    ``{"q8","scale"}`` dicts quantize_variables leaves behind — flatten
    into their two component arrays like any other subtree."""
    flat: dict[str, np.ndarray] = {}

    def walk(node, prefix):
        if isinstance(node, dict):
            for key, child in node.items():
                walk(child, f"{prefix}/{key}" if prefix else str(key))
            return
        flat[prefix] = np.asarray(node)

    walk(variables, "")
    return flat


def unflatten_variables(flat: dict[str, np.ndarray]) -> dict:
    tree: dict = {}
    for path, arr in flat.items():
        parts = path.split("/")
        node = tree
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = arr
    return tree


def shard_digest(path: str, arr: np.ndarray) -> str:
    """Content digest of one shard: tree path + dtype + shape + raw
    bytes. Computed over the decoded array, not the wire bytes — npz
    compression is not byte-stable across encodes, array content is."""
    arr = np.ascontiguousarray(arr)
    h = hashlib.sha256()
    h.update(path.encode())
    h.update(str(arr.dtype).encode())
    h.update(repr(tuple(arr.shape)).encode())
    h.update(arr.tobytes())
    return h.hexdigest()


def encode_shard(path: str, arr: np.ndarray) -> bytes:
    buf = io.BytesIO()
    np.savez_compressed(
        buf,
        meta=np.frombuffer(
            json.dumps({"v": _WIRE_VERSION, "path": path}).encode(),
            np.uint8),
        arr=np.ascontiguousarray(np.asarray(arr)))
    return buf.getvalue()


def decode_shard(data: bytes) -> tuple[str, np.ndarray]:
    """Parse one wire shard. Same contract as ``KVHandoff.from_bytes``:
    every malformation — truncated npz, garbage meta, missing arrays —
    is a ``ValueError`` the fetcher turns into a different-peer retry."""
    try:
        with np.load(io.BytesIO(data), allow_pickle=False) as z:
            meta = json.loads(z["meta"].tobytes().decode())
            if meta.get("v") != _WIRE_VERSION:
                raise ValueError(
                    f"weight shard wire version {meta.get('v')!r}; "
                    f"this replica speaks {_WIRE_VERSION}")
            return str(meta["path"]), np.asarray(z["arr"])
    except ValueError:
        raise
    except Exception as err:  # noqa: BLE001 - BadZipFile, KeyError, ...
        raise ValueError(f"malformed weight shard: "
                         f"{type(err).__name__}: {err}") from err


@dataclasses.dataclass
class WeightManifest:
    """Versioned table of contents for one replica's resident weights:
    ``shards[path] = {"sha256", "dtype", "shape"}``."""

    version: int
    shards: dict[str, dict]

    @classmethod
    def of(cls, variables, version: int) -> "WeightManifest":
        flat = flatten_variables(variables)
        return cls(version=int(version), shards={
            path: {"sha256": shard_digest(path, arr),
                   "dtype": str(arr.dtype),
                   "shape": list(arr.shape)}
            for path, arr in flat.items()})

    def to_bytes(self) -> bytes:
        meta = {"v": _WIRE_VERSION, "version": self.version,
                "shards": self.shards}
        buf = io.BytesIO()
        np.savez_compressed(
            buf, meta=np.frombuffer(json.dumps(meta).encode(), np.uint8))
        return buf.getvalue()

    @classmethod
    def from_bytes(cls, data: bytes) -> "WeightManifest":
        try:
            with np.load(io.BytesIO(data), allow_pickle=False) as z:
                meta = json.loads(z["meta"].tobytes().decode())
                if meta.get("v") != _WIRE_VERSION:
                    raise ValueError(
                        f"weight manifest wire version {meta.get('v')!r}; "
                        f"this replica speaks {_WIRE_VERSION}")
                shards = meta["shards"]
                if not isinstance(shards, dict) or not shards:
                    raise ValueError("weight manifest carries no shards")
                return cls(version=int(meta["version"]),
                           shards={str(p): dict(s)
                                   for p, s in shards.items()})
        except ValueError:
            raise
        except Exception as err:  # noqa: BLE001
            raise ValueError(f"malformed weight manifest: "
                             f"{type(err).__name__}: {err}") from err


class WeightPlane:
    """The serving side: owns the (possibly int8-quantized) variables a
    replica would hand a joining peer, plus their version and manifest.
    ``install`` re-snapshots after a live swap so peers always stream
    the bytes the engine is actually decoding with."""

    def __init__(self, variables, version: int = 1):
        self.install(variables, version)

    def install(self, variables, version: int) -> None:
        self._flat = flatten_variables(variables)
        self.version = int(version)
        self._manifest = WeightManifest(version=self.version, shards={
            path: {"sha256": shard_digest(path, arr),
                   "dtype": str(arr.dtype),
                   "shape": list(arr.shape)}
            for path, arr in self._flat.items()})

    def manifest(self) -> WeightManifest:
        return self._manifest

    def shard_bytes(self, path: str) -> bytes:
        if path not in self._flat:
            raise ValueError(f"unknown weight shard {path!r}")
        return encode_shard(path, self._flat[path])


class InProcessWeightPeer:
    """A peer handle over an in-process :class:`WeightPlane` — the
    fleet-in-one-process shape tests and the bench use. The chaos
    injector rides the shard path exactly where the HTTP wire would
    corrupt: a ``ChaosKill`` from ``on_shard`` marks the peer dead for
    the rest of the fetch (a pod SIGKILLed mid-stream answers nothing,
    not garbage)."""

    def __init__(self, name: str, plane: WeightPlane, chaos=None):
        self.name = name
        self.plane = plane
        self.chaos = chaos
        self._dead = False

    def _check(self) -> None:
        if self._dead:
            raise ConnectionError(f"{self.name}: peer is dead")

    def manifest_bytes(self, deadline_s=None) -> bytes:
        self._check()
        return self.plane.manifest().to_bytes()

    def shard(self, path: str, deadline_s=None) -> bytes:
        self._check()
        data = self.plane.shard_bytes(path)
        if self.chaos is not None:
            try:
                data = self.chaos.on_shard(self.name, path, data)
            except ChaosKill:
                self._dead = True
                raise ConnectionError(
                    f"{self.name}: peer died mid-stream") from None
        return data


class HttpWeightPeer:
    """A peer handle over the serve template's weights listener
    (``GET /weights/manifest`` and ``GET /weights/<quoted-path>`` on
    ``M2KT_WEIGHTS_PORT``). The remaining deadline budget rides the
    same ``X-M2KT-Deadline`` header as every other fleet hop and also
    caps the socket timeout."""

    def __init__(self, name: str, base_url: str, timeout_s: float = 10.0):
        self.name = name
        self.base_url = base_url.rstrip("/")
        self.timeout_s = timeout_s

    def _get(self, tail: str, deadline_s=None) -> bytes:
        req = urllib.request.Request(self.base_url + tail)
        timeout = self.timeout_s
        if deadline_s is not None:
            req.add_header(DEADLINE_HEADER, f"{deadline_s:.3f}")
            timeout = max(0.001, min(timeout, deadline_s))
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.read()

    def manifest_bytes(self, deadline_s=None) -> bytes:
        return self._get("/weights/manifest", deadline_s)

    def shard(self, path: str, deadline_s=None) -> bytes:
        return self._get("/weights/" + urllib.parse.quote(path, safe=""),
                         deadline_s)


def peers_from_env(spec: str | None = None) -> list[HttpWeightPeer]:
    """``M2KT_WEIGHTS_PEERS`` — comma list of ``host:port`` weights
    listeners (the decode role's headless Service DNS fans one name out
    to every pod IP at resolve time; unresolvable names still become
    peers and fail as ``connection`` at fetch time)."""
    import os
    import socket

    raw = spec if spec is not None else os.environ.get(
        "M2KT_WEIGHTS_PEERS", "")
    peers: list[HttpWeightPeer] = []
    for entry in [e.strip() for e in raw.split(",") if e.strip()]:
        host, _, port = entry.rpartition(":")
        try:
            infos = socket.getaddrinfo(host, int(port),
                                       type=socket.SOCK_STREAM)
        except (OSError, ValueError):
            infos = []
        addrs = sorted({i[4][0] for i in infos})
        if not addrs:
            peers.append(HttpWeightPeer(entry, f"http://{entry}"))
        for addr in addrs:
            peers.append(
                HttpWeightPeer(f"{addr}:{port}", f"http://{addr}:{port}"))
    return peers


def fetch_from_peers(peers, registry=None, deadline_s=None,
                     max_attempts_per_shard: int | None = None,
                     want_version: int | None = None):
    """Stream a complete verified weight set from serving peers.

    Returns ``(variables, version)`` or ``None`` when no healthy peer
    set could produce every shard digest-verified inside the deadline —
    the caller then falls back to checkpoint restore. Every attempt
    outcome is counted under ``source="peer"``; a shard that fails
    verification is retried from a *different* peer (the attempt index
    rotates the peer list) up to ``max_attempts_per_shard`` times
    (default ``len(peers) + 1``).

    ``want_version`` pins the fetch to one weight generation — the
    rolling-swap case: the first pod of a swap finds no peer at the new
    version (every peer is ``stale``) and falls back to the store; every
    later pod streams the new generation P2P from the already-swapped
    ones."""
    counter = weights_fetch_counter(registry) if registry is not None \
        else None

    def count(reason: str) -> None:
        if counter is not None:
            counter.labels(source="peer", reason=reason).inc()

    live = [p for p in peers]
    if not live:
        count("no_peer")
        return None
    t_end = None if deadline_s is None else time.monotonic() + deadline_s

    def remaining():
        return None if t_end is None else t_end - time.monotonic()

    manifest = None
    for peer in list(live):
        rem = remaining()
        if rem is not None and rem <= 0:
            count("deadline")
            return None
        try:
            got = WeightManifest.from_bytes(
                peer.manifest_bytes(deadline_s=rem))
        except ValueError:
            count("malformed")
            continue
        except (OSError, ConnectionError):
            count("connection")
            live.remove(peer)
            continue
        if want_version is not None and got.version != want_version:
            # a peer still on the old generation: streaming its resident
            # tree would re-install the weights the swap is replacing
            count("stale")
            continue
        manifest = got
        break
    if manifest is None:
        count("no_peer")
        return None

    budget = (max_attempts_per_shard if max_attempts_per_shard is not None
              else len(peers) + 1)
    flat: dict[str, np.ndarray] = {}
    for i, path in enumerate(sorted(manifest.shards)):
        want = manifest.shards[path]
        arr = None
        attempts = 0
        while arr is None and attempts < budget and live:
            rem = remaining()
            if rem is not None and rem <= 0:
                count("deadline")
                return None
            # rotate: a failed attempt moves to a DIFFERENT peer; the
            # i-offset spreads the initial load across the fleet
            peer = live[(i + attempts) % len(live)]
            attempts += 1
            try:
                got_path, got = decode_shard(
                    peer.shard(path, deadline_s=rem))
                if (got_path != path
                        or shard_digest(path, got) != want["sha256"]):
                    count("digest_mismatch")
                    continue
                arr = got
            except ValueError:
                count("malformed")
            except (OSError, ConnectionError):
                count("connection")
                if peer in live:
                    live.remove(peer)
        if arr is None:
            count("exhausted")
            return None
        flat[path] = arr
    count("ok")
    return unflatten_variables(flat), manifest.version
