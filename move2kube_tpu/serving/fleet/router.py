"""Fleet request router: prefix-affine load balancing over N engine
replicas.

Placement is the fleet's cache policy: the prefix cache
(fleet/prefixcache.py) lives *inside* each replica, so a request only
hits if earlier requests with the same prefix landed on the same
replica. The router therefore routes by **session affinity on the
prefix hash** — rendezvous (highest-random-weight) hashing of the
first ``affinity_tokens`` prompt tokens plus a salt, which keeps the
tenant->replica mapping stable as replicas come and go (only keys
owned by a dead replica move). When the affine replica is unhealthy or
its queue is deep, the router spills to the least-loaded healthy
replica; failures mark the replica down and retry elsewhere (bounded),
and an optional hedge fires a duplicate to the runner-up when the
primary sits on a request too long.

Fault tolerance (docs/ARCHITECTURE.md "fleet resilience"): the router
keeps a per-request **journal** of every token a replica has streamed
(the engine's ``on_token`` hook feeds it); when a replica dies
mid-stream the journaled tokens are force-fed as a prompt suffix on a
surviving replica — greedy decode is deterministic and the prefix
cache makes the re-prefill cheap — so the resumed stream is
byte-identical to an uninterrupted run (``m2kt_router_resumed_total``
counts them by failure reason). Deadlines propagate router -> replica
-> engine via the ``X-M2KT-Deadline`` header carrying the *remaining*
budget in seconds (skew-free: recomputed at each hop), and every wait
in this file derives from it — there are no hard-coded request
timeouts. Replicas drain gracefully (finish in-flight, refuse new,
flip ``/readyz``), and readmission probes back off exponentially with
deterministic jitter so a restarting replica is not thundering-herded.

Everything observable exports as ``m2kt_router_*`` through the PR-5
registry; the HTTP front serves ``/generate`` plus the standard
``/healthz``/``/readyz``/``/metrics`` trio so the emitted router pods
scrape and gate exactly like engine pods.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import queue
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from move2kube_tpu.obs import tracing
from move2kube_tpu.obs.metrics import Registry
from move2kube_tpu.obs.slo import TENANT_HEADER, clean_tenant, max_tenants
from move2kube_tpu.obs.tracing import TRACEPARENT_HEADER
from move2kube_tpu.serving.engine import (
    DeadlineExceeded,
    EngineConfig,
    EngineDraining,
    Request,
    ServingEngine,
)
from move2kube_tpu.serving.sched import AdmissionController, SchedThrottled

# remaining deadline budget in seconds (gRPC-style relative value, not a
# wall-clock timestamp — immune to clock skew between pods); each hop
# recomputes the remainder before forwarding
DEADLINE_HEADER = "X-M2KT-Deadline"


def probe_timeout_s() -> float:
    """Health-probe timeout (NOT a request timeout — request waits all
    derive from the propagated deadline). Probes need their own small
    bound so a hung replica cannot stall the whole probe sweep."""
    try:
        return float(os.environ.get("M2KT_PROBE_TIMEOUT_S", "") or 2.0)
    except ValueError:
        return 2.0


class ReplicaDraining(RuntimeError):
    """The replica refused (or abandoned) the request because it is
    draining. Retryable: the router re-routes to a surviving replica."""


class RequestPreempted(RuntimeError):
    """The engine evicted the request mid-stream to make room for a
    higher-priority tenant (finish_reason ``"preempted"``). Retryable
    like a replica death — the journal makes the retry a token-exact
    resume — but NOT the replica's fault: the router neither marks the
    replica down nor excludes it from the resume placement."""


class ReplicaHTTPError(RuntimeError):
    """A replica answered with a non-2xx status. Carries the status code
    and a body excerpt so the router's mark-down reason and logs say
    *what the replica said*, not just that urllib raised."""

    def __init__(self, replica: str, path: str, status: int, body: str):
        self.replica = replica
        self.path = path
        self.status = int(status)
        self.body_excerpt = (body or "").strip()[:200]
        super().__init__(
            f"{replica}{path}: HTTP {self.status}: "
            f"{self.body_excerpt or '<empty body>'}")


def failure_reason(err: Exception) -> str:
    """A bounded-cardinality label for why a replica call failed —
    the value the reason-labeled retry/mark-down counters carry."""
    if isinstance(err, ReplicaHTTPError):
        return f"http_{err.status}"
    if isinstance(err, RequestPreempted):
        return "preempted"
    if isinstance(err, SchedThrottled):
        return "throttled"
    if isinstance(err, DeadlineExceeded):
        return "deadline"
    if isinstance(err, (ReplicaDraining, EngineDraining)):
        return "draining"
    if isinstance(err, TimeoutError):
        return "timeout"
    if isinstance(err, (urllib.error.URLError, ConnectionError, OSError)):
        return "connection"
    return type(err).__name__.lower()


def prefix_hash(tokens, salt: str = "", k: int = 16) -> int:
    """Stable across processes (the Helm-lifted salt is the only input
    besides the tokens): hash of the first ``k`` prompt tokens."""
    h = hashlib.sha256(salt.encode())
    for t in list(tokens)[:k]:
        h.update(int(t).to_bytes(8, "little", signed=True))
    return int.from_bytes(h.digest()[:8], "little")


def _rendezvous_score(key: int, name: str) -> int:
    h = hashlib.sha256(f"{key}:{name}".encode())
    return int.from_bytes(h.digest()[:8], "little")


class TokenFanout:
    """Bounded-queue stream fan-out between the engine's step thread and
    SSE/streaming subscribers (PR 19).

    The async decode pipeline makes the step thread's time precious —
    every millisecond it spends is a dispatch gap the device idles
    through. So the step thread's half of streaming is ONE non-blocking
    ``put_nowait`` onto a shared bounded queue; a dedicated worker
    thread drains it into per-subscriber bounded buffers. A slow SSE
    consumer can therefore never stall decode: when *its* buffer fills,
    that subscriber alone is cut with a ``lagged`` event and counted
    (``m2kt_serve_fanout_lagged_total``); if the shared queue itself
    fills (the worker is starved), tokens are counted dropped
    (``m2kt_serve_fanout_dropped_total``) rather than blocking the step.

    The router's token *journal* does NOT ride this path — journaling
    stays synchronous in the step thread because the lag-1 exactness
    guarantee ("never journal a token the device hasn't committed, never
    lose one it has") depends on it. Fan-out is best-effort delivery for
    human eyeballs; the journal is the source of truth for resume.

    Subscriber protocol: :meth:`subscribe` returns a ``queue.Queue`` of
    ``("token", int)``, ``("finish", reason)`` and ``("lagged", None)``
    events; ``finish``/``lagged`` are terminal."""

    _STOP = object()

    def __init__(self, registry: Registry | None = None,
                 maxsize: int = 4096, sub_maxsize: int = 256):
        self._q: queue.Queue = queue.Queue(maxsize)
        self._subs: dict[str, list[queue.Queue]] = {}
        self._lock = threading.Lock()
        reg = registry if registry is not None else Registry()
        self._dropped = reg.counter(
            "m2kt_serve_fanout_dropped_total",
            "Stream tokens dropped because the fan-out queue was full "
            "(the step thread never blocks on streaming)")
        self._lagged = reg.counter(
            "m2kt_serve_fanout_lagged_total",
            "Streaming subscribers disconnected for falling behind")
        self._sub_maxsize = max(1, sub_maxsize)
        self._stop = False
        self._thread = threading.Thread(
            target=self._run, name="token-fanout", daemon=True)
        self._thread.start()

    def subscribe(self, rid: str) -> queue.Queue:
        """Register a subscriber for ``rid``'s tokens; call BEFORE the
        request is submitted or the head of the stream may be missed."""
        sub: queue.Queue = queue.Queue(self._sub_maxsize)
        with self._lock:
            self._subs.setdefault(rid, []).append(sub)
        return sub

    def unsubscribe(self, rid: str, sub: queue.Queue) -> None:
        with self._lock:
            subs = self._subs.get(rid)
            if subs and sub in subs:
                subs.remove(sub)
                if not subs:
                    self._subs.pop(rid, None)

    def publish(self, rid: str, tok: int) -> None:
        """Step-thread half: enqueue and return, never block."""
        try:
            self._q.put_nowait(("token", rid, tok))
        except queue.Full:
            self._dropped.inc()

    def finish(self, rid: str, reason: str = "") -> None:
        try:
            self._q.put_nowait(("finish", rid, reason))
        except queue.Full:
            self._dropped.inc()

    def close(self) -> None:
        self._stop = True
        try:
            self._q.put_nowait(self._STOP)
        except queue.Full:
            pass
        self._thread.join(timeout=5)

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is self._STOP or self._stop:
                return
            kind, rid, payload = item
            with self._lock:
                subs = list(self._subs.get(rid, ()))
            for sub in subs:
                try:
                    sub.put_nowait((kind, payload))
                except queue.Full:
                    # this subscriber alone falls off the stream; the
                    # terminal marker jumps the queue so it learns why
                    self._lagged.inc()
                    try:
                        sub.queue.clear()  # make room for the marker
                        sub.put_nowait(("lagged", None))
                    except queue.Full:
                        pass
                    self.unsubscribe(rid, sub)
            if kind == "finish":
                with self._lock:
                    self._subs.pop(rid, None)


class ReplicaHandle:
    """One engine replica as the router sees it. ``deadline_s`` is the
    remaining budget for the call (None = unbounded); ``on_token`` is
    the router's journal hook — called with each token the moment the
    engine emits it, so a mid-stream death loses nothing."""

    name: str = "replica"

    def generate(self, prompt, max_new_tokens: int | None = None,
                 rid: str | None = None, tenant: str = "",
                 traceparent: str = "", deadline_s: float | None = None,
                 on_token=None, adapter: str = "") -> dict:
        raise NotImplementedError

    def queue_depth(self) -> float:
        raise NotImplementedError

    def healthy(self) -> bool:
        raise NotImplementedError


class InProcessReplica(ReplicaHandle):
    """A ServingEngine plus its worker thread, wired like the emitted
    serve template's server loop — used by tests and ``fleet-smoke``
    to stand up a whole fleet in one CPU process. ``fail_next`` makes
    the next N calls raise, for failover/hedging drills."""

    def __init__(self, name: str, engine: ServingEngine):
        self.name = name
        self.engine = engine
        self.fail_next = 0
        self.hold_s = 0.0  # artificial service delay, for hedging drills
        # optional ServingChaos (serving/fleet/chaos.py): hooks into the
        # token stream / generate entry / health checks for fault drills
        self.chaos = None
        # optional TokenFanout: best-effort streaming fan-out off the
        # step thread; the journal callback above it stays synchronous
        self.fanout: TokenFanout | None = None
        self._lock = threading.Lock()
        self._waiters: dict[str, tuple[threading.Event, list]] = {}
        self._token_cbs: dict[str, object] = {}
        self._seq = 0
        self._stop = False
        self._draining = False
        self._thread: threading.Thread | None = None
        self._up = True
        engine.on_token = self._on_token

    def start(self) -> "InProcessReplica":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name=f"replica-{self.name}", daemon=True)
            self._thread.start()
        return self

    def close(self) -> None:
        self._stop = True
        if self._thread is not None:
            self._thread.join(timeout=5)

    def revive(self) -> "InProcessReplica":
        """Bring a crashed/closed replica back — the in-process stand-in
        for a restarted pod: fresh worker thread, same engine."""
        if self._thread is not None and self._thread.is_alive():
            self._stop = True
            self._thread.join(timeout=5)
        self._thread = None
        self._stop = False
        self._draining = False
        self._up = True
        self.engine.undrain()
        return self.start()

    def _on_token(self, rid: str, tok: int) -> None:
        """Engine token-emission fan-out. The caller's journal callback
        runs FIRST so a chaos kill-at-token-N still leaves token N in
        the journal — exactly the state a real mid-stream death leaves."""
        cb = self._token_cbs.get(rid)
        if cb is not None:
            cb(tok)
        if self.chaos is not None:
            self.chaos.on_token(self.name, rid, tok)
        if self.fanout is not None:
            self.fanout.publish(rid, tok)

    def _loop(self) -> None:
        while not self._stop:
            try:
                with self._lock:
                    work = self.engine.has_work()
                    done = self.engine.step() if work else []
            except Exception as err:  # noqa: BLE001 - replica "process" died
                self._crash(err)
                return
            for comp in done:
                self._token_cbs.pop(comp.rid, None)
                waiter = self._waiters.pop(comp.rid, None)
                if waiter is not None:
                    event, box = waiter
                    box.append(comp)
                    event.set()
            if not work:
                time.sleep(0.002)

    def _crash(self, err: Exception) -> None:
        """The worker thread died mid-step (the in-process equivalent of
        a replica pod crashing): go unhealthy and fail every waiter so
        no caller hangs — the router journals + resumes them."""
        self._up = False
        self._stop = True
        waiters, self._waiters = dict(self._waiters), {}
        self._token_cbs.clear()
        for _rid, (event, box) in waiters.items():
            box.append(err)
            event.set()

    def set_healthy(self, up: bool) -> None:
        self._up = up

    def healthy(self) -> bool:
        if self.chaos is not None and not self.chaos.on_probe(self.name):
            return False
        return self._up and not self._stop and not self._draining

    def queue_depth(self) -> float:
        stats = self.engine.stats()
        return float(stats["queue_depth"] + stats["active_slots"])

    def drain(self, grace_s: float = 30.0) -> bool:
        """Graceful drain: stop admitting, keep decoding until in-flight
        work finishes or the grace period lapses. Returns True when the
        replica drained clean. Requests still unfinished at the deadline
        fail their waiters with :class:`ReplicaDraining`, which the
        router treats as retryable — so even an ungraceful cutoff loses
        nothing. ``healthy()`` flips immediately, pulling the replica
        out of the placement ring."""
        self._draining = True
        self.engine.drain()
        deadline = time.perf_counter() + max(0.0, grace_s)
        while time.perf_counter() < deadline:
            if self._stop:
                break  # crashed mid-drain; _crash already failed waiters
            with self._lock:
                busy = self.engine.has_work()
            if not busy and not self._waiters:
                break
            time.sleep(0.002)
        clean = not self._waiters
        waiters, self._waiters = dict(self._waiters), {}
        self._token_cbs.clear()
        for rid, (event, box) in waiters.items():
            box.append(ReplicaDraining(
                f"{self.name}: drained before {rid} finished"))
            event.set()
        return clean

    @staticmethod
    def _result(comp) -> dict:
        if isinstance(comp, Exception):
            raise comp
        if comp.finish_reason == "shed":
            raise DeadlineExceeded(
                f"{comp.rid}: shed while queued (deadline expired)")
        if comp.finish_reason == "preempted":
            # paused work, not an error: every emitted token is already
            # in the caller's journal, so the router resumes it
            raise RequestPreempted(
                f"{comp.rid}: preempted after {len(comp.tokens)} tokens")
        return comp

    def generate(self, prompt, max_new_tokens=None, rid=None,
                 tenant: str = "", traceparent: str = "",
                 deadline_s: float | None = None, on_token=None,
                 adapter: str = "") -> dict:
        if self.fail_next > 0:
            self.fail_next -= 1
            raise RuntimeError(f"{self.name}: injected failure")
        if self._draining:
            raise ReplicaDraining(f"{self.name}: draining, not admitting")
        if self.chaos is not None:
            self.chaos.on_generate(self.name, rid or "")
        if self.hold_s:
            time.sleep(self.hold_s)
        self.start()
        with self._lock:
            self._seq += 1
            rid = rid or f"{self.name}-{self._seq}"
            event, box = threading.Event(), []
            self._waiters[rid] = (event, box)
            if on_token is not None:
                self._token_cbs[rid] = on_token
            try:
                self.engine.submit(Request(rid=rid, prompt=list(prompt),
                                           max_new_tokens=max_new_tokens,
                                           tenant=tenant,
                                           traceparent=traceparent,
                                           deadline_s=deadline_s,
                                           adapter=adapter))
            except EngineDraining as err:
                self._waiters.pop(rid, None)
                self._token_cbs.pop(rid, None)
                raise ReplicaDraining(str(err)) from err
            except Exception:
                self._waiters.pop(rid, None)
                self._token_cbs.pop(rid, None)
                raise
        # the wait derives from the propagated deadline; with none, the
        # crash/drain paths guarantee the event always fires eventually
        if not event.wait(timeout=deadline_s):
            self._waiters.pop(rid, None)
            self._token_cbs.pop(rid, None)
            raise TimeoutError(
                f"{self.name}: request {rid} missed its "
                f"{deadline_s:.3f}s deadline")
        comp = self._result(box[0])
        return {"rid": comp.rid, "replica": self.name,
                "prompt_len": comp.prompt_len, "tokens": comp.tokens,
                "finish_reason": comp.finish_reason}

    def swap(self, variables=None, version: int | None = None) -> int:
        """Live weight swap: install a new parameter tree *between*
        decode steps — the step lock guarantees no jitted step is in
        flight while the swap lands, and every in-flight stream simply
        decodes its next token with the new weights. A chaos ``on_swap``
        kill crashes the replica exactly as a pod dying mid-rolling-
        update would: waiters fail over to the router's journal/resume
        path, so even a swap death loses nothing."""
        from move2kube_tpu.serving.fleet.chaos import ChaosKill

        if variables is None:
            raise ValueError(f"{self.name}: no weight source for swap")
        try:
            if self.chaos is not None:
                self.chaos.on_swap(self.name)
        except ChaosKill as err:
            self._crash(err)
            raise
        with self._lock:
            return self.engine.install_weights(variables, version)

    def install(self, handoff_bytes: bytes, tenant: str = "",
                traceparent: str = "",
                deadline_s: float | None = None) -> dict:
        """Seat a disagg KV handoff and decode it to completion. The
        handoff wire format already carries tenant/traceparent; the
        kwargs exist for signature parity with :class:`HttpReplica`."""
        from move2kube_tpu.serving.fleet.disagg import KVHandoff

        if self._draining:
            raise ReplicaDraining(f"{self.name}: draining, not admitting")
        if self.chaos is not None:
            handoff_bytes = self.chaos.on_handoff(self.name, handoff_bytes)
        h = KVHandoff.from_bytes(handoff_bytes)
        event, box = threading.Event(), []
        self.start()
        installed = False
        expires = (time.perf_counter() + deadline_s
                   if deadline_s is not None else None)
        while not installed:
            if self._stop:
                raise ReplicaDraining(f"{self.name}: replica stopped")
            if expires is not None and time.perf_counter() > expires:
                raise TimeoutError(
                    f"{self.name}: handoff {h.rid} missed its "
                    f"{deadline_s:.3f}s deadline before install")
            with self._lock:
                ok, done = self.engine.install_prefilled(
                    h.request(), h.kv, h.first_token, h.prompt_len)
                if ok:
                    installed = True
                    if done:
                        box.extend(done)
                        event.set()
                    else:
                        self._waiters[h.rid] = (event, box)
            if not installed:
                time.sleep(0.002)  # engine full: let the loop drain a step
        remaining = (expires - time.perf_counter()
                     if expires is not None else None)
        if not event.wait(timeout=remaining):
            self._waiters.pop(h.rid, None)
            raise TimeoutError(
                f"{self.name}: handoff {h.rid} missed its "
                f"{deadline_s:.3f}s deadline")
        comp = self._result(box[0])
        return {"rid": comp.rid, "replica": self.name,
                "prompt_len": comp.prompt_len, "tokens": comp.tokens,
                "finish_reason": comp.finish_reason}


class HttpReplica(ReplicaHandle):
    """A remote engine pod: ``/generate`` (and ``/install`` for disagg)
    on the serving port, ``/readyz`` + ``/stats`` on the telemetry
    port (obs/server.py)."""

    def __init__(self, name: str, base_url: str,
                 health_url: str | None = None,
                 timeout_s: float | None = None):
        self.name = name
        self.base_url = base_url.rstrip("/")
        self.health_url = (health_url or base_url).rstrip("/")
        # fallback socket timeout for deadline-less calls only; every
        # deadlined call derives its timeout from the remaining budget
        self.timeout_s = timeout_s

    def _post(self, path: str, data: bytes, ctype: str,
              tenant: str = "", traceparent: str = "",
              deadline_s: float | None = None) -> bytes:
        """POST with trace/tenant/deadline header injection. A non-2xx
        answer is surfaced as :class:`ReplicaHTTPError` with the status
        and a body excerpt — urllib's bare ``HTTP Error 500`` hid what
        the replica actually said."""
        headers = {"Content-Type": ctype}
        if tenant:
            headers[TENANT_HEADER] = tenant
        if traceparent:
            headers[TRACEPARENT_HEADER] = traceparent
        if deadline_s is not None:
            headers[DEADLINE_HEADER] = f"{deadline_s:.3f}"
        timeout = deadline_s if deadline_s is not None else self.timeout_s
        req = urllib.request.Request(
            f"{self.base_url}{path}", data=data, headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.read()
        except urllib.error.HTTPError as err:
            try:
                body = err.read(512).decode("utf-8", "replace")
            except OSError:
                body = ""
            raise ReplicaHTTPError(self.name, path, err.code,
                                   body) from err

    def generate(self, prompt, max_new_tokens=None, rid=None,
                 tenant: str = "", traceparent: str = "",
                 deadline_s: float | None = None, on_token=None,
                 adapter: str = "") -> dict:
        # request/response transport: there is no mid-stream token feed,
        # so ``on_token`` replays the whole completion at once — a death
        # before the reply resumes as a whole-request retry, which is
        # trivially token-exact
        body = json.dumps({"prompt": list(prompt),
                           "max_new_tokens": max_new_tokens,
                           "rid": rid, "adapter": adapter}).encode()
        out = json.loads(self._post(
            "/generate", body, "application/json",
            tenant=tenant, traceparent=traceparent,
            deadline_s=deadline_s).decode())
        if on_token is not None:
            for tok in out.get("tokens", []):
                on_token(tok)
        if out.get("finish_reason") == "preempted":
            # journal already replayed above; the raise turns the reply
            # into the same resume path the in-process replica takes
            raise RequestPreempted(
                f"{out.get('rid')}: preempted after "
                f"{len(out.get('tokens', []))} tokens")
        return out

    def install(self, handoff_bytes: bytes, tenant: str = "",
                traceparent: str = "",
                deadline_s: float | None = None) -> dict:
        return json.loads(self._post(
            "/install", handoff_bytes, "application/octet-stream",
            tenant=tenant, traceparent=traceparent,
            deadline_s=deadline_s).decode())

    def swap(self, variables=None, version: int | None = None) -> int:
        """POST /swap: the pod re-pulls its own weights (peers first,
        checkpoint-store fallback) and live-installs them. A parameter
        tree cannot ride this hop — remote swaps are pull-based."""
        if variables is not None:
            raise ValueError(
                f"{self.name}: HTTP replicas pull weights themselves; "
                "swap(variables=...) is in-process only")
        body = json.dumps({"version": version}).encode()
        out = json.loads(self._post(
            "/swap", body, "application/json").decode())
        return int(out.get("weights_version", 0))

    def prefill(self, request):
        """Disagg prefill over HTTP: POST the prompt, get back the
        serialized KV handoff (``KVHandoff.to_bytes`` wire format)."""
        from move2kube_tpu.serving.fleet.disagg import KVHandoff

        body = json.dumps({"prompt": list(request.prompt),
                           "max_new_tokens": request.max_new_tokens,
                           "rid": request.rid}).encode()
        return KVHandoff.from_bytes(self._post(
            "/prefill", body, "application/json",
            tenant=request.tenant, traceparent=request.traceparent,
            deadline_s=request.deadline_s))

    def queue_depth(self) -> float:
        try:
            with urllib.request.urlopen(f"{self.health_url}/stats",
                                        timeout=probe_timeout_s()) as resp:
                stats = json.loads(resp.read().decode())
            return float(stats.get("queue_depth", 0)
                         + stats.get("active_slots", 0))
        except (OSError, ValueError):
            return float("inf")

    def healthy(self) -> bool:
        try:
            with urllib.request.urlopen(f"{self.health_url}/readyz",
                                        timeout=probe_timeout_s()) as resp:
                return resp.status == 200
        except (OSError, ValueError):
            return False


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    affinity_tokens: int = 16   # prompt prefix length hashed for affinity
    salt: str = ""              # M2KT_FLEET_AFFINITY_SALT (Helm-lifted)
    max_retries: int = 2        # additional replicas tried on failure
    spill_queue_depth: float = 8.0  # affine queue deeper than this spills
    hedge_after_s: float | None = None  # None = hedging off
    disagg_threshold: int = 0   # prompt length that routes via prefill; 0=off
    # default per-request deadline budget (M2KT_DEADLINE_S, Helm-lifted);
    # every downstream wait derives from it. None/<=0 = no deadline
    deadline_s: float | None = 120.0
    # eos id for completing a resume locally when the journal already
    # ends in eos (the engine owns eos semantics; the router only needs
    # it to avoid asking a replica to decode past the end)
    eos_id: int | None = None
    # readmission-probe exponential backoff (after FAILED probes only —
    # a fresh markdown is still probed immediately, so recovery latency
    # does not regress)
    probe_backoff_base_s: float = 0.5
    probe_backoff_cap_s: float = 30.0
    # scheduler plane (PR 17): the same tenant specs the engines parse —
    # admission throttles HERE, before placement, so an over-quota
    # tenant never costs a replica round-trip. Malformed entries warn
    # and are skipped inside the sched parser (quant.py tolerance).
    sched_tenants: str = ""
    sched_priorities: str = ""
    sched_quotas: str = ""
    # how many preemption resumes one request may take before the
    # router gives up (a bound on best-effort starvation spin, NOT a
    # replica-failure retry — those stay on max_retries)
    max_preempt_resumes: int = 64

    @classmethod
    def from_env(cls, **overrides) -> "RouterConfig":
        def _num(name, default, cast):
            try:
                return cast(os.environ.get(name, "") or default)
            except ValueError:
                return default

        hedge = _num("M2KT_ROUTER_HEDGE_MS", 0.0, float)
        deadline = _num("M2KT_DEADLINE_S", cls.deadline_s or 0.0, float)
        cfg = dict(
            affinity_tokens=_num("M2KT_ROUTER_AFFINITY_TOKENS",
                                 cls.affinity_tokens, int),
            salt=os.environ.get("M2KT_FLEET_AFFINITY_SALT", cls.salt),
            max_retries=_num("M2KT_ROUTER_RETRIES", cls.max_retries, int),
            spill_queue_depth=_num("M2KT_ROUTER_SPILL_DEPTH",
                                   cls.spill_queue_depth, float),
            hedge_after_s=(hedge / 1e3) if hedge > 0 else None,
            disagg_threshold=_num("M2KT_FLEET_DISAGG_THRESHOLD", 0, int),
            deadline_s=deadline if deadline > 0 else None,
            probe_backoff_base_s=_num("M2KT_ROUTER_PROBE_BACKOFF_S",
                                      cls.probe_backoff_base_s, float),
            probe_backoff_cap_s=_num("M2KT_ROUTER_PROBE_BACKOFF_CAP_S",
                                     cls.probe_backoff_cap_s, float),
            sched_tenants=os.environ.get("M2KT_SCHED_TENANTS",
                                         cls.sched_tenants),
            sched_priorities=os.environ.get("M2KT_SCHED_PRIORITIES",
                                            cls.sched_priorities),
            sched_quotas=os.environ.get("M2KT_SCHED_QUOTAS",
                                        cls.sched_quotas),
            max_preempt_resumes=_num("M2KT_ROUTER_PREEMPT_RESUMES",
                                     cls.max_preempt_resumes, int),
        )
        cfg.update(overrides)
        return cls(**cfg)


class Router:
    def __init__(self, replicas, config: RouterConfig | None = None,
                 prefill_replicas=(), registry: Registry | None = None,
                 tracer=None):
        self.replicas = list(replicas)
        self.prefill_replicas = list(prefill_replicas)
        self.config = config or RouterConfig()
        self.registry = registry if registry is not None else Registry()
        # the router's span ring: every routed request opens a
        # router.request root, every replica hop a router.call child
        # whose traceparent() rides the outbound headers
        self.tracer = tracer if tracer is not None else (
            tracing.get() if tracing.enabled() else None)
        # last-known health, refreshed by probe(); a failed call marks
        # the replica down immediately without waiting for a probe
        self._up: dict[str, bool] = {r.name: True for r in self.replicas}
        self._rr = 0  # round-robin cursor over prefill replicas
        # scheduler plane: the router front runs admission (token-bucket
        # throttling) against the same specs the engines parse, so the
        # two sides can never disagree on who a tenant is
        self.admission = AdmissionController.from_specs(
            self.config.sched_tenants, self.config.sched_priorities,
            self.config.sched_quotas, registry=self.registry)
        # readmission-probe backoff: replica -> (consecutive failed
        # probes, monotonic ts before which it is not probed again)
        self._probe_state: dict[str, tuple[int, float]] = {}
        reg = self.registry
        self._requests = reg.counter(
            "m2kt_router_requests_total", "Routed requests by outcome",
            labels=("outcome",))
        self._resumed = reg.counter(
            "m2kt_router_resumed_total",
            "Mid-stream requests resumed on a surviving replica with "
            "their journaled tokens force-fed, by failure reason",
            labels=("reason",))
        self._sched_resumed = reg.counter(
            "m2kt_sched_resumed_total",
            "Preempted requests resumed token-exactly from the journal, "
            "by the reason the resume was needed", labels=("reason",))
        self._retries = reg.counter(
            "m2kt_router_retries_total", "Requests retried on another "
            "replica after a failure")
        self._retry_reasons = reg.counter(
            "m2kt_router_retries_by_reason_total",
            "Retries by the failure reason that triggered them",
            labels=("reason",))
        self._markdowns = reg.counter(
            "m2kt_router_marked_down_total",
            "Replicas marked down, by replica and failure reason",
            labels=("replica", "reason"))
        self._hedges = reg.counter(
            "m2kt_router_hedges_total", "Duplicate requests fired at the "
            "runner-up after the hedge deadline")
        self._affinity_hits = reg.counter(
            "m2kt_router_affinity_hits_total",
            "Requests routed to their prefix-affine replica")
        self._spills = reg.counter(
            "m2kt_router_spills_total",
            "Requests spilled to the least-loaded replica (affine replica "
            "down or queue too deep)")
        self._replica_up = reg.gauge(
            "m2kt_router_replica_up", "1 if the replica passed its last "
            "health check", labels=("replica",))
        self._replica_queue = reg.gauge(
            "m2kt_router_replica_queue_depth",
            "Queued + active requests on the replica at last poll",
            labels=("replica",))
        self._inflight = reg.gauge(
            "m2kt_router_inflight", "Requests currently being routed")
        self._disagg = reg.counter(
            "m2kt_router_disagg_total",
            "Requests served via prefill->decode handoff")
        self._swaps = reg.counter(
            "m2kt_router_swap_total",
            "Live weight-swap fan-out, by per-replica outcome",
            labels=("outcome",))
        # demand attribution in TOKENS, not requests: prompt + max_new
        # estimated at admission (the forecaster needs the demand the
        # moment it is admitted, not after generation finishes), then
        # corrected at completion — over-estimates land in the paired
        # unused counter because a Prometheus counter cannot go down.
        # Net demand = admitted - unused (admitted_tokens()).
        tenant_cap = max_tenants() + 1
        self._admitted_tokens = reg.counter(
            "m2kt_router_admitted_tokens_total",
            "Admitted demand in tokens by tenant (prompt + max_new at "
            "admission, under-estimates topped up at completion)",
            labels=("tenant",), max_series=tenant_cap)
        self._admitted_unused = reg.counter(
            "m2kt_router_admitted_tokens_unused_total",
            "Admission-estimate tokens the completion did not use "
            "(early EOS / shed) — subtract from admitted for net demand",
            labels=("tenant",), max_series=tenant_cap)
        # optional pull source for POST /swap with no inline tree:
        # a callable returning (variables, version)
        self.weight_source = None
        for r in self.replicas:
            self._replica_up.labels(replica=r.name).set(1.0)

    # ------------------------------------------------------------------
    # placement
    # ------------------------------------------------------------------

    def _probe_delay(self, name: str, fails: int) -> float:
        """Exponential backoff with deterministic jitter for readmission
        probes: base * 2^(fails-1), capped, +0..50% jitter hashed from
        (replica, fails) — every router instance spreads its probes the
        same way without sharing a clock or an RNG."""
        base = self.config.probe_backoff_base_s
        cap = self.config.probe_backoff_cap_s
        delay = min(cap, base * (2 ** max(0, fails - 1)))
        jitter = (_rendezvous_score(fails, name) % 1000) / 2000.0
        return delay * (1.0 + jitter)

    def probe(self) -> dict:
        """Poll replica health endpoints and refresh the up/queue gauges.
        Recovered replicas rejoin the affinity ring here. A replica whose
        last probe FAILED is skipped until its backoff lapses, so a fleet
        of routers does not thundering-herd a replica that just
        restarted; a freshly marked-down replica (no failed probe yet) is
        still probed immediately."""
        now = time.monotonic()
        out = {}
        for r in self.replicas:
            fails, next_ts = self._probe_state.get(r.name, (0, 0.0))
            if fails and now < next_ts:
                out[r.name] = self._up.get(r.name, False)
                continue
            up = bool(r.healthy())
            self._up[r.name] = up
            self._replica_up.labels(replica=r.name).set(1.0 if up else 0.0)
            if up:
                self._probe_state.pop(r.name, None)
                self._replica_queue.labels(replica=r.name).set(
                    r.queue_depth())
            else:
                fails += 1
                self._probe_state[r.name] = (
                    fails, now + self._probe_delay(r.name, fails))
            out[r.name] = up
        return out

    def _healthy(self):
        return [r for r in self.replicas if self._up.get(r.name, True)]

    def pick(self, prompt, exclude=()) -> ReplicaHandle | None:
        """Affine replica by rendezvous hash of the prompt prefix,
        spilling to least-loaded when it is excluded, down, or
        backlogged. Pure placement — no side effects beyond metrics."""
        excluded = {r.name for r in exclude}
        healthy = [r for r in self._healthy() if r.name not in excluded]
        if not healthy:
            return None
        key = prefix_hash(prompt, self.config.salt,
                          self.config.affinity_tokens)
        affine = max(healthy,
                     key=lambda r: _rendezvous_score(key, r.name))
        if affine.queue_depth() <= self.config.spill_queue_depth:
            self._affinity_hits.inc()
            return affine
        self._spills.inc()
        return min(healthy, key=lambda r: r.queue_depth())

    def _mark_down(self, replica: ReplicaHandle,
                   reason: str = "probe") -> None:
        self._up[replica.name] = False
        self._replica_up.labels(replica=replica.name).set(0.0)
        self._markdowns.labels(replica=replica.name, reason=reason).inc()

    # ------------------------------------------------------------------
    # weight plane
    # ------------------------------------------------------------------

    def swap(self, variables=None, version: int | None = None) -> dict:
        """Roll a live weight swap across the fleet, one replica at a
        time — the in-process analogue of a PDB-respecting rolling
        update: at most one replica is ever inside its swap, every
        other replica keeps serving, and unhealthy replicas are skipped
        (they re-pull on readmission). A replica that dies mid-swap
        (chaos ``M2KT_CHAOS_SWAP=kill``) is marked down and the roll
        continues — its in-flight streams resume on survivors via the
        journal, so a swap under chaos drops zero requests."""
        if variables is None and self.weight_source is not None:
            variables, version = self.weight_source()
        swapped = failed = skipped = 0
        installed = None
        for replica in list(self.replicas):
            if not self._up.get(replica.name, True):
                skipped += 1
                self._swaps.labels(outcome="skipped").inc()
                continue
            try:
                installed = replica.swap(variables, version)
                if version is None:
                    # first success pins the generation the rest of the
                    # roll installs, so the fleet converges on one number
                    version = installed
                swapped += 1
                self._swaps.labels(outcome="ok").inc()
            except Exception as err:  # noqa: BLE001 - keep rolling
                self._mark_down(replica, failure_reason(err))
                failed += 1
                self._swaps.labels(outcome="failed").inc()
        return {"weights_version": installed, "swapped": swapped,
                "failed": failed, "skipped": skipped}

    # ------------------------------------------------------------------
    # request path
    # ------------------------------------------------------------------

    def _open_call(self, root, replica: ReplicaHandle, hop: str):
        """Open a ``router.call`` child span for one replica hop and
        return ``(span, traceparent_header)`` — the header is what rides
        the outbound request so the replica's root parents under it."""
        if self.tracer is None or root is None:
            return None, ""
        span = self.tracer.start(
            "router.call",
            attrs={"replica": getattr(replica, "name", hop), "hop": hop},
            parent=root, detached=True)
        return span, span.traceparent()

    def admitted_tokens(self) -> float:
        """Net admitted token demand across every tenant: the admission
        estimates minus the completion corrections. Monotone except for
        the moment a correction lands, which a windowed rate absorbs —
        this is the counter the demand forecaster differences."""
        return (self._admitted_tokens.total()
                - self._admitted_unused.total())

    def generate(self, prompt, max_new_tokens: int | None = None,
                 rid: str | None = None, tenant: str = "",
                 traceparent: str | None = None,
                 deadline_s: float | None = None,
                 adapter: str = "") -> dict:
        prompt = list(prompt)
        tenant = clean_tenant(tenant)
        # admission runs before any placement or span work: an
        # over-quota tenant costs the fleet nothing but this check
        # (the HTTP front maps SchedThrottled to 429)
        try:
            self.admission.admit(tenant)
        except SchedThrottled:
            self._requests.labels(outcome="throttled").inc()
            raise
        # token-demand attribution at admission: the forecaster reads
        # this rate, so it must move when demand ARRIVES, not when the
        # decode finishes minutes later
        est_tokens = len(prompt) + int(
            max_new_tokens or EngineConfig.max_new_tokens)
        self._admitted_tokens.labels(tenant=tenant).inc(est_tokens)
        self._inflight.inc()
        # ONE absolute deadline per request (caller's X-M2KT-Deadline
        # remainder, else the configured default): the disagg attempt,
        # its direct-path fallback, and every resume hop all spend from
        # the same budget
        budget = deadline_s if deadline_s is not None \
            else self.config.deadline_s
        deadline = (time.perf_counter() + budget
                    if budget and budget > 0 else None)
        root = None
        if self.tracer is not None:
            # many requests route concurrently in one process: the root
            # is detached and identity threads through explicitly. An
            # inbound traceparent (a client already tracing) wins.
            root = self.tracer.start(
                "router.request",
                attrs={"prompt_len": len(prompt), "tenant": tenant},
                detached=True, remote_parent=traceparent)
        try:
            out = None
            if (self.config.disagg_threshold
                    and len(prompt) >= self.config.disagg_threshold
                    and self.prefill_replicas):
                try:
                    out = self._generate_disagg(prompt, max_new_tokens,
                                                rid, tenant, root,
                                                deadline)
                except DeadlineExceeded:
                    raise  # no budget left for the direct fallback either
                except Exception:  # noqa: BLE001 - fall back to direct path
                    out = None
            if out is None:
                out = self._generate_direct(prompt, max_new_tokens, rid,
                                            tenant, root, deadline,
                                            adapter=adapter)
            # completion correction: top up an under-estimate, park an
            # over-estimate (early EOS) in the unused counter
            actual = len(prompt) + len(out.get("tokens", ()))
            if actual > est_tokens:
                self._admitted_tokens.labels(tenant=tenant).inc(
                    actual - est_tokens)
            elif actual < est_tokens:
                self._admitted_unused.labels(tenant=tenant).inc(
                    est_tokens - actual)
            self._requests.labels(outcome="ok").inc()
            return out
        except Exception as err:
            self._requests.labels(outcome="error").inc()
            if root is not None:
                root.attrs["error"] = failure_reason(err)
            raise
        finally:
            if root is not None:
                self.tracer.end(root)
            self._inflight.dec()

    @staticmethod
    def _remaining(deadline: float | None) -> float | None:
        return (deadline - time.perf_counter()
                if deadline is not None else None)

    def _generate_direct(self, prompt, max_new_tokens, rid, tenant="",
                         root=None, deadline: float | None = None,
                         adapter: str = "") -> dict:
        tried: list[ReplicaHandle] = []
        last_err: Exception | None = None
        # the journal: every token any replica has emitted for this
        # request, in order, fed by the engine's on_token hook. On a
        # mid-stream death it is what makes the retry a RESUME — the
        # journaled tokens ride the next attempt as a forced prompt
        # suffix, and greedy decode regenerates the rest byte-identically
        emitted: list[int] = []
        max_new = max_new_tokens or EngineConfig.max_new_tokens
        attempt = preempts = 0
        while attempt <= self.config.max_retries:
            journal = list(emitted)
            resumed = bool((attempt or preempts) and journal)
            if journal and (len(journal) >= max_new
                            or (self.config.eos_id is not None
                                and journal[-1] == self.config.eos_id)):
                # the dead replica had already emitted the final token;
                # nothing left to decode — complete locally
                reason = (failure_reason(last_err)
                          if last_err is not None else "complete")
                self._resumed.labels(reason=reason).inc()
                return {"rid": rid, "replica": tried[-1].name if tried
                        else "", "prompt_len": len(prompt),
                        "tokens": journal, "resumed": True,
                        "finish_reason": "length"
                        if len(journal) >= max_new else "eos"}
            remaining = self._remaining(deadline)
            if remaining is not None and remaining <= 0:
                if last_err is None:
                    last_err = DeadlineExceeded(
                        f"{rid or 'request'}: deadline spent at the router")
                break
            replica = self.pick(prompt, exclude=tried)
            if replica is None:
                break
            if attempt:
                self._retries.inc()
                if last_err is not None:
                    self._retry_reasons.labels(
                        failure_reason(last_err)).inc()
            if resumed:
                self._resumed.labels(reason=failure_reason(last_err)
                                     if last_err is not None
                                     else "unknown").inc()
                if isinstance(last_err, RequestPreempted):
                    self._sched_resumed.labels(reason="preempted").inc()
            tried.append(replica)
            try:
                if self.config.hedge_after_s is not None:
                    out = self._call_hedged(
                        replica, prompt + journal, max_new - len(journal),
                        rid, tried, tenant, root, remaining,
                        adapter=adapter)
                else:
                    out = self._call_one(
                        replica, prompt + journal, max_new - len(journal),
                        rid, tenant, root, remaining,
                        on_token=emitted.append,
                        hop="resume" if resumed else "generate",
                        adapter=adapter)
                if journal:
                    out = dict(out)
                    out["tokens"] = journal + list(out["tokens"])
                    out["prompt_len"] = len(prompt)
                    out["resumed"] = True
                return out
            except DeadlineExceeded:
                raise  # the caller's problem; not the replica's fault
            except RequestPreempted as err:
                # paused, not failed: the replica stays up AND stays
                # eligible — the same engine usually resumes the work
                # once the higher-priority burst passes. Bounded so a
                # best-effort request cannot spin forever under flood.
                last_err = err
                tried.pop()
                preempts += 1
                if preempts > self.config.max_preempt_resumes:
                    break
            except Exception as err:  # noqa: BLE001 - any failure fails over
                last_err = err
                self._mark_down(replica, failure_reason(err))
                attempt += 1
        if last_err is not None:
            raise last_err
        raise RuntimeError("router: no healthy replica available")

    def _call_one(self, replica, prompt, max_new_tokens, rid, tenant,
                  root, deadline_s: float | None = None, on_token=None,
                  hop: str = "generate", adapter: str = "") -> dict:
        span, header = self._open_call(root, replica, hop)
        # adapter rides only when set, so pre-sched ReplicaHandle
        # subclasses keep their narrower generate() signature
        extra = {"adapter": adapter} if adapter else {}
        try:
            return replica.generate(prompt, max_new_tokens, rid,
                                    tenant=tenant, traceparent=header,
                                    deadline_s=deadline_s,
                                    on_token=on_token, **extra)
        except Exception as err:  # noqa: BLE001 - annotate, then re-raise
            if span is not None:
                span.attrs["error"] = failure_reason(err)
            raise
        finally:
            if span is not None:
                self.tracer.end(span)

    def _call_hedged(self, primary, prompt, max_new_tokens, rid,
                     tried, tenant="", root=None,
                     deadline_s: float | None = None,
                     adapter: str = "") -> dict:
        """Fire ``primary``; if it has not answered within the hedge
        deadline, fire the runner-up too and take whichever finishes
        first. The loser's work is wasted by design — hedging trades
        duplicate decode for tail latency."""
        done = threading.Event()
        results: list[dict] = []
        errors: list[Exception] = []

        def call(replica):
            try:
                # hedges carry no journal feed: two replicas racing one
                # request would interleave a single journal — hedging is
                # its own redundancy, so the loser is simply discarded
                results.append(self._call_one(
                    replica, prompt, max_new_tokens, rid, tenant, root,
                    deadline_s, adapter=adapter))
                done.set()
            except Exception as err:  # noqa: BLE001 - collected below
                errors.append(err)
                if len(errors) >= len(threads):
                    done.set()

        threads = [threading.Thread(target=call, args=(primary,),
                                    daemon=True)]
        threads[0].start()
        if not done.wait(self.config.hedge_after_s):
            backup = self.pick(prompt, exclude=tried)
            if backup is not None:
                self._hedges.inc()
                tried.append(backup)
                threads.append(threading.Thread(target=call, args=(backup,),
                                                daemon=True))
                threads[1].start()
        done.wait()
        while not results and any(t.is_alive() for t in threads):
            time.sleep(0.005)
        if results:
            return results[0]
        raise errors[0] if errors else RuntimeError("hedge: no result")

    def _generate_disagg(self, prompt, max_new_tokens, rid, tenant="",
                         root=None, deadline: float | None = None) -> dict:
        """Long prompts route prefill->decode: round-robin a prefill
        replica for the KV handoff, then seat it on the prefix-affine
        decode replica (same placement as the direct path, so the
        decode side's cache locality is preserved). Both hops get their
        own router.call span; the handoff wire carries the install
        hop's traceparent so the decode replica's root stitches under
        it even when the bytes travel through a queue. Both hops spend
        from the request's one deadline budget."""
        prefill = self.prefill_replicas[self._rr
                                        % len(self.prefill_replicas)]
        self._rr += 1
        pspan, pheader = self._open_call(root, prefill, "prefill")
        try:
            handoff = prefill.prefill(Request(
                rid=rid or f"disagg-{self._rr}", prompt=list(prompt),
                max_new_tokens=max_new_tokens, tenant=tenant,
                traceparent=pheader,
                deadline_s=self._remaining(deadline)))
        finally:
            if pspan is not None:
                self.tracer.end(pspan)
        decode = self.pick(prompt)
        if decode is None:
            raise RuntimeError("router: no healthy decode replica")
        dspan, dheader = self._open_call(root, decode, "install")
        handoff.tenant = tenant
        handoff.traceparent = dheader
        try:
            out = decode.install(handoff.to_bytes(), tenant=tenant,
                                 traceparent=dheader,
                                 deadline_s=self._remaining(deadline))
        finally:
            if dspan is not None:
                self.tracer.end(dspan)
        self._disagg.inc()
        return out


class RouterHTTPServer:
    """stdlib-HTTP front for the router role (assets/jax/serve_tpu.py
    runs this when ``M2KT_FLEET_ROLE=router``). ``/readyz`` reports
    serving once any backend replica is healthy, so the router pod's
    readiness gate composes with the engines' own gates."""

    def __init__(self, router: Router, port: int = 8000,
                 default_max_new: int | None = None):
        self.router = router
        self.default_max_new = default_max_new
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def _send(self, code: int, body: bytes,
                      ctype: str = "application/json") -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    self._send(200, b'{"status":"ok"}')
                elif self.path == "/readyz":
                    up = outer.router.probe()
                    ready = any(up.values())
                    body = json.dumps({"ready": ready,
                                       "replicas": up}).encode()
                    self._send(200 if ready else 503, body)
                elif self.path == "/metrics":
                    self._send(200, outer.router.registry.render().encode(),
                               "text/plain; version=0.0.4")
                else:
                    self._send(404, b'{"error":"not found"}')

            def do_POST(self):
                if self.path == "/swap":
                    # fan a rolling live weight swap across the fleet;
                    # the body may pin the generation number
                    try:
                        n = int(self.headers.get("Content-Length", 0))
                        doc = json.loads(self.rfile.read(n) or b"{}")
                        raw = doc.get("version")
                        out = outer.router.swap(
                            version=int(raw) if raw is not None else None)
                        code = 200 if out["swapped"] else 503
                        self._send(code, json.dumps(out).encode())
                    except Exception as err:  # noqa: BLE001
                        self._send(500, json.dumps(
                            {"error": str(err)}).encode())
                    return
                if self.path != "/generate":
                    self._send(404, b'{"error":"not found"}')
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(n).decode())
                    raw_deadline = self.headers.get(DEADLINE_HEADER)
                    deadline_s = (float(raw_deadline)
                                  if raw_deadline else None)
                    out = outer.router.generate(
                        payload["prompt"],
                        payload.get("max_new_tokens",
                                    outer.default_max_new),
                        payload.get("rid"),
                        tenant=self.headers.get(TENANT_HEADER, ""),
                        traceparent=self.headers.get(
                            TRACEPARENT_HEADER),
                        deadline_s=deadline_s,
                        adapter=payload.get("adapter", "") or "")
                    self._send(200, json.dumps(out).encode())
                except SchedThrottled as err:
                    self._send(429, json.dumps(
                        {"error": str(err)}).encode())
                except DeadlineExceeded as err:
                    self._send(504, json.dumps(
                        {"error": str(err)}).encode())
                except Exception as err:  # noqa: BLE001 - surface as 500
                    self._send(500, json.dumps(
                        {"error": str(err)}).encode())

        self._server = ThreadingHTTPServer(("0.0.0.0", port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="m2kt-router",
            daemon=True)

    def start(self) -> "RouterHTTPServer":
        self._thread.start()
        return self

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()


def build_fleet(model, variables, n_replicas: int,
                engine_config: EngineConfig | None = None,
                router_config: RouterConfig | None = None,
                registry: Registry | None = None) -> Router:
    """An in-process fleet: N engine replicas behind a router. The
    CPU-mode stand-in for the emitted per-role pods, used by
    ``fleet-smoke`` and the bench fleet phase."""
    cfg = engine_config or EngineConfig.from_env()
    replicas = [
        InProcessReplica(f"replica-{i}",
                         ServingEngine(model, variables, cfg)).start()
        for i in range(n_replicas)]
    return Router(replicas, config=router_config, registry=registry)
