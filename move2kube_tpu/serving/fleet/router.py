"""Fleet request router: prefix-affine load balancing over N engine
replicas.

Placement is the fleet's cache policy: the prefix cache
(fleet/prefixcache.py) lives *inside* each replica, so a request only
hits if earlier requests with the same prefix landed on the same
replica. The router therefore routes by **session affinity on the
prefix hash** — rendezvous (highest-random-weight) hashing of the
first ``affinity_tokens`` prompt tokens plus a salt, which keeps the
tenant->replica mapping stable as replicas come and go (only keys
owned by a dead replica move). When the affine replica is unhealthy or
its queue is deep, the router spills to the least-loaded healthy
replica; failures mark the replica down and retry elsewhere (bounded),
and an optional hedge fires a duplicate to the runner-up when the
primary sits on a request too long.

Everything observable exports as ``m2kt_router_*`` through the PR-5
registry; the HTTP front serves ``/generate`` plus the standard
``/healthz``/``/readyz``/``/metrics`` trio so the emitted router pods
scrape and gate exactly like engine pods.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from move2kube_tpu.obs import tracing
from move2kube_tpu.obs.metrics import Registry
from move2kube_tpu.obs.slo import TENANT_HEADER, clean_tenant
from move2kube_tpu.obs.tracing import TRACEPARENT_HEADER
from move2kube_tpu.serving.engine import EngineConfig, Request, ServingEngine


class ReplicaHTTPError(RuntimeError):
    """A replica answered with a non-2xx status. Carries the status code
    and a body excerpt so the router's mark-down reason and logs say
    *what the replica said*, not just that urllib raised."""

    def __init__(self, replica: str, path: str, status: int, body: str):
        self.replica = replica
        self.path = path
        self.status = int(status)
        self.body_excerpt = (body or "").strip()[:200]
        super().__init__(
            f"{replica}{path}: HTTP {self.status}: "
            f"{self.body_excerpt or '<empty body>'}")


def failure_reason(err: Exception) -> str:
    """A bounded-cardinality label for why a replica call failed —
    the value the reason-labeled retry/mark-down counters carry."""
    if isinstance(err, ReplicaHTTPError):
        return f"http_{err.status}"
    if isinstance(err, TimeoutError):
        return "timeout"
    if isinstance(err, (urllib.error.URLError, ConnectionError, OSError)):
        return "connection"
    return type(err).__name__.lower()


def prefix_hash(tokens, salt: str = "", k: int = 16) -> int:
    """Stable across processes (the Helm-lifted salt is the only input
    besides the tokens): hash of the first ``k`` prompt tokens."""
    h = hashlib.sha256(salt.encode())
    for t in list(tokens)[:k]:
        h.update(int(t).to_bytes(8, "little", signed=True))
    return int.from_bytes(h.digest()[:8], "little")


def _rendezvous_score(key: int, name: str) -> int:
    h = hashlib.sha256(f"{key}:{name}".encode())
    return int.from_bytes(h.digest()[:8], "little")


class ReplicaHandle:
    """One engine replica as the router sees it."""

    name: str = "replica"

    def generate(self, prompt, max_new_tokens: int | None = None,
                 rid: str | None = None, tenant: str = "",
                 traceparent: str = "") -> dict:
        raise NotImplementedError

    def queue_depth(self) -> float:
        raise NotImplementedError

    def healthy(self) -> bool:
        raise NotImplementedError


class InProcessReplica(ReplicaHandle):
    """A ServingEngine plus its worker thread, wired like the emitted
    serve template's server loop — used by tests and ``fleet-smoke``
    to stand up a whole fleet in one CPU process. ``fail_next`` makes
    the next N calls raise, for failover/hedging drills."""

    def __init__(self, name: str, engine: ServingEngine):
        self.name = name
        self.engine = engine
        self.fail_next = 0
        self.hold_s = 0.0  # artificial service delay, for hedging drills
        self._lock = threading.Lock()
        self._waiters: dict[str, tuple[threading.Event, list]] = {}
        self._seq = 0
        self._stop = False
        self._thread: threading.Thread | None = None
        self._up = True

    def start(self) -> "InProcessReplica":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name=f"replica-{self.name}", daemon=True)
            self._thread.start()
        return self

    def close(self) -> None:
        self._stop = True
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _loop(self) -> None:
        while not self._stop:
            with self._lock:
                work = self.engine.has_work()
                done = self.engine.step() if work else []
            for comp in done:
                waiter = self._waiters.pop(comp.rid, None)
                if waiter is not None:
                    event, box = waiter
                    box.append(comp)
                    event.set()
            if not work:
                time.sleep(0.002)

    def set_healthy(self, up: bool) -> None:
        self._up = up

    def healthy(self) -> bool:
        return self._up and not self._stop

    def queue_depth(self) -> float:
        stats = self.engine.stats()
        return float(stats["queue_depth"] + stats["active_slots"])

    def generate(self, prompt, max_new_tokens=None, rid=None,
                 tenant: str = "", traceparent: str = "") -> dict:
        if self.fail_next > 0:
            self.fail_next -= 1
            raise RuntimeError(f"{self.name}: injected failure")
        if self.hold_s:
            time.sleep(self.hold_s)
        self.start()
        with self._lock:
            self._seq += 1
            rid = rid or f"{self.name}-{self._seq}"
            event, box = threading.Event(), []
            self._waiters[rid] = (event, box)
            self.engine.submit(Request(rid=rid, prompt=list(prompt),
                                       max_new_tokens=max_new_tokens,
                                       tenant=tenant,
                                       traceparent=traceparent))
        if not event.wait(timeout=120):
            self._waiters.pop(rid, None)
            raise TimeoutError(f"{self.name}: request {rid} timed out")
        comp = box[0]
        return {"rid": comp.rid, "replica": self.name,
                "prompt_len": comp.prompt_len, "tokens": comp.tokens,
                "finish_reason": comp.finish_reason}

    def install(self, handoff_bytes: bytes, tenant: str = "",
                traceparent: str = "") -> dict:
        """Seat a disagg KV handoff and decode it to completion. The
        handoff wire format already carries tenant/traceparent; the
        kwargs exist for signature parity with :class:`HttpReplica`."""
        from move2kube_tpu.serving.fleet.disagg import KVHandoff

        h = KVHandoff.from_bytes(handoff_bytes)
        event, box = threading.Event(), []
        self.start()
        installed = False
        while not installed:
            with self._lock:
                ok, done = self.engine.install_prefilled(
                    h.request(), h.kv, h.first_token, h.prompt_len)
                if ok:
                    installed = True
                    if done:
                        box.extend(done)
                        event.set()
                    else:
                        self._waiters[h.rid] = (event, box)
            if not installed:
                time.sleep(0.002)  # engine full: let the loop drain a step
        if not event.wait(timeout=120):
            self._waiters.pop(h.rid, None)
            raise TimeoutError(f"{self.name}: handoff {h.rid} timed out")
        comp = box[0]
        return {"rid": comp.rid, "replica": self.name,
                "prompt_len": comp.prompt_len, "tokens": comp.tokens,
                "finish_reason": comp.finish_reason}


class HttpReplica(ReplicaHandle):
    """A remote engine pod: ``/generate`` (and ``/install`` for disagg)
    on the serving port, ``/readyz`` + ``/stats`` on the telemetry
    port (obs/server.py)."""

    def __init__(self, name: str, base_url: str,
                 health_url: str | None = None, timeout_s: float = 120.0):
        self.name = name
        self.base_url = base_url.rstrip("/")
        self.health_url = (health_url or base_url).rstrip("/")
        self.timeout_s = timeout_s

    def _post(self, path: str, data: bytes, ctype: str,
              tenant: str = "", traceparent: str = "") -> bytes:
        """POST with trace/tenant header injection. A non-2xx answer is
        surfaced as :class:`ReplicaHTTPError` with the status and a body
        excerpt — urllib's bare ``HTTP Error 500`` hid what the replica
        actually said."""
        headers = {"Content-Type": ctype}
        if tenant:
            headers[TENANT_HEADER] = tenant
        if traceparent:
            headers[TRACEPARENT_HEADER] = traceparent
        req = urllib.request.Request(
            f"{self.base_url}{path}", data=data, headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                return resp.read()
        except urllib.error.HTTPError as err:
            try:
                body = err.read(512).decode("utf-8", "replace")
            except OSError:
                body = ""
            raise ReplicaHTTPError(self.name, path, err.code,
                                   body) from err

    def generate(self, prompt, max_new_tokens=None, rid=None,
                 tenant: str = "", traceparent: str = "") -> dict:
        body = json.dumps({"prompt": list(prompt),
                           "max_new_tokens": max_new_tokens,
                           "rid": rid}).encode()
        return json.loads(self._post(
            "/generate", body, "application/json",
            tenant=tenant, traceparent=traceparent).decode())

    def install(self, handoff_bytes: bytes, tenant: str = "",
                traceparent: str = "") -> dict:
        return json.loads(self._post(
            "/install", handoff_bytes, "application/octet-stream",
            tenant=tenant, traceparent=traceparent).decode())

    def prefill(self, request):
        """Disagg prefill over HTTP: POST the prompt, get back the
        serialized KV handoff (``KVHandoff.to_bytes`` wire format)."""
        from move2kube_tpu.serving.fleet.disagg import KVHandoff

        body = json.dumps({"prompt": list(request.prompt),
                           "max_new_tokens": request.max_new_tokens,
                           "rid": request.rid}).encode()
        return KVHandoff.from_bytes(self._post(
            "/prefill", body, "application/json",
            tenant=request.tenant, traceparent=request.traceparent))

    def queue_depth(self) -> float:
        try:
            with urllib.request.urlopen(f"{self.health_url}/stats",
                                        timeout=2) as resp:
                stats = json.loads(resp.read().decode())
            return float(stats.get("queue_depth", 0)
                         + stats.get("active_slots", 0))
        except (OSError, ValueError):
            return float("inf")

    def healthy(self) -> bool:
        try:
            with urllib.request.urlopen(f"{self.health_url}/readyz",
                                        timeout=2) as resp:
                return resp.status == 200
        except (OSError, ValueError):
            return False


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    affinity_tokens: int = 16   # prompt prefix length hashed for affinity
    salt: str = ""              # M2KT_FLEET_AFFINITY_SALT (Helm-lifted)
    max_retries: int = 2        # additional replicas tried on failure
    spill_queue_depth: float = 8.0  # affine queue deeper than this spills
    hedge_after_s: float | None = None  # None = hedging off
    disagg_threshold: int = 0   # prompt length that routes via prefill; 0=off

    @classmethod
    def from_env(cls, **overrides) -> "RouterConfig":
        import os

        def _num(name, default, cast):
            try:
                return cast(os.environ.get(name, "") or default)
            except ValueError:
                return default

        hedge = _num("M2KT_ROUTER_HEDGE_MS", 0.0, float)
        cfg = dict(
            affinity_tokens=_num("M2KT_ROUTER_AFFINITY_TOKENS",
                                 cls.affinity_tokens, int),
            salt=os.environ.get("M2KT_FLEET_AFFINITY_SALT", cls.salt),
            max_retries=_num("M2KT_ROUTER_RETRIES", cls.max_retries, int),
            spill_queue_depth=_num("M2KT_ROUTER_SPILL_DEPTH",
                                   cls.spill_queue_depth, float),
            hedge_after_s=(hedge / 1e3) if hedge > 0 else None,
            disagg_threshold=_num("M2KT_FLEET_DISAGG_THRESHOLD", 0, int),
        )
        cfg.update(overrides)
        return cls(**cfg)


class Router:
    def __init__(self, replicas, config: RouterConfig | None = None,
                 prefill_replicas=(), registry: Registry | None = None,
                 tracer=None):
        self.replicas = list(replicas)
        self.prefill_replicas = list(prefill_replicas)
        self.config = config or RouterConfig()
        self.registry = registry if registry is not None else Registry()
        # the router's span ring: every routed request opens a
        # router.request root, every replica hop a router.call child
        # whose traceparent() rides the outbound headers
        self.tracer = tracer if tracer is not None else (
            tracing.get() if tracing.enabled() else None)
        # last-known health, refreshed by probe(); a failed call marks
        # the replica down immediately without waiting for a probe
        self._up: dict[str, bool] = {r.name: True for r in self.replicas}
        self._rr = 0  # round-robin cursor over prefill replicas
        reg = self.registry
        self._requests = reg.counter(
            "m2kt_router_requests_total", "Routed requests by outcome",
            labels=("outcome",))
        self._retries = reg.counter(
            "m2kt_router_retries_total", "Requests retried on another "
            "replica after a failure")
        self._retry_reasons = reg.counter(
            "m2kt_router_retries_by_reason_total",
            "Retries by the failure reason that triggered them",
            labels=("reason",))
        self._markdowns = reg.counter(
            "m2kt_router_marked_down_total",
            "Replicas marked down, by replica and failure reason",
            labels=("replica", "reason"))
        self._hedges = reg.counter(
            "m2kt_router_hedges_total", "Duplicate requests fired at the "
            "runner-up after the hedge deadline")
        self._affinity_hits = reg.counter(
            "m2kt_router_affinity_hits_total",
            "Requests routed to their prefix-affine replica")
        self._spills = reg.counter(
            "m2kt_router_spills_total",
            "Requests spilled to the least-loaded replica (affine replica "
            "down or queue too deep)")
        self._replica_up = reg.gauge(
            "m2kt_router_replica_up", "1 if the replica passed its last "
            "health check", labels=("replica",))
        self._replica_queue = reg.gauge(
            "m2kt_router_replica_queue_depth",
            "Queued + active requests on the replica at last poll",
            labels=("replica",))
        self._inflight = reg.gauge(
            "m2kt_router_inflight", "Requests currently being routed")
        self._disagg = reg.counter(
            "m2kt_router_disagg_total",
            "Requests served via prefill->decode handoff")
        for r in self.replicas:
            self._replica_up.labels(replica=r.name).set(1.0)

    # ------------------------------------------------------------------
    # placement
    # ------------------------------------------------------------------

    def probe(self) -> dict:
        """Poll every replica's health endpoint and refresh the up/queue
        gauges. Recovered replicas rejoin the affinity ring here."""
        out = {}
        for r in self.replicas:
            up = bool(r.healthy())
            self._up[r.name] = up
            self._replica_up.labels(replica=r.name).set(1.0 if up else 0.0)
            if up:
                self._replica_queue.labels(replica=r.name).set(
                    r.queue_depth())
            out[r.name] = up
        return out

    def _healthy(self):
        return [r for r in self.replicas if self._up.get(r.name, True)]

    def pick(self, prompt, exclude=()) -> ReplicaHandle | None:
        """Affine replica by rendezvous hash of the prompt prefix,
        spilling to least-loaded when it is excluded, down, or
        backlogged. Pure placement — no side effects beyond metrics."""
        excluded = {r.name for r in exclude}
        healthy = [r for r in self._healthy() if r.name not in excluded]
        if not healthy:
            return None
        key = prefix_hash(prompt, self.config.salt,
                          self.config.affinity_tokens)
        affine = max(healthy,
                     key=lambda r: _rendezvous_score(key, r.name))
        if affine.queue_depth() <= self.config.spill_queue_depth:
            self._affinity_hits.inc()
            return affine
        self._spills.inc()
        return min(healthy, key=lambda r: r.queue_depth())

    def _mark_down(self, replica: ReplicaHandle,
                   reason: str = "probe") -> None:
        self._up[replica.name] = False
        self._replica_up.labels(replica=replica.name).set(0.0)
        self._markdowns.labels(replica=replica.name, reason=reason).inc()

    # ------------------------------------------------------------------
    # request path
    # ------------------------------------------------------------------

    def _open_call(self, root, replica: ReplicaHandle, hop: str):
        """Open a ``router.call`` child span for one replica hop and
        return ``(span, traceparent_header)`` — the header is what rides
        the outbound request so the replica's root parents under it."""
        if self.tracer is None or root is None:
            return None, ""
        span = self.tracer.start(
            "router.call",
            attrs={"replica": getattr(replica, "name", hop), "hop": hop},
            parent=root, detached=True)
        return span, span.traceparent()

    def generate(self, prompt, max_new_tokens: int | None = None,
                 rid: str | None = None, tenant: str = "",
                 traceparent: str | None = None) -> dict:
        prompt = list(prompt)
        tenant = clean_tenant(tenant)
        self._inflight.inc()
        root = None
        if self.tracer is not None:
            # many requests route concurrently in one process: the root
            # is detached and identity threads through explicitly. An
            # inbound traceparent (a client already tracing) wins.
            root = self.tracer.start(
                "router.request",
                attrs={"prompt_len": len(prompt), "tenant": tenant},
                detached=True, remote_parent=traceparent)
        try:
            if (self.config.disagg_threshold
                    and len(prompt) >= self.config.disagg_threshold
                    and self.prefill_replicas):
                try:
                    out = self._generate_disagg(prompt, max_new_tokens,
                                                rid, tenant, root)
                    self._requests.labels(outcome="ok").inc()
                    return out
                except Exception:  # noqa: BLE001 - fall back to direct path
                    pass
            out = self._generate_direct(prompt, max_new_tokens, rid,
                                        tenant, root)
            self._requests.labels(outcome="ok").inc()
            return out
        except Exception as err:
            self._requests.labels(outcome="error").inc()
            if root is not None:
                root.attrs["error"] = failure_reason(err)
            raise
        finally:
            if root is not None:
                self.tracer.end(root)
            self._inflight.dec()

    def _generate_direct(self, prompt, max_new_tokens, rid, tenant="",
                         root=None) -> dict:
        tried: list[ReplicaHandle] = []
        last_err: Exception | None = None
        for attempt in range(self.config.max_retries + 1):
            replica = self.pick(prompt, exclude=tried)
            if replica is None:
                break
            if attempt:
                self._retries.inc()
                if last_err is not None:
                    self._retry_reasons.labels(
                        failure_reason(last_err)).inc()
            tried.append(replica)
            try:
                if self.config.hedge_after_s is not None:
                    return self._call_hedged(replica, prompt,
                                             max_new_tokens, rid, tried,
                                             tenant, root)
                return self._call_one(replica, prompt, max_new_tokens,
                                      rid, tenant, root)
            except Exception as err:  # noqa: BLE001 - any failure fails over
                last_err = err
                self._mark_down(replica, failure_reason(err))
        if last_err is not None:
            raise last_err
        raise RuntimeError("router: no healthy replica available")

    def _call_one(self, replica, prompt, max_new_tokens, rid, tenant,
                  root) -> dict:
        span, header = self._open_call(root, replica, "generate")
        try:
            return replica.generate(prompt, max_new_tokens, rid,
                                    tenant=tenant, traceparent=header)
        except Exception as err:  # noqa: BLE001 - annotate, then re-raise
            if span is not None:
                span.attrs["error"] = failure_reason(err)
            raise
        finally:
            if span is not None:
                self.tracer.end(span)

    def _call_hedged(self, primary, prompt, max_new_tokens, rid,
                     tried, tenant="", root=None) -> dict:
        """Fire ``primary``; if it has not answered within the hedge
        deadline, fire the runner-up too and take whichever finishes
        first. The loser's work is wasted by design — hedging trades
        duplicate decode for tail latency."""
        done = threading.Event()
        results: list[dict] = []
        errors: list[Exception] = []

        def call(replica):
            try:
                results.append(self._call_one(
                    replica, prompt, max_new_tokens, rid, tenant, root))
                done.set()
            except Exception as err:  # noqa: BLE001 - collected below
                errors.append(err)
                if len(errors) >= len(threads):
                    done.set()

        threads = [threading.Thread(target=call, args=(primary,),
                                    daemon=True)]
        threads[0].start()
        if not done.wait(self.config.hedge_after_s):
            backup = self.pick(prompt, exclude=tried)
            if backup is not None:
                self._hedges.inc()
                tried.append(backup)
                threads.append(threading.Thread(target=call, args=(backup,),
                                                daemon=True))
                threads[1].start()
        done.wait()
        while not results and any(t.is_alive() for t in threads):
            time.sleep(0.005)
        if results:
            return results[0]
        raise errors[0] if errors else RuntimeError("hedge: no result")

    def _generate_disagg(self, prompt, max_new_tokens, rid, tenant="",
                         root=None) -> dict:
        """Long prompts route prefill->decode: round-robin a prefill
        replica for the KV handoff, then seat it on the prefix-affine
        decode replica (same placement as the direct path, so the
        decode side's cache locality is preserved). Both hops get their
        own router.call span; the handoff wire carries the install
        hop's traceparent so the decode replica's root stitches under
        it even when the bytes travel through a queue."""
        prefill = self.prefill_replicas[self._rr
                                        % len(self.prefill_replicas)]
        self._rr += 1
        pspan, pheader = self._open_call(root, prefill, "prefill")
        try:
            handoff = prefill.prefill(Request(
                rid=rid or f"disagg-{self._rr}", prompt=list(prompt),
                max_new_tokens=max_new_tokens, tenant=tenant,
                traceparent=pheader))
        finally:
            if pspan is not None:
                self.tracer.end(pspan)
        decode = self.pick(prompt)
        if decode is None:
            raise RuntimeError("router: no healthy decode replica")
        dspan, dheader = self._open_call(root, decode, "install")
        handoff.tenant = tenant
        handoff.traceparent = dheader
        try:
            out = decode.install(handoff.to_bytes(), tenant=tenant,
                                 traceparent=dheader)
        finally:
            if dspan is not None:
                self.tracer.end(dspan)
        self._disagg.inc()
        return out


class RouterHTTPServer:
    """stdlib-HTTP front for the router role (assets/jax/serve_tpu.py
    runs this when ``M2KT_FLEET_ROLE=router``). ``/readyz`` reports
    serving once any backend replica is healthy, so the router pod's
    readiness gate composes with the engines' own gates."""

    def __init__(self, router: Router, port: int = 8000,
                 default_max_new: int | None = None):
        self.router = router
        self.default_max_new = default_max_new
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def _send(self, code: int, body: bytes,
                      ctype: str = "application/json") -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    self._send(200, b'{"status":"ok"}')
                elif self.path == "/readyz":
                    up = outer.router.probe()
                    ready = any(up.values())
                    body = json.dumps({"ready": ready,
                                       "replicas": up}).encode()
                    self._send(200 if ready else 503, body)
                elif self.path == "/metrics":
                    self._send(200, outer.router.registry.render().encode(),
                               "text/plain; version=0.0.4")
                else:
                    self._send(404, b'{"error":"not found"}')

            def do_POST(self):
                if self.path != "/generate":
                    self._send(404, b'{"error":"not found"}')
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(n).decode())
                    out = outer.router.generate(
                        payload["prompt"],
                        payload.get("max_new_tokens",
                                    outer.default_max_new),
                        payload.get("rid"),
                        tenant=self.headers.get(TENANT_HEADER, ""),
                        traceparent=self.headers.get(
                            TRACEPARENT_HEADER))
                    self._send(200, json.dumps(out).encode())
                except Exception as err:  # noqa: BLE001 - surface as 500
                    self._send(500, json.dumps(
                        {"error": str(err)}).encode())

        self._server = ThreadingHTTPServer(("0.0.0.0", port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="m2kt-router",
            daemon=True)

    def start(self) -> "RouterHTTPServer":
        self._thread.start()
        return self

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()


def build_fleet(model, variables, n_replicas: int,
                engine_config: EngineConfig | None = None,
                router_config: RouterConfig | None = None,
                registry: Registry | None = None) -> Router:
    """An in-process fleet: N engine replicas behind a router. The
    CPU-mode stand-in for the emitted per-role pods, used by
    ``fleet-smoke`` and the bench fleet phase."""
    cfg = engine_config or EngineConfig.from_env()
    replicas = [
        InProcessReplica(f"replica-{i}",
                         ServingEngine(model, variables, cfg)).start()
        for i in range(n_replicas)]
    return Router(replicas, config=router_config, registry=registry)
