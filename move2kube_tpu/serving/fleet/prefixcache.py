"""Refcounted prefix/prompt cache over the paged KV pool.

The fleet's cheapest token is the one never prefilled: multi-tenant
serving traffic is dominated by shared prompt *prefixes* (system
prompts, few-shot preambles), and the paged KV cache already stores
K/V in position-aligned fixed-size pages — so two requests that agree
on their first ``k*block_size`` tokens can point their block tables at
the *same* pages. This module is the host-side index that makes that
sharing safe:

- a **trie** keyed on token chunks (one node per page; dict buckets
  hash the chunk tuples, the same prefix hash the router's session
  affinity uses) maps cached prefixes to immutable page runs;
- every cached page holds one **reference** in the
  :class:`~move2kube_tpu.serving.kvcache.PageAllocator`, so a page
  outlives the sequence that prefilled it and is returned to the pool
  only when both the cache and every borrowing slot have dropped it;
- pages handed out by :meth:`PrefixCache.lookup` are *shared*
  (refcount > 1) and therefore **immutable** — a slot that must write
  into one (the partially-filled boundary page, or re-feeding the last
  prompt token of a fully-covered prompt) copy-on-writes it first
  (kvcache.copy_page), which the engine enforces.

Eviction is LRU over trie *leaves* (interior nodes are pinned by their
descendants — evicting a parent before its child would orphan the
child's positional prefix). Evicting a node drops the cache's
reference; the allocator reclaims the page once no slot borrows it.

Single-threaded by design: one cache belongs to one engine, and the
engine's admission loop is the only caller.
"""

from __future__ import annotations

import dataclasses

from move2kube_tpu.serving.kvcache import PageAllocator


@dataclasses.dataclass
class PrefixHit:
    """A successful lookup. ``pages`` are the covering pages in block
    order — the allocator references for them are already taken on the
    caller's behalf (release with ``allocator.free`` when done, whether
    or not the hit is used)."""

    pages: list[int]
    covered: int  # tokens of K/V those pages hold


class _Node:
    __slots__ = ("chunk", "page", "children", "partials", "last_used")

    def __init__(self, chunk: tuple, page: int) -> None:
        self.chunk = chunk
        self.page = page
        self.children: dict[tuple, _Node] = {}
        # partially-filled boundary pages (< block_size tokens); always
        # leaves — a deeper full page can't stack on a partial one
        self.partials: list[_Node] = []
        self.last_used = 0

    def is_leaf(self) -> bool:
        return not self.children and not self.partials


class PrefixCache:
    def __init__(self, block_size: int, allocator: PageAllocator,
                 max_pages: int = 0) -> None:
        self.block_size = int(block_size)
        self.allocator = allocator
        self.max_pages = int(max_pages)  # 0 = bounded only by pool pressure
        self._root = _Node((), -1)
        self._clock = 0
        self._pages = 0
        self.hits = 0
        self.misses = 0
        self.hit_tokens = 0
        self.inserted_pages = 0
        self.evicted_pages = 0

    # ------------------------------------------------------------------

    @property
    def total_pages(self) -> int:
        return self._pages

    def __len__(self) -> int:
        return self._pages

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def lookup(self, tokens) -> PrefixHit | None:
        """Longest cached prefix of ``tokens``. On a hit, takes one
        allocator reference per returned page (the pages cannot be
        evicted out from under the caller)."""
        tokens = list(tokens)
        bs = self.block_size
        now = self._tick()
        node, pages, covered = self._root, [], 0
        while covered + bs <= len(tokens):
            child = node.children.get(tuple(tokens[covered:covered + bs]))
            if child is None:
                break
            child.last_used = now
            pages.append(child.page)
            covered += bs
            node = child
        # longest partial boundary page that is a prefix of the remainder
        rest = tokens[covered:]
        best = None
        for part in node.partials:
            n = len(part.chunk)
            if n <= len(rest) and tuple(rest[:n]) == part.chunk:
                if best is None or n > len(best.chunk):
                    best = part
        if best is not None:
            best.last_used = now
            pages.append(best.page)
            covered += len(best.chunk)
        if not pages:
            self.misses += 1
            return None
        self.allocator.incref(pages)
        self.hits += 1
        self.hit_tokens += covered
        return PrefixHit(pages=list(pages), covered=covered)

    def insert(self, tokens, pages) -> int:
        """Adopt a freshly prefilled prompt's page run. ``pages`` are
        the covering pages in block order (``ceil(len(tokens)/bs)`` of
        them, last one partial when the length isn't page-aligned).
        Chunks already cached keep their existing page (the newcomer's
        duplicate stays private to its slot); new chunks incref the
        donor's page into the cache. Returns pages adopted."""
        tokens = list(tokens)
        bs = self.block_size
        now = self._tick()
        node, adopted, idx = self._root, 0, 0
        while (idx + 1) * bs <= len(tokens):
            chunk = tuple(tokens[idx * bs:(idx + 1) * bs])
            child = node.children.get(chunk)
            if child is None:
                child = _Node(chunk, int(pages[idx]))
                self.allocator.incref([child.page])
                node.children[chunk] = child
                adopted += 1
                self._pages += 1
            child.last_used = now
            node = child
            idx += 1
        rest = tuple(tokens[idx * bs:])
        if rest and idx < len(pages):
            if not any(p.chunk == rest for p in node.partials):
                part = _Node(rest, int(pages[idx]))
                self.allocator.incref([part.page])
                part.last_used = now
                node.partials.append(part)
                adopted += 1
                self._pages += 1
        self.inserted_pages += adopted
        if self.max_pages and self._pages > self.max_pages:
            self.evict(self._pages - self.max_pages)
        return adopted

    def evict(self, n_pages: int) -> int:
        """Drop LRU leaves until ``n_pages`` allocator pages were
        actually reclaimed (a dropped page still borrowed by a live
        slot frees later, so keep going) or the trie is empty.
        Returns the number of cache pages dropped."""
        before = self.allocator.available
        dropped = 0
        while self.allocator.available - before < n_pages and self._pages:
            victim, parent = self._lru_leaf()
            if victim is None:
                break
            self._drop(victim, parent)
            dropped += 1
        return dropped

    def clear(self) -> int:
        return self.evict(self._pages) if self._pages else 0

    def stats(self) -> dict:
        total = self.hits + self.misses
        return {
            "pages": self._pages,
            "hits": self.hits,
            "misses": self.misses,
            "hit_rate": self.hits / total if total else 0.0,
            "hit_tokens": self.hit_tokens,
            "inserted_pages": self.inserted_pages,
            "evicted_pages": self.evicted_pages,
        }

    # ------------------------------------------------------------------

    def _lru_leaf(self) -> tuple[_Node | None, _Node | None]:
        best, best_parent = None, None
        stack = [self._root]
        while stack:
            node = stack.pop()
            for part in node.partials:
                if best is None or part.last_used < best.last_used:
                    best, best_parent = part, node
            for child in node.children.values():
                if child.is_leaf():
                    if best is None or child.last_used < best.last_used:
                        best, best_parent = child, node
                else:
                    stack.append(child)
        return best, best_parent

    def _drop(self, node: _Node, parent: _Node) -> None:
        if node in parent.partials:
            parent.partials.remove(node)
        else:
            parent.children.pop(node.chunk, None)
        self.allocator.free([node.page])
        self._pages -= 1
        self.evicted_pages += 1
