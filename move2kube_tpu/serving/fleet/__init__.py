"""Fleet-scale serving: request router, refcounted prefix cache, and
disaggregated prefill/decode over the continuous-batching engine.

Layering (no cycles): ``prefixcache`` depends only on the paged KV
allocator; the engine (serving/engine.py) consumes it. ``router`` and
``disagg`` sit *above* the engine and import it. This ``__init__``
stays import-free so ``engine -> fleet.prefixcache`` never drags the
router's HTTP machinery into the decode hot path.
"""
