"""Deterministic chaos injectors for the serving fleet.

resilience/faults.py proves the *training* resume story on CPU; this
module does the same for serving: the recovery path ("a replica that
dies mid-stream loses nothing — the router resumes the stream
token-exactly on a survivor") is only real if CI can kill a replica at
an exact token, damage a KV handoff in flight, or flap a health check,
all deterministically and without a cluster. The injectors hang off
:class:`~move2kube_tpu.serving.fleet.router.InProcessReplica` (its
``chaos`` attribute) and the serve template, and are driven entirely by
``M2KT_CHAOS_*`` env vars — all inert when unset, so production pods
carry them dormant exactly like the training faults.

Knobs (docs/USAGE.md):

- ``M2KT_CHAOS_KILL_TOKEN`` — kill the replica when it emits its Nth
  token (1-based) for a matching request; the token IS journaled first,
  so the router's resume starts from exactly N tokens — the same state
  a real mid-emission death leaves. ``0`` kills at generate entry
  (before any token).
- ``M2KT_CHAOS_KILL_RID``   — rid substring the kill applies to
  (empty = any request)
- ``M2KT_CHAOS_HANDOFF``    — ``drop`` (the bytes never arrive) |
  ``truncate`` (half the npz arrives — must 4xx, not crash)
- ``M2KT_CHAOS_SLOW_S``     — injected latency at generate entry
  (a straggling replica; not marker-gated — slowness persists)
- ``M2KT_CHAOS_FLAP_N``     — the replica's first N health probes
  report down, then it recovers (readmission/backoff drills)
- ``M2KT_CHAOS_SHARD``      — weight-plane shard damage on the serving
  peer: ``corrupt`` (valid wire, tampered payload — the fetcher's
  digest check must catch it) | ``truncate`` (half the npz — must
  surface as a clean ValueError, not a zipfile crash)
- ``M2KT_CHAOS_SHARD_KILL_N`` — the peer dies after serving its Nth
  weight shard (a pod SIGKILLed mid-fan-out; the fetcher must finish
  from the surviving peers)
- ``M2KT_CHAOS_SWAP``       — ``kill`` kills the replica inside its
  live weight swap (mid-rolling-update death; the router marks it down
  and the swap continues across the survivors)
- ``M2KT_CHAOS_MARKER``     — exactly-once marker file shared with the
  training faults' semantics: kill/handoff/shard/swap faults fire only
  while the marker is absent and create it first, so the recovered
  attempt survives. Without a marker they fire every time.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time

from move2kube_tpu.resilience.faults import _marker_fired

log = logging.getLogger("m2kt.chaos")


class ChaosKill(RuntimeError):
    """Injected replica death — the in-process stand-in for a serving
    pod being SIGKILLed mid-decode."""


@dataclasses.dataclass(frozen=True)
class ChaosConfig:
    kill_token: int | None = None  # Nth emitted token; 0 = at entry
    kill_rid: str = ""             # rid substring filter ("" = any)
    handoff: str = ""              # "" | "drop" | "truncate"
    slow_s: float = 0.0            # injected latency per generate
    flap_n: int = 0                # first N probes report down
    shard: str = ""                # "" | "corrupt" | "truncate"
    shard_kill_n: int = 0          # peer dies after Nth shard served
    swap: str = ""                 # "" | "kill" (die mid-weight-swap)
    marker: str = ""               # exactly-once marker path

    @classmethod
    def from_env(cls, **overrides) -> "ChaosConfig":
        def _num(name, default, cast):
            try:
                raw = os.environ.get(name, "")
                return cast(raw) if raw else default
            except ValueError:
                return default

        cfg = dict(
            kill_token=_num("M2KT_CHAOS_KILL_TOKEN", None, int),
            kill_rid=os.environ.get("M2KT_CHAOS_KILL_RID", ""),
            handoff=os.environ.get("M2KT_CHAOS_HANDOFF", ""),
            slow_s=_num("M2KT_CHAOS_SLOW_S", 0.0, float),
            flap_n=_num("M2KT_CHAOS_FLAP_N", 0, int),
            shard=os.environ.get("M2KT_CHAOS_SHARD", ""),
            shard_kill_n=_num("M2KT_CHAOS_SHARD_KILL_N", 0, int),
            swap=os.environ.get("M2KT_CHAOS_SWAP", ""),
            marker=os.environ.get("M2KT_CHAOS_MARKER", ""),
        )
        cfg.update(overrides)
        return cls(**cfg)

    def armed(self) -> bool:
        return (self.kill_token is not None or bool(self.handoff)
                or self.slow_s > 0 or self.flap_n > 0
                or bool(self.shard) or self.shard_kill_n > 0
                or bool(self.swap))


class ServingChaos:
    """One injector instance, shared by every replica it is attached to
    (per-replica state is keyed by replica name). All hooks are cheap
    no-ops for the faults that are not configured."""

    def __init__(self, config: ChaosConfig | None = None):
        self.config = config or ChaosConfig.from_env()
        self._emitted: dict[str, int] = {}   # rid -> tokens seen
        self._probes: dict[str, int] = {}    # replica -> probes seen
        self._shards: dict[str, int] = {}    # peer -> shards served

    def _matches(self, rid: str) -> bool:
        return not self.config.kill_rid or self.config.kill_rid in rid

    def _fire_once(self) -> bool:
        """True when this exactly-once fault should fire now (claims the
        marker). Marker-less configs fire every time."""
        return not _marker_fired(self.config.marker)

    def on_token(self, replica: str, rid: str, tok: int) -> None:
        """Called AFTER the router's journal recorded ``tok`` (see
        ``InProcessReplica._on_token``): a kill at token N leaves
        exactly N tokens journaled."""
        n = self.config.kill_token
        if n is None or n < 1 or not self._matches(rid):
            return
        seen = self._emitted.get(rid, 0) + 1
        self._emitted[rid] = seen
        if seen < n:
            return
        if not self._fire_once():
            return
        log.warning("chaos: killing %s at token %d of %s", replica, seen,
                    rid)
        print(f"[m2kt] CHAOS: killed {replica} at token {seen} of {rid}",
              flush=True)
        raise ChaosKill(f"{replica}: killed at token {seen} of {rid}")

    def on_generate(self, replica: str, rid: str) -> None:
        if self.config.slow_s > 0:
            time.sleep(self.config.slow_s)
        if (self.config.kill_token == 0 and self._matches(rid)
                and self._fire_once()):
            log.warning("chaos: killing %s at generate entry (%s)",
                        replica, rid)
            raise ChaosKill(f"{replica}: killed before token 0 of {rid}")

    def on_handoff(self, replica: str, data: bytes) -> bytes:
        mode = self.config.handoff
        if not mode or not self._fire_once():
            return data
        log.warning("chaos: %s KV handoff into %s (%d bytes)", mode,
                    replica, len(data))
        if mode == "drop":
            raise ChaosKill(f"{replica}: KV handoff dropped in transit")
        if mode == "truncate":
            return data[:max(1, len(data) // 2)]
        return data

    def on_shard(self, peer: str, path: str, data: bytes) -> bytes:
        """Weight-plane faults on the SERVING side of a P2P fetch: kill
        the peer after its Nth shard, or damage one shard in flight.
        ``corrupt`` re-encodes a tampered payload — valid wire bytes
        with the wrong content, the exact failure only the fetcher's
        sha256 check can catch (truncation already dies in decode)."""
        n = self.config.shard_kill_n
        if n > 0:
            served = self._shards.get(peer, 0) + 1
            self._shards[peer] = served
            if served >= n and self._fire_once():
                log.warning("chaos: killing peer %s after shard %d (%s)",
                            peer, served, path)
                print(f"[m2kt] CHAOS: peer {peer} died after "
                      f"{served} shards", flush=True)
                raise ChaosKill(f"{peer}: died serving shard {path}")
        mode = self.config.shard
        if not mode or not self._fire_once():
            return data
        log.warning("chaos: %s weight shard %s from %s (%d bytes)", mode,
                    path, peer, len(data))
        if mode == "truncate":
            return data[:max(1, len(data) // 2)]
        if mode == "corrupt":
            from move2kube_tpu.serving.fleet import weights as weightslib

            spath, arr = weightslib.decode_shard(data)
            flipped = arr.copy()
            flipped.flat[0] = -flipped.flat[0] if flipped.flat[0] else 1
            return weightslib.encode_shard(spath, flipped)
        return data

    def on_swap(self, replica: str) -> None:
        """Called at the top of a replica's live weight swap."""
        if self.config.swap == "kill" and self._fire_once():
            log.warning("chaos: killing %s mid-weight-swap", replica)
            print(f"[m2kt] CHAOS: killed {replica} mid-swap", flush=True)
            raise ChaosKill(f"{replica}: killed mid-weight-swap")

    def on_probe(self, replica: str) -> bool:
        """False while the replica should flap unhealthy."""
        if self.config.flap_n <= 0:
            return True
        seen = self._probes.get(replica, 0) + 1
        self._probes[replica] = seen
        return seen > self.config.flap_n


def maybe_chaos() -> ServingChaos | None:
    """A ServingChaos when any ``M2KT_CHAOS_*`` knob is set, else None.
    The serve template calls this once at startup — production pods
    (no knobs) pay nothing."""
    cfg = ChaosConfig.from_env()
    return ServingChaos(cfg) if cfg.armed() else None
