"""Arrival-rate forecaster for the predictive autoscaler.

The reactive HPAs (fleet_wiring) scale on queue depth — a signal that
only moves AFTER capacity is already short, which at diurnal traffic
means the fleet is perpetually one cold-join behind the curve. This
module predicts the demand instead, from the router's own per-tenant
admitted-token counters (``m2kt_router_admitted_tokens_total`` minus
the completion corrections):

- **Holt level + trend**: exponentially-weighted level with a
  per-second trend term, normalized for irregular sample cadence, so a
  ramp extrapolates instead of lagging by one smoothing constant;
- **additive diurnal seasonal component**: the day is discretized into
  bins and each bin keeps an EWMA of the deviation from the level, so
  tomorrow's 9am spike is priced into today's 9am-minus-lead forecast
  the second time it happens;
- **horizon = cold-join time**: the forecaster is always asked for the
  demand at ``now + lead``, where the lead is the measured time a new
  replica needs to join and warm (the PR-14 prewarm speedup is exactly
  the lead this loop gets to spend).

The clock is injectable and nothing here imports the engine: the fleet
simulator drives the same forecaster through millions of synthetic
seconds, and the emitted controller Deployment feeds it from a scraped
``/metrics`` text page. Stdlib-only (vendored into emitted images).
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass

from move2kube_tpu.obs.metrics import WindowRate

DAY_S = 86400.0


@dataclass(frozen=True)
class ForecastConfig:
    """Smoothing constants for the Holt-Winters-style estimator.

    The defaults assume samples every ~15-60s: level follows minutes,
    trend follows tens of minutes, the seasonal field follows days.
    ``season_bins`` trades seasonal resolution against warm-up time —
    48 bins = one seat per half hour of the day."""

    alpha: float = 0.3      # level gain per observation
    beta: float = 0.1       # trend gain (per-second slope units)
    gamma: float = 0.3      # seasonal-deviation gain per bin visit
    season_s: float = DAY_S
    season_bins: int = 48
    # trend is clamped so one noisy burst cannot extrapolate the fleet
    # to max replicas: |trend| <= level * max_trend_frac per second
    max_trend_frac: float = 0.01
    # time constant of the slow reference mean the seasonal field and
    # the trend are measured against (None = one season period). Short
    # values make the trend chase fast ramps — the bench live smoke
    # uses that; production wants the default so the diurnal swing
    # stays OUT of the mean and lands in the seasonal bins.
    mean_tau_s: float | None = None


class DemandForecaster:
    """EWMA level+trend with an additive diurnal seasonal component.

    Feed it demand-rate observations (tokens/s) via :meth:`observe`, or
    raw monotone counter readings via :meth:`observe_counter`; ask for
    the rate expected ``horizon_s`` from now via :meth:`forecast`.

    ``epoch`` anchors the seasonal bins (defaults to the first
    observation's timestamp) so synthetic timelines and the simulator
    get reproducible bin placement.
    """

    def __init__(self, config: ForecastConfig | None = None,
                 clock=time.monotonic, epoch: float | None = None) -> None:
        self.config = config or ForecastConfig()
        self._clock = clock
        self._epoch = epoch
        self.level = 0.0
        self.trend = 0.0  # tokens/s per second
        self.mean = 0.0   # slow reference mean (seasonal baseline)
        self._seasonal = [0.0] * max(1, int(self.config.season_bins))
        self._seen_bins = [False] * len(self._seasonal)
        self._last_t: float | None = None
        self.observations = 0

    # -- seasonal bins -----------------------------------------------------

    def _bin(self, t: float) -> int:
        period = max(1e-9, float(self.config.season_s))
        phase = ((t - (self._epoch or 0.0)) % period) / period
        return min(len(self._seasonal) - 1,
                   int(phase * len(self._seasonal)))

    def seasonal(self, t: float) -> float:
        """The learned deviation-from-level for ``t``'s bin of the day
        (0 until that bin has been visited)."""
        b = self._bin(t)
        return self._seasonal[b] if self._seen_bins[b] else 0.0

    # -- updates -----------------------------------------------------------

    def observe(self, tps: float, t: float | None = None) -> None:
        """One demand-rate observation (tokens/s) at time ``t``
        (default: now). Robust to irregular cadence: the trend is a
        per-second slope, projected over the actual gap."""
        now = self._clock() if t is None else float(t)
        tps = max(0.0, float(tps))
        if self._epoch is None:
            self._epoch = now
        cfg = self.config
        b = self._bin(now)
        season = self._seasonal[b] if self._seen_bins[b] else 0.0
        if self._last_t is None:
            self.mean = tps
            self.level = tps - season
        else:
            dt = max(1e-9, now - self._last_t)
            # slow reference mean, cadence-free (gain derives from the
            # actual gap, so 0.2s and 30min tickers see the same tau)
            tau = cfg.mean_tau_s if cfg.mean_tau_s else cfg.season_s
            gain = 1.0 - math.exp(-dt / max(1e-9, tau))
            prev_mean = self.mean
            self.mean = gain * tps + (1.0 - gain) * self.mean
            # trend = smoothed slope of the SLOW mean: secular growth
            # only. Tracking the level's slope here double-counts the
            # diurnal swing the seasonal field already prices in
            # (measured 2.2x WORSE than persistence on a clean diurnal
            # signal; this form measures ~0.4x).
            slope = (self.mean - prev_mean) / dt
            self.trend = cfg.beta * slope + (1.0 - cfg.beta) * self.trend
            predicted = self.level + self.trend * dt
            self.level = (cfg.alpha * (tps - season)
                          + (1.0 - cfg.alpha) * predicted)
            cap = abs(self.level) * cfg.max_trend_frac
            self.trend = max(-cap, min(cap, self.trend))
        # the seasonal field learns the deviation from the slow mean —
        # NOT from the fast level, which chases the curve and eats the
        # seasonality before the bins can learn it. A bin's first visit
        # snaps to the full residual so day one already prices the
        # curve; later visits blend at gamma.
        if not self._seen_bins[b]:
            self._seasonal[b] = tps - self.mean
        else:
            self._seasonal[b] = (cfg.gamma * (tps - self.mean)
                                 + (1.0 - cfg.gamma) * season)
        self._seen_bins[b] = True
        self._last_t = now
        self.observations += 1

    def forecast(self, horizon_s: float = 0.0,
                 now: float | None = None) -> float:
        """Expected demand rate (tokens/s) ``horizon_s`` from now:
        level, plus the trend projected over the horizon, plus the
        seasonal deviation of the bin the horizon LANDS in — which is
        the whole point: the forecast prices in the part of the day the
        new capacity will serve, not the part it was decided in."""
        if self.observations == 0:
            return 0.0
        if now is None:
            now = self._last_t if self._last_t is not None \
                else self._clock()
        target = now + max(0.0, float(horizon_s))
        return max(0.0, self.level + self.trend * max(0.0, horizon_s)
                   + self.seasonal(target))


class TenantDemandForecaster:
    """Per-tenant :class:`DemandForecaster` bank behind the aggregate
    forecaster's API — the ROADMAP item-2 leftover: the emitted
    controller used to forecast one aggregate rate, so a burst in one
    tenant was smeared across the seasonal memory of all of them.

    ``observe(tenant, tps)`` routes to that tenant's forecaster
    (first-come seats up to ``max_tenants``; later tenants share one
    overflow forecaster, the same bounded-cardinality convention as the
    metric labels). ``forecast(horizon_s)`` sums the per-tenant
    forecasts, so a :class:`PredictiveAutoscaler` holding this object
    needs no changes; :meth:`forecast_by_tenant` exposes the split for
    gauges and chargeback-aware scaling. All tenants share one seasonal
    epoch so their diurnal bins align."""

    OVERFLOW = "other"

    def __init__(self, config: ForecastConfig | None = None,
                 clock=time.monotonic, epoch: float | None = None,
                 max_tenants: int = 8) -> None:
        self.config = config or ForecastConfig()
        self._clock = clock
        self._epoch = epoch
        self.max_tenants = max(1, int(max_tenants))
        self._forecasters: dict[str, DemandForecaster] = {}

    def _get(self, tenant: str) -> DemandForecaster:
        f = self._forecasters.get(tenant)
        if f is None:
            if (len(self._forecasters) >= self.max_tenants
                    and tenant != self.OVERFLOW):
                return self._get(self.OVERFLOW)
            f = self._forecasters[tenant] = DemandForecaster(
                self.config, clock=self._clock, epoch=self._epoch)
        return f

    def tenants(self) -> list[str]:
        return list(self._forecasters)

    def observe(self, tenant: str, tps: float,
                t: float | None = None) -> None:
        now = self._clock() if t is None else float(t)
        if self._epoch is None:
            # one shared epoch: every tenant's seasonal bins align
            self._epoch = now
        self._get(str(tenant)).observe(tps, t=now)

    def forecast_by_tenant(self, horizon_s: float = 0.0,
                           now: float | None = None) -> dict[str, float]:
        return {tenant: f.forecast(horizon_s, now=now)
                for tenant, f in self._forecasters.items()}

    def forecast(self, horizon_s: float = 0.0,
                 now: float | None = None) -> float:
        """Aggregate demand = sum of per-tenant forecasts — the shape
        :class:`PredictiveAutoscaler` consumes unchanged."""
        return sum(self.forecast_by_tenant(horizon_s, now=now).values())

    @property
    def observations(self) -> int:
        return sum(f.observations for f in self._forecasters.values())


class CounterDemand:
    """Demand-rate source over a monotone token counter: wraps the
    shared :class:`WindowRate` sampler (obs/metrics.py) and feeds a
    forecaster, so neither the in-process autoscaler (reading
    ``router.admitted_tokens``) nor the emitted controller (reading a
    scraped counter value) re-implements the window math."""

    def __init__(self, read, forecaster: DemandForecaster,
                 clock=time.monotonic, window_s: float = 60.0) -> None:
        self.forecaster = forecaster
        self.window_s = float(window_s)
        self._rate = WindowRate(read, clock=clock,
                                horizon_s=max(600.0, 10 * window_s))
        self._clock = clock

    def tick(self, t: float | None = None,
             value: float | None = None) -> float:
        """Sample the counter, fold the windowed rate into the
        forecaster, return the observed tokens/s."""
        now, _val = self._rate.sample(t=t, value=value)
        tps = self._rate.rate(self.window_s, now=now)
        self.forecaster.observe(tps, t=now)
        return tps


class TenantCounterDemand:
    """Per-tenant :class:`CounterDemand`: one :class:`WindowRate` per
    tenant over scraped counter values, feeding a
    :class:`TenantDemandForecaster`. The emitted controller ticks this
    with the per-tenant net-admitted-token dict each scrape."""

    def __init__(self, forecaster: TenantDemandForecaster,
                 clock=time.monotonic, window_s: float = 60.0) -> None:
        self.forecaster = forecaster
        self.window_s = float(window_s)
        self._clock = clock
        self._rates: dict[str, WindowRate] = {}

    def _seat(self, tenant: str) -> str:
        """The rate-window key ``tenant`` lands on: its own seat while
        seats remain, the shared overflow seat after (cap + 1 windows
        total, the same convention as the metric labels)."""
        if tenant in self._rates:
            return tenant
        if len(self._rates) >= self.forecaster.max_tenants:
            tenant = TenantDemandForecaster.OVERFLOW
            if tenant in self._rates:
                return tenant
        self._rates[tenant] = WindowRate(
            lambda: 0.0, clock=self._clock,
            horizon_s=max(600.0, 10 * self.window_s))
        return tenant

    def tick(self, totals: dict[str, float],
             t: float | None = None) -> dict[str, float]:
        """Fold one scrape's per-tenant counter totals in; returns the
        observed per-tenant tokens/s. Tenants beyond the seat cap fold
        into the shared overflow rate BEFORE differencing, so their
        combined counter still differences correctly."""
        now = self._clock() if t is None else float(t)
        folded: dict[str, float] = {}
        for tenant, value in totals.items():
            key = self._seat(str(tenant))
            folded[key] = folded.get(key, 0.0) + float(value)
        out: dict[str, float] = {}
        for key, value in folded.items():
            rate = self._rates[key]
            rate.sample(t=now, value=value)
            tps = rate.rate(self.window_s, now=now)
            self.forecaster.observe(key, tps, t=now)
            out[key] = tps
        return out
