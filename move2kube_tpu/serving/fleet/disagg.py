"""Disaggregated prefill/decode: dedicated prefill replicas hand
finished KV page runs to decode replicas.

One engine interleaves a bucketed prefill into every decode step, so a
long prompt stalls every in-flight sequence for a full prefill's
latency. Disaggregation moves prefill onto its own replicas: a
:class:`PrefillReplica` runs the model's ``return_kv`` forward (no
paged cache, no decode slots), trims the per-layer K/V to the prompt,
and ships it as a :class:`KVHandoff`; the decode engine seats it with
:meth:`ServingEngine.install_prefilled` — one jitted scatter, no local
prefill executable.

Transfer is host-side today (numpy ``.npz`` bytes — what an HTTP hop
between pods carries). The interface is shaped for an ICI fast path
later: :class:`KVTransport` is the seam, and the arrays stay per-layer
``[1, bucket, kv_heads, head_dim]`` exactly as a device-to-device copy
would want them.
"""

from __future__ import annotations

import dataclasses
import functools
import io
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from move2kube_tpu.obs import tracing
from move2kube_tpu.obs.metrics import Registry
from move2kube_tpu.serving.engine import Completion, EngineConfig, Request

_WIRE_VERSION = 1


@dataclasses.dataclass
class KVHandoff:
    """A finished prefill, ready to decode anywhere: the prompt (decode
    replicas re-derive positions and may index it into their prefix
    cache), the per-layer K/V padded to the prefill bucket, and the
    first generated token (the prefill's logits already paid for it)."""

    rid: str
    prompt: list[int]
    prompt_len: int
    bucket: int
    first_token: int
    kv: list[tuple[np.ndarray, np.ndarray]]  # per layer, [1, bucket, h, d]
    max_new_tokens: int | None = None
    # fleet attribution rides the handoff: the tenant header and the
    # router's span traceparent, so the decode replica's serve.request
    # stitches into the same trace the router opened
    tenant: str = ""
    traceparent: str = ""

    def to_bytes(self) -> bytes:
        meta = {
            "v": _WIRE_VERSION, "rid": self.rid,
            "prompt_len": self.prompt_len, "bucket": self.bucket,
            "first_token": self.first_token,
            "max_new_tokens": self.max_new_tokens,
            "tenant": self.tenant, "traceparent": self.traceparent,
        }
        buf = io.BytesIO()
        np.savez_compressed(
            buf,
            meta=np.frombuffer(json.dumps(meta).encode(), np.uint8),
            prompt=np.asarray(self.prompt, np.int32),
            k=np.stack([k for k, _ in self.kv]),
            v=np.stack([v for _, v in self.kv]))
        return buf.getvalue()

    @classmethod
    def from_bytes(cls, data: bytes) -> "KVHandoff":
        """Parse a wire handoff. EVERY malformation — truncated or
        garbage npz, missing arrays/keys, undecodable meta — surfaces as
        ``ValueError`` so the ingesting replica answers a clean 4xx
        instead of crashing its worker thread on a zipfile/OS error
        (chaos injector: ``M2KT_CHAOS_HANDOFF=truncate``)."""
        try:
            with np.load(io.BytesIO(data), allow_pickle=False) as z:
                meta = json.loads(z["meta"].tobytes().decode())
                if meta.get("v") != _WIRE_VERSION:
                    raise ValueError(
                        f"KV handoff wire version {meta.get('v')!r}; "
                        f"this replica speaks {_WIRE_VERSION}")
                ks, vs = z["k"], z["v"]
                return cls(
                    rid=str(meta["rid"]),
                    prompt=[int(t) for t in z["prompt"]],
                    prompt_len=int(meta["prompt_len"]),
                    bucket=int(meta["bucket"]),
                    first_token=int(meta["first_token"]),
                    kv=[(ks[i], vs[i]) for i in range(ks.shape[0])],
                    max_new_tokens=meta["max_new_tokens"],
                    # older peers' handoffs simply lack attribution keys
                    tenant=str(meta.get("tenant", "") or ""),
                    traceparent=str(meta.get("traceparent", "") or ""))
        except ValueError:
            raise
        except Exception as err:  # noqa: BLE001 - BadZipFile, KeyError, ...
            raise ValueError(f"malformed KV handoff: "
                             f"{type(err).__name__}: {err}") from err

    def request(self) -> Request:
        return Request(rid=self.rid, prompt=list(self.prompt),
                       max_new_tokens=self.max_new_tokens,
                       tenant=self.tenant, traceparent=self.traceparent)


class PrefillReplica:
    """Prefill-only worker: same bucketing discipline as the engine
    (at most ``len(buckets)`` executables) but no paged cache and no
    decode step — its whole job is turning prompts into handoffs."""

    def __init__(self, model, variables, config: EngineConfig | None = None,
                 registry: Registry | None = None, tracer=None):
        from move2kube_tpu.serving import quant as quantlib

        self.tracer = tracer if tracer is not None else (
            tracing.get() if tracing.enabled() else None)
        self.model = model
        self.config = config or EngineConfig.from_env()
        # same weight policy as the decode engine: the prefill executable
        # carries int8 parameter buffers and dequantizes inside the jit
        # (the handoff K/V stays full precision — the decode side's
        # scatter quantizes it into its own cache layout)
        policy = quantlib.policy(self.config.quant)
        if policy.quantize_weights:
            variables = quantlib.quantize_variables(variables)
        dq = (quantlib.dequantize_variables if policy.quantize_weights
              else (lambda v: v))
        self.variables = variables
        self.buckets = self.config.resolved_buckets()
        self.registry = registry if registry is not None else Registry()
        self._prefills = self.registry.counter(
            "m2kt_disagg_prefills_total", "Prompts prefilled for handoff")
        self._prefill_time = self.registry.counter(
            "m2kt_disagg_prefill_seconds_total",
            "Wall time spent in prefill forwards")

        @functools.partial(jax.jit, static_argnums=())
        def prefill(variables, ids, prompt_len):
            logits, kvs = model.apply(dq(variables), ids, return_kv=True)
            first = jnp.argmax(logits[0, prompt_len - 1]).astype(jnp.int32)
            return first, kvs

        self._prefill = prefill

    def _bucket_for(self, plen: int) -> int:
        for b in self.buckets:
            if plen <= b:
                return b
        raise ValueError(f"no bucket fits prompt length {plen}")

    def prefill(self, req: Request) -> KVHandoff:
        plen = len(req.prompt)
        if plen < 1:
            raise ValueError(f"{req.rid}: empty prompt")
        bucket = self._bucket_for(plen)
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :plen] = req.prompt
        t0 = time.perf_counter()
        first, kvs = self._prefill(self.variables, ids, np.int32(plen))
        kv_np = [(np.asarray(k), np.asarray(v)) for k, v in kvs]
        t1 = time.perf_counter()
        self._prefill_time.inc(t1 - t0)
        self._prefills.inc()
        if self.tracer is not None:
            # record() keeps the forward's own perf_counter readings; the
            # remote parent (the router's call span) is resolved by hand
            remote = tracing.parse_traceparent(req.traceparent or None)
            self.tracer.record(
                "prefill.request", t0, t1,
                attrs={"rid": req.rid, "prompt_len": plen, "bucket": bucket,
                       "tenant": req.tenant or "default"},
                trace_id=remote[0] if remote else None,
                parent_id=remote[1] if remote else "")
        return KVHandoff(
            rid=req.rid, prompt=list(req.prompt), prompt_len=plen,
            bucket=bucket, first_token=int(first), kv=kv_np,
            max_new_tokens=req.max_new_tokens,
            tenant=req.tenant, traceparent=req.traceparent)


class KVTransport:
    """The prefill->decode seam. ``send`` delivers one handoff to the
    decode side; implementations decide the medium (in-process list,
    HTTP POST of ``to_bytes()``, ICI copy later)."""

    def send(self, handoff: KVHandoff) -> None:
        raise NotImplementedError


class InProcessTransport(KVTransport):
    """Same-process delivery that still exercises the wire format:
    every handoff round-trips through ``to_bytes``/``from_bytes`` so
    tests and the smoke catch serialization drift, not just happy-path
    object passing."""

    def __init__(self) -> None:
        self.inbox: list[KVHandoff] = []

    def send(self, handoff: KVHandoff) -> None:
        self.inbox.append(KVHandoff.from_bytes(handoff.to_bytes()))


class DisaggPair:
    """One prefill replica feeding one decode engine — the smallest
    disaggregated deployment, used by tests and the fleet bench."""

    def __init__(self, prefill: PrefillReplica, engine,
                 transport: KVTransport | None = None):
        self.prefill_replica = prefill
        self.engine = engine
        self.transport = transport or InProcessTransport()

    def run(self, requests) -> list[Completion]:
        for req in requests:
            self.transport.send(self.prefill_replica.prefill(req))
        inbox = getattr(self.transport, "inbox", None)
        if inbox is None:
            raise TypeError("DisaggPair.run needs a transport with an inbox")
        completions: list[Completion] = []
        while inbox or self.engine.has_work():
            while inbox:
                h = inbox[0]
                ok, done = self.engine.install_prefilled(
                    h.request(), h.kv, h.first_token, h.prompt_len)
                completions.extend(done)
                if not ok:
                    break  # no slot/pages free: decode a step, retry
                inbox.pop(0)
            completions.extend(self.engine.step())
        return completions
