"""Discrete-event fleet simulator: autoscaler policies at planet scale.

The CPU probe can physically serve a few requests per second; real
diurnal traffic is millions of users. This module closes that gap the
only honest way a CI gate can: **no model execution at all**. Requests
are events; service times are drawn from per-phase latency
distributions — either the engine's own Prometheus histograms
(:class:`~move2kube_tpu.obs.metrics.HistogramSnapshot` inverse-CDF,
so the simulator replays the measured latency shape) or synthetic
lognormals; the fleet is an aggregate multi-server queue with
simulated cold-join delay and replica-hours billing. A 24h trace with
over a million distinct simulated users runs in seconds on a laptop
CPU, which is what lets the bench ``autoscale`` phase gate a policy
comparison (predictive forecaster vs reactive HPA) on every push.

Model, deliberately simple and stated here so its biases are known:

- **arrivals**: per-tick Poisson counts from a diurnal sinusoid plus
  optional burst windows, users drawn from a large id pool (zipfian
  tenant attribution rides along for per-tenant attainment);
- **service**: ``prefill + new_tokens * per_token``; TTFT = queue wait
  + prefill; no shedding — under-capacity shows up as TTFT misses,
  which is exactly the signal the policies are judged on;
- **capacity**: replicas * slots, fungible (no affinity); scale-up
  becomes serving capacity ``cold_join_s`` after the decision but is
  **billed from the decision** (real clouds charge for the boot);
  scale-down stops admissions on the shrinking share immediately and
  releases a replica only when enough streams have finished — the
  aggregate analogue of the PR-13 drain, so a scaling decision can
  never lose a stream (``lost_streams`` is asserted 0, not measured);
- **policies**: :class:`ReactiveHPAPolicy` mimics a queue-occupancy
  HPA (15s sync, 300s scale-down stabilization window); the
  predictive side runs the REAL production controller
  (:class:`~move2kube_tpu.serving.fleet.autoscaler.PredictiveAutoscaler`
  + :class:`~move2kube_tpu.serving.fleet.forecast.DemandForecaster`)
  against simulated time — the simulator is a harness, not a fork.

Determinism: one ``numpy`` seed fixes the trace and every sample;
equal seeds give bit-equal results, which the tests pin.
"""

from __future__ import annotations

import math
import time
from collections import deque
from dataclasses import dataclass, field
from heapq import heappop, heappush

import numpy as np

from move2kube_tpu.obs.metrics import Registry
from move2kube_tpu.serving.fleet.autoscaler import (
    AutoscaleConfig, PredictiveAutoscaler)
from move2kube_tpu.serving.fleet.forecast import (
    DemandForecaster, ForecastConfig)

DAY_S = 86400.0
_INF = float("inf")


# ---------------------------------------------------------------------------
# latency model
# ---------------------------------------------------------------------------

def _snapshot_sampler(snap):
    """Vectorized inverse-CDF over a HistogramSnapshot: maps uniforms
    to values with the recorded bucket shape (linear within buckets,
    +Inf clamped to the last finite edge)."""
    counts = np.asarray(snap.bucket_counts, dtype=np.float64)
    edges = np.asarray(snap.buckets, dtype=np.float64)
    total = counts.sum()
    if total <= 0:
        return lambda n, rng: np.zeros(n)
    cdf = np.cumsum(counts) / total
    finite = edges[np.isfinite(edges)]
    hi = np.where(np.isfinite(edges), edges,
                  finite[-1] if finite.size else 0.0)
    lo = np.concatenate(([0.0], hi[:-1]))
    prev_cdf = np.concatenate(([0.0], cdf[:-1]))
    width = np.maximum(1e-12, cdf - prev_cdf)

    def sample(n, rng):
        u = rng.random(n)
        idx = np.searchsorted(cdf, u, side="left")
        idx = np.minimum(idx, len(cdf) - 1)
        frac = (u - prev_cdf[idx]) / width[idx]
        return lo[idx] + (hi[idx] - lo[idx]) * np.clip(frac, 0.0, 1.0)

    return sample


def _lognormal_sampler(mean: float, sigma: float):
    # parameterized so the SAMPLE mean equals ``mean``
    mu = math.log(max(1e-9, mean)) - 0.5 * sigma * sigma

    def sample(n, rng):
        return rng.lognormal(mu, sigma, n)

    return sample


class LatencyModel:
    """Per-phase service-time samplers: ``prefill_s`` per request and
    ``per_token_s`` per decoded token."""

    def __init__(self, prefill_sampler, per_token_sampler) -> None:
        self._prefill = prefill_sampler
        self._per_token = per_token_sampler

    @classmethod
    def from_histograms(cls, prefill_snap, per_token_snap):
        """Build from the engine's own histogram snapshots — the
        simulator then replays the measured latency distributions."""
        return cls(_snapshot_sampler(prefill_snap),
                   _snapshot_sampler(per_token_snap))

    @classmethod
    def synthetic(cls, prefill_mean_s: float = 0.15,
                  per_token_mean_s: float = 0.04,
                  sigma: float = 0.35):
        return cls(_lognormal_sampler(prefill_mean_s, sigma),
                   _lognormal_sampler(per_token_mean_s, sigma))

    def sample(self, n: int, rng):
        return self._prefill(n, rng), self._per_token(n, rng)


# ---------------------------------------------------------------------------
# trace
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class TraceConfig:
    """A diurnal/bursty arrival trace over a large user population.

    Defaults are sized for the bench gate: ~1.1M requests over 24h
    drawn from a 20M-user pool, which yields > 1M DISTINCT simulated
    users while keeping two full policy runs comfortably inside the
    60s CI budget."""

    duration_s: float = DAY_S
    requests_total: int = 1_100_000
    user_pool: int = 20_000_000
    tick_s: float = 60.0
    # diurnal sinusoid: rate = base * (1 + amplitude*sin(phase)), with
    # the peak centered at ``peak_hour``
    diurnal_amplitude: float = 0.8
    peak_hour: float = 14.0
    # burst windows: (start_s, duration_s, rate_multiplier) — the
    # defaults model two recurring daily surges (a morning login rush
    # and an evening flash event), the traffic reactive HPAs lose to
    bursts: tuple = ((9.5 * 3600.0, 1800.0, 2.5),
                     (20.0 * 3600.0, 1800.0, 3.0))
    tenants: int = 8
    zipf_exponent: float = 1.2
    prompt_tokens_mean: float = 128.0
    decode_tokens_mean: float = 96.0
    seed: int = 0


@dataclass(frozen=True)
class FleetConfig:
    slots_per_replica: int = 8
    min_replicas: int = 2
    max_replicas: int = 32
    initial_replicas: int = 4
    cold_join_s: float = 120.0
    ttft_slo_s: float = 2.0


@dataclass
class SimResult:
    policy: str = ""
    requests: int = 0
    distinct_users: int = 0
    duration_s: float = 0.0
    attainment: float = 0.0          # fraction of requests inside SLO
    p95_ttft_s: float = 0.0
    replica_hours: float = 0.0
    mean_replicas: float = 0.0
    peak_replicas: int = 0
    scale_events: int = 0
    lost_streams: int = 0            # 0 by construction; asserted
    per_tenant_attainment: dict = field(default_factory=dict)
    wall_s: float = 0.0

    def to_dict(self) -> dict:
        out = dict(self.__dict__)
        out["per_tenant_attainment"] = dict(self.per_tenant_attainment)
        return out


class Trace:
    """Pre-generated arrival trace: times, per-request tokens/service
    samples, tenants — everything the event loop indexes, nothing it
    computes."""

    def __init__(self, cfg: TraceConfig, latency: LatencyModel,
                 rng=None) -> None:
        self.cfg = cfg
        rng = rng or np.random.default_rng(cfg.seed)
        n_ticks = int(math.ceil(cfg.duration_s / cfg.tick_s))
        tick_t = np.arange(n_ticks) * cfg.tick_s
        shape = self.rate_shape(tick_t)
        base = cfg.requests_total / max(1e-9, shape.sum() * cfg.tick_s)
        counts = rng.poisson(base * shape * cfg.tick_s)
        total = int(counts.sum())
        offsets = rng.random(total) * cfg.tick_s
        self.arrival_s = np.sort(
            np.repeat(tick_t, counts) + offsets)
        self.n = total
        users = rng.integers(0, cfg.user_pool, total)
        self.distinct_users = int(np.unique(users).size)
        # zipfian tenant attribution (rank-frequency over ``tenants``)
        ranks = np.arange(1, cfg.tenants + 1, dtype=np.float64)
        probs = ranks ** -cfg.zipf_exponent
        probs /= probs.sum()
        self.tenant = rng.choice(cfg.tenants, size=total, p=probs)
        prompt = rng.poisson(cfg.prompt_tokens_mean, total)
        decode = np.maximum(1, rng.poisson(cfg.decode_tokens_mean, total))
        self.tokens = (prompt + decode).astype(np.float64)
        prefill_s, per_token_s = latency.sample(total, rng)
        self.prefill_s = prefill_s
        self.service_s = prefill_s + decode * per_token_s
        # per-tick admitted-token demand, the counter the forecaster
        # differences in production — vectorized here so the predictive
        # policy's observe() costs nothing in the hot loop
        bins = np.minimum((self.arrival_s / cfg.tick_s).astype(np.int64),
                          n_ticks - 1)
        self.tokens_per_tick = np.bincount(
            bins, weights=self.tokens, minlength=n_ticks)
        self.mean_slot_tps = float(
            self.tokens.mean() / max(1e-9, self.service_s.mean()))

    def rate_shape(self, t) -> np.ndarray:
        """Relative arrival rate at time(s) ``t`` (unnormalized)."""
        cfg = self.cfg
        phase = 2.0 * math.pi * (t / DAY_S - cfg.peak_hour / 24.0)
        shape = 1.0 + cfg.diurnal_amplitude * np.cos(phase)
        shape = np.maximum(0.05, shape)
        for start, dur, mult in cfg.bursts:
            shape = np.where((t >= start) & (t < start + dur),
                             shape * mult, shape)
        return shape


# ---------------------------------------------------------------------------
# policies
# ---------------------------------------------------------------------------

class ReactiveHPAPolicy:
    """Queue-occupancy HPA as Kubernetes runs it: desired =
    ceil(current * occupancy / target), 15s sync period, scale-down
    takes the max recommendation over a 300s stabilization window.
    Cold-joining replicas are invisible to the metric (not ready), so
    the formula overshoots on ramps — which is the documented behavior
    this phase quantifies, not a strawman."""

    name = "reactive_hpa"

    def __init__(self, fleet: FleetConfig, sync_s: float = 15.0,
                 target_occupancy: float = 0.7,
                 down_stabilization_s: float = 300.0) -> None:
        self.fleet = fleet
        self.interval_s = float(sync_s)
        self.target = float(target_occupancy)
        self.stab_s = float(down_stabilization_s)
        self._window: deque = deque()

    def decide(self, now: float, busy: int, active: int,
               provisioned: int, tps_observed: float) -> int:
        cap = max(1, active * self.fleet.slots_per_replica)
        occupancy = busy / cap
        desired = int(math.ceil(active * occupancy / self.target)) \
            if occupancy > 0 else self.fleet.min_replicas
        desired = max(self.fleet.min_replicas,
                      min(self.fleet.max_replicas, desired))
        self._window.append((now, desired))
        floor = now - self.stab_s
        while self._window and self._window[0][0] < floor:
            self._window.popleft()
        if desired > provisioned:
            return desired
        # scale-down: most conservative (max) recommendation in window
        rec = max(d for _, d in self._window)
        return min(provisioned, max(rec, self.fleet.min_replicas))


class PredictivePolicy:
    """The production predictive controller run against simulated time:
    a real DemandForecaster fed the per-tick admitted-token rate, and a
    real PredictiveAutoscaler making the replica decision. ``warmup``
    pre-trains the seasonal field on one synthetic prior day (the
    production controller has yesterday's counters; the simulator must
    grant the same memory or the comparison is rigged against it)."""

    name = "predictive"

    def __init__(self, trace: Trace, fleet: FleetConfig,
                 target_util: float = 0.7, down_delay_s: float = 180.0,
                 warmup: bool = True) -> None:
        self.interval_s = float(trace.cfg.tick_s)
        self.fleet = fleet
        replica_tps = trace.mean_slot_tps * fleet.slots_per_replica
        self._tokens_per_tick = trace.tokens_per_tick
        self._tick_s = trace.cfg.tick_s
        self.forecaster = DemandForecaster(
            ForecastConfig(), clock=lambda: 0.0, epoch=0.0)
        self.scaler = PredictiveAutoscaler(
            self.forecaster, replica_tps,
            config=AutoscaleConfig(
                interval_s=self.interval_s,
                min_replicas=fleet.min_replicas,
                max_replicas=fleet.max_replicas,
                target_util=target_util,
                lead_time_s=fleet.cold_join_s,
                down_delay_s=down_delay_s),
            clock=lambda: 0.0, registry=Registry())
        if warmup:
            # yesterday: the same diurnal expectation, observed at tick
            # cadence with t shifted one period back
            ticks = np.arange(len(self._tokens_per_tick)) * self._tick_s
            shape = trace.rate_shape(ticks)
            mean_tps = (self._tokens_per_tick.sum()
                        / max(1e-9, len(ticks) * self._tick_s))
            expected = shape / max(1e-9, shape.mean()) * mean_tps
            for i, tps in enumerate(expected):
                self.forecaster.observe(float(tps),
                                        t=ticks[i] - trace.cfg.duration_s)

    def decide(self, now: float, busy: int, active: int,
               provisioned: int, tps_observed: float) -> int:
        self.forecaster.observe(tps_observed, t=now)
        return self.scaler.decide(provisioned, now=now)


# ---------------------------------------------------------------------------
# event loop
# ---------------------------------------------------------------------------

def simulate(trace: Trace, fleet: FleetConfig, policy) -> SimResult:
    """Run one policy over one trace. Single pass over arrivals with a
    completion heap — O((N + ticks) log c) — so a million-request day
    takes seconds."""
    wall0 = time.perf_counter()
    cfg = trace.cfg
    spr = fleet.slots_per_replica
    arrival = trace.arrival_s.tolist()
    service = trace.service_s.tolist()
    prefill = trace.prefill_s.tolist()
    n = trace.n
    ttft = np.empty(n, dtype=np.float64)

    heap: list = []  # (finish_time, request_index) — index unused, kept
    queue: deque = deque()          # request indices waiting for a slot
    queued_at: deque = deque()
    busy = 0
    active = fleet.initial_replicas          # serving replicas
    pending_up: deque = deque()              # cold-join effective times
    pending_down = 0                         # replicas draining
    scale_events = 0
    # billing: integral of billed replicas (active + cold-joining) over
    # time, charged from the moment of the scale-up decision
    billed = active
    bill_t = 0.0
    replica_seconds = 0.0
    peak = billed
    tick_i = 0
    tick_s = policy.interval_s
    next_tick = tick_s
    tokens_per_tick = trace.tokens_per_tick
    trace_tick_s = cfg.tick_s

    def bill(now: float) -> None:
        nonlocal replica_seconds, bill_t
        replica_seconds += billed * (now - bill_t)
        bill_t = now

    def control(now: float) -> None:
        nonlocal billed, pending_down, scale_events, peak, tick_i
        provisioned = active + len(pending_up) - pending_down
        # the demand rate the production controller would read off the
        # admitted-tokens counter over the last trace tick
        ti = min(int(now / trace_tick_s), len(tokens_per_tick) - 1)
        tps = float(tokens_per_tick[ti]) / trace_tick_s
        target = policy.decide(now, busy, active, provisioned, tps)
        target = max(fleet.min_replicas,
                     min(fleet.max_replicas, target))
        if target > provisioned:
            bill(now)
            grow = target - provisioned
            # cancel drains first: un-draining a replica is free
            cancel = min(grow, pending_down)
            pending_down -= cancel
            grow -= cancel
            billed += grow
            peak = max(peak, billed)
            for _ in range(grow):
                pending_up.append(now + fleet.cold_join_s)
            scale_events += 1
        elif target < provisioned:
            pending_down += provisioned - target
            scale_events += 1

    def on_complete(tc: float) -> None:
        nonlocal busy, active, pending_down, billed
        if pending_down and active > 1 \
                and busy - 1 <= (active - 1) * spr:
            # a draining replica's last stream finished: release it
            busy -= 1
            bill(tc)
            active -= 1
            billed -= 1
            pending_down -= 1
        elif queue and busy - 1 < (active - pending_down) * spr:
            j = queue.popleft()
            ta = queued_at.popleft()
            ttft[j] = (tc - ta) + prefill[j]
            heappush(heap, tc + service[j])
        else:
            busy -= 1

    def on_join(tj: float) -> None:
        nonlocal active, busy
        active += 1
        pending_up.popleft()
        cap = (active - pending_down) * spr
        while queue and busy < cap:
            j = queue.popleft()
            ta = queued_at.popleft()
            ttft[j] = (tj - ta) + prefill[j]
            heappush(heap, tj + service[j])
            busy += 1

    for i in range(n):
        t = arrival[i]
        while True:
            tc = heap[0] if heap else _INF
            tj = pending_up[0] if pending_up else _INF
            te = min(next_tick, tj, tc)
            if te > t:
                break
            if tc == te:
                heappop(heap)
                on_complete(tc)
            elif tj == te:
                on_join(tj)
            else:
                control(next_tick)
                next_tick += tick_s
        if not queue and busy < (active - pending_down) * spr:
            busy += 1
            ttft[i] = prefill[i]
            heappush(heap, t + service[i])
        else:
            queue.append(i)
            queued_at.append(t)

    # epilogue: drain everything still queued or in flight (control
    # keeps ticking so late scale-downs are billed honestly)
    while heap or queue:
        tc = heap[0] if heap else _INF
        tj = pending_up[0] if pending_up else _INF
        te = min(next_tick, tj, tc)
        if tc == te:
            heappop(heap)
            on_complete(tc)
        elif tj == te:
            on_join(tj)
        else:
            control(next_tick)
            next_tick += tick_s
    bill(max(cfg.duration_s, bill_t))

    good = ttft <= fleet.ttft_slo_s
    per_tenant = {}
    # captured traces carry the real tenant names; synthetic ones rank
    names = getattr(trace, "tenant_names", None) \
        or [f"tenant-{tid}" for tid in range(cfg.tenants)]
    for tid in range(cfg.tenants):
        mask = trace.tenant == tid
        if mask.any():
            per_tenant[names[tid]] = float(good[mask].mean())
    return SimResult(
        policy=getattr(policy, "name", type(policy).__name__),
        requests=n,
        distinct_users=trace.distinct_users,
        duration_s=float(cfg.duration_s),
        attainment=float(good.mean()),
        p95_ttft_s=float(np.percentile(ttft, 95)),
        replica_hours=replica_seconds / 3600.0,
        mean_replicas=replica_seconds / max(1e-9, cfg.duration_s),
        peak_replicas=int(peak),
        scale_events=scale_events,
        lost_streams=0,
        per_tenant_attainment=per_tenant,
        wall_s=time.perf_counter() - wall0,
    )


def compare_policies(trace_cfg: TraceConfig | None = None,
                     fleet_cfg: FleetConfig | None = None,
                     latency: LatencyModel | None = None,
                     trace=None) -> dict:
    """The bench gate: one trace, both policies, verdict. Returns
    ``{"trace": ..., "reactive": ..., "predictive": ...,
    "predictive_wins": bool}`` where winning means better SLO
    attainment AND fewer replica-hours on the SAME trace.

    ``trace`` accepts a prebuilt trace — in particular a
    :class:`~move2kube_tpu.serving.fleet.capture.CapturedTrace`
    replaying recorded production traffic — in place of the synthetic
    diurnal generator; any duck-typed trace exposing the
    :class:`Trace` surface works."""
    fleet_cfg = fleet_cfg or FleetConfig()
    wall0 = time.perf_counter()
    if trace is None:
        trace_cfg = trace_cfg or TraceConfig()
        latency = latency or LatencyModel.synthetic()
        trace = Trace(trace_cfg, latency)
    else:
        trace_cfg = trace.cfg
    reactive = simulate(trace, fleet_cfg,
                        ReactiveHPAPolicy(fleet_cfg))
    predictive = simulate(trace, fleet_cfg,
                          PredictivePolicy(trace, fleet_cfg))
    wins = (predictive.attainment >= reactive.attainment
            and predictive.replica_hours < reactive.replica_hours)
    return {
        "trace": {
            "requests": trace.n,
            "distinct_users": trace.distinct_users,
            "duration_s": trace_cfg.duration_s,
            "seed": trace_cfg.seed,
        },
        "reactive": reactive.to_dict(),
        "predictive": predictive.to_dict(),
        "predictive_wins": bool(wins),
        "wall_s": time.perf_counter() - wall0,
    }
