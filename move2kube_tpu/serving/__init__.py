"""TPU serving hot path: paged KV cache + continuous batching.

The training side of this repo moves detected GPU workloads onto TPU
JobSets; this package is the *serving* half of the story (reference
Move2Kube emits Knative Service YAML as a first-class target). It makes
the translated decoder LMs fast to serve:

- :mod:`move2kube_tpu.serving.kvcache` — fixed-size-page KV cache with a
  per-sequence block table, donated across decode steps so it stays
  device-resident;
- :mod:`move2kube_tpu.serving.engine` — continuous batching: admit and
  finish sequences mid-flight, interleave prefill with decode, bucket
  prompt lengths so the compiled-executable count stays bounded;
- :mod:`move2kube_tpu.serving.fleet` — the layer above one engine:
  request router with prefix-hash session affinity, refcounted
  copy-on-write prefix cache, and disaggregated prefill/decode.

Vendored into emitted serving images alongside ``models``/``ops`` —
keep it free of imports on the QA/YAML half of the repo.
"""

from move2kube_tpu.serving.engine import (  # noqa: F401
    EngineConfig,
    Request,
    ServingEngine,
)
from move2kube_tpu.serving.kvcache import (  # noqa: F401
    KVCacheConfig,
    PageAllocator,
    init_cache,
    pages_for,
    spec_for_model,
)
