"""Scheduler plane, multi-LoRA side: paged adapter weights.

S-LoRA's observation is that thousands of fine-tunes can share one base
model if the adapter weights are paged like KV and the A/B matmuls are
gathered per-slot inside the fixed-shape decode step. Here the adapter
store reuses the serving page machinery directly: rows in two stacked
host arrays (``a [rows, d_model, rank]``, ``b [rows, rank, vocab]``)
are handed out by the same refcounted :class:`PageAllocator` that backs
the KV pool — row 0 is the reserved NULL row and holds zeros, so the
base model is "adapter 0" and a batch mixing adapted and plain requests
needs no masking, just the gather.

The stacks ride into every prefill/decode executable as *traced*
arguments, so registering or swapping adapters never recompiles; the
executable-count bound is untouched. Adapters with rank below the
configured maximum are zero-padded on the rank axis, which is exact.

On-disk registry format (``M2KT_LORA_DIR``): a directory of
``<name>.npz`` files, each with arrays ``a [d_model, r]`` and
``b [r, vocab]``, ``r <= lora_rank``.
"""

from __future__ import annotations

import os
import threading

import numpy as np

from move2kube_tpu.serving.kvcache import PageAllocator

NULL_ADAPTER = 0  # row 0: all-zeros delta == base model


class AdapterStore:
    """Up to ``max_loras`` resident adapters as stacked A/B rows.

    ``register`` pins a row (refcount 1, the registration's);
    ``acquire``/``release`` bracket a request's use of it so
    ``unregister`` can't yank weights out from under an in-flight
    batch (the row only returns to the pool at refcount zero, exactly
    the KV-page lifecycle)."""

    def __init__(self, d_model: int, vocab: int, rank: int,
                 max_loras: int) -> None:
        if max_loras < 1:
            raise ValueError(f"max_loras must be >= 1, got {max_loras}")
        if rank < 1:
            raise ValueError(f"lora rank must be >= 1, got {rank}")
        self.d_model = int(d_model)
        self.vocab = int(vocab)
        self.rank = int(rank)
        self.max_loras = int(max_loras)
        self._a = np.zeros((max_loras + 1, d_model, rank), np.float32)
        self._b = np.zeros((max_loras + 1, rank, vocab), np.float32)
        self._rows = PageAllocator(max_loras + 1)
        self._row_by_name: dict[str, int] = {}
        self._unregistered: set[int] = set()
        self._lock = threading.Lock()
        self._version = 0        # bumped per register/unregister
        self._device = None      # (version, a_dev, b_dev) cache

    # ------------------------------------------------------------------
    # registry
    # ------------------------------------------------------------------

    @property
    def names(self) -> list:
        with self._lock:
            return sorted(self._row_by_name)

    @property
    def version(self) -> int:
        return self._version

    def register(self, name: str, a, b) -> int:
        """Install adapter ``name``; returns its row id."""
        if not name:
            raise ValueError("adapter name must be non-empty")
        a = np.asarray(a, np.float32)
        b = np.asarray(b, np.float32)
        if a.ndim != 2 or b.ndim != 2 or a.shape[1] != b.shape[0]:
            raise ValueError(
                f"adapter {name!r}: want a [d,r] / b [r,v], got "
                f"{a.shape} / {b.shape}")
        r = a.shape[1]
        if a.shape[0] != self.d_model or b.shape[1] != self.vocab:
            raise ValueError(
                f"adapter {name!r}: shape {a.shape}/{b.shape} does not "
                f"match model d={self.d_model} vocab={self.vocab}")
        if r > self.rank:
            raise ValueError(
                f"adapter {name!r}: rank {r} exceeds configured "
                f"lora_rank {self.rank}")
        with self._lock:
            if name in self._row_by_name:
                raise ValueError(f"adapter {name!r} already registered")
            got = self._rows.alloc(1)
            if got is None:
                raise ValueError(
                    f"adapter store full ({self.max_loras} rows); "
                    "unregister one first")
            row = got[0]
            self._a[row] = 0.0
            self._b[row] = 0.0
            self._a[row, :, :r] = a
            self._b[row, :r, :] = b
            self._row_by_name[name] = row
            self._unregistered.discard(row)
            self._version += 1
            return row

    def unregister(self, name: str) -> None:
        """Drop the registration ref; the row frees once in-flight
        requests release it."""
        with self._lock:
            row = self._row_by_name.pop(name)
            self._unregistered.add(row)
            self._rows.free([row])
            self._version += 1

    def load_dir(self, path: str, *, warn=None) -> int:
        """Load every ``<name>.npz`` under ``path``; returns the count.
        Malformed files warn and are skipped (quant.py tolerance)."""
        if warn is None:
            def warn(msg):
                print(f"[m2kt] WARNING: {msg}", flush=True)
        n = 0
        for fname in sorted(os.listdir(path)):
            if not fname.endswith(".npz"):
                continue
            name = fname[:-4]
            try:
                with np.load(os.path.join(path, fname)) as z:
                    self.register(name, z["a"], z["b"])
                n += 1
            except Exception as e:  # tolerant: skip the bad file
                warn(f"adapter file {fname!r} skipped: {e}")
        return n

    # ------------------------------------------------------------------
    # per-request row lifecycle
    # ------------------------------------------------------------------

    def acquire(self, name: str) -> int:
        """Take a ref on ``name``'s row for one request; '' is the base
        model (row 0, no ref needed). Unknown names raise ValueError —
        submit-time rejection, same as an over-long prompt."""
        if not name:
            return NULL_ADAPTER
        with self._lock:
            row = self._row_by_name.get(name)
            if row is None:
                raise ValueError(f"unknown adapter {name!r} "
                                 f"(registered: {sorted(self._row_by_name)})")
            self._rows.incref([row])
            return row

    def release(self, row: int) -> None:
        if row == NULL_ADAPTER:
            return
        with self._lock:
            self._rows.free([row])

    def refcount(self, row: int) -> int:
        return self._rows.refcount(row)

    # ------------------------------------------------------------------
    # what the executables see
    # ------------------------------------------------------------------

    def stacks(self):
        """The (a, b) stacks as device arrays, cached per registry
        version — traced arguments to the serving executables, so a
        registry change is just a new pair of buffers, no recompile."""
        import jax.numpy as jnp
        with self._lock:
            cached = self._device
            if cached is not None and cached[0] == self._version:
                return cached[1], cached[2]
            a_dev = jnp.asarray(self._a)
            b_dev = jnp.asarray(self._b)
            self._device = (self._version, a_dev, b_dev)
            return a_dev, b_dev
