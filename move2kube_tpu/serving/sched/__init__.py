"""Multi-tenant scheduler plane (PR 17).

Consumed from both ends of the serving stack: the router front runs
admission control (per-tenant token buckets + priority classes) and the
engine consults the same policies for preemption ordering, interleaves
chunked prefill into decode steps, and serves paged multi-LoRA adapters
— all behind one spec string so the two sides can never disagree.
"""

from move2kube_tpu.serving.sched.admission import (  # noqa: F401
    DEFAULT_PRIORITY,
    PRIORITIES,
    AdmissionController,
    SchedThrottled,
    TenantPolicy,
    TokenBucket,
    merge_split_specs,
    parse_tenant_spec,
)
from move2kube_tpu.serving.sched.lora import (  # noqa: F401
    NULL_ADAPTER,
    AdapterStore,
)

__all__ = [
    "AdmissionController",
    "AdapterStore",
    "DEFAULT_PRIORITY",
    "NULL_ADAPTER",
    "PRIORITIES",
    "SchedThrottled",
    "TenantPolicy",
    "TokenBucket",
    "merge_split_specs",
    "parse_tenant_spec",
]
