"""Scheduler plane, admission side: tenant policies + token buckets.

One spec string configures the whole plane so the router and the engine
can parse it independently (the tenant already rides every Request; the
priority must not have to ride the replica wire format too):

    M2KT_SCHED_TENANTS="gold:prio=high,rate=50,burst=100;free:prio=besteffort"

``prio`` is one of ``high | standard | besteffort`` (higher class may
preempt lower under pressure, see engine._admit_one). ``rate`` is the
token-bucket refill in requests/s and ``burst`` the bucket depth; 0 (or
absent) means unlimited. The QA/Helm plane carries the same information
split across two simpler knobs (serve.sched.priorities / .quotas →
M2KT_SCHED_PRIORITIES / M2KT_SCHED_QUOTAS):

    M2KT_SCHED_PRIORITIES="gold:high;free:besteffort"
    M2KT_SCHED_QUOTAS="gold:50/100;free:5/10"        # rate/burst

Both forms merge (the combined spec wins per field). Parsing is
tolerant by the quant.py convention: a malformed entry warns and is
skipped, never raises — a typo in a Helm value must not take down the
router.
"""

from __future__ import annotations

import dataclasses
import threading
import time

# priority classes, higher may preempt lower. Keys are what the spec /
# QA answers say; values order the scheduler.
PRIORITIES = {"high": 2, "standard": 1, "besteffort": 0}
DEFAULT_PRIORITY = "standard"


class SchedThrottled(ValueError):
    """Raised at admission when a tenant is over its token-bucket quota.

    A ValueError so existing submit-time rejection paths treat it as a
    client error; the router HTTP front maps it to 429."""


@dataclasses.dataclass
class TenantPolicy:
    """One tenant's scheduling contract."""

    name: str
    priority: str = DEFAULT_PRIORITY
    rate: float = 0.0   # requests/s refill; 0 = unlimited
    burst: float = 0.0  # bucket depth; 0 = unlimited

    @property
    def priority_class(self) -> int:
        return PRIORITIES.get(self.priority, PRIORITIES[DEFAULT_PRIORITY])


def _warn(msg: str) -> None:
    print(f"[m2kt] WARNING: {msg}", flush=True)


def parse_tenant_spec(spec: str, *, warn=_warn) -> dict:
    """``"gold:prio=high,rate=50,burst=100;free:prio=besteffort"`` →
    {tenant: TenantPolicy}. Malformed entries warn and are skipped."""
    policies: dict[str, TenantPolicy] = {}
    for entry in (spec or "").split(";"):
        entry = entry.strip()
        if not entry:
            continue
        name, _, body = entry.partition(":")
        name = name.strip()
        if not name:
            warn(f"sched tenant entry {entry!r} has no tenant name; skipped")
            continue
        pol = policies.get(name) or TenantPolicy(name)
        ok = True
        for field in body.split(","):
            field = field.strip()
            if not field:
                continue
            key, _, val = field.partition("=")
            key, val = key.strip(), val.strip()
            if key == "prio":
                if val not in PRIORITIES:
                    warn(f"sched tenant {name!r}: unknown priority {val!r} "
                         f"(want one of {sorted(PRIORITIES)}); skipped")
                    ok = False
                    break
                pol.priority = val
            elif key in ("rate", "burst"):
                try:
                    num = float(val)
                except ValueError:
                    num = -1.0
                if num < 0:
                    warn(f"sched tenant {name!r}: bad {key} {val!r}; skipped")
                    ok = False
                    break
                setattr(pol, key, num)
            else:
                warn(f"sched tenant {name!r}: unknown field {key!r}; skipped")
                ok = False
                break
        if ok:
            policies[name] = pol
    return policies


def merge_split_specs(policies: dict, priorities: str = "",
                      quotas: str = "", *, warn=_warn) -> dict:
    """Layer the split QA knobs under an (optionally empty) combined
    spec: ``priorities`` is ``"gold:high;free:besteffort"``, ``quotas``
    is ``"gold:50/100"`` (rate/burst). The combined spec wins."""
    out = {n: dataclasses.replace(p) for n, p in policies.items()}

    def _base(name: str) -> TenantPolicy:
        return out.setdefault(name, TenantPolicy(name))

    for entry in (priorities or "").split(";"):
        entry = entry.strip()
        if not entry:
            continue
        name, _, prio = entry.partition(":")
        name, prio = name.strip(), prio.strip()
        if not name or prio not in PRIORITIES:
            warn(f"sched priority entry {entry!r} malformed "
                 f"(want tenant:{'|'.join(sorted(PRIORITIES))}); skipped")
            continue
        pol = _base(name)
        if name not in policies:
            pol.priority = prio
    for entry in (quotas or "").split(";"):
        entry = entry.strip()
        if not entry:
            continue
        name, _, q = entry.partition(":")
        rate_s, _, burst_s = q.partition("/")
        try:
            rate, burst = float(rate_s), float(burst_s)
            if rate < 0 or burst < 0:
                raise ValueError(q)
        except ValueError:
            warn(f"sched quota entry {entry!r} malformed "
                 "(want tenant:rate/burst); skipped")
            continue
        name = name.strip()
        if not name:
            warn(f"sched quota entry {entry!r} has no tenant name; skipped")
            continue
        pol = _base(name)
        if name not in policies:
            pol.rate, pol.burst = rate, burst
    return out


class TokenBucket:
    """Classic token bucket with an injectable monotonic clock (tests
    drive refill deterministically, like SLOTracker)."""

    def __init__(self, rate: float, burst: float,
                 clock=time.monotonic) -> None:
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = clock
        self._tokens = self.burst
        self._last = clock()
        self._lock = threading.Lock()

    @property
    def tokens(self) -> float:
        with self._lock:
            self._refill()
            return self._tokens

    def _refill(self) -> None:
        now = self._clock()
        dt = max(0.0, now - self._last)
        self._last = now
        self._tokens = min(self.burst, self._tokens + dt * self.rate)

    def take(self, n: float = 1.0) -> bool:
        with self._lock:
            self._refill()
            if self._tokens + 1e-9 >= n:
                self._tokens -= n
                return True
            return False


class AdmissionController:
    """Per-tenant token-bucket quotas + priority lookup.

    Lives at the router front (throttling before placement) and, for
    priority only, inside the engine (preemption ordering). Unknown
    tenants get the default policy: standard priority, unlimited."""

    def __init__(self, policies: dict, registry=None,
                 clock=time.monotonic) -> None:
        self.policies = dict(policies or {})
        self._clock = clock
        self._buckets: dict[str, TokenBucket] = {}
        for name, pol in self.policies.items():
            if pol.rate > 0 and pol.burst > 0:
                self._buckets[name] = TokenBucket(pol.rate, pol.burst,
                                                  clock=clock)
        self._throttled = None
        if registry is not None:
            self._throttled = registry.counter(
                "m2kt_sched_throttled_total",
                "Requests refused at admission by the scheduler",
                labels=("reason",))

    @classmethod
    def from_specs(cls, tenants: str = "", priorities: str = "",
                   quotas: str = "", registry=None,
                   clock=time.monotonic, warn=_warn) -> "AdmissionController":
        policies = merge_split_specs(parse_tenant_spec(tenants, warn=warn),
                                     priorities, quotas, warn=warn)
        return cls(policies, registry=registry, clock=clock)

    @property
    def configured(self) -> bool:
        return bool(self.policies)

    def policy(self, tenant: str) -> TenantPolicy:
        pol = self.policies.get(tenant or "")
        return pol if pol is not None else TenantPolicy(tenant or "")

    def priority(self, tenant: str) -> int:
        return self.policy(tenant).priority_class

    def distinct_priorities(self) -> bool:
        """Preemption only makes sense when the policies actually rank
        tenants differently; with a flat (or empty) spec the engine
        keeps its historical never-preempt behavior."""
        classes = {p.priority_class for p in self.policies.values()}
        classes.add(PRIORITIES[DEFAULT_PRIORITY])
        return len(classes) > 1

    def admit(self, tenant: str) -> None:
        """Raise SchedThrottled when the tenant is over quota."""
        bucket = self._buckets.get(tenant or "")
        if bucket is not None and not bucket.take():
            if self._throttled is not None:
                self._throttled.labels(reason="quota").inc()
            raise SchedThrottled(
                f"tenant {tenant!r} over quota "
                f"({bucket.rate:g} req/s, burst {bucket.burst:g})")
