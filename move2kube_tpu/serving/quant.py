"""Post-training int8 serving quantization + draft-model helpers.

Decode is HBM-bandwidth-bound (the roofline gauges classify every decode
executable that way), so bytes-per-weight and bytes-per-KV-row are the
throughput levers: this module provides the *weight* half and the policy
that selects both halves, mirroring models/precision.py's shape (named
frozen policies, ``policy(name)``, tolerant ``from_env``).

Weight quantization is symmetric per-output-channel int8 applied once at
restore time: every matmul kernel (flax ``Dense`` leaves, the only
2-D+ params named ``kernel``) becomes an int8 tensor plus fp32 scales
over its last (output-channel) axis; embeddings, layernorm/RMSNorm
scales, and biases stay high precision. The engine's jitted steps call
:func:`dequantize_variables` *inside* the compiled program, so the
executable's parameter buffers — what lives in HBM and what
``memory_analysis`` counts — are the int8 tensors, and the dequantized
fp32 view is a transient the scheduler fuses into the consuming matmul.

The KV half lives in serving/kvcache.py (``cache_dtype=int8`` +
per-row scale pools); ``ops/attention.quantize_kv_rows`` is the shared
row quantizer. Policies:

- ``off``     — fp32/bf16 weights, compute-dtype KV cache (the anchor)
- ``int8``    — int8 weights, compute-dtype KV cache
- ``int8-kv`` — int8 weights AND int8 paged KV cache

Draft-model helpers for speculative decoding: ``draft_config`` shrinks a
model config to its first ``num_layers // factor`` layers and
``draft_variables_from`` prunes the restored variables to match —
embeddings, final norm, and lm_head are shared with the target, so the
draft is a free byproduct of the restore, not a second checkpoint.
"""

from __future__ import annotations

import dataclasses
import os
import re

import jax.numpy as jnp

QUANT_OPTIONS = ("off", "int8", "int8-kv")

# quantized-kernel marker leaves: {"q8": int8 kernel, "scale": fp32 per-
# output-channel scales (broadcastable: [1, ..., out])}
_Q_KEYS = frozenset(("q8", "scale"))

_LAYER_RE = re.compile(r"^(?:layer|h)_(\d+)$")


@dataclasses.dataclass(frozen=True)
class QuantPolicy:
    name: str = "off"
    quantize_weights: bool = False
    quantize_kv: bool = False

    @property
    def cache_dtype(self):
        """Storage dtype for the paged KV cache under this policy
        (None = the model's compute dtype)."""
        return jnp.int8 if self.quantize_kv else None


_POLICIES = {
    "off": QuantPolicy(),
    "int8": QuantPolicy(name="int8", quantize_weights=True),
    "int8-kv": QuantPolicy(name="int8-kv", quantize_weights=True,
                           quantize_kv=True),
}


def policy(name: str) -> QuantPolicy:
    try:
        return _POLICIES[name]
    except KeyError:
        raise ValueError(
            f"unknown serving quant policy {name!r}; options: "
            f"{', '.join(QUANT_OPTIONS)}") from None


def from_env(default: str = "off", env=None) -> QuantPolicy:
    """``M2KT_SERVE_QUANT`` names the policy; unknown names fall back to
    ``default`` rather than killing a serving pod over an env typo."""
    env = os.environ if env is None else env
    name = env.get("M2KT_SERVE_QUANT", "") or default
    try:
        return policy(name)
    except ValueError:
        return policy(default)


def _is_quantized_leaf(node) -> bool:
    return isinstance(node, dict) and set(node) == _Q_KEYS


def quantize_array(w):
    """Symmetric per-output-channel int8 of one matmul kernel: the last
    axis is the output-channel axis (flax Dense kernel [in, out]), every
    other axis folds into the amax. Scales keep ``keepdims`` so the
    dequant broadcast needs no reshape."""
    w32 = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(w32), axis=tuple(range(w.ndim - 1)),
                   keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return {"q8": q, "scale": scale.astype(jnp.float32)}


def quantize_variables(variables):
    """Quantize every matmul kernel in a restored variables pytree.

    Kernels are the 2-D+ floating leaves named ``kernel`` — embeddings
    (``embedding``), norm ``scale``/``bias``, and Dense biases are 1-D
    or differently named and pass through in full precision, exactly
    the policy the issue states. The result is still a dict pytree
    (quantized leaves become ``{"q8", "scale"}`` sub-dicts), so it jits,
    donates, and checkpoints like the original."""
    def walk(node, name):
        if isinstance(node, dict):
            return {k: walk(v, k) for k, v in node.items()}
        if (name == "kernel" and hasattr(node, "ndim") and node.ndim >= 2
                and jnp.issubdtype(node.dtype, jnp.floating)):
            return quantize_array(node)
        return node

    return walk(variables, "")


def dequantize_variables(variables):
    """Inverse view of :func:`quantize_variables` — called INSIDE the
    engine's jitted steps so the executable's parameter inputs stay
    int8 and the fp32 kernels exist only as fused transients."""
    def walk(node):
        if _is_quantized_leaf(node):
            return node["q8"].astype(jnp.float32) * node["scale"]
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        return node

    return walk(variables)


def param_bytes(variables) -> int:
    """Total parameter-buffer bytes of a (possibly quantized) variables
    pytree — what the compiled executables hold resident in HBM. The
    quant bench gate checks the int8 tree genuinely shrank."""
    import jax

    return sum(x.size * x.dtype.itemsize
               for x in jax.tree_util.tree_leaves(variables)
               if hasattr(x, "dtype"))


def draft_config(cfg, factor: int = 2):
    """Shrunk same-family draft config: the first
    ``max(1, num_layers // factor)`` layers of the target. Everything
    else (vocab, widths, heads) must match — the draft proposes token
    ids the target verifies, so the vocab is load-bearing."""
    return dataclasses.replace(
        cfg, num_layers=max(1, cfg.num_layers // max(1, factor)))


def draft_variables_from(variables, draft_cfg):
    """Prune restored target variables down to ``draft_cfg``'s depth:
    keep ``layer_i``/``h_i`` subtrees with ``i < draft_layers`` (they
    are contiguous from 0, so no renumbering), share embeddings, final
    norm, and lm_head verbatim. Works on quantized trees too — the
    ``{"q8", "scale"}`` marker leaves are opaque dicts whose keys never
    collide with the layer pattern."""
    n = draft_cfg.num_layers

    def prune(node):
        if _is_quantized_leaf(node) or not isinstance(node, dict):
            return node
        out = {}
        for key, sub in node.items():
            m = _LAYER_RE.match(key)
            if m and int(m.group(1)) >= n:
                continue
            out[key] = prune(sub)
        return out

    return prune(variables)


def logit_gate(ref, got, eps: float = 1e-6) -> dict:
    """Logit-error comparison between a reference (fp32) and a quantized
    run over aligned logit rows: max absolute error, max relative error
    (normalized by the reference row's dynamic range), and greedy top-1
    agreement. The bench quant phase FAILS on divergence through these
    numbers, not on slowness alone."""
    import numpy as np

    ref = np.asarray(ref, np.float32)
    got = np.asarray(got, np.float32)
    if ref.shape != got.shape:
        raise ValueError(f"logit shape mismatch: {ref.shape} vs {got.shape}")
    flat_ref = ref.reshape(-1, ref.shape[-1])
    flat_got = got.reshape(-1, got.shape[-1])
    span = np.maximum(
        flat_ref.max(axis=-1) - flat_ref.min(axis=-1), eps)
    abs_err = np.abs(flat_ref - flat_got).max(axis=-1)
    agree = (flat_ref.argmax(axis=-1) == flat_got.argmax(axis=-1))
    return {
        "rows": int(flat_ref.shape[0]),
        "max_abs_err": float(abs_err.max() if abs_err.size else 0.0),
        "max_rel_err": float((abs_err / span).max() if abs_err.size
                             else 0.0),
        "top1_agreement": float(agree.mean() if agree.size else 1.0),
    }
