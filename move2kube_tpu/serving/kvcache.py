"""Paged KV cache: fixed-size pages + per-sequence block tables.

The decode hot path's memory problem is fragmentation: contiguous
per-sequence KV buffers sized for the max context waste HBM on every
short sequence and force compaction when sequences finish mid-flight.
Pages fix both — the cache is a pool of ``[block_size]``-token pages per
layer, a sequence owns whichever pages the host-side
:class:`PageAllocator` hands it, and an int32 block table maps its
logical positions onto them. Finishing a sequence returns its pages to
the free list; nothing moves.

Page 0 is **reserved** (the "null page"): unused block-table entries and
padded prompt positions all point at it, so scatter/gather index math
needs no bounds branches inside jit — garbage lands in, and masked reads
come from, a page no live sequence owns.

The cache pytree is donated across decode steps (``donate_argnums``), so
K/V pages stay device-resident and are updated in place. Donation is a
*request*, not a guarantee — :func:`assert_cache_donated` compiles the
step and counts the executable's input-output aliases, the same
verification the PR-1 trainer uses (models/train.assert_state_donated).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax.numpy as jnp

NULL_PAGE = 0

# every per-page pool a cache pytree may carry; copy_page and the engine's
# model-cache assembly iterate this instead of hardcoding k/v, so the int8
# scale pools ride every page operation the fp pools do
PAGE_KEYS = ("k", "v", "k_scale", "v_scale")


@dataclasses.dataclass(frozen=True)
class KVCacheConfig:
    num_layers: int
    num_kv_heads: int
    head_dim: int
    block_size: int = 16        # tokens per page
    num_pages: int = 65         # pool size, INCLUDING the reserved page 0
    max_batch: int = 8          # concurrent decode slots
    max_pages_per_seq: int = 16  # block-table row length
    # dtype the K/V pages are STORED in — fp32/bf16 caches store compute-
    # dtype rows, int8 caches store quantized rows plus per-row fp32
    # scales ([num_pages, block_size, kv_heads] per layer, one scale per
    # written token row per kv head). One config field drives one shared
    # code path; nothing downstream re-derives the dtype from the model.
    dtype: Any = jnp.float32
    scale_dtype: Any = jnp.float32

    @property
    def max_seq(self) -> int:
        return self.max_pages_per_seq * self.block_size

    @property
    def quantized(self) -> bool:
        """True when pages store int8 rows and the cache carries the
        ``k_scale``/``v_scale`` per-row scale pools."""
        return jnp.dtype(self.dtype) == jnp.dtype(jnp.int8)


def spec_for_model(model_cfg, *, block_size: int = 16, max_batch: int = 8,
                   max_seq: int | None = None,
                   num_pages: int | None = None,
                   cache_dtype: Any = None) -> KVCacheConfig:
    """Cache geometry for a model config (LlamaConfig or GPT2Config,
    duck-typed: MHA models have no ``num_kv_heads``). ``num_pages``
    defaults to one full-length context per slot plus the null page.
    ``cache_dtype`` overrides the page storage dtype (int8 enables the
    quantized layout); default is the model's compute dtype."""
    num_kv_heads = getattr(model_cfg, "num_kv_heads", model_cfg.num_heads)
    head_dim = model_cfg.d_model // model_cfg.num_heads
    if max_seq is None:
        max_seq = getattr(model_cfg, "max_len", None) or getattr(
            model_cfg, "n_positions")
    max_pages = -(-max_seq // block_size)
    if num_pages is None:
        num_pages = 1 + max_batch * max_pages
    return KVCacheConfig(
        num_layers=model_cfg.num_layers, num_kv_heads=num_kv_heads,
        head_dim=head_dim, block_size=block_size, num_pages=num_pages,
        max_batch=max_batch, max_pages_per_seq=max_pages,
        dtype=model_cfg.dtype if cache_dtype is None else cache_dtype)


def pages_for(n_tokens: int, block_size: int) -> int:
    return -(-int(n_tokens) // int(block_size))


def sanitized_views(cache: dict, active):
    """Decode-time ``(block_tables, positions)`` views with every
    inactive row redirected at the null page / position 0 (jit-safe).

    Every decode-shaped executable (single-step, multi-substep, spec
    verify) runs the *full* ``max_batch`` regardless of how many slots
    hold live requests — empty rows still index the page pool. This is
    the one place that makes those rows harmless: their writes land in
    the reserved page 0 and their position math stays in range, so no
    executable needs a bounds branch and no variant can drift from the
    others' masking (a variant that forgot the redirect would scribble
    a garbage row into a *live* sequence's page)."""
    bt = jnp.where(active[:, None], cache["block_tables"], NULL_PAGE)
    pos = jnp.where(active, cache["seq_lens"], 0)
    return bt, pos


def init_cache(cfg: KVCacheConfig) -> dict:
    """Zeroed device cache pytree. ``k``/``v`` are per-layer *lists* of
    page pools — 2·num_layers separate buffers, so every one of them gets
    its own input-output alias when the decode step donates the pytree
    (a single stacked array would leave aliasing of the per-layer
    dynamic-update-slices to XLA's discretion)."""
    shape = (cfg.num_pages, cfg.block_size, cfg.num_kv_heads, cfg.head_dim)
    cache = {
        "k": [jnp.zeros(shape, cfg.dtype) for _ in range(cfg.num_layers)],
        "v": [jnp.zeros(shape, cfg.dtype) for _ in range(cfg.num_layers)],
        "block_tables": jnp.zeros((cfg.max_batch, cfg.max_pages_per_seq),
                                  jnp.int32),
        "seq_lens": jnp.zeros((cfg.max_batch,), jnp.int32),
    }
    if cfg.quantized:
        sshape = (cfg.num_pages, cfg.block_size, cfg.num_kv_heads)
        cache["k_scale"] = [jnp.zeros(sshape, cfg.scale_dtype)
                            for _ in range(cfg.num_layers)]
        cache["v_scale"] = [jnp.zeros(sshape, cfg.scale_dtype)
                            for _ in range(cfg.num_layers)]
    _check_page_schema(cache, "init_cache")
    return cache


def _check_page_schema(cache: dict, where: str) -> None:
    """Fail loudly when the cache's page pools and ``PAGE_KEYS`` drift.

    scatter_prefill hardcodes the k/v/k_scale/v_scale pools and
    copy_page iterates ``PAGE_KEYS`` — a pool added to one but not the
    others would be silently dropped from prefill writes or COW copies
    (a shared page whose new pool isn't copied dequantizes or attends
    with stale rows). Checked once at init_cache and again by the page
    ops, so the break surfaces as this error instead of bad logits.
    """
    pools = tuple(k for k in cache if isinstance(cache[k], list))
    unknown = [k for k in pools if k not in PAGE_KEYS]
    expected = PAGE_KEYS if "k_scale" in cache else PAGE_KEYS[:2]
    if unknown or tuple(k for k in PAGE_KEYS if k in cache) != expected:
        raise ValueError(
            f"{where}: page-pool schema mismatch — cache carries pools "
            f"{pools}, PAGE_KEYS declares {PAGE_KEYS} (expected "
            f"{expected}). Teach init_cache, scatter_prefill and "
            "copy_page about the new pool before serving with it.")


def scatter_prefill(cache: dict, kvs, slot, bt_row, prompt_len,
                    block_size: int) -> dict:
    """Write a prefilled prompt's per-layer K/V into the paged cache
    (jit-safe — runs inside the bucketed prefill step).

    ``kvs``: the ``return_kv=True`` output of the model's full forward,
    one ``(k, v)`` pair per layer shaped ``[1, bucket, kv_heads, hd]``.
    ``bt_row``: this sequence's page table ``[max_pages_per_seq]`` (pads
    with the null page). Positions past ``prompt_len`` (bucket padding)
    are redirected to the null page. Also installs the row and the
    sequence length into the cache's table.
    """
    _check_page_schema(cache, "scatter_prefill")
    bucket = kvs[0][0].shape[1]
    pos = jnp.arange(bucket)
    blk = jnp.where(pos < prompt_len, bt_row[pos // block_size], NULL_PAGE)
    off = pos % block_size
    quantized = "k_scale" in cache
    out = dict(cache)
    if quantized:
        from move2kube_tpu.ops.attention import quantize_kv_rows

        new_k, new_v, new_ks, new_vs = [], [], [], []
        for layer, (k, v) in enumerate(kvs):
            qk, sk = quantize_kv_rows(k[0])
            qv, sv = quantize_kv_rows(v[0])
            new_k.append(cache["k"][layer].at[blk, off].set(qk))
            new_v.append(cache["v"][layer].at[blk, off].set(qv))
            new_ks.append(cache["k_scale"][layer].at[blk, off].set(sk))
            new_vs.append(cache["v_scale"][layer].at[blk, off].set(sv))
        out["k_scale"], out["v_scale"] = new_ks, new_vs
    else:
        dtype = cache["k"][0].dtype
        new_k, new_v = [], []
        for layer, (k, v) in enumerate(kvs):
            new_k.append(cache["k"][layer].at[blk, off].set(
                k[0].astype(dtype)))
            new_v.append(cache["v"][layer].at[blk, off].set(
                v[0].astype(dtype)))
    out["k"], out["v"] = new_k, new_v
    out["block_tables"] = cache["block_tables"].at[slot].set(bt_row)
    out["seq_lens"] = cache["seq_lens"].at[slot].set(prompt_len)
    return out


def copy_page(cache: dict, src, dst) -> dict:
    """Copy one page's K/V across every layer (jit-safe). The device
    half of copy-on-write: a slot about to write into a *shared* page
    (refcount > 1 in :class:`PageAllocator`) first duplicates it into a
    private page, then points its block-table entry at the copy — the
    shared original stays immutable for every other holder.

    Dtype-generic over every page pool the cache carries (``PAGE_KEYS``):
    an int8 cache's ``k_scale``/``v_scale`` rows are copied alongside the
    quantized pages, so a shared page and its scales stay byte-immutable
    together — a COW copy that dropped the scales would dequantize the
    copied rows with zeros."""
    _check_page_schema(cache, "copy_page")
    out = dict(cache)
    for key in PAGE_KEYS:
        if key in cache:
            out[key] = [a.at[dst].set(a[src]) for a in cache[key]]
    return out


def install_block_table(cache: dict, slot, bt_row, seq_len) -> dict:
    """Point a decode slot at an existing page run (jit-safe). A
    prefix-cache hit admits by table surgery alone — the shared pages'
    K/V are already resident, so no prefill executable runs."""
    out = dict(cache)
    out["block_tables"] = cache["block_tables"].at[slot].set(bt_row)
    out["seq_lens"] = cache["seq_lens"].at[slot].set(seq_len)
    return out


class PageAllocator:
    """Host-side refcounted free list over the page pool. Page 0 never
    leaves the reserve. Allocation is all-or-nothing: a request that
    cannot get every page it needs gets none (the engine keeps it queued
    instead of deadlocking half-admitted).

    Pages carry a reference count so the prefix cache (serving/fleet/
    prefixcache.py) can hold a sequence's prompt pages after the
    sequence releases them: ``alloc`` hands out pages at refcount 1,
    ``incref`` adds holders, and ``free`` is a decref that only returns
    a page to the free list when the last holder drops it. A page with
    refcount > 1 is *shared* — holders must never write it in place;
    the engine copy-on-writes (:func:`copy_page`) before the first
    write. The LIFO free order is kept (freshly released pages are the
    warmest), with a shadow set making release bursts O(1) per page
    instead of the old O(n) list-membership scan."""

    def __init__(self, num_pages: int) -> None:
        if num_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is reserved)")
        self._free = list(range(num_pages - 1, 0, -1))
        self._free_set = set(self._free)
        self._refs: dict[int, int] = {}

    @property
    def available(self) -> int:
        return len(self._free)

    def alloc(self, n: int) -> list[int] | None:
        if n > len(self._free):
            return None
        pages = []
        for _ in range(n):
            p = self._free.pop()
            self._free_set.remove(p)
            self._refs[p] = 1
            pages.append(p)
        return pages

    def incref(self, pages) -> None:
        """Add a holder to already-allocated pages (prefix-cache shares)."""
        for p in pages:
            if p == NULL_PAGE:
                raise ValueError("page 0 is reserved and never allocated")
            if p not in self._refs:
                raise ValueError(f"incref of unallocated page {p}")
            self._refs[p] += 1

    def refcount(self, page: int) -> int:
        return self._refs.get(int(page), 0)

    def is_shared(self, page: int) -> bool:
        """More than one holder — writes must copy-on-write first."""
        return self._refs.get(int(page), 0) > 1

    def reclaimable(self, pages) -> int:
        """How many of ``pages`` would actually return to the free list
        if their holder freed them now — shared pages (prefix cache,
        copy-on-write siblings) stay resident under their other holders.
        The scheduler's page-pressure preemption consults this before
        evicting a victim: a slot whose pages are all shared buys the
        incoming request nothing, so killing its stream is pure waste."""
        return sum(1 for p in pages if self._refs.get(int(p), 0) == 1)

    def free(self, pages) -> None:
        """Drop one reference per page; a page returns to the free list
        only when its last holder releases it."""
        for p in pages:
            if p == NULL_PAGE:
                raise ValueError("page 0 is reserved and never allocated")
            if p in self._free_set:
                raise ValueError(f"double free of page {p}")
            n = self._refs.get(p, 0)
            if n <= 0:
                raise ValueError(f"double free of page {p}")
            if n == 1:
                del self._refs[p]
                self._free.append(p)
                self._free_set.add(p)
            else:
                self._refs[p] = n - 1


def assert_cache_donated(step_fn, *args, num_layers: int,
                         min_aliased: int | None = None) -> int:
    """Compile ``step_fn(*args)`` and assert the executable aliases at
    least ``min_aliased`` input buffers into outputs (default: the
    2·num_layers K/V page pools). Same executable-text check as
    models/train.compiled_alias_count — donate_argnums alone proves
    nothing."""
    from move2kube_tpu.models.train import compiled_alias_count

    if not hasattr(step_fn, "lower"):
        raise TypeError("step_fn is not jit-compiled (no .lower); donation "
                        "cannot be verified")
    compiled = step_fn.lower(*args).compile()
    n = compiled_alias_count(compiled.as_text())
    floor = 2 * num_layers if min_aliased is None else min_aliased
    if n < floor:
        raise AssertionError(
            f"compiled decode step aliases only {n} input buffers; expected "
            f">= {floor} — the KV cache is being copied, not donated")
    return n
