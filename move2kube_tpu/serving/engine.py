"""Continuous-batching decode engine over the paged KV cache.

Static-batch serving wastes the accelerator twice: short sequences pad
to the longest, and a finished sequence's slot idles until the whole
batch drains. This engine runs vLLM-style continuous batching instead —
sequences are admitted into free decode slots mid-flight (one prefill
interleaved per decode step, so running sequences never stall behind an
admission burst) and release their slot and pages the step they finish.

Compiled-shape discipline: everything the device executes comes from TWO
jit functions — a bucketed prefill (prompts pad to the smallest
configured bucket that fits, so at most ``len(buckets)`` executables)
and a fixed-shape decode step (one executable). A mixed-length request
stream therefore compiles at most ``num_buckets + 1`` distinct
executables; the serve smoke asserts ``<= num_buckets + 2`` through the
persistent compile cache (models/compile_cache.py) to leave headroom for
one backend-initiated recompile.

Prefix-cache hits (serving/fleet/prefixcache.py, ``prefix_cache=True``)
skip the bucketed prefill entirely: the shared prefix's pages are
installed into the slot's block table by table surgery (one jitted
install + at most one copy-on-write page copy), and the prompt's
*suffix* tokens are force-fed one per decode step — argmax outputs are
discarded while forced tokens remain, so the first generated token
comes from exactly the same logits the uncached path would have
computed. Hits are only taken when the suffix is short (default
``2 * block_size`` tokens); longer misses prefill cold and donate their
prompt pages to the cache for the next request.

Env knobs (docs/USAGE.md):

- ``M2KT_SERVE_MAX_BATCH``  concurrent decode slots   (default 8)
- ``M2KT_SERVE_MAX_SEQ``    max context per sequence  (default 256)
- ``M2KT_KV_BLOCK_SIZE``    tokens per KV-cache page  (default 16)
- ``M2KT_SERVE_BUCKETS``    prefill buckets, comma-sep (default: powers
  of two from 32 up to max_seq)
- ``M2KT_SERVE_ADMIT_BURST`` admissions per step; <= 0 = all free
  slots (default 1)
- ``M2KT_SERVE_PREFIX_CACHE`` enable cross-request prefix sharing
  (default off)
- ``M2KT_PREFIX_MAX_SUFFIX`` longest un-cached suffix a hit may
  decode-feed before falling back to cold prefill (default 2 pages)
- ``M2KT_SERVE_QUANT``      serving quant policy off|int8|int8-kv
  (serving/quant.py; default off)
- ``M2KT_SPEC_K``           speculative-decoding proposal length; 0
  disables (default 0)
- ``M2KT_SERVE_KERNELS``    fused-kernel dispatch auto|on|off
  (ops/attention.py serve_kernels_mode; default auto)
- ``M2KT_SCHED_TENANTS``    scheduler tenant spec — priorities drive
  preemption ordering here, quotas are enforced at the router
  (serving/sched/admission.py; default empty = never preempt)
- ``M2KT_SCHED_CHUNK_PREFILL`` chunk size for interleaved chunked
  prefill of long prompts; 0 disables (default 0)
- ``M2KT_SCHED_MAX_LORAS``  resident paged LoRA adapter rows
  (serving/sched/lora.py); 0 disables (default 0)
- ``M2KT_LORA_RANK``        max adapter rank the stacks hold (default 8)
- ``M2KT_ASYNC_DECODE``     async double-buffered decode pipeline
  auto|on|off (auto = on whenever spec decode is off; default auto)
- ``M2KT_DECODE_SUBSTEPS``  in-graph decode micro-steps per dispatch
  (a fori_loop inside ONE executable; default 1)

Scheduler plane (``serving/sched/``, PR 17): when the tenant spec ranks
tenants into distinct priority classes, an admission that finds no free
slot (or no free pages) may *preempt* the lowest-priority,
most-recently-admitted slot — its pages free immediately and its
Completion carries ``finish_reason="preempted"``; the router treats
that as paused work and resumes it token-exactly by force-feeding the
journal, so preemption loses zero tokens. Chunked prefill
(``chunk_prefill > 0``) admits a long prompt into a slot up front and
feeds it through ONE extra fixed-shape decode-mode executable, one
chunk per engine step, interleaved with the running decode batch.
Multi-LoRA (``max_loras > 0``) serves per-request adapters from stacked
paged A/B weights gathered by slot inside the SAME prefill/decode
executables (the stacks are traced operands — registering an adapter
never recompiles).

Low-precision serving (``quant``): weights are quantized ONCE at engine
construction (per-output-channel int8, serving/quant.py) and dequantized
inside the jitted steps, so every compiled executable carries int8
parameter buffers; ``int8-kv`` additionally stores the paged KV cache in
int8 with per-row scale pools that ride every page operation COW does.

Speculative decoding (``spec_k`` > 0): a shrunk same-family draft model
(first half of the target's layers, sharing its embeddings and head)
proposes ``k`` tokens per step with ``k + 1`` reuses of ONE fixed-shape
draft decode executable, and the target verifies the whole window in ONE
fixed-shape verify executable (``k + 1`` decode passes unrolled inside a
single jit). The verify step REPLACES the plain decode step in the
engine loop, so the target-model executable count stays
``num_buckets + 1``; the draft adds at most ``num_buckets + 1`` more
small-model executables, reported separately by ``compile_report``.
Acceptance is greedy-exact: emitted tokens are always the target's own
argmax choices, so spec-on and spec-off decode the same token stream.

Async decode pipeline (``async_decode`` != off, PR 19): the decode
executable feeds its own sampled tokens back as *device-resident*
operands (tokens and ``seq_lens`` advance in-graph), so the host
dispatches window k+1 before it has read window k, and consumes window
k's tokens while the device computes — journaling, stream fan-out,
TTFT/latency records, admissions, evictions and preemptions all happen
at a lag-1 window boundary. ``substeps`` > 1 additionally folds N decode
micro-steps into ONE dispatch (a fori_loop inside the same executable;
EOS is handled host-side at substep granularity, over-generated rows
are trimmed and their pages released through the refcounted allocator),
cutting the host's per-token dispatch tax by N. The multi-step
executable REPLACES the synchronous decode step — jit is lazy, the
unused variant never compiles — so the executable budget stays
``num_buckets + 1``. Spec decoding is host-synchronous by construction
(greedy-exact acceptance is a host decision) and forces the synchronous
path. Token streams are bit-identical across sync/async/substeps; the
async tests and the bench's interleaved async-vs-sync capture gate on
exactly that.
"""

from __future__ import annotations

import dataclasses
import functools
import os
import time
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np

from move2kube_tpu.obs import numerics as numericslib
from move2kube_tpu.obs import slo as slolib
from move2kube_tpu.obs import tracing
from move2kube_tpu.obs.metrics import Registry
from move2kube_tpu.serving import kvcache
from move2kube_tpu.serving import quant as quantlib
from move2kube_tpu.serving import sched as schedlib
from move2kube_tpu.serving.fleet.prefixcache import PrefixCache, PrefixHit
from move2kube_tpu.serving.kvcache import (
    NULL_PAGE,
    PAGE_KEYS,
    PageAllocator,
    copy_page,
    init_cache,
    install_block_table,
    pages_for,
    sanitized_views,
    scatter_prefill,
    spec_for_model,
)


# decode steps run sub-ms on TPU and tens of ms on forced host devices;
# span both so percentile interpolation has resolution at either end
LATENCY_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                   0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0)

# request-shape buckets (tokens): power-of-two edges matching the
# prefill bucket ladder, so a recorded histogram replays onto the same
# compile buckets the engine actually serves
LENGTH_BUCKETS = (16.0, 32.0, 64.0, 128.0, 256.0, 512.0, 1024.0,
                  2048.0, 4096.0)


def select_decode_matmul(mesh=None):
    """Pick the decode-projection matmul for this deployment.

    A mesh with a ``model`` axis shards the big projections, and a
    one-token decode step has no batch slack to hide the cross-shard
    reduction behind — so when kernels are enabled
    (``M2KT_SERVE_KERNELS`` != off) the collective-overlapped ring
    matmul (parallel/overlap.py) is selected: reduce-scatter hops
    interleave with per-chunk shard matmuls instead of serializing a
    psum after the full product. Everything else (no mesh, data-only
    mesh, kernels off) gets the plain ``x @ w``.
    """
    from move2kube_tpu.ops.attention import serve_kernels_mode
    from move2kube_tpu.parallel import overlap

    if (mesh is not None and overlap.has_model_axis(mesh)
            and serve_kernels_mode() != "off"):
        return functools.partial(overlap.collective_decode_matmul, mesh)
    return lambda x, w: x @ w


def _default_buckets(max_seq: int) -> tuple[int, ...]:
    buckets, b = [], 32
    while b < max_seq:
        buckets.append(b)
        b *= 2
    buckets.append(max_seq)
    return tuple(buckets)


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    max_batch: int = 8
    max_seq: int = 256
    block_size: int = 16
    buckets: tuple[int, ...] = ()
    max_new_tokens: int = 32   # per-request default
    eos_id: int | None = None
    admit_burst: int = 1       # admissions per step; <= 0 = all free slots
    prefix_cache: bool = False
    prefix_max_suffix: int = 0  # 0 -> 2 * block_size
    quant: str = "off"         # off | int8 | int8-kv (serving/quant.py)
    # quant-drift audit: fraction of cold admissions whose prefill is
    # re-run through the retained fp reference weights, exporting
    # max-rel logit error as m2kt_serve_quant_drift — the runtime
    # counterpart of the build-time logit gates (catches a corrupted
    # int8 scale pool in production). 0 = off, no fp copy kept.
    quant_audit_rate: float = 0.0
    spec_k: int = 0            # draft proposals per step; 0 = no spec decode
    # draft depth divisor: num_layers // factor layers (1 = full-depth
    # draft — acceptance 1.0, useful as a correctness anchor)
    spec_draft_factor: int = 2
    # scheduler plane (serving/sched): the combined tenant spec plus the
    # split QA-knob forms, merged at construction. Priorities order
    # preemption in the engine; quotas only bite at the router.
    sched_tenants: str = ""
    sched_priorities: str = ""
    sched_quotas: str = ""
    # chunked prefill: prompts longer than this many tokens prefill as
    # interleaved decode-mode chunks of this size (0 = off)
    chunk_prefill: int = 0
    # paged multi-LoRA serving: resident adapter rows (0 = off) and the
    # max rank the stacked A/B weights hold
    max_loras: int = 0
    lora_rank: int = 8
    # async decode pipeline (PR 19): "auto" engages whenever spec decode
    # is off, "on" insists (warns and falls back when spec decode wins),
    # "off" keeps the synchronous reference loop. substeps folds N
    # decode micro-steps into one dispatched executable (1 = one
    # token per dispatch)
    async_decode: str = "auto"
    substeps: int = 1

    def resolved_buckets(self) -> tuple[int, ...]:
        buckets = self.buckets or _default_buckets(self.max_seq)
        buckets = tuple(sorted(set(min(b, self.max_seq) for b in buckets)))
        if buckets[-1] < self.max_seq:
            buckets = buckets + (self.max_seq,)
        return buckets

    @classmethod
    def from_env(cls, **overrides) -> "EngineConfig":
        def _int(name, default):
            try:
                return int(os.environ.get(name, "") or default)
            except ValueError:
                return default

        buckets: tuple[int, ...] = ()
        raw = os.environ.get("M2KT_SERVE_BUCKETS", "")
        if raw:
            try:
                buckets = tuple(int(x) for x in raw.split(",") if x.strip())
            except ValueError:
                buckets = ()
        cfg = dict(
            max_batch=_int("M2KT_SERVE_MAX_BATCH", cls.max_batch),
            max_seq=_int("M2KT_SERVE_MAX_SEQ", cls.max_seq),
            block_size=_int("M2KT_KV_BLOCK_SIZE", cls.block_size),
            buckets=buckets,
            admit_burst=_int("M2KT_SERVE_ADMIT_BURST", cls.admit_burst),
            prefix_cache=os.environ.get(
                "M2KT_SERVE_PREFIX_CACHE", "").lower() in ("1", "true", "on"),
            prefix_max_suffix=_int("M2KT_PREFIX_MAX_SUFFIX",
                                   cls.prefix_max_suffix),
            quant=(lambda q: q if q in quantlib.QUANT_OPTIONS else "off")(
                os.environ.get("M2KT_SERVE_QUANT", "") or cls.quant),
            quant_audit_rate=numericslib.audit_rate(),
            spec_k=max(0, _int("M2KT_SPEC_K", cls.spec_k)),
            # sched fields share _int's tolerance: a bad value in a Helm
            # override warns inside the spec parser / defaults here, it
            # never takes the engine down (quant.py convention)
            sched_tenants=os.environ.get("M2KT_SCHED_TENANTS",
                                         cls.sched_tenants),
            sched_priorities=os.environ.get("M2KT_SCHED_PRIORITIES",
                                            cls.sched_priorities),
            sched_quotas=os.environ.get("M2KT_SCHED_QUOTAS",
                                        cls.sched_quotas),
            chunk_prefill=max(0, _int("M2KT_SCHED_CHUNK_PREFILL",
                                      cls.chunk_prefill)),
            max_loras=max(0, _int("M2KT_SCHED_MAX_LORAS", cls.max_loras)),
            lora_rank=max(1, _int("M2KT_LORA_RANK", cls.lora_rank)),
            async_decode=(os.environ.get("M2KT_ASYNC_DECODE", "")
                          or cls.async_decode),
            substeps=max(1, _int("M2KT_DECODE_SUBSTEPS", cls.substeps)),
        )
        cfg.update(overrides)
        return cls(**cfg)


class DeadlineExceeded(ValueError):
    """The request's propagated deadline cannot be met: already expired,
    or provably unmeetable from the engine's own decode-latency history.
    A ValueError so every existing reject path (HTTP 4xx mapping, router
    no-retry) treats it as the caller's problem, not the replica's."""


class EngineDraining(RuntimeError):
    """submit() refused because the engine is draining: it finishes its
    in-flight work but admits nothing new. The caller (router) should
    re-route, not retry here."""


@dataclasses.dataclass
class Request:
    rid: str
    prompt: list[int]
    max_new_tokens: int | None = None
    # multi-tenant attribution: the X-M2KT-Tenant header value, carried
    # router -> replica -> engine ("" = the default tenant)
    tenant: str = ""
    # W3C traceparent of the caller's span: the engine's serve.request
    # root adopts its trace id so cross-process traces stitch
    traceparent: str = ""
    # remaining deadline budget (seconds) at submission, carried by the
    # X-M2KT-Deadline header; None = no deadline. Admission sheds
    # requests that cannot finish inside it (reject-fast beats
    # timeout-slow), and queued requests that expire before a slot
    # frees complete with finish_reason "shed"
    deadline_s: float | None = None
    # named LoRA adapter to decode under ("" = base model); must be
    # registered in the engine's adapter store or submit rejects
    adapter: str = ""


@dataclasses.dataclass
class Completion:
    rid: str
    prompt_len: int
    tokens: list[int]
    # "eos" | "length" | "shed" | "preempted" — preempted is paused
    # work, not failure: the router resumes it token-exactly from its
    # journal (the tokens so far already rode on_token)
    finish_reason: str
    # the engine's weight generation at release time — a stream that
    # rode across a live swap finishes stamped with the NEW version
    weights_version: int = 0


@dataclasses.dataclass
class _Slot:
    req: Request
    pages: list[int]
    tokens: list[int]
    last_token: int
    max_new: int
    # prompt suffix a prefix-cache hit still owes the cache: fed one
    # token per decode step; argmax output is discarded until empty
    pending: list[int] = dataclasses.field(default_factory=list)
    prefix_hit: bool = False
    # scheduler plane: admission order + priority class (preemption
    # picks the lowest class, most recent seq), the slot's row in the
    # adapter store, and the chunked-prefill marker (a chunking slot is
    # excluded from decode until its whole prompt has landed)
    seq: int = 0
    priority: int = 1
    adapter_row: int = 0
    chunking: bool = False
    # async pipeline: True while the slot's next input token lives only
    # on the device (the feedback carry of the newest dispatched
    # window) — the host hasn't consumed it yet, so a dispatch must
    # seed from the carry instead of force-feeding ``last_token``
    feedback: bool = False
    # tokens this slot will append once its dispatched, not-yet-consumed
    # windows land; the dispatcher skips rows whose length budget is
    # already fully scheduled instead of burning substeps on output the
    # consume side would only trim
    inflight_scheduled: int = 0


@dataclasses.dataclass
class _ChunkJob:
    """The (single) in-flight chunked prefill: one chunk of the slot's
    prompt runs per engine step, interleaved with the decode batch."""
    slot_idx: int
    done: int = 0  # prompt tokens already written into the slot's pages


@dataclasses.dataclass
class _Window:
    """One in-flight async decode dispatch: ``substeps`` micro-steps of
    generation for the slots captured in ``entries``. ``toks``/``logits``
    are *unfulfilled* device arrays until :meth:`_consume_window`
    materializes them — dispatch returns before the device computed
    anything, which is the whole point."""
    toks: object    # [max_batch, substeps] int32 device array
    logits: object  # [max_batch, substeps, vocab]
    # (slot_idx, rid, keep): outputs j < keep re-fed a cached prompt's
    # suffix and are discarded, exactly mirroring the synchronous
    # pending-token rule; rid guards against a slot released (EOS /
    # preemption) after this window was dispatched at lag-1
    entries: list
    t0: float       # dispatch timestamp


class ServingEngine:
    """Greedy-decoding continuous-batching engine for the repo's decoder
    LMs (models/llama.py, models/gpt2.py — anything whose ``__call__``
    carries the prefill/decode modes).

    ``variables`` is the model's full init output (``{"params": ...}``);
    only the KV cache is donated, parameters stay shared across steps.
    """

    def __init__(self, model, variables, config: EngineConfig | None = None,
                 registry: Registry | None = None,
                 tracer: "tracing.SpanRecorder | None" = None,
                 mesh=None):
        self.model = model
        self.config = config or EngineConfig.from_env()
        # model-parallel serving meshes swap the decode projections onto
        # the collective-overlapped ring matmul (select_decode_matmul)
        self.mesh = mesh
        self.decode_matmul = select_decode_matmul(mesh)
        self.quant = quantlib.policy(self.config.quant)
        # quant-drift auditor: retain the pre-quant fp weights so a
        # sampled fraction of cold prefills can be replayed through the
        # reference path at runtime. Only when quantizing AND auditing —
        # the fp copy roughly doubles resident parameters, a price paid
        # knowingly via M2KT_QUANT_AUDIT_RATE.
        self._audit_rate = (max(0.0, min(1.0, self.config.quant_audit_rate))
                            if self.quant.quantize_weights else 0.0)
        self._audit_fp_variables = variables if self._audit_rate else None
        self._audit_apply = None   # lazily jitted fp prefill
        self._audit_accum = 0.0    # deterministic rate accumulator
        self._drift_last = 0.0
        self._drift_max = 0.0
        if self.quant.quantize_weights:
            # once, at construction: the jitted steps dequantize INSIDE
            # the compiled program, so the executables' parameter buffers
            # are the int8 tensors
            variables = quantlib.quantize_variables(variables)
        self.variables = variables
        # weight generation: bumped by install_weights (live swap);
        # stamped into completions, spans, and m2kt_weights_version
        self.weights_version = 1
        self._dq = (quantlib.dequantize_variables
                    if self.quant.quantize_weights else (lambda v: v))
        self.buckets = self.config.resolved_buckets()
        self.cache_cfg = spec_for_model(
            model.cfg, block_size=self.config.block_size,
            max_batch=self.config.max_batch, max_seq=self.config.max_seq,
            cache_dtype=self.quant.cache_dtype)
        self._cache = init_cache(self.cache_cfg)
        self._allocator = PageAllocator(self.cache_cfg.num_pages)
        self._slots: list[_Slot | None] = [None] * self.config.max_batch
        self._pending: deque[Request] = deque()
        # ---- scheduler plane (serving/sched) -------------------------
        # tenant policies shared with the router: priorities order
        # preemption here; quotas only bite at the router front
        self.sched = schedlib.AdmissionController.from_specs(
            self.config.sched_tenants, self.config.sched_priorities,
            self.config.sched_quotas)
        self._preempt_enabled = self.sched.distinct_priorities()
        self._admit_seq = 0
        self._preempt_count = 0
        self._chunk_count = 0
        # paged multi-LoRA (sched/lora.py): mutually exclusive with spec
        # decode — the draft shares the target's head, and a proposer
        # blind to the adapter would collapse acceptance anyway
        self.max_loras = max(0, self.config.max_loras)
        if self.max_loras and self.config.spec_k:
            print("[m2kt] WARNING: M2KT_SCHED_MAX_LORAS is incompatible "
                  "with spec decode (M2KT_SPEC_K); disabling multi-LoRA",
                  flush=True)
            self.max_loras = 0
        self.adapters: schedlib.AdapterStore | None = None
        if self.max_loras:
            self.adapters = schedlib.AdapterStore(
                d_model=model.cfg.d_model, vocab=model.cfg.vocab_size,
                rank=max(1, self.config.lora_rank),
                max_loras=self.max_loras)
        self._req_adapter: dict[str, int] = {}
        # chunked prefill: spec decode keeps its own window discipline
        # and opts out
        self.chunk_prefill = max(0, self.config.chunk_prefill)
        if self.chunk_prefill and self.config.spec_k:
            print("[m2kt] WARNING: M2KT_SCHED_CHUNK_PREFILL is "
                  "incompatible with spec decode (M2KT_SPEC_K); "
                  "disabling chunked prefill", flush=True)
            self.chunk_prefill = 0
        self._chunk_job: _ChunkJob | None = None
        # ---- async decode pipeline (PR 19) ---------------------------
        # spec decode is host-synchronous by design (greedy-exact
        # acceptance is a host decision), so async engages only without
        # it — "auto" is therefore on for every plain-decode engine
        self.spec_k = max(0, self.config.spec_k)
        mode = (self.config.async_decode or "auto").strip().lower()
        if mode not in ("auto", "on", "off"):
            print(f"[m2kt] WARNING: M2KT_ASYNC_DECODE={mode!r} is not "
                  "auto|on|off; using auto", flush=True)
            mode = "auto"
        self.async_mode = mode
        if mode == "on" and self.spec_k:
            print("[m2kt] WARNING: M2KT_ASYNC_DECODE=on is incompatible "
                  "with spec decode (M2KT_SPEC_K); running the "
                  "synchronous loop", flush=True)
        self.async_decode = mode != "off" and not self.spec_k
        self.substeps = max(1, int(self.config.substeps))
        if self.substeps > 1 and not self.async_decode:
            print("[m2kt] WARNING: M2KT_DECODE_SUBSTEPS>1 needs the "
                  "async pipeline (M2KT_ASYNC_DECODE != off, spec "
                  "decode off); running 1 substep", flush=True)
            self.substeps = 1
        # capacity slack: a spec verify window or an in-flight async
        # window pair may write K/V past the point a stream finishes —
        # async overruns by up to 2*substeps-1 positions (the tail of
        # the window that emitted EOS plus one whole lag-1 window
        # already dispatched). Those writes are stale-by-construction
        # but must land inside the slot's own block table, so every
        # capacity check reserves the positions like the spec scratch.
        self._spec_slack = self.spec_k
        self._async_slack = (2 * self.substeps - 1 if self.async_decode
                             else 0)
        self._overrun_slack = self._spec_slack + self._async_slack
        # double-buffer state: windows dispatched but not yet consumed,
        # the device-resident feedback token of the newest window, and
        # completions surfaced by an out-of-step pipeline flush
        self._inflight: deque[_Window] = deque()
        self._carry_tok = None
        self._flush_backlog: list[Completion] = []
        self._last_consume_done: float | None = None
        self._gap_total = 0.0
        self._busy_total = 0.0
        # --------------------------------------------------------------
        self._prefill = self._make_prefill()
        # the async multi-substep executable REPLACES the synchronous
        # decode step (jit is lazy — the unused variant never compiles),
        # keeping the target-model executable budget at num_buckets + 1
        self._decode = (self._make_decode_multi() if self.async_decode
                        else self._make_decode())
        self._install, self._copy, self._install_kv = self._make_table_ops()
        self._chunk = (self._make_chunk_prefill()
                       if self.chunk_prefill else None)
        # speculative decoding: draft model (shrunk same-family config
        # sharing the target's embeddings/head) + its own paged cache with
        # IDENTICAL page geometry, so page indices map 1:1 and every
        # allocator/prefix-cache decision covers both caches
        self._draft_cache = None
        if self.spec_k:
            draft_cfg = quantlib.draft_config(
                model.cfg, self.config.spec_draft_factor)
            self._draft_cfg = draft_cfg
            self._draft_model = type(model)(draft_cfg)
            self.draft_variables = quantlib.draft_variables_from(
                self.variables, draft_cfg)
            self._draft_cache = init_cache(dataclasses.replace(
                self.cache_cfg, num_layers=draft_cfg.num_layers))
            self._draft_prefill = self._make_prefill(self._draft_model)
            self._draft_decode = self._make_decode(self._draft_model)
            self._verify = self._make_verify()
        self._prefix: PrefixCache | None = None
        if self.config.prefix_cache:
            self._prefix = PrefixCache(self.cache_cfg.block_size,
                                       self._allocator)
        # opt-in logit capture for the equivalence gates: per-rid rows of
        # the logits each *generated* token was argmaxed from
        self.capture_logits = False
        self.logit_log: dict[str, list[np.ndarray]] = {}
        # decode stats for the bench phase (tokens/s, p50/p95 per token)
        self._decode_time = 0.0
        self._decode_tokens = 0
        self._prefill_count = 0
        self._submit_ts: dict[str, float] = {}
        self._req_tenant: dict[str, str] = {}
        # absolute (perf_counter) deadlines for queued requests; a queued
        # request whose deadline passes before a slot frees is shed at
        # admission instead of burning a slot on a dead-on-arrival stream
        self._deadline_abs: dict[str, float] = {}
        # graceful drain: finish in-flight work, admit nothing new
        self._draining = False
        # token-emission hook for the fleet layer: called
        # ``on_token(rid, token)`` the moment a generated token lands in
        # its slot, at every emission site (decode step, spec window,
        # prefill first token, disagg install first token). The router's
        # journal rides this so a replica death mid-stream loses nothing
        self.on_token = None
        # per-request distributed traces (admit -> queue-wait -> prefill
        # -> decode steps -> complete); identity is threaded explicitly
        # because many live request traces interleave in one thread
        self.tracer = tracer if tracer is not None else (
            tracing.get() if tracing.enabled() else None)
        self._req_spans: dict[str, tracing.Span] = {}
        # a private registry by default: engine instruments must not
        # cross-pollute between engines tests build in one process; the
        # serve template passes obs.default_registry() so /metrics sees it
        self.registry = registry if registry is not None else Registry()
        self._init_metrics()
        # per-tenant SLO ledger: attainment windows + burn-rate gauges on
        # the same registry /metrics scrapes
        self.slo = slolib.SLOTracker(registry=self.registry)
        self._snapshot_persistent_cache()

    def _init_metrics(self) -> None:
        reg = self.registry
        # fixed-bucket histograms: bounded memory for long-running
        # servers (stats() used to keep a grow-forever latency list)
        self._lat_hist = reg.histogram(
            "m2kt_serve_token_latency_seconds",
            "Per-token decode step latency", buckets=LATENCY_BUCKETS)
        self._ttft_hist = reg.histogram(
            "m2kt_serve_ttft_seconds",
            "Time from submit to first token (queue wait + prefill)",
            buckets=LATENCY_BUCKETS)
        self._queue_depth = reg.gauge(
            "m2kt_serve_queue_depth", "Requests waiting for a decode slot")
        self._active_slots = reg.gauge(
            "m2kt_serve_active_slots", "Decode slots currently occupied")
        self._slot_occupancy = reg.gauge(
            "m2kt_serve_slot_occupancy",
            "Fraction of decode slots occupied")
        self._page_util = reg.gauge(
            "m2kt_serve_page_pool_utilization",
            "Fraction of KV-cache pages allocated")
        self._admitted = reg.counter(
            "m2kt_serve_admitted_total", "Requests admitted into a slot")
        self._rejected = reg.counter(
            "m2kt_serve_rejected_total",
            "Requests rejected at submit (too long / empty)")
        self._deadline_shed = reg.counter(
            "m2kt_serve_deadline_shed_total",
            "Requests shed because their propagated deadline is "
            "expired, unmeetable, or passed while queued",
            labels=("reason",))
        self._completed = reg.counter(
            "m2kt_serve_completed_total", "Completed sequences by reason",
            labels=("reason",))
        self._decode_steps_total = reg.counter(
            "m2kt_serve_decode_steps_total", "Decode steps executed")
        self._tokens_total = reg.counter(
            "m2kt_serve_decode_tokens_total", "Tokens generated")
        self._prefix_hits = reg.counter(
            "m2kt_serve_prefix_hits_total",
            "Admissions served from the prefix cache (no prefill)")
        self._prefix_misses = reg.counter(
            "m2kt_serve_prefix_misses_total",
            "Admissions that ran a cold prefill")
        self._prefix_hit_tokens = reg.counter(
            "m2kt_serve_prefix_hit_tokens_total",
            "Prompt tokens whose K/V came from shared pages")
        self._cow_copies = reg.counter(
            "m2kt_serve_cow_copies_total",
            "Shared pages copy-on-written before a slot's first write")
        self._prefix_pages = reg.gauge(
            "m2kt_serve_prefix_cache_pages",
            "KV pages currently pinned by the prefix cache")
        self._sched_preempted = reg.counter(
            "m2kt_sched_preempted_total",
            "Slots evicted by the scheduler as paused work (the router "
            "journal resumes them token-exactly)", labels=("reason",))
        self._sched_chunked = reg.counter(
            "m2kt_sched_chunked_total",
            "Long prompts prefilled as interleaved decode-mode chunks",
            labels=("reason",))
        self._spec_proposed = reg.counter(
            "m2kt_serve_spec_proposed_total",
            "Draft tokens proposed to the verify step")
        self._spec_accepted = reg.counter(
            "m2kt_serve_spec_accepted_total",
            "Draft tokens accepted by the verify step")
        self._spec_acceptance = reg.gauge(
            "m2kt_serve_spec_acceptance_rate",
            "Accepted / proposed draft tokens (cumulative)")
        # per-tenant attribution lives in NEW families (the unlabelled
        # m2kt_serve_* histograms keep their label-less default child,
        # which stats() depends on); cardinality is capped — tenant K+1
        # and beyond collapse into the "other" series
        cap = slolib.max_tenants()
        self._tenant_ttft = reg.histogram(
            "m2kt_serve_tenant_ttft_seconds",
            "Time to first token by tenant", buckets=LATENCY_BUCKETS,
            labels=("tenant",), max_series=cap + 1)
        self._tenant_lat = reg.histogram(
            "m2kt_serve_tenant_token_latency_seconds",
            "Per-token decode latency by tenant", buckets=LATENCY_BUCKETS,
            labels=("tenant",), max_series=cap + 1)
        self._tenant_admitted = reg.counter(
            "m2kt_serve_tenant_admitted_total",
            "Requests admitted into a slot by tenant",
            labels=("tenant",), max_series=cap + 1)
        self._tenant_rejected = reg.counter(
            "m2kt_serve_tenant_rejected_total",
            "Requests rejected at submit by tenant",
            labels=("tenant",), max_series=cap + 1)
        # request-shape histograms: the usage ledger snapshots these so
        # the fleet capture can replay each tenant's prompt/output
        # length mix, not just its aggregate token rate
        self._tenant_prompt_tokens = reg.histogram(
            "m2kt_serve_tenant_prompt_tokens",
            "Prompt length (tokens) of completed requests by tenant",
            buckets=LENGTH_BUCKETS, labels=("tenant",), max_series=cap + 1)
        self._tenant_decode_tokens = reg.histogram(
            "m2kt_serve_tenant_decode_tokens",
            "Generated length (tokens) of completed requests by tenant",
            buckets=LENGTH_BUCKETS, labels=("tenant",), max_series=cap + 1)
        self._quant_mode = reg.gauge(
            "m2kt_serve_quant_mode",
            "Serving quant policy (0=off, 1=int8, 2=int8-kv)")
        self._quant_mode.set(quantlib.QUANT_OPTIONS.index(self.quant.name))
        self._quant_drift = reg.gauge(
            "m2kt_serve_quant_drift",
            "Max-rel logit error of the last audited prefill vs the fp "
            "reference weights (0 until a request is audited)")
        self._quant_audits = reg.counter(
            "m2kt_serve_quant_audit_total",
            "Cold admissions replayed through the fp reference path")
        self._weights_version_gauge = reg.gauge(
            "m2kt_weights_version",
            "Weight generation currently installed in the engine")
        self._weights_version_gauge.set(self.weights_version)
        self._dispatch_gap = reg.histogram(
            "m2kt_serve_dispatch_gap_seconds",
            "Host time between consuming decode step k and dispatching "
            "k+1 (0 when the async pipeline kept the device fed)",
            buckets=LATENCY_BUCKETS)
        self._host_overhead = reg.gauge(
            "m2kt_serve_host_overhead_ratio",
            "Fraction of serving wall time the device spent starved on "
            "the host: dispatch gaps / (gaps + device-busy time)")
        self._inflight_gauge = reg.gauge(
            "m2kt_serve_inflight_windows",
            "Async decode windows dispatched but not yet consumed")
        self._total_pages = max(1, self.cache_cfg.num_pages - 1)  # page 0 reserved
        # /metrics re-renders gauges from the host-side snapshot taken
        # at the last step-sync point — a tight Prometheus scrape can
        # never add a host-device sync to the decode hot loop
        self._gauge_snapshot: dict = {}
        self._update_occupancy()
        reg.add_collect_hook(self._refresh_gauges)

    def _close_ttft(self, rid: str, ttft: float) -> None:
        """Per-tenant side of a TTFT close: the tenant histogram and the
        SLO ledger see the same reading the fleet histogram recorded."""
        tenant = self._req_tenant.get(rid, "default")
        self._tenant_ttft.labels(tenant).observe(ttft)
        self.slo.record(tenant, ok=True, ttft_s=ttft)

    def _update_occupancy(self) -> None:
        """Snapshot the occupancy gauges' inputs at a step-sync point.
        Everything here is HOST state (slot list, allocator free list,
        prefix index) — the one rule that keeps /metrics off the device:
        anything derived from device arrays (seq_lens, the async carry)
        must be captured into the snapshot HERE, never read at scrape
        time (:meth:`_refresh_gauges`)."""
        active = sum(1 for s in self._slots if s is not None)
        snap = {
            "queue_depth": len(self._pending),
            "active_slots": active,
            "slot_occupancy": active / max(1, self.config.max_batch),
            "page_util": 1.0 - self._allocator.available / self._total_pages,
            "inflight": len(self._inflight),
        }
        if self._prefix is not None:
            snap["prefix_pages"] = self._prefix.total_pages
        self._gauge_snapshot = snap
        self._refresh_gauges()

    def _refresh_gauges(self) -> None:
        """Re-set the occupancy gauges from the cached snapshot — runs
        as a registry collect hook on every /metrics render, touching
        nothing but host floats."""
        snap = self._gauge_snapshot
        if not snap:
            return
        self._queue_depth.set(snap["queue_depth"])
        self._active_slots.set(snap["active_slots"])
        self._slot_occupancy.set(snap["slot_occupancy"])
        self._page_util.set(snap["page_util"])
        self._inflight_gauge.set(snap["inflight"])
        if "prefix_pages" in snap:
            self._prefix_pages.set(snap["prefix_pages"])

    # ------------------------------------------------------------------
    # jitted device steps (the ONLY code that runs on the accelerator)
    # ------------------------------------------------------------------

    def _make_prefill(self, model=None):
        model = model or self.model
        block_size, dq = self.cache_cfg.block_size, self._dq

        @functools.partial(jax.jit, donate_argnums=(1,))
        def prefill(variables, cache, ids, bt_row, slot, prompt_len,
                    *lora):
            # lora: () or the scheduler's (a_stack, b_stack, rows) —
            # traced operands, so the same executable serves every
            # adapter mix (and the no-lora engine never pays for it)
            logits, kvs = model.apply(dq(variables), ids, return_kv=True,
                                      lora=lora if lora else None)
            cache = scatter_prefill(cache, kvs, slot, bt_row, prompt_len,
                                    block_size)
            first = jnp.argmax(logits[0, prompt_len - 1]).astype(jnp.int32)
            return first, logits[0], cache

        return prefill

    def _make_decode(self, model=None):
        model, dq = model or self.model, self._dq

        @functools.partial(jax.jit, donate_argnums=(1,))
        def decode(variables, cache, tokens, active, *lora):
            # sanitize freed/idle slots: their stale tables must not write
            # into pages the allocator may have handed to someone else
            bt, pos = sanitized_views(cache, active)
            model_cache = {k: cache[k] for k in PAGE_KEYS if k in cache}
            model_cache["block_tables"] = bt
            model_cache["seq_lens"] = pos + 1
            logits, model_cache = model.apply(
                dq(variables), tokens, positions=pos, cache=model_cache,
                lora=lora if lora else None)
            new_cache = {k: model_cache[k] for k in PAGE_KEYS if k in cache}
            new_cache["block_tables"] = cache["block_tables"]
            new_cache["seq_lens"] = (cache["seq_lens"]
                                     + active.astype(jnp.int32))
            next_tokens = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return logits, next_tokens, new_cache

        return decode

    def _make_decode_multi(self):
        """The async pipeline's decode executable: ``substeps`` decode
        micro-steps folded into ONE dispatch by a fori_loop, with the
        sampled token fed back in-graph — the host touches the device
        once per N tokens, and never between a window's micro-steps.

        Per-row input selection makes the window token-exact with the
        synchronous loop: micro-step j consumes ``forced[:, j]`` while
        ``j < fcount`` (the slot's last token followed by a prefix-hit's
        still-owed prompt suffix — ground truth, not the model's to
        choose) and the previous micro-step's argmax after. A slot whose
        next input only exists on the device (``_Slot.feedback``) seeds
        from ``seed`` — the carry returned by the PREVIOUS window, still
        unread by the host when this one is dispatched. ``seq_lens``
        advances to ``base + substeps`` in-graph for active rows, so the
        next window can be dispatched before this one is consumed.

        Returns ``(tokens [B, N], logits [B, N, vocab], carry [B],
        cache)``; the carry is the last micro-step's argmax, the next
        window's seed."""
        model, dq, N = self.model, self._dq, self.substeps
        vocab = model.cfg.vocab_size

        @functools.partial(jax.jit, donate_argnums=(1,))
        def decode_multi(variables, cache, seed, forced, fcount, active,
                         *lora):
            params = dq(variables)
            bt, base = sanitized_views(cache, active)
            pages = {k: cache[k] for k in PAGE_KEYS if k in cache}
            B = seed.shape[0]
            toks0 = jnp.zeros((B, N), jnp.int32)
            logits0 = jnp.zeros((B, N, vocab), jnp.float32)

            def body(j, carry):
                pages, tok, toks_out, logits_out = carry
                pos = base + j
                mc = dict(pages)
                mc["block_tables"] = bt
                mc["seq_lens"] = pos + 1
                logits, mc = model.apply(params, tok, positions=pos,
                                         cache=mc,
                                         lora=lora if lora else None)
                pages = {k: mc[k] for k in pages}
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                toks_out = toks_out.at[:, j].set(nxt)
                logits_out = logits_out.at[:, j].set(
                    logits.astype(jnp.float32))
                jn = jnp.minimum(j + 1, N - 1)
                nxt_in = jnp.where(j + 1 < fcount, forced[:, jn], nxt)
                return pages, nxt_in.astype(jnp.int32), toks_out, logits_out

            tok0 = jnp.where(fcount > 0, forced[:, 0], seed)
            pages, carry_tok, toks_out, logits_out = jax.lax.fori_loop(
                0, N, body, (pages, tok0.astype(jnp.int32), toks0, logits0))
            new_cache = dict(cache)
            new_cache.update(pages)
            new_cache["seq_lens"] = jnp.where(
                active, base + N, cache["seq_lens"]).astype(jnp.int32)
            return toks_out, logits_out, carry_tok, new_cache

        return decode_multi

    def _make_verify(self):
        """The spec-decode verify step: ``spec_k + 1`` single-token decode
        passes unrolled inside ONE jit — one fixed-shape executable
        regardless of how the window's tokens split between forced
        prompt-suffix tokens and draft proposals. ``tokens`` is
        ``[max_batch, spec_k + 1]`` (the slot's last token followed by
        the window); returns the target logits after each consumed token
        ``[max_batch, spec_k + 1, vocab]``. ``seq_lens`` is NOT advanced
        here — the host sets it to the accepted length, which only
        acceptance (a host decision) can know."""
        model, dq, W = self.model, self._dq, self.spec_k + 1

        @functools.partial(jax.jit, donate_argnums=(1,))
        def verify(variables, cache, tokens, active):
            params = dq(variables)
            bt, base = sanitized_views(cache, active)
            pages = {k: cache[k] for k in PAGE_KEYS if k in cache}
            all_logits = []
            for j in range(W):
                pos = base + j
                model_cache = dict(pages)
                model_cache["block_tables"] = bt
                model_cache["seq_lens"] = pos + 1
                logits, model_cache = model.apply(
                    params, tokens[:, j], positions=pos, cache=model_cache)
                pages = {k: model_cache[k] for k in pages}
                all_logits.append(logits)
            new_cache = dict(cache)
            new_cache.update(pages)
            return jnp.stack(all_logits, axis=1), new_cache

        return verify

    def _make_chunk_prefill(self):
        """The chunked-prefill executable: ONE fixed-shape jit that
        feeds ``chunk_prefill`` prompt tokens of a single slot through
        the decode-mode path (K/V written page-wise at each position),
        carrying the page pools through a fori_loop. The engine runs one
        chunk per step, after the decode batch, so a max-length prompt
        shares the device with the running streams instead of stalling
        them. Returns the logits after the chunk's LAST token — on the
        final chunk that is exactly the reading a whole bucketed prefill
        would have produced for the prompt's last position — plus the
        updated cache (``seq_lens`` advances to ``start + count``
        in-graph)."""
        model, dq, C = self.model, self._dq, self.chunk_prefill
        vocab = model.cfg.vocab_size

        @functools.partial(jax.jit, donate_argnums=(1,))
        def chunk(variables, cache, tokens, slot, start, count, *lora):
            params = dq(variables)
            n = cache["seq_lens"].shape[0]
            onehot = jnp.arange(n) == slot
            # only the chunking slot's table is live; every other row is
            # redirected to the null page so the loop's writes cannot
            # touch a running stream's pages
            bt = jnp.where(onehot[:, None], cache["block_tables"],
                           NULL_PAGE)
            pages = {k: cache[k] for k in PAGE_KEYS if k in cache}

            def body(j, carry):
                pages, last = carry
                act = onehot & (j < count)
                pos = jnp.where(act, start + j, 0)
                toks = jnp.where(act, tokens[j], 0).astype(jnp.int32)
                mc = dict(pages)
                mc["block_tables"] = jnp.where(act[:, None], bt, NULL_PAGE)
                mc["seq_lens"] = pos + 1
                logits, mc = model.apply(params, toks, positions=pos,
                                         cache=mc,
                                         lora=lora if lora else None)
                pages = {k: mc[k] for k in pages}
                last = jnp.where(j + 1 == count,
                                 logits[slot].astype(jnp.float32), last)
                return pages, last

            pages, last = jax.lax.fori_loop(
                0, C, body, (pages, jnp.zeros((vocab,), jnp.float32)))
            new_cache = dict(cache)
            new_cache.update(pages)
            new_cache["seq_lens"] = jnp.where(
                onehot, start + count, cache["seq_lens"]).astype(jnp.int32)
            return last, new_cache

        return chunk

    def _make_table_ops(self):
        """Three small donated steps for admissions that skip prefill:
        block-table install (prefix hit), copy-on-write page copy, and
        the disagg-side K/V scatter. They compile lazily — an engine
        that never shares pages never builds them."""
        block_size = self.cache_cfg.block_size

        @functools.partial(jax.jit, donate_argnums=(0,))
        def install(cache, slot, bt_row, seq_len):
            return install_block_table(cache, slot, bt_row, seq_len)

        @functools.partial(jax.jit, donate_argnums=(0,))
        def copy(cache, src, dst):
            return copy_page(cache, src, dst)

        @functools.partial(jax.jit, donate_argnums=(0,))
        def install_kv(cache, kvs, bt_row, slot, prompt_len):
            return scatter_prefill(cache, kvs, slot, bt_row, prompt_len,
                                   block_size)

        return install, copy, install_kv

    # ------------------------------------------------------------------
    # host-side continuous batching
    # ------------------------------------------------------------------

    def submit(self, req: Request) -> None:
        plen = len(req.prompt)
        max_new = req.max_new_tokens or self.config.max_new_tokens
        tenant = slolib.clean_tenant(req.tenant)
        if self._draining:
            raise EngineDraining(f"{req.rid}: engine is draining")
        try:
            if plen < 1:
                raise ValueError(f"{req.rid}: empty prompt")
            if plen > self.buckets[-1]:
                raise ValueError(
                    f"{req.rid}: prompt length {plen} exceeds the largest "
                    f"prefill bucket {self.buckets[-1]}")
            if plen + max_new + self._overrun_slack > self.cache_cfg.max_seq:
                slack = (f" + overrun slack {self._overrun_slack}"
                         if self._overrun_slack else "")
                raise ValueError(
                    f"{req.rid}: prompt + max_new_tokens = {plen + max_new}"
                    f"{slack} exceeds max_seq {self.cache_cfg.max_seq}")
            reason = self._deadline_verdict(req.deadline_s, max_new)
            if reason is not None:
                self._deadline_shed.labels(reason=reason).inc()
                raise DeadlineExceeded(
                    f"{req.rid}: deadline {req.deadline_s:.3f}s {reason} "
                    f"for {max_new} new tokens")
            if req.adapter:
                if self.adapters is None:
                    raise ValueError(
                        f"{req.rid}: adapter {req.adapter!r} requested "
                        "but the engine serves no adapters "
                        "(M2KT_SCHED_MAX_LORAS=0)")
                # refcounted row acquire (unknown adapter raises): the
                # store cannot drop the weights while this stream runs
                self._req_adapter[req.rid] = self.adapters.acquire(
                    req.adapter)
        except ValueError:
            self._rejected.inc()
            self._tenant_rejected.labels(tenant).inc()
            self.slo.record(tenant, ok=False)
            raise
        if req.deadline_s is not None:
            self._deadline_abs[req.rid] = (time.perf_counter()
                                           + req.deadline_s)
        self._submit_ts[req.rid] = time.perf_counter()
        self._req_tenant[req.rid] = tenant
        if self.tracer is not None:
            # adopt the caller's trace id when the request carries a
            # traceparent so the fleet collector stitches router and
            # replica rings into one trace
            self._req_spans[req.rid] = self.tracer.start(
                "serve.request",
                attrs={"rid": req.rid, "prompt_len": plen,
                       "tenant": tenant},
                detached=True, remote_parent=req.traceparent or None)
        self._pending.append(req)
        # refresh the snapshot, not the bare gauge: the /metrics collect
        # hook re-renders from the snapshot and would clobber a direct set
        self._update_occupancy()

    def _deadline_verdict(self, deadline_s: float | None,
                          max_new: int) -> str | None:
        """Shed reason for a deadline, or None when it is acceptable.
        "expired" = already past; "unmeetable" = the engine's own
        observed p50 decode-step latency says ``max_new`` tokens cannot
        land inside the remaining budget (no history = benefit of the
        doubt)."""
        if deadline_s is None:
            return None
        if deadline_s <= 0:
            return "expired"
        p50 = self._lat_hist.quantile(0.50) if self._lat_hist.count else 0.0
        if p50 > 0 and max_new * p50 > deadline_s:
            return "unmeetable"
        return None

    def drain(self) -> None:
        """Stop admitting new requests; in-flight work keeps stepping.
        The caller pumps :meth:`step` until :meth:`has_work` clears."""
        self._draining = True

    def undrain(self) -> None:
        self._draining = False

    @property
    def draining(self) -> bool:
        return self._draining

    def _shed(self, req: Request, reason: str) -> Completion:
        """Complete a queued request as shed: counted, SLO-charged, and
        surfaced as a Completion so no waiter hangs."""
        self._deadline_shed.labels(reason=reason).inc()
        tenant = self._req_tenant.pop(req.rid, "default")
        self.slo.record(tenant, ok=False)
        self._submit_ts.pop(req.rid, None)
        self._deadline_abs.pop(req.rid, None)
        if self.adapters is not None:
            self.adapters.release(self._req_adapter.pop(req.rid, 0))
        self._completed.labels(reason="shed").inc()
        if self.tracer is not None:
            root = self._req_spans.pop(req.rid, None)
            if root is not None:
                self.tracer.end(root, attrs={"finish_reason": "shed",
                                             "shed_reason": reason})
        self._update_occupancy()
        return Completion(rid=req.rid, prompt_len=len(req.prompt),
                          tokens=[], finish_reason="shed")

    def _emit_token(self, rid: str, tok: int) -> None:
        cb = self.on_token
        if cb is not None:
            cb(rid, tok)

    def has_work(self) -> bool:
        return (bool(self._pending) or bool(self._inflight)
                or bool(self._flush_backlog)
                or any(s is not None for s in self._slots))

    def step(self) -> list[Completion]:
        """One engine iteration: admit pending requests into free slots
        (up to ``admit_burst``; bucketed prefill, or block-table install
        on a prefix-cache hit), then run one decode step for every
        active slot. Returns the sequences that finished this
        iteration. Under the async pipeline a step *dispatches* one
        window and *consumes* the window before it (lag-1), so tokens
        surface one step after their window was dispatched."""
        finished = self._flush_backlog
        self._flush_backlog = []
        finished.extend(self._admit_pending())
        if self.spec_k:
            return self._spec_step(finished)
        if self.async_decode:
            return self._async_step(finished)
        # a chunking slot owns pages and a block table but has no prompt
        # resident yet: it sits out the decode batch until _chunk_step
        # lands its final chunk
        active_mask = np.array([s is not None and not s.chunking
                                for s in self._slots])
        if not active_mask.any():
            self._chunk_step(finished)
            self._update_occupancy()
            self._maybe_reset_gap()
            return finished
        tokens = np.array(
            [s.last_token if s is not None and not s.chunking else 0
             for s in self._slots], np.int32)
        t0 = time.perf_counter()
        if self._last_consume_done is not None:
            # the synchronous loop's dispatch gap: every microsecond of
            # host bookkeeping between reading step k and dispatching
            # k+1 is device idle time — the async pipeline's baseline
            gap = max(0.0, t0 - self._last_consume_done)
            self._dispatch_gap.observe(gap)
            self._gap_total += gap
        logits, next_tokens, cache = self._decode(
            self.variables, self._cache, tokens, active_mask,
            *self._lora_args())
        next_tokens = np.asarray(next_tokens)  # blocks until ready
        dt = time.perf_counter() - t0
        self._busy_total += dt
        self._last_consume_done = t0 + dt
        denom = self._gap_total + self._busy_total
        if denom > 0:
            self._host_overhead.set(self._gap_total / denom)
        self._cache = cache
        # slots still force-feeding a cached prompt's suffix consume the
        # step but produce nothing: their argmax is discarded below
        produced = sum(1 for s in self._slots
                       if s is not None and not s.pending)
        self._decode_time += dt
        self._decode_tokens += produced
        self._lat_hist.observe(dt)
        self._decode_steps_total.inc()
        self._tokens_total.inc(produced)
        logits_np = np.asarray(logits) if self.capture_logits else None
        for i, slot in enumerate(self._slots):
            if slot is None or slot.chunking:
                continue
            if slot.pending:
                # the cache covered positions < seq_len; the next prompt
                # token is ground truth, not the model's to choose
                slot.last_token = slot.pending.pop(0)
                continue
            tok = int(next_tokens[i])
            if slot.prefix_hit and not slot.tokens:
                # first generated token of a hit: TTFT closes here (the
                # cold path closes it at prefill)
                submit_ts = self._submit_ts.pop(slot.req.rid, None)
                if submit_ts is not None:
                    ttft = t0 + dt - submit_ts
                    self._ttft_hist.observe(ttft)
                    self._close_ttft(slot.req.rid, ttft)
                    root = self._req_spans.get(slot.req.rid)
                    if root is not None:
                        root.attrs["ttft_s"] = ttft
            self._tenant_lat.labels(
                self._req_tenant.get(slot.req.rid, "default")).observe(dt)
            if logits_np is not None:
                self.logit_log.setdefault(slot.req.rid, []).append(
                    logits_np[i].copy())
            slot.tokens.append(tok)
            slot.last_token = tok
            self._emit_token(slot.req.rid, tok)
            if self.tracer is not None:
                root = self._req_spans.get(slot.req.rid)
                if root is not None:
                    # reuse the step's own t0/dt readings: the span adds
                    # no clock calls to the decode hot path
                    self.tracer.record(
                        "serve.decode_step", t0, t0 + dt,
                        attrs={"token_index": len(slot.tokens)},
                        trace_id=root.trace_id, parent_id=root.span_id)
            done = self._finish_reason(slot, tok)
            if done:
                finished.append(self._release(i, done))
        self._chunk_step(finished)
        self._update_occupancy()
        self._maybe_reset_gap()
        return finished

    def _maybe_reset_gap(self) -> None:
        """Restart dispatch-gap accounting when the engine goes idle:
        the wait for the NEXT request stream is load, not host overhead
        — without this, inter-stream idle dwarfs the per-step gaps the
        metric exists to expose."""
        if not self.has_work():
            self._last_consume_done = None

    def _lora_args(self, rows=None) -> tuple:
        """Extra traced operands for the jitted steps when multi-LoRA is
        on: the stacked A/B adapter weights plus each slot's row in
        them. Empty when the engine serves no adapters — the executables
        then compile without the gather entirely."""
        if not self.max_loras:
            return ()
        a, b = self.adapters.stacks()
        if rows is None:
            rows = [s.adapter_row if s is not None else 0
                    for s in self._slots]
        return (a, b, np.asarray(rows, np.int32))

    # ------------------------------------------------------------------
    # async double-buffered decode pipeline (PR 19)
    # ------------------------------------------------------------------

    def _async_step(self, finished: list[Completion]) -> list[Completion]:
        """One async engine iteration: dispatch window k+1, then consume
        window k's tokens while the device computes. The pipeline holds
        at most two windows — dispatch deepens it to two, consume brings
        it back to one, so the device always has queued work while the
        host journals, streams, and admits. At the stream's tail
        (nothing left to dispatch) the remaining window drains."""
        if len(self._inflight) >= 2:
            # the oldest window is (nearly) landed and the device still
            # holds the newer one: consuming BEFORE dispatching keeps
            # the device busy AND lets the slots this consume frees
            # re-enter the very next window instead of idling a full
            # extra dispatch
            self._consume_window(finished)
            # refill every slot the consume freed before dispatching:
            # the window boundary is the async loop's admission point,
            # so admit_burst paces per WINDOW (N tokens), not per
            # micro-step — otherwise wide windows starve the batch
            for _ in range(self.config.max_batch):
                if not self._pending:
                    break
                before = len(self._pending)
                finished.extend(self._admit_pending())
                if len(self._pending) == before:
                    break
        dispatched = self._dispatch_window()
        if self._inflight and not dispatched:
            # stream tail: nothing left to dispatch, drain the pipeline
            self._consume_window(finished)
        self._chunk_step(finished)
        self._update_occupancy()
        self._maybe_reset_gap()
        return finished

    def _dispatch_window(self) -> bool:
        """Dispatch one decode window without waiting for it; returns
        False when no slot can decode. Per-slot input bookkeeping
        mirrors the synchronous pending rule exactly: with ``r`` suffix
        tokens still owed, ``min(r, N-1)`` ride this window as forced
        inputs after the slot's last token, outputs ``j < r`` are marked
        for discard (``keep``), and the slot only enters device-feedback
        mode once the suffix is exhausted."""
        N = self.substeps
        B = self.config.max_batch
        active = np.zeros((B,), bool)
        forced = np.zeros((B, N), np.int32)
        fcount = np.zeros((B,), np.int32)
        entries: list[tuple[int, str, int]] = []
        for i, s in enumerate(self._slots):
            if s is None or s.chunking:
                continue
            if (not s.pending
                    and len(s.tokens) + s.inflight_scheduled >= s.max_new):
                # the slot's length budget is fully covered by windows
                # already in flight: a fresh row would only produce
                # output the consume side trims — leave it inactive
                continue
            active[i] = True
            if s.feedback:
                # next input is the previous window's device-resident
                # carry; the host never saw it and never needs to
                entries.append((i, s.req.rid, 0))
                s.inflight_scheduled += N
                continue
            r = len(s.pending)
            c = min(r, N - 1)
            forced[i, 0] = s.last_token
            if c:
                forced[i, 1:1 + c] = s.pending[:c]
            fcount[i] = c + 1
            entries.append((i, s.req.rid, r))
            s.inflight_scheduled += max(0, N - r)
            del s.pending[:c]
            if s.pending:
                # suffix longer than the window: the next window is
                # forced too, starting from the next owed token
                s.last_token = s.pending.pop(0)
            else:
                s.feedback = True
        if not entries:
            return False
        seed = self._carry_tok
        if seed is None:
            # committed like the carry outputs it stands in for — a
            # host-resident seed would flip the jit signature between
            # the first dispatch and every later one (two executables,
            # busting the compile budget)
            seed = jax.device_put(np.zeros((B,), np.int32))
        t0 = time.perf_counter()
        if not self._inflight and self._last_consume_done is not None:
            # with a window still in flight the device cannot be starved
            # and the gap is zero by construction; an empty pipeline
            # means the device waited since the last consume finished
            gap = max(0.0, t0 - self._last_consume_done)
        else:
            gap = 0.0
        self._dispatch_gap.observe(gap)
        self._gap_total += gap
        toks, logits, carry, cache = self._decode(
            self.variables, self._cache, seed, forced, fcount, active,
            *self._lora_args())
        self._cache = cache
        self._carry_tok = carry
        self._decode_steps_total.inc()
        self._inflight.append(
            _Window(toks=toks, logits=logits, entries=entries, t0=t0))
        return True

    def _consume_window(self, finished: list[Completion]) -> None:
        """Materialize the OLDEST in-flight window and run the host side
        for its tokens: journal fan-out (``on_token``), TTFT/latency
        records, logit capture, EOS/length checks. Rows whose slot was
        released or re-seated after dispatch are stale and skipped — a
        lag-1 pipeline never journals a token the device hasn't
        committed, and never mis-attributes one to a new occupant. A
        stream finishing mid-window has its over-generated tail trimmed
        here; the window's stale writes past EOS land only in the
        slot's own (refcount-released) pages."""
        win = self._inflight.popleft()
        t_wait = time.perf_counter()
        toks = np.asarray(win.toks)  # blocks until the window lands
        t_ready = time.perf_counter()
        self._busy_total += t_ready - t_wait
        start = (self._last_consume_done
                 if self._last_consume_done is not None else win.t0)
        wall = max(t_ready - start, 1e-9)
        N = self.substeps
        logits_np = np.asarray(win.logits) if self.capture_logits else None
        produced = 0
        for i, rid, keep in win.entries:
            slot = self._slots[i]
            if slot is None or slot.req.rid != rid:
                continue  # released/preempted after dispatch: stale row
            slot.inflight_scheduled = max(
                0, slot.inflight_scheduled - max(0, N - keep))
            lat_done = False
            done = None
            for j in range(keep, N):
                tok = int(toks[i, j])
                if slot.prefix_hit and not slot.tokens:
                    submit_ts = self._submit_ts.pop(rid, None)
                    if submit_ts is not None:
                        ttft = t_ready - submit_ts
                        self._ttft_hist.observe(ttft)
                        self._close_ttft(rid, ttft)
                        root = self._req_spans.get(rid)
                        if root is not None:
                            root.attrs["ttft_s"] = ttft
                if not lat_done:
                    self._tenant_lat.labels(
                        self._req_tenant.get(rid, "default")).observe(
                            wall / N)
                    lat_done = True
                if logits_np is not None:
                    self.logit_log.setdefault(rid, []).append(
                        logits_np[i, j].copy())
                slot.tokens.append(tok)
                slot.last_token = tok
                produced += 1
                self._emit_token(rid, tok)
                done = self._finish_reason(slot, tok)
                if done:
                    break
            if self.tracer is not None:
                root = self._req_spans.get(rid)
                if root is not None:
                    self.tracer.record(
                        "serve.decode_step", win.t0, t_ready,
                        attrs={"token_index": len(slot.tokens),
                               "substeps": N},
                        trace_id=root.trace_id, parent_id=root.span_id)
            if done:
                finished.append(self._release(i, done))
        # wall is consume-to-consume: the engine's true per-window
        # cadence, host bookkeeping included — so async tok/s is honest
        # about everything, unlike the sync path's device-only dt
        self._decode_time += wall
        self._decode_tokens += produced
        self._lat_hist.observe(wall / N)
        self._tokens_total.inc(produced)
        self._last_consume_done = time.perf_counter()
        denom = self._gap_total + self._busy_total
        if denom > 0:
            self._host_overhead.set(self._gap_total / denom)

    def _flush_pipeline(self) -> None:
        """Drain every in-flight window to a committed host-coherent
        boundary — required before anything that mutates state a window
        in flight still depends on (weight swap, donation audit).
        Completions surfacing here are returned by the NEXT step()
        call; slots fall back out of device-feedback mode because the
        carry is dropped with the pipeline."""
        while self._inflight:
            self._consume_window(self._flush_backlog)
        self._carry_tok = None
        for s in self._slots:
            if s is not None:
                s.feedback = False
                s.inflight_scheduled = 0

    def _chunk_step(self, finished: list[Completion]) -> None:
        """Run at most one chunk of the in-flight chunked prefill —
        called once per engine step, after the decode batch, so the long
        prompt and the running streams interleave on the device."""
        job = self._chunk_job
        if job is None:
            return
        slot_idx = job.slot_idx
        slot = self._slots[slot_idx]
        prompt = slot.req.prompt
        start = job.done
        count = min(self.chunk_prefill, len(prompt) - start)
        toks = np.zeros((self.chunk_prefill,), np.int32)
        toks[:count] = prompt[start:start + count]
        t0 = time.perf_counter()
        last, cache = self._chunk(
            self.variables, self._cache, toks, np.int32(slot_idx),
            np.int32(start), np.int32(count), *self._lora_args())
        self._cache = cache
        job.done += count
        root = self._req_spans.get(slot.req.rid)
        if self.tracer is not None and root is not None:
            self.tracer.record(
                "serve.chunk_prefill", t0, time.perf_counter(),
                attrs={"start": start, "count": count},
                trace_id=root.trace_id, parent_id=root.span_id)
        if job.done < len(prompt):
            return
        # final chunk: its last reading is the logits a whole bucketed
        # prefill would have produced for the prompt's last position —
        # the first generated token argmaxes from them, TTFT closes here
        self._chunk_job = None
        slot.chunking = False
        self._prefill_count += 1
        last_np = np.asarray(last)
        tok = int(np.argmax(last_np))
        if self.capture_logits:
            self.logit_log.setdefault(slot.req.rid, []).append(
                last_np.copy())
        slot.tokens.append(tok)
        slot.last_token = tok
        self._emit_token(slot.req.rid, tok)
        submit_ts = self._submit_ts.pop(slot.req.rid, None)
        if submit_ts is not None:
            now = time.perf_counter()
            self._ttft_hist.observe(now - submit_ts)
            self._close_ttft(slot.req.rid, now - submit_ts)
            if root is not None:
                root.attrs["ttft_s"] = now - submit_ts
        done = self._finish_reason(slot, tok)
        if done:
            finished.append(self._release(slot_idx, done))

    def _spec_step(self, finished: list[Completion]) -> list[Completion]:
        """One speculative engine iteration. Window layout per slot:
        ``X = [last_token, w_1 .. w_k]`` where the first
        ``f = min(len(pending), k)`` window tokens are forced ground
        truth (a prefix-hit's prompt suffix) and the rest are draft
        proposals. The draft runs ``k + 1`` micro-steps of its one
        fixed-shape decode executable (micro-step j writes ``X[j]``'s
        draft KV and proposes ``X[j + 1]``; the last proposal is
        discarded), then ONE verify executable scores the whole window.

        Greedy-exact acceptance: proposal ``X[f+1+i]`` is accepted iff it
        equals the target's argmax after consuming ``X[0..f+i]``, and the
        first miss is replaced by that argmax (the bonus token) — so
        every emitted token is the target's own greedy choice, and the
        worst case (0 accepted) still emits 1 token like plain decode.
        KV written past the accepted length is stale-by-construction:
        ``seq_lens`` is rolled back to the accepted length, masking it
        until later steps overwrite it."""
        k = self.spec_k
        active_mask = np.array([s is not None for s in self._slots])
        if not active_mask.any():
            return finished
        base = np.asarray(self._cache["seq_lens"]).copy()
        X = np.zeros((self.config.max_batch, k + 1), np.int32)
        X[:, 0] = [s.last_token if s else 0 for s in self._slots]
        forced = np.zeros((self.config.max_batch,), np.int64)
        for i, s in enumerate(self._slots):
            if s is not None and s.pending:
                f = min(len(s.pending), k)
                X[i, 1:1 + f] = s.pending[:f]
                forced[i] = f
        t0 = time.perf_counter()
        draft_cache = self._draft_cache
        for j in range(k + 1):
            _, nxt, draft_cache = self._draft_decode(
                self.draft_variables, draft_cache, X[:, j].copy(),
                active_mask)
            if j < k:
                proposals = np.asarray(nxt)
                fill = forced <= j  # rows whose slot j+1 is not forced
                X[fill, j + 1] = proposals[fill]
        logits, cache = self._verify(
            self.variables, self._cache, X, active_mask)
        logits_np = np.asarray(logits)  # [max_batch, k+1, vocab]; blocks
        dt = time.perf_counter() - t0
        targets = np.argmax(logits_np, axis=-1).astype(np.int32)
        produced = 0
        new_lens = base.copy()
        for i, slot in enumerate(list(self._slots)):
            if slot is None:
                continue
            f = int(forced[i])
            del slot.pending[:f]
            a = 0
            while f + 1 + a <= k and X[i, f + 1 + a] == targets[i, f + a]:
                a += 1
            self._spec_proposed.inc(k - f)
            self._spec_accepted.inc(a)
            if slot.pending:
                # suffix longer than the window: every input was ground
                # truth (f == k), nothing is emitted this step
                slot.last_token = int(X[i, k])
                new_lens[i] = base[i] + k + 1
                continue
            emitted = [int(targets[i, f + j]) for j in range(a + 1)]
            new_lens[i] = base[i] + f + a + 1
            slot.last_token = emitted[-1]
            if slot.prefix_hit and not slot.tokens:
                submit_ts = self._submit_ts.pop(slot.req.rid, None)
                if submit_ts is not None:
                    ttft = t0 + dt - submit_ts
                    self._ttft_hist.observe(ttft)
                    self._close_ttft(slot.req.rid, ttft)
                    root = self._req_spans.get(slot.req.rid)
                    if root is not None:
                        root.attrs["ttft_s"] = ttft
            self._tenant_lat.labels(
                self._req_tenant.get(slot.req.rid, "default")).observe(dt)
            done = None
            for m, tok in enumerate(emitted):
                if self.capture_logits:
                    self.logit_log.setdefault(slot.req.rid, []).append(
                        logits_np[i, f + m].copy())
                slot.tokens.append(tok)
                produced += 1
                self._emit_token(slot.req.rid, tok)
                done = self._finish_reason(slot, tok)
                if done:
                    slot.last_token = tok
                    break
            if self.tracer is not None:
                root = self._req_spans.get(slot.req.rid)
                if root is not None:
                    self.tracer.record(
                        "serve.spec_step", t0, t0 + dt,
                        attrs={"proposed": k - f, "accepted": a,
                               "emitted": len(slot.tokens)},
                        trace_id=root.trace_id, parent_id=root.span_id)
            if done:
                finished.append(self._release(i, done))
        cache["seq_lens"] = jnp.asarray(new_lens, jnp.int32)
        draft_cache["seq_lens"] = jnp.asarray(new_lens, jnp.int32)
        self._cache = cache
        self._draft_cache = draft_cache
        self._decode_time += dt
        self._decode_tokens += produced
        self._lat_hist.observe(dt)
        self._decode_steps_total.inc()
        self._tokens_total.inc(produced)
        prop = self._spec_proposed.value
        if prop:
            self._spec_acceptance.set(self._spec_accepted.value / prop)
        self._update_occupancy()
        return finished

    def run(self, requests) -> list[Completion]:
        for req in requests:
            self.submit(req)
        completions: list[Completion] = []
        stall = 0
        while self.has_work():
            got = self.step()
            completions.extend(got)
            if not got and not any(s is not None for s in self._slots):
                stall += 1
                if stall > self.config.max_batch + 1:
                    raise RuntimeError(
                        "engine stalled: pending requests cannot be "
                        "admitted (page pool too small?)")
            else:
                stall = 0
        return completions

    def register_adapter(self, name: str, a, b) -> int:
        """Install a LoRA adapter (``a [d_model, r]``, ``b [r, vocab]``,
        ``r <= lora_rank``) into the paged store; returns its row. The
        stacks are traced operands of every executable, so this never
        recompiles — the next step simply gathers the new row."""
        if self.adapters is None:
            raise ValueError("engine serves no adapters "
                             "(M2KT_SCHED_MAX_LORAS=0)")
        return self.adapters.register(name, a, b)

    def install_weights(self, variables, version: int | None = None) -> int:
        """Live weight swap: replace the parameters *between* decode
        steps without dropping in-flight requests. Every jitted step
        (prefill/decode/verify and the draft pair) takes ``variables``
        as a traced argument — the closures capture only the model — so
        a same-shape tree swaps in with ZERO recompiles; the next step
        simply decodes with the new weights. A tree whose structure,
        shape, or dtype differs from the resident one raises
        ``ValueError`` naming the offending shard (half-installing a
        mismatched tree would corrupt every in-flight stream and force
        a recompile storm).

        Not safe concurrently with :meth:`step` — the fleet layer
        serializes the swap under the replica's step lock. Returns the
        installed version (explicit ``version`` for fleet-wide
        agreement, else the resident version + 1)."""
        from move2kube_tpu.serving.fleet import weights as weightslib

        if self.async_decode:
            # windows in flight were dispatched under the OLD weights;
            # drain them to a committed boundary so no stream mixes
            # checkpoints mid-window (their completions surface from
            # the next step() call)
            self._flush_pipeline()
        if self.quant.quantize_weights:
            if self._audit_rate:
                # the drift auditor must reference the NEW checkpoint,
                # or every post-swap audit would report false drift
                self._audit_fp_variables = variables
            # same policy as construction: the executables' parameter
            # buffers are int8 (+ scales), so that is what swaps in
            variables = quantlib.quantize_variables(variables)
        old = weightslib.flatten_variables(self.variables)
        new = weightslib.flatten_variables(variables)
        if set(old) != set(new):
            missing = sorted(set(old) - set(new))[:3]
            extra = sorted(set(new) - set(old))[:3]
            raise ValueError(
                f"install_weights: parameter tree mismatch — "
                f"missing {missing}, unexpected {extra}")
        for path in sorted(old):
            if (old[path].shape != new[path].shape
                    or old[path].dtype != new[path].dtype):
                raise ValueError(
                    f"install_weights: shard {path!r} is "
                    f"{new[path].dtype}{list(new[path].shape)}; the "
                    f"resident executables want "
                    f"{old[path].dtype}{list(old[path].shape)}")
        self.variables = jax.tree_util.tree_map(jnp.asarray, variables)
        if self.spec_k:
            # the draft shares the target's embeddings/head by pruning:
            # re-derive so the proposer speaks the new checkpoint too
            self.draft_variables = quantlib.draft_variables_from(
                self.variables, self._draft_cfg)
        if self._prefix is not None:
            # cached prefix KV was computed under the OLD weights; a
            # post-swap admission hitting it would decode against a KV
            # history the new checkpoint never produced. Drop the cache
            # (pages still borrowed by in-flight slots survive until
            # those streams release them — that is the COW contract)
            self._prefix.clear()
            self._update_occupancy()
        self.weights_version = (int(version) if version is not None
                                else self.weights_version + 1)
        self._weights_version_gauge.set(self.weights_version)
        return self.weights_version

    def _finish_reason(self, slot: _Slot, tok: int) -> str | None:
        if self.config.eos_id is not None and tok == self.config.eos_id:
            return "eos"
        if len(slot.tokens) >= slot.max_new:
            return "length"
        return None

    def _release(self, slot_idx: int, reason: str) -> Completion:
        slot = self._slots[slot_idx]
        self._allocator.free(slot.pages)
        self._slots[slot_idx] = None
        self._completed.labels(reason=reason).inc()
        tenant = self._req_tenant.pop(slot.req.rid, None) or "default"
        if reason != "preempted":
            # a preempted stream resumes and releases again — recording
            # it here would double-count the request's shape
            self._tenant_prompt_tokens.labels(tenant).observe(
                float(len(slot.req.prompt)))
            self._tenant_decode_tokens.labels(tenant).observe(
                float(len(slot.tokens)))
        self._deadline_abs.pop(slot.req.rid, None)
        self._submit_ts.pop(slot.req.rid, None)
        if self.adapters is not None:
            self.adapters.release(self._req_adapter.pop(slot.req.rid, 0))
        if self.tracer is not None:
            root = self._req_spans.pop(slot.req.rid, None)
            if root is not None:
                self.tracer.end(root, attrs={
                    "finish_reason": reason, "tokens": len(slot.tokens),
                    "weights_version": self.weights_version})
        self._update_occupancy()
        return Completion(rid=slot.req.rid, prompt_len=len(slot.req.prompt),
                          tokens=list(slot.tokens), finish_reason=reason,
                          weights_version=self.weights_version)

    def _bucket_for(self, plen: int) -> int:
        for b in self.buckets:
            if plen <= b:
                return b
        raise ValueError(f"no bucket fits prompt length {plen}")

    def _admit_pending(self) -> list[Completion]:
        """Admit queued requests into free slots, up to ``admit_burst``
        per step (<= 0 means every free slot — an admission burst after
        a bulk release no longer drains one slot per decode step)."""
        burst = self.config.admit_burst
        limit = self.config.max_batch if burst <= 0 else burst
        finished: list[Completion] = []
        for _ in range(limit):
            admitted, done = self._admit_one()
            finished.extend(done)
            if not admitted:
                break
        return finished

    def _admit_one(self) -> tuple[bool, list[Completion]]:
        if not self._pending:
            return False, []
        req = self._pending[0]
        dl = self._deadline_abs.get(req.rid)
        if dl is not None and time.perf_counter() > dl:
            # expired while queued: sheds even with no free slot, so a
            # saturated engine still rejects dead-on-arrival work fast
            self._pending.popleft()
            return True, [self._shed(req, "queued_expired")]
        plen = len(req.prompt)
        max_new = req.max_new_tokens or self.config.max_new_tokens
        chunked = (self._chunk is not None and plen > self.chunk_prefill)
        if chunked and self._chunk_job is not None:
            return False, []  # one chunk job at a time; wait for it
        pre: list[Completion] = []
        free = [i for i, s in enumerate(self._slots) if s is None]
        if not free:
            # under slot pressure a higher-priority tenant's request may
            # evict the lowest-priority running stream (paused work, not
            # failure — the router journal resumes it token-exactly)
            victim = self._preempt_victim(req)
            if victim is None:
                return False, []
            pre.append(self._preempt(victim, "slots"))
            free = [victim]
        hit = self._try_prefix_hit(req, plen)
        if hit is not None:
            ok, done = self._admit_hit(req, free[0], hit, plen, max_new)
        elif chunked:
            ok, done = self._admit_chunked(req, free[0], plen, max_new)
        else:
            ok, done = self._admit_cold(req, free[0], plen, max_new)
        return ok or bool(pre), pre + done

    def _req_priority(self, req: Request) -> int:
        return self.sched.priority(
            self._req_tenant.get(req.rid, req.tenant))

    def _next_seq(self) -> int:
        self._admit_seq += 1
        return self._admit_seq

    def _preempt_victim(self, req: Request) -> int | None:
        """Slot to evict for ``req``: the lowest-priority active slot,
        most recently admitted among ties — and only one strictly below
        the incoming request's class, so a flat (or empty) tenant spec
        keeps the historical never-preempt behavior. Chunking slots (a
        chunk job in flight, nothing in the journal yet) and slots still
        force-feeding a prefix suffix are not candidates."""
        if not self._preempt_enabled:
            return None
        prio = self._req_priority(req)
        best, best_key = None, None
        for i, s in enumerate(self._slots):
            if s is None or s.chunking or s.pending:
                continue
            if s.priority >= prio:
                continue
            key = (s.priority, -s.seq)
            if best_key is None or key < best_key:
                best, best_key = i, key
        return best

    def _preempt(self, slot_idx: int, reason: str) -> Completion:
        """Evict a slot as *paused work*: its pages free immediately and
        its Completion carries ``finish_reason="preempted"`` — the
        tokens so far already rode ``on_token`` into the router journal,
        so the resume (journal force-fed as ground truth) is
        token-exact. Preemption loses zero tokens."""
        self._preempt_count += 1
        self._sched_preempted.labels(reason=reason).inc()
        return self._release(slot_idx, "preempted")

    def _alloc_preempting(self, req: Request,
                          n: int) -> tuple[list[int] | None,
                                           list[Completion]]:
        """``_alloc_with_evict`` escalated to preemption: when shedding
        cold prefix-cache entries still leaves the pool short, release a
        strictly-lower-priority slot's pages and hand its stream back to
        the router as paused work."""
        pre: list[Completion] = []
        pages = self._alloc_with_evict(n)
        while pages is None:
            victim = self._preempt_victim(req)
            if victim is None:
                break
            if not self._allocator.reclaimable(self._slots[victim].pages):
                # every page is shared (prefix cache / CoW siblings):
                # evicting this stream frees nothing — keep it running
                break
            pre.append(self._preempt(victim, "pages"))
            pages = self._alloc_with_evict(n)
        return pages, pre

    def _admit_chunked(self, req: Request, slot_idx: int, plen: int,
                       max_new: int) -> tuple[bool, list[Completion]]:
        """Seat a long prompt for chunked prefill: allocate its full
        page run and block table up front (``seq_len`` starts at 0),
        mark the slot ``chunking`` so decode skips it, and let
        :meth:`_chunk_step` land the prompt one chunk per engine step."""
        n_pages = pages_for(plen + max_new + self._overrun_slack,
                            self.cache_cfg.block_size)
        pages, pre = self._alloc_preempting(req, n_pages)
        if pages is None:
            return False, pre
        self._pending.popleft()
        bt_row = np.full((self.cache_cfg.max_pages_per_seq,), NULL_PAGE,
                         np.int32)
        bt_row[:len(pages)] = pages
        self._cache = self._install(self._cache, np.int32(slot_idx),
                                    bt_row, np.int32(0))
        slot = _Slot(req=req, pages=pages, tokens=[], last_token=0,
                     max_new=max_new, chunking=True,
                     priority=self._req_priority(req),
                     adapter_row=self._req_adapter.get(req.rid, 0),
                     seq=self._next_seq())
        self._slots[slot_idx] = slot
        self._chunk_job = _ChunkJob(slot_idx=slot_idx)
        self._chunk_count += 1
        self._sched_chunked.labels(reason="long_prompt").inc()
        self._admitted.inc()
        self._tenant_admitted.labels(
            self._req_tenant.get(req.rid, "default")).inc()
        if self._prefix is not None:
            # chunked prompts are not donated to the prefix cache (their
            # pages fill across many steps); they count as misses
            self._prefix_misses.inc()
        self._update_occupancy()
        return True, pre

    def _alloc_with_evict(self, n: int) -> list[int] | None:
        pages = self._allocator.alloc(n)
        if pages is None and self._prefix is not None and len(self._prefix):
            # admission beats retention: shed cold prefix-cache entries
            self._prefix.evict(n - self._allocator.available)
            pages = self._allocator.alloc(n)
        return pages

    def _try_prefix_hit(self, req: Request, plen: int) -> PrefixHit | None:
        """A cached-prefix hit worth taking, or None (refs dropped).
        Coverage is capped at ``plen - 1`` so at least one prompt token
        always runs through decode and yields the first token's logits;
        hits whose un-cached suffix would take longer to decode-feed
        than a cold prefill are declined."""
        if self._prefix is None:
            return None
        hit = self._prefix.lookup(req.prompt)
        if hit is None:
            return None
        bs = self.cache_cfg.block_size
        c = min(hit.covered, plen - 1)
        max_suffix = self.config.prefix_max_suffix or 2 * bs
        if c < bs or plen - c > max_suffix:
            self._allocator.free(hit.pages)
            return None
        return PrefixHit(pages=hit.pages, covered=c)

    def _admit_hit(self, req: Request, slot_idx: int, hit: PrefixHit,
                   plen: int, max_new: int) -> tuple[bool, list[Completion]]:
        bs = self.cache_cfg.block_size
        c = hit.covered
        w = c // bs  # page index position c (the first write) lands in
        n_total = pages_for(plen + max_new + self._overrun_slack, bs)
        priv = self._alloc_with_evict(n_total - w)
        if priv is None:
            self._allocator.free(hit.pages)
            return False, []
        self._pending.popleft()
        bt_row = np.full((self.cache_cfg.max_pages_per_seq,), NULL_PAGE,
                         np.int32)
        bt_row[:w] = hit.pages[:w]
        bt_row[w:n_total] = priv
        t0 = time.perf_counter()
        cache = self._install(self._cache, np.int32(slot_idx), bt_row,
                              np.int32(c))
        cow = w < len(hit.pages)
        if cow:
            # position c lands inside a shared page (partial boundary,
            # or a fully-covered prompt re-feeding its final token):
            # write into a private copy, never the shared original
            cache = self._copy(cache, np.int32(hit.pages[w]),
                               np.int32(int(bt_row[w])))
            self._cow_copies.inc()
        self._cache = cache
        if self._draft_cache is not None:
            # pages map 1:1, so the shared pages' DRAFT K/V (written when
            # the prefix first prefilled cold) is hit for free — mirror
            # the table surgery, including the COW copy
            dc = self._install(self._draft_cache, np.int32(slot_idx),
                               bt_row, np.int32(c))
            if cow:
                dc = self._copy(dc, np.int32(hit.pages[w]),
                                np.int32(int(bt_row[w])))
            self._draft_cache = dc
        if hit.pages[w:]:
            self._allocator.free(hit.pages[w:])  # refs not kept past copy
        slot = _Slot(req=req, pages=list(hit.pages[:w]) + priv, tokens=[],
                     last_token=int(req.prompt[c]), max_new=max_new,
                     pending=[int(t) for t in req.prompt[c + 1:]],
                     prefix_hit=True, priority=self._req_priority(req),
                     adapter_row=self._req_adapter.get(req.rid, 0),
                     seq=self._next_seq())
        self._slots[slot_idx] = slot
        self._admitted.inc()
        self._tenant_admitted.labels(
            self._req_tenant.get(req.rid, "default")).inc()
        self._prefix_hits.inc()
        self._prefix_hit_tokens.inc(c)
        submit_ts = self._submit_ts.get(req.rid)
        root = self._req_spans.get(req.rid)
        if self.tracer is not None and root is not None \
                and submit_ts is not None:
            now = time.perf_counter()
            self.tracer.record(
                "serve.queue_wait", submit_ts, t0,
                trace_id=root.trace_id, parent_id=root.span_id)
            self.tracer.record(
                "serve.prefix_install", t0, now,
                attrs={"covered": c, "suffix": plen - c, "cow": int(cow)},
                trace_id=root.trace_id, parent_id=root.span_id)
        self._update_occupancy()
        return True, []

    def _maybe_audit_quant(self, rid: str, ids: np.ndarray, plen: int,
                           logits0) -> None:
        """Quant-drift audit of a cold prefill: replay the padded prompt
        through the retained fp reference weights and compare the prompt
        rows' logits (serving/quant.py's ``logit_gate`` — the same
        metric the build-time tiers gate on). Sampling is a
        deterministic rate accumulator, not an RNG: an audit rate of
        0.1 audits exactly every 10th cold admission, so tests and
        replays see identical audit schedules. Best-effort — the audit
        must never fail a request it rides on."""
        self._audit_accum += self._audit_rate
        if self._audit_accum < 1.0:
            return
        self._audit_accum -= 1.0
        try:
            t0 = time.perf_counter()
            if self._audit_apply is None:
                model = self.model
                self._audit_apply = jax.jit(
                    lambda v, x: model.apply(v, x))
            ref = self._audit_apply(self._audit_fp_variables,
                                    jnp.asarray(ids))
            gate = quantlib.logit_gate(np.asarray(ref[0, :plen]),
                                       np.asarray(logits0[:plen]))
            drift = float(gate["max_rel_err"])
        except Exception:  # noqa: BLE001 - telemetry never fails serving
            return
        self._drift_last = drift
        self._drift_max = max(self._drift_max, drift)
        self._quant_drift.set(drift)
        self._quant_audits.inc()
        root = self._req_spans.get(rid)
        if self.tracer is not None and root is not None:
            self.tracer.record(
                "serve.quant_audit", t0, time.perf_counter(),
                attrs={"max_rel_err": drift,
                       "top1_agreement": float(gate["top1_agreement"])},
                trace_id=root.trace_id, parent_id=root.span_id)

    def _admit_cold(self, req: Request, slot_idx: int, plen: int,
                    max_new: int) -> tuple[bool, list[Completion]]:
        bs = self.cache_cfg.block_size
        n_pages = pages_for(plen + max_new + self._overrun_slack, bs)
        # a page-unaligned prompt that will be donated to the prefix
        # cache needs one spare page: the boundary page becomes shared
        # at insert, and this slot's own generation copy-on-writes it
        want_partial = (self._prefix is not None and plen >= bs
                        and plen % bs != 0)
        spare: list[int] | None = None
        pages = None
        if want_partial:
            got = self._alloc_with_evict(n_pages + 1)
            if got is not None:
                pages, spare = got[:n_pages], got[n_pages:]
        pre: list[Completion] = []
        if pages is None:
            pages, pre = self._alloc_preempting(req, n_pages)
        if pages is None:
            return False, pre  # wait for running sequences to free pages
        self._pending.popleft()
        adapter_row = self._req_adapter.get(req.rid, 0)
        bucket = self._bucket_for(plen)
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :plen] = req.prompt
        bt_row = np.full((self.cache_cfg.max_pages_per_seq,), NULL_PAGE,
                         np.int32)
        bt_row[:len(pages)] = pages
        t_prefill = time.perf_counter()
        first, logits0, cache = self._prefill(
            self.variables, self._cache, ids, bt_row,
            np.int32(slot_idx), np.int32(plen),
            *self._lora_args(rows=[adapter_row]))
        self._cache = cache
        if self._draft_cache is not None:
            # same ids, same pages: the draft's K/V for this prompt lands
            # in the SAME page indices the target owns
            _, _, dc = self._draft_prefill(
                self.draft_variables, self._draft_cache, ids, bt_row,
                np.int32(slot_idx), np.int32(plen))
            self._draft_cache = dc
        self._prefill_count += 1
        self._admitted.inc()
        self._tenant_admitted.labels(
            self._req_tenant.get(req.rid, "default")).inc()
        if self._prefix is not None:
            self._prefix_misses.inc()
        submit_ts = self._submit_ts.pop(req.rid, None)
        if submit_ts is not None:
            # ONE clock reading closes both the histogram sample and the
            # trace: queue_wait + prefill spans sum to exactly the TTFT
            # the histogram observed (the trace decomposes the metric,
            # it doesn't approximate it)
            now = time.perf_counter()
            self._ttft_hist.observe(now - submit_ts)
            self._close_ttft(req.rid, now - submit_ts)
            root = self._req_spans.get(req.rid)
            if self.tracer is not None and root is not None:
                self.tracer.record(
                    "serve.queue_wait", submit_ts, t_prefill,
                    trace_id=root.trace_id, parent_id=root.span_id)
                self.tracer.record(
                    "serve.prefill", t_prefill, now,
                    attrs={"bucket": bucket, "prompt_len": plen},
                    trace_id=root.trace_id, parent_id=root.span_id)
                root.attrs["ttft_s"] = now - submit_ts
        tok = int(first)
        if self.capture_logits:
            self.logit_log.setdefault(req.rid, []).append(
                np.asarray(logits0[plen - 1]).copy())
        if self._audit_rate and adapter_row == 0:
            # adapter-carrying prefills skip the audit: the fp reference
            # path runs the base model, so the LoRA delta would read as
            # false drift
            self._maybe_audit_quant(req.rid, ids, plen, logits0)
        slot = _Slot(req=req, pages=pages, tokens=[tok], last_token=tok,
                     max_new=max_new, priority=self._req_priority(req),
                     adapter_row=adapter_row, seq=self._next_seq())
        self._slots[slot_idx] = slot
        self._emit_token(req.rid, tok)
        self._insert_prefix(slot_idx, slot, bt_row, plen, spare)
        done = self._finish_reason(slot, tok)
        if done:
            return True, pre + [self._release(slot_idx, done)]
        return True, pre

    def _insert_prefix(self, slot_idx: int, slot: _Slot, bt_row: np.ndarray,
                       plen: int, spare: list[int] | None) -> None:
        """Donate a cold prompt's pages to the prefix cache. Prompts
        shorter than one page can never clear the hit gate, so they are
        not worth indexing."""
        bs = self.cache_cfg.block_size
        if self._prefix is None or plen < bs:
            if spare:
                self._allocator.free(spare)
            return
        m = pages_for(plen, bs)
        f = plen % bs
        if f and spare is None:
            # no spare to copy-on-write the boundary into: share the
            # full pages only
            self._prefix.insert(slot.req.prompt[:plen - f],
                                slot.pages[:m - 1])
            return
        self._prefix.insert(slot.req.prompt[:plen], slot.pages[:m])
        if not f:
            return
        boundary = slot.pages[m - 1]
        if not self._allocator.is_shared(boundary):
            # an equivalent boundary page was already cached; ours
            # stayed private and the spare goes back
            self._allocator.free(spare)
            return
        # the cache adopted the boundary page, and this slot writes
        # position plen into it next step -> move the slot to a copy
        new = int(spare[0])
        bt_row = bt_row.copy()
        bt_row[m - 1] = new
        cache = self._install(self._cache, np.int32(slot_idx), bt_row,
                              np.int32(plen))
        self._cache = self._copy(cache, np.int32(boundary), np.int32(new))
        self._cow_copies.inc()
        if self._draft_cache is not None:
            dc = self._install(self._draft_cache, np.int32(slot_idx),
                               bt_row, np.int32(plen))
            self._draft_cache = self._copy(dc, np.int32(boundary),
                                           np.int32(new))
        slot.pages[m - 1] = new
        self._allocator.free([boundary])  # slot's ref; the cache keeps its

    def install_prefilled(self, req: Request, kvs, first_token: int,
                          prompt_len: int) -> tuple[bool, list[Completion]]:
        """Admit a request whose prefill ran on another replica
        (serving/fleet/disagg.py): allocate pages, scatter the
        handed-off per-layer K/V into them, and seat the slot with the
        prefill's first token — no local prefill executable runs.
        ``kvs`` is the prefill's ``return_kv`` output, per layer
        ``(k, v)`` shaped ``[1, bucket, kv_heads, head_dim]`` (host or
        device arrays). Returns ``(installed, completions)``;
        not-installed means no free slot or pages right now — retry
        after a :meth:`step`."""
        plen = int(prompt_len)
        max_new = req.max_new_tokens or self.config.max_new_tokens
        bucket = int(kvs[0][0].shape[1])
        tenant = slolib.clean_tenant(req.tenant)
        if self._draining:
            raise EngineDraining(f"{req.rid}: engine is draining")
        reason = self._deadline_verdict(req.deadline_s, max_new)
        if reason is not None:
            self._deadline_shed.labels(reason=reason).inc()
            self._rejected.inc()
            self._tenant_rejected.labels(tenant).inc()
            self.slo.record(tenant, ok=False)
            raise DeadlineExceeded(
                f"{req.rid}: handoff deadline {req.deadline_s:.3f}s "
                f"{reason} for {max_new} new tokens")
        if (plen < 1
                or plen + max_new + self._overrun_slack > self.cache_cfg.max_seq):
            self._rejected.inc()
            self._tenant_rejected.labels(tenant).inc()
            self.slo.record(tenant, ok=False)
            raise ValueError(f"{req.rid}: handoff of {plen} prompt + "
                             f"{max_new} new tokens does not fit max_seq "
                             f"{self.cache_cfg.max_seq}")
        if req.adapter:
            # the handoff carries only base-model K/V and a first token
            # the prefill replica argmaxed WITHOUT the adapter delta —
            # admitting it would silently serve the wrong head
            self._rejected.inc()
            self._tenant_rejected.labels(tenant).inc()
            self.slo.record(tenant, ok=False)
            raise ValueError(f"{req.rid}: disagg handoff does not carry "
                             f"adapter state (adapter {req.adapter!r})")
        if bucket > self.cache_cfg.max_seq:
            self._rejected.inc()
            self._tenant_rejected.labels(tenant).inc()
            self.slo.record(tenant, ok=False)
            raise ValueError(f"{req.rid}: handoff bucket {bucket} exceeds "
                             f"max_seq {self.cache_cfg.max_seq}")
        free = [i for i, s in enumerate(self._slots) if s is None]
        if not free:
            return False, []
        pages = self._alloc_with_evict(pages_for(
            plen + max_new + self._overrun_slack, self.cache_cfg.block_size))
        if pages is None:
            return False, []
        slot_idx = free[0]
        t_install = time.perf_counter()
        bt_row = np.full((self.cache_cfg.max_pages_per_seq,), NULL_PAGE,
                         np.int32)
        bt_row[:len(pages)] = pages
        kvs = [(jnp.asarray(k), jnp.asarray(v)) for k, v in kvs]
        self._cache = self._install_kv(self._cache, kvs, bt_row,
                                       np.int32(slot_idx), np.int32(plen))
        if self._draft_cache is not None:
            # the handoff carries only the TARGET model's K/V; the draft's
            # comes from a local draft prefill over the same prompt — a
            # small-model forward, still no target prefill on this replica
            ids = np.zeros((1, bucket), np.int32)
            ids[0, :plen] = req.prompt[:plen]
            _, _, dc = self._draft_prefill(
                self.draft_variables, self._draft_cache, ids, bt_row,
                np.int32(slot_idx), np.int32(plen))
            self._draft_cache = dc
        self._admitted.inc()
        self._tenant_admitted.labels(tenant).inc()
        self._req_tenant[req.rid] = tenant
        # availability counts the seat; TTFT closed on the prefill side,
        # where the request's submit clock lives
        self.slo.record(tenant, ok=True)
        if self.tracer is not None and req.rid not in self._req_spans:
            # the decode replica opens its own root for the handed-off
            # request; remote_parent stitches it under the router's span
            root = self.tracer.start(
                "serve.request",
                attrs={"rid": req.rid, "prompt_len": plen,
                       "tenant": tenant, "disagg": 1},
                detached=True, remote_parent=req.traceparent or None)
            self._req_spans[req.rid] = root
            self.tracer.record(
                "serve.kv_install", t_install, time.perf_counter(),
                attrs={"bucket": bucket, "prompt_len": plen},
                trace_id=root.trace_id, parent_id=root.span_id)
        tok = int(first_token)
        slot = _Slot(req=req, pages=pages, tokens=[tok], last_token=tok,
                     max_new=max_new, priority=self._req_priority(req),
                     seq=self._next_seq())
        self._slots[slot_idx] = slot
        self._emit_token(req.rid, tok)
        self._update_occupancy()
        done = self._finish_reason(slot, tok)
        if done:
            return True, [self._release(slot_idx, done)]
        return True, []

    # ------------------------------------------------------------------
    # verification + stats
    # ------------------------------------------------------------------

    def verify_cache_donated(self) -> int:
        """Compile the decode step and assert the KV pages really alias
        into the outputs (device-resident across steps). Returns the
        alias count. In async mode the audited executable is the
        multi-substep window — donation matters MORE there: a copied
        cache would break the in-graph feedback chain's ordering."""
        B = self.config.max_batch
        active = np.zeros((B,), bool)
        lora = self._lora_args(rows=np.zeros((B,), np.int32))
        if self.async_decode:
            self._flush_pipeline()
            args = (self.variables, self._cache,
                    np.zeros((B,), np.int32),
                    np.zeros((B, self.substeps), np.int32),
                    np.zeros((B,), np.int32), active) + lora
        else:
            args = (self.variables, self._cache,
                    np.zeros((B,), np.int32), active) + lora
        return kvcache.assert_cache_donated(
            self._decode, *args, num_layers=self.cache_cfg.num_layers)

    def _snapshot_persistent_cache(self) -> None:
        self._cache_dir = None
        self._cache_dir_before: set[str] = set()
        try:
            path = jax.config.jax_compilation_cache_dir
        except AttributeError:
            return
        if path and os.path.isdir(path):
            self._cache_dir = path
            self._cache_dir_before = set(os.listdir(path))

    def persistent_cache_new_entries(self) -> int | None:
        """Executables added to the persistent compile cache since this
        engine was built (None when no cache dir is configured). The
        serve smoke bounds this by num_buckets + 2."""
        if not self._cache_dir or not os.path.isdir(self._cache_dir):
            return None
        return len(set(os.listdir(self._cache_dir))
                   - self._cache_dir_before)

    def compile_report(self, include_cost: bool = False) -> dict:
        def cache_size(fn) -> int:
            try:
                return int(fn._cache_size())
            except Exception:  # noqa: BLE001 - jax internals shifted
                return -1

        report = {
            "num_buckets": len(self.buckets),
            "prefill_executables": cache_size(self._prefill),
            "decode_executables": cache_size(self._decode),
            "persistent_cache_new_entries":
                self.persistent_cache_new_entries(),
        }
        counted = [report["prefill_executables"],
                   report["decode_executables"]]
        if self._chunk is not None:
            # chunked prefill is the one extra fixed-shape executable the
            # scheduler plane adds; it rides inside the num_buckets + 2
            # headroom the serve smoke already grants
            report["chunk_prefill_executables"] = cache_size(self._chunk)
            counted.append(report["chunk_prefill_executables"])
        if self.spec_k:
            # the verify step REPLACES decode in the engine loop, so the
            # target-model total stays <= num_buckets + 1; the draft's
            # small-model executables are reported but not counted — the
            # bound is about the big-model programs device memory holds
            report["verify_executables"] = cache_size(self._verify)
            report["draft_prefill_executables"] = \
                cache_size(self._draft_prefill)
            report["draft_decode_executables"] = \
                cache_size(self._draft_decode)
            counted.append(report["verify_executables"])
        if all(c >= 0 for c in counted):
            report["total_executables"] = sum(counted)
        if include_cost:
            # opt-in: lowering every bucket is seconds of work, too slow
            # for the fast smokes that only count executables
            report["cost"] = self.cost_report()
        return report

    def cost_report(self, accelerator: str = "") -> dict:
        """Roofline/MFU cost model of every bucketed executable
        (obs/costmodel.py): each prefill bucket and the decode step are
        AOT-lowered with zero-filled example args, their
        ``cost_analysis``/``memory_analysis`` folded into per-executable
        FLOPs / intensity / peak-HBM entries, and the serving gauges
        (``m2kt_serve_roofline_bound{executable=...}`` etc.) set on this
        engine's registry. Decode MFU uses the engine's own measured
        per-step decode time when any decode has run. Best-effort: an
        executable that fails to lower is simply absent."""
        from move2kube_tpu.obs import costmodel

        reports: dict = {}
        bt_row = np.full((self.cache_cfg.max_pages_per_seq,), NULL_PAGE,
                         np.int32)
        for bucket in self.buckets:
            compiled = costmodel.lower_and_compile(
                self._prefill, self.variables, self._cache,
                np.zeros((1, bucket), np.int32), bt_row,
                np.int32(0), np.int32(1))
            if compiled is not None:
                reports[f"prefill_{bucket}"] = \
                    costmodel.analyze_compiled(compiled)
        B = self.config.max_batch
        if self.async_decode:
            decode_args = (np.zeros((B,), np.int32),
                           np.zeros((B, self.substeps), np.int32),
                           np.zeros((B,), np.int32),
                           np.zeros((B,), bool))
        else:
            decode_args = (np.zeros((B,), np.int32),
                           np.zeros((B,), bool))
        compiled = costmodel.lower_and_compile(
            self._decode, self.variables, self._cache, *decode_args)
        if compiled is not None:
            decode = costmodel.analyze_compiled(compiled)
            reports["decode"] = decode
            # decode is the steady-state resident: its memory analysis is
            # what the OOM flight sidecar should carry for a serving pod
            costmodel.note_memory_report(decode)
        if self.spec_k:
            compiled = costmodel.lower_and_compile(
                self._verify, self.variables, self._cache,
                np.zeros((self.config.max_batch, self.spec_k + 1), np.int32),
                np.zeros((self.config.max_batch,), bool))
            if compiled is not None:
                verify = costmodel.analyze_compiled(compiled)
                reports["verify"] = verify
                # with spec on, verify (not decode) is the steady-state
                # resident the OOM sidecar should describe
                costmodel.note_memory_report(verify)
        spec, _ = costmodel.chip_spec(accelerator)
        decode_step = (self._decode_time / self._lat_hist.count
                       if self._lat_hist.count else None)
        costmodel.export_serving_gauges(
            reports, self.registry, accelerator=accelerator,
            decode_step_seconds=decode_step, quant=self.quant.name)
        out = {}
        for name, rep in reports.items():
            entry = rep.to_dict()
            entry["roofline"] = rep.roofline(spec)
            out[name] = entry
        int8 = self.quant.name != "off"
        for name in ("decode", "verify"):
            if name in out:
                out[name]["achieved_mfu"] = reports[name].mfu(
                    decode_step, spec, int8=int8)
        return out

    def stats(self) -> dict:
        # percentiles come from the fixed-bucket histogram (bucket-edge
        # interpolation), NOT a per-step latency list: a server decoding
        # for weeks must not grow host memory with every step. Keys are
        # unchanged — /stats consumers and the bench phase still parse.
        out = {
            "decode_steps": int(self._lat_hist.count),
            "decode_tokens": self._decode_tokens,
            "prefills": self._prefill_count,
            "decode_throughput_tokens_s": (
                self._decode_tokens / self._decode_time
                if self._decode_time else 0.0),
            "decode_p50_latency_ms": self._lat_hist.quantile(0.50) * 1e3,
            "decode_p95_latency_ms": self._lat_hist.quantile(0.95) * 1e3,
            # the router's least-loaded fallback reads these two
            "queue_depth": len(self._pending),
            "active_slots": sum(1 for s in self._slots if s is not None),
            "ttft_p50_ms": self._ttft_hist.quantile(0.50) * 1e3,
            "ttft_p95_ms": self._ttft_hist.quantile(0.95) * 1e3,
            # host-overlap evidence (PR 19): how long the device sat
            # starved between consuming step k and dispatching k+1
            "async_decode": bool(self.async_decode),
            "dispatch_gap_p50_ms": self._dispatch_gap.quantile(0.50) * 1e3,
            "dispatch_gap_total_s": self._gap_total,
            "host_overhead_ratio": (
                self._gap_total / (self._gap_total + self._busy_total)
                if self._gap_total + self._busy_total > 0 else 0.0),
        }
        if self.async_decode:
            out["decode_substeps"] = self.substeps
        if self._prefix is not None:
            hits = self._prefix_hits.value
            misses = self._prefix_misses.value
            out["prefix_hits"] = int(hits)
            out["prefix_misses"] = int(misses)
            out["prefix_hit_rate"] = (hits / (hits + misses)
                                      if hits + misses else 0.0)
            out["prefix_hit_tokens"] = int(self._prefix_hit_tokens.value)
            out["prefix_cache_pages"] = self._prefix.total_pages
            out["cow_copies"] = int(self._cow_copies.value)
        if self._audit_rate:
            out["quant_audits"] = int(self._quant_audits.value)
            out["quant_drift_last_rel"] = self._drift_last
            out["quant_drift_max_rel"] = self._drift_max
        if self._preempt_enabled:
            out["preempted"] = self._preempt_count
        if self._chunk is not None:
            out["chunked_prefills"] = self._chunk_count
        if self.adapters is not None:
            out["lora_adapters"] = len(self.adapters.names)
        if self.spec_k:
            prop = self._spec_proposed.value
            acc = self._spec_accepted.value
            out["spec_proposed"] = int(prop)
            out["spec_accepted"] = int(acc)
            out["spec_acceptance_rate"] = acc / prop if prop else 0.0
            # tokens landed per verify step: the spec-decode payoff —
            # 1.0 means plain decode, > 1 means freed bandwidth became
            # accepted tokens
            steps = int(self._lat_hist.count)
            out["spec_tokens_per_step"] = (
                self._decode_tokens / steps if steps else 0.0)
        return out
