from move2kube_tpu.engine.planner import create_plan, curate_plan  # noqa: F401
from move2kube_tpu.engine.translator import translate  # noqa: F401
