"""Collect driver.

Parity: ``internal/move2kube/collector.go:29-63`` — runs all collectors
with annotation filtering into ``m2kt_collect/``.
"""

from __future__ import annotations

import os

from move2kube_tpu.utils import common
from move2kube_tpu.utils.log import get_logger

log = get_logger("collector")


def get_collectors() -> list:
    from move2kube_tpu.collector.cfapps import CfAppsCollector
    from move2kube_tpu.collector.cfcontainertypes import CFContainerTypesCollector
    from move2kube_tpu.collector.cluster import ClusterCollector
    from move2kube_tpu.collector.images import ImagesCollector

    return [ClusterCollector(), ImagesCollector(),
            CFContainerTypesCollector(), CfAppsCollector()]


def collect(source_dir: str, out_dir: str, annotations: list[str] | None = None) -> None:
    out_dir = os.path.join(os.path.abspath(out_dir), common.COLLECT_OUTPUT_DIR)
    os.makedirs(out_dir, exist_ok=True)
    for collector in get_collectors():
        if annotations and not set(annotations) & set(collector.get_annotations()):
            continue
        try:
            collector.collect(source_dir, out_dir)
        except Exception as e:  # noqa: BLE001 - collectors are environment-gated
            log.warning("collector %s failed: %s", type(collector).__name__, e)
