"""Plan creation and curation.

Parity: ``internal/move2kube/planner.go`` — ``create_plan`` iterates source
loaders' service options + metadata loaders' update_plan (:30-62);
``curate_plan`` interactively narrows services, build types, target options
and output artifact type through the QA engine (:65-239).
"""

from __future__ import annotations

import os

from move2kube_tpu import containerizer, qa
from move2kube_tpu.metadata import get_loaders
from move2kube_tpu.metadata import clusters as cluster_profiles
from move2kube_tpu.source import get_source_loaders
from move2kube_tpu.types import plan as plantypes
from move2kube_tpu.utils import common, trace
from move2kube_tpu.utils.log import get_logger

log = get_logger("planner")


def create_plan(root_dir: str, name: str = "") -> plantypes.Plan:
    root_dir = os.path.abspath(root_dir)
    plan = plantypes.new_plan(name or os.path.basename(root_dir.rstrip(os.sep))
                              or common.DEFAULT_PROJECT_NAME)
    plan.root_dir = root_dir
    containerizer.init_containerizers(root_dir)
    for translator in get_source_loaders():
        with trace.span(f"plan.{translator.get_translation_type().lower()}"):
            try:
                services = translator.get_service_options(plan)
            except Exception as e:  # noqa: BLE001 - plugin tolerance (planner.go:40-45)
                log.warning("translator %s failed during planning: %s",
                            type(translator).__name__, e)
                continue
        for svc in services:
            plan.add_service(svc)
    with trace.span("plan.metadata"):
        for loader in get_loaders():
            try:
                loader.update_plan(plan)
            except Exception as e:  # noqa: BLE001
                log.warning("metadata loader %s failed: %s", type(loader).__name__, e)
    return plan


def curate_plan(plan: plantypes.Plan) -> plantypes.Plan:
    """Interactive narrowing (planner.go:65-239): pick services, one
    containerization option per service, artifact type and target cluster."""
    if not plan.services:
        log.warning("no services found in the plan")
    service_names = sorted(plan.services.keys())
    chosen_names = qa.fetch_multi_select(
        "m2kt.services.select",
        "Select the services to translate",
        [], service_names, service_names,
    )
    new_services: dict[str, list[plantypes.PlanService]] = {}
    for name in chosen_names:
        options = plan.services[name]
        if len(options) > 1:
            descs = [
                f"{o.container_build_type}"
                + (f" ({o.containerization_target_options[0]})"
                   if o.containerization_target_options else "")
                for o in options
            ]
            picked = qa.fetch_select(
                f"m2kt.services.{name}.build",
                f"Select the containerization technique for service [{name}]",
                [], descs[0], descs,
            )
            option = options[descs.index(picked)]
        else:
            option = options[0]
        if len(option.containerization_target_options) > 1:
            target = qa.fetch_select(
                f"m2kt.services.{name}.target",
                f"Select the containerization target for service [{name}]",
                [], option.containerization_target_options[0],
                option.containerization_target_options,
            )
            option.containerization_target_options = [target]
        new_services[name] = [option]
    plan.services = new_services

    artifact = qa.fetch_select(
        "m2kt.target.artifacttype",
        "Select the output artifact type",
        ["Yamls: plain kubernetes yamls | Helm: a helm chart | Knative: knative serving yamls"],
        plan.kubernetes.effective_artifact_type(),
        [plantypes.TargetArtifactType.YAMLS, plantypes.TargetArtifactType.HELM,
         plantypes.TargetArtifactType.KNATIVE],
    )
    plan.kubernetes.artifact_type = artifact

    cluster_options = sorted(cluster_profiles.builtin_clusters().keys())
    collected = plan.target_info_artifacts.get(plantypes.Plan.TARGET_CLUSTERS_ARTIFACT, [])
    cluster_options += collected
    default_cluster = (plan.kubernetes.target_cluster.type
                       or plan.kubernetes.target_cluster.path
                       or cluster_profiles.DEFAULT_CLUSTER)
    chosen_cluster = qa.fetch_select(
        "m2kt.target.cluster",
        "Select the target cluster type",
        [], default_cluster, cluster_options,
    )
    if chosen_cluster in cluster_profiles.builtin_clusters():
        plan.kubernetes.target_cluster = plantypes.TargetCluster(type=chosen_cluster)
    else:
        plan.kubernetes.target_cluster = plantypes.TargetCluster(path=chosen_cluster)
    return plan
