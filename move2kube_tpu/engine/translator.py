"""The 8-stage translate pipeline.

Parity: ``internal/move2kube/translator.go:34-110`` —
source.Translate -> metadata.LoadToIR -> optimize -> ComposeTransformer ->
customize -> [Helm] parameterize -> [new containers] CICD(Tekton) ->
K8s|Knative transform + write.
"""

from __future__ import annotations

import os

from move2kube_tpu import containerizer
from move2kube_tpu.metadata import get_loaders
from move2kube_tpu.passes import customize, optimize, parameterize
from move2kube_tpu.source import translate_sources
from move2kube_tpu.transformer.base import get_transformer
from move2kube_tpu.transformer.compose import ComposeTransformer
from move2kube_tpu.types import plan as plantypes
from move2kube_tpu.types.ir import IR
from move2kube_tpu.types.plan import TargetArtifactType
from move2kube_tpu.utils import trace
from move2kube_tpu.utils.log import get_logger

log = get_logger("translator")


def translate(plan: plantypes.Plan, out_dir: str) -> IR:
    out_dir = os.path.abspath(out_dir)
    os.makedirs(out_dir, exist_ok=True)
    containerizer.init_containerizers(plan.root_dir)

    log.info("translating %d services", len(plan.services))
    trace.count("services", len(plan.services))
    with trace.span("translate.sources"):
        ir = translate_sources(plan)

    with trace.span("translate.metadata"):
        for loader in get_loaders():
            try:
                loader.load_to_ir(plan, ir)
            except Exception as e:  # noqa: BLE001
                log.warning("metadata loader %s failed: %s", type(loader).__name__, e)

    with trace.span("translate.optimize"):
        ir = optimize(ir)

    with trace.span("translate.compose"):
        compose_tf = ComposeTransformer()
        try:
            compose_tf.transform(ir)
            compose_tf.write_objects(out_dir, ir)
        except Exception as e:  # noqa: BLE001
            log.warning("compose transformer failed: %s", e)

    with trace.span("translate.customize"):
        ir = customize(ir)

    if ir.kubernetes.effective_artifact_type() == TargetArtifactType.HELM:
        with trace.span("translate.parameterize"):
            ir = parameterize(ir)

    if any(c.new for c in ir.containers):
        with trace.span("translate.cicd"):
            try:
                from move2kube_tpu.transformer.cicd import CICDTransformer

                cicd = CICDTransformer()
                cicd.transform(ir)
                cicd.write_objects(out_dir, ir)
            except Exception as e:  # noqa: BLE001
                log.warning("cicd transformer failed: %s", e)

    with trace.span("translate.write"):
        transformer = get_transformer(ir)
        transformer.transform(ir)
        transformer.write_objects(out_dir, ir)
    trace.count("containers_built", sum(1 for c in ir.containers if c.new))
    log.info("translation written to %s", out_dir)
    return ir
