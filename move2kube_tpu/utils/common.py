"""Shared helpers for the translate engine.

TPU-native rebuild of the reference's ``internal/common/utils.go`` +
``internal/common/constants.go`` surface (file finders, YAML/JSON IO with
kind checking, template rendering, fuzzy matching, DNS-1123 sanitizers,
common-directory math). Behavior parity, idiomatic Python.
"""

from __future__ import annotations

import difflib
import functools
import hashlib
import json
import os
import re
from typing import Any, Iterable

import yaml

from move2kube_tpu import API_VERSION, GROUP_NAME
from move2kube_tpu.utils.log import get_logger

log = get_logger("common")

# ---------------------------------------------------------------------------
# Constants (parity: internal/common/constants.go:27-110)
# ---------------------------------------------------------------------------

DEFAULT_PLAN_FILE = "m2kt.plan"
DEFAULT_PROJECT_NAME = "myproject"
QA_CACHE_FILE = "m2ktqacache.yaml"
IGNORE_FILENAME = ".m2ktignore"
# Also honored for drop-in compatibility with reference source trees.
LEGACY_IGNORE_FILENAMES = (".m2kignore",)
EXPOSE_SERVICE_ANNOTATION = GROUP_NAME + "/service.expose"
DEFAULT_SERVICE_PORT = 8080
DEFAULT_PVC_SIZE = "100Mi"
DEFAULT_REGISTRY_URL = "quay.io"
DEFAULT_STORAGE_CLASS = "default"
CONTAINERS_DIR = "containers"
CICD_DIR = "cicd"
COLLECT_OUTPUT_DIR = "m2kt_collect"

# Global toggle (parity: common.IgnoreEnvironment): when True, nothing is
# derived from the local environment (env vars, docker daemon, kubeconfig).
IGNORE_ENVIRONMENT = False

# ---------------------------------------------------------------------------
# File finders (parity: GetFilesByExt utils.go:47, GetFilesByName utils.go:85)
# ---------------------------------------------------------------------------


def get_files_by_ext(root: str, exts: Iterable[str]) -> list[str]:
    """Recursively find files under root with one of the given extensions."""
    exts = tuple(e if e.startswith(".") else "." + e for e in exts)
    out: list[str] = []
    root = os.path.abspath(root)
    for dirpath, dirnames, filenames in os.walk(root, followlinks=False):
        dirnames[:] = [d for d in dirnames if d not in (".git",)]
        for f in filenames:
            if f.endswith(exts):
                out.append(os.path.join(dirpath, f))
    out.sort()
    return out


def get_files_by_name(root: str, names: Iterable[str]) -> list[str]:
    """Recursively find files under root whose basename is in names."""
    nameset = set(names)
    out: list[str] = []
    root = os.path.abspath(root)
    for dirpath, dirnames, filenames in os.walk(root, followlinks=False):
        dirnames[:] = [d for d in dirnames if d not in (".git",)]
        for f in filenames:
            if f in nameset:
                out.append(os.path.join(dirpath, f))
    out.sort()
    return out


def find_common_directory(paths: Iterable[str]) -> str:
    """Longest common ancestor directory of paths (utils.go:527)."""
    paths = [os.path.abspath(p) for p in paths]
    if not paths:
        return ""
    return os.path.commonpath(paths)


# ---------------------------------------------------------------------------
# YAML / JSON IO (parity: ReadMove2KubeYaml utils.go:210, WriteYaml)
# ---------------------------------------------------------------------------


# libyaml's C dumper/loader when present (~5x on emission-heavy
# translates); the pure-Python classes are a drop-in fallback
_BaseDumper = getattr(yaml, "CSafeDumper", yaml.SafeDumper)
_BaseLoader = getattr(yaml, "CSafeLoader", yaml.SafeLoader)


class _M2KTDumper(_BaseDumper):
    """Block-style dumper that never emits aliases (k8s YAML convention)."""

    def ignore_aliases(self, data: Any) -> bool:  # noqa: ARG002
        return True


def _str_presenter(dumper: yaml.Dumper, data: str) -> yaml.Node:
    if "\n" in data:
        return dumper.represent_scalar("tag:yaml.org,2002:str", data, style="|")
    return dumper.represent_scalar("tag:yaml.org,2002:str", data)


_M2KTDumper.add_representer(str, _str_presenter)


def to_yaml(obj: Any) -> str:
    # width: keep Helm {{ ... }} expressions on one line — folded scalars
    # technically survive Go template parsing but are fragile and unreadable
    return yaml.dump(obj, Dumper=_M2KTDumper, default_flow_style=False,
                     sort_keys=False, width=1000)


# Parse cache keyed by (path, mtime, size): plan-time consumers (compose
# finder, metadata loaders, collectors) each scan the same tree; the walks
# are cheap but re-parsing every YAML 3x is not.
_yaml_cache: dict[str, tuple[tuple[float, int], Any]] = {}


def read_yaml(path: str) -> Any:
    path = os.path.abspath(path)
    try:
        st = os.stat(path)
        stamp = (st.st_mtime, st.st_size)
    except OSError:
        stamp = None
    import copy

    if stamp is not None:
        hit = _yaml_cache.get(path)
        if hit is not None and hit[0] == stamp:
            return copy.deepcopy(hit[1])  # callers may mutate their copy
    with open(path, "r", encoding="utf-8") as f:
        doc = yaml.load(f, Loader=_BaseLoader)
    if stamp is not None:
        if len(_yaml_cache) > 4096:
            _yaml_cache.clear()
        _yaml_cache[path] = (stamp, copy.deepcopy(doc))
    return doc


def write_yaml(path: str, obj: Any) -> None:
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        f.write(to_yaml(obj))


def read_m2kt_yaml(path: str, expected_kind: str) -> dict:
    """Read a YAML doc and verify it is ours and of the expected kind.

    Parity: common.ReadMove2KubeYaml (utils.go:210) — rejects docs whose
    apiVersion group is not ours or whose kind mismatches.
    """
    doc = read_yaml(path)
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: not a YAML mapping")
    api_version = str(doc.get("apiVersion", ""))
    if "/" in api_version:
        group = api_version.rsplit("/", 1)[0]
    else:
        group = api_version
    if group != GROUP_NAME:
        raise ValueError(
            f"{path}: apiVersion group {group!r} is not {GROUP_NAME!r}"
        )
    kind = str(doc.get("kind", ""))
    if kind != expected_kind:
        raise ValueError(f"{path}: kind {kind!r} != expected {expected_kind!r}")
    return doc


def new_m2kt_doc(kind: str, name: str = "") -> dict:
    doc: dict[str, Any] = {"apiVersion": API_VERSION, "kind": kind}
    if name:
        doc["metadata"] = {"name": name}
    return doc


def read_json(path: str) -> Any:
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)


# ---------------------------------------------------------------------------
# Templates (parity: GetStringFromTemplate utils.go:348, WriteTemplateToFile)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=256)
def _compile_template(template_str: str):
    import jinja2

    env = jinja2.Environment(undefined=jinja2.StrictUndefined,
                             keep_trailing_newline=True)
    return env.from_string(template_str)


def render_template(template_str: str, params: dict) -> str:
    """Render a Jinja2 template string with strict undefined handling.

    Compiled templates are lru-cached by source: a translate run renders
    the same trainer/build-script templates once per service, and jinja
    compilation dominated the translate profile (~half the wall time)
    before caching."""
    return _compile_template(template_str).render(**params)


def write_template_to_file(template_str: str, params: dict, path: str, mode: int = 0o644) -> None:
    out = render_template(template_str, params)
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        f.write(out)
    os.chmod(path, mode)


# ---------------------------------------------------------------------------
# Fuzzy matching (parity: GetClosestMatchingString utils.go:377)
# ---------------------------------------------------------------------------


def closest_matching_string(target: str, options: list[str]) -> str:
    """Return the option closest to target (case/space-insensitive)."""
    if not options:
        return ""
    norm = lambda s: re.sub(r"\s+", "", s.lower())  # noqa: E731
    t = norm(target)
    best, best_score = options[0], -1.0
    for opt in options:
        score = difflib.SequenceMatcher(None, t, norm(opt)).ratio()
        if score > best_score:
            best, best_score = opt, score
    return best


# ---------------------------------------------------------------------------
# DNS-1123 sanitizers (parity: MakeStringDNSNameCompliant utils.go:445 et seq.)
# ---------------------------------------------------------------------------

_DNS_NAME_MAX = 253
_DNS_LABEL_MAX = 63


def _dns_sanitize(s: str, maxlen: int) -> str:
    s = s.lower()
    s = re.sub(r"[^a-z0-9\-.]", "-", s)
    s = re.sub(r"\.+", ".", s)
    s = s.strip("-.")
    if len(s) > maxlen:
        digest = hashlib.sha256(s.encode()).hexdigest()[:8]
        s = s[: maxlen - 9].rstrip("-.") + "-" + digest
    return s or "app"


def make_dns_name(s: str) -> str:
    """Sanitize to a DNS-1123 subdomain (lowercase alnum, '-', '.')."""
    return _dns_sanitize(s, _DNS_NAME_MAX)


def make_dns_label(s: str) -> str:
    """Sanitize to a DNS-1123 label (lowercase alnum and '-', <=63 chars)."""
    return _dns_sanitize(make_dns_name(s).replace(".", "-"), _DNS_LABEL_MAX)


def make_env_name(s: str) -> str:
    """Sanitize to a C_IDENTIFIER env-var name."""
    s = re.sub(r"[^A-Za-z0-9_]", "_", s)
    if s and s[0].isdigit():
        s = "_" + s
    return s.upper() or "_"


def unique_name(base: str, taken: Iterable[str]) -> str:
    taken = set(taken)
    if base not in taken:
        return base
    i = 2
    while f"{base}-{i}" in taken:
        i += 1
    return f"{base}-{i}"


# ---------------------------------------------------------------------------
# Path helpers
# ---------------------------------------------------------------------------


def is_parent(path: str, parent: str) -> bool:
    """True if parent is an ancestor of (or equal to) path."""
    path = os.path.abspath(path)
    parent = os.path.abspath(parent)
    return path == parent or path.startswith(parent.rstrip(os.sep) + os.sep)


def relpath_under(path: str, root: str) -> str | None:
    """Root-relative form of path if under root, else None."""
    if not is_parent(path, root):
        return None
    return os.path.relpath(os.path.abspath(path), os.path.abspath(root))


def write_file(path: str, contents: str, mode: int = 0o644) -> None:
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        f.write(contents)
    os.chmod(path, mode)
