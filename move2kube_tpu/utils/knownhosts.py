"""known_hosts handling for generated git SSH secrets.

Parity: ``internal/common/knownhosts/knownhosts.go:84-160`` — parse
OpenSSH known_hosts lines into domain -> host-key entries, with baked-in
public host keys for the major git forges so Tekton git-clone can verify
them without any user setup. The baked-in keys below are the forges'
published public host keys (public information, shipped identically by
the reference).
"""

from __future__ import annotations

import os

# Publicly published SSH host keys of the major git forges (same set the
# reference bakes in; ed25519 entries are the current published ones).
BUILTIN_HOST_KEYS: dict[str, list[str]] = {
    "github.com": [
        "ssh-ed25519 AAAAC3NzaC1lZDI1NTE5AAAAIOMqqnkVzrm0SdG6UOoqKLsabgH5C9okWi0dh2l9GKJl",
        "ecdsa-sha2-nistp256 AAAAE2VjZHNhLXNoYTItbmlzdHAyNTYAAAAIbmlzdHAyNTYAAABBBEmKSENjQEezOmxkZMy7opKgwFB9nkt5YRrYMjNuG5N87uRgg6CLrbo5wAdT/y6v0mKV0U2w0WZ2YB/++Tpockg=",  # noqa: line-length (host key data)
    ],
    "gitlab.com": [
        "ssh-ed25519 AAAAC3NzaC1lZDI1NTE5AAAAIAfuCHKVTjquxvt6CM6tdG4SLp1Btn/nOeHHE5UOzRdf",
        "ecdsa-sha2-nistp256 AAAAE2VjZHNhLXNoYTItbmlzdHAyNTYAAAAIbmlzdHAyNTYAAABBBFSMqzJeV9rUzU4kWitGjeR4PWSa29SPqJ1fVkhtj3Hw9xjLVXVYrU9QlYWrOLXBpQ6KWjbjTDTdDkoohFzgbEY=",  # noqa: line-length (host key data)
    ],
    "bitbucket.org": [
        "ssh-ed25519 AAAAC3NzaC1lZDI1NTE5AAAAIIazEu89wgQZ4bqs3d63QSMzYVa0MuJ2e2gKTKqu+UUO",
        "ecdsa-sha2-nistp256 AAAAE2VjZHNhLXNoYTItbmlzdHAyNTYAAAAIbmlzdHAyNTYAAABBBPIQmuzMBuKdWeF4+a2sjSSpBK0iqitSQ+5BM9KhpexuGt20JpTVM7u5BDZngncgrqDMbWdxMWWOGtZ9UgbqgZE=",  # noqa: line-length (host key data)
    ],
}


def parse_known_hosts(text: str) -> dict[str, list[str]]:
    """OpenSSH known_hosts text -> {domain: ["keytype key", ...]}.
    Hashed entries (|1|...) are skipped — they can't be matched to a
    domain without the salt (knownhosts.go:84)."""
    out: dict[str, list[str]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#") or line.startswith("|"):
            continue
        parts = line.split()
        if len(parts) < 3:
            continue
        hosts, keytype, key = parts[0], parts[1], parts[2]
        for host in hosts.split(","):
            host = host.strip().lstrip("[").split("]")[0]
            if host:
                out.setdefault(host, []).append(f"{keytype} {key}")
    return out


def load_known_hosts(extra_path: str | None = None) -> dict[str, list[str]]:
    """Built-in forge keys merged with the user's ~/.ssh/known_hosts
    (or ``extra_path``)."""
    merged = {d: list(keys) for d, keys in BUILTIN_HOST_KEYS.items()}
    path = extra_path or os.path.expanduser("~/.ssh/known_hosts")
    try:
        with open(path, encoding="utf-8") as f:
            user = parse_known_hosts(f.read())
    except OSError:
        user = {}
    for domain, keys in user.items():
        mine = merged.setdefault(domain, [])
        for k in keys:
            if k not in mine:
                mine.append(k)
    return merged


def known_hosts_lines(domain: str, table: dict[str, list[str]] | None = None) -> str:
    """Render the known_hosts file content for one domain."""
    table = table if table is not None else load_known_hosts()
    return "\n".join(f"{domain} {entry}" for entry in table.get(domain, []))
