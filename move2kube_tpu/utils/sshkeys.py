"""SSH private-key loading for generated Tekton git secrets.

Parity: ``internal/common/sshkeys/sshkeys.go:50-240`` — enumerate the
user's ~/.ssh private keys, let the QA engine pick which key to embed for
a git domain (with a passphrase prompt for encrypted keys), and pair it
with the domain's known_hosts entries. Everything is environment-gated:
with IGNORE_ENVIRONMENT set or no ~/.ssh present, secrets are emitted
with placeholder contents for the user to fill in.
"""

from __future__ import annotations

import os

from move2kube_tpu.qa import engine as qaengine
from move2kube_tpu.utils import common
from move2kube_tpu.utils.knownhosts import known_hosts_lines, load_known_hosts
from move2kube_tpu.utils.log import get_logger

log = get_logger("sshkeys")

NO_KEY = "none (fill in manually)"
_PEM_MARKERS = ("PRIVATE KEY", "OPENSSH PRIVATE KEY")


def list_private_keys(ssh_dir: str | None = None) -> list[str]:
    """Paths of private key files in ~/.ssh (sshkeys.go loadSSHKeys)."""
    if common.IGNORE_ENVIRONMENT:
        return []
    directory = ssh_dir or os.path.expanduser("~/.ssh")
    keys: list[str] = []
    try:
        entries = sorted(os.listdir(directory))
    except OSError:
        return []
    for name in entries:
        path = os.path.join(directory, name)
        if not os.path.isfile(path) or name in ("known_hosts", "config",
                                                "authorized_keys"):
            continue
        if name.endswith(".pub"):
            continue
        try:
            with open(path, encoding="utf-8", errors="ignore") as f:
                head = f.read(4096)
        except OSError:
            continue
        if any(marker in head for marker in _PEM_MARKERS):
            keys.append(path)
    return keys


def _is_encrypted(key_text: str) -> bool:
    return "ENCRYPTED" in key_text or "Proc-Type: 4,ENCRYPTED" in key_text


def _decrypt(key_text: str, passphrase: str) -> str:
    """Best-effort decrypt so the embedded key works without an agent.
    Falls back to the original (still-encrypted) text."""
    try:
        from cryptography.hazmat.primitives import serialization

        key = serialization.load_ssh_private_key(
            key_text.encode(), password=passphrase.encode())
        return key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.OpenSSH,
            serialization.NoEncryption(),
        ).decode()
    except Exception as e:  # noqa: BLE001 - wrong pass, PEM format, no lib
        try:
            from cryptography.hazmat.primitives import serialization

            key = serialization.load_pem_private_key(
                key_text.encode(), password=passphrase.encode())
            return key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.TraditionalOpenSSL,
                serialization.NoEncryption(),
            ).decode()
        except Exception:  # noqa: BLE001
            log.warning("could not decrypt SSH key (%s); embedding as-is", e)
            return key_text


def get_ssh_key(domain: str, ssh_dir: str | None = None) -> str:
    """Private key text to embed for a git domain, chosen via QA
    (sshkeys.go GetSSHKey). '' when the user opts out or none exist."""
    candidates = list_private_keys(ssh_dir)
    if not candidates:
        return ""
    options = [os.path.basename(p) for p in candidates] + [NO_KEY]
    answer = qaengine.fetch_select(
        id=f"m2kt.sshkeys.key.{domain}",
        desc=f"Select the SSH private key to use for git domain {domain}:",
        context=["The key is embedded in the generated Tekton git secret."],
        default=NO_KEY, options=options,
    )
    if answer in (NO_KEY, "", None):
        return ""
    path = os.path.join(os.path.dirname(candidates[0]), str(answer))
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError as e:
        log.warning("cannot read SSH key %s: %s", path, e)
        return ""
    if _is_encrypted(text):
        passphrase = qaengine.fetch_password(
            id=f"m2kt.sshkeys.passphrase.{os.path.basename(path)}",
            desc=f"Passphrase for SSH key {os.path.basename(path)}:",
            context=[],
        ) or ""
        text = _decrypt(text, str(passphrase))
    return text


def git_secret_data(domain: str, ssh_dir: str | None = None,
                    known_hosts_path: str | None = None) -> dict[str, str]:
    """stringData for a kubernetes.io/ssh-auth secret for one git domain."""
    key = get_ssh_key(domain, ssh_dir)
    hosts = known_hosts_lines(domain, load_known_hosts(known_hosts_path))
    return {
        "ssh-privatekey": key or "<paste the private key for "
                                 f"{domain} here>",
        "known_hosts": hosts,
    }
