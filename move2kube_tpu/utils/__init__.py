from move2kube_tpu.utils import common  # noqa: F401
from move2kube_tpu.utils.log import get_logger  # noqa: F401
