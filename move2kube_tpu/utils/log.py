"""Leveled logging for the translate engine.

Mirrors the reference's logrus usage (a ``--verbose`` debug flag and
warn-and-continue plugin loops; cmd/move2kube/move2kube.go:41-46) on top of
stdlib logging.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time

_CONFIGURED = False


class _ColorFormatter(logging.Formatter):
    COLORS = {
        logging.DEBUG: "\x1b[36m",  # cyan
        logging.INFO: "\x1b[32m",  # green
        logging.WARNING: "\x1b[33m",  # yellow
        logging.ERROR: "\x1b[31m",  # red
        logging.CRITICAL: "\x1b[41m",  # red bg
    }
    RESET = "\x1b[0m"

    def __init__(self, use_color: bool) -> None:
        super().__init__("%(levelname)s[%(asctime)s] %(message)s", "%H:%M:%S")
        self.use_color = use_color

    def format(self, record: logging.LogRecord) -> str:
        msg = super().format(record)
        if self.use_color:
            color = self.COLORS.get(record.levelno, "")
            return f"{color}{msg}{self.RESET}"
        return msg


class _JsonFormatter(logging.Formatter):
    """One JSON object per line (M2KT_LOG_JSON=1): what log pipelines
    (Fluent Bit / Cloud Logging) expect from pods — no ANSI, no
    multi-line records, structured level + logger fields."""

    def format(self, record: logging.LogRecord) -> str:
        entry = {
            "ts": round(record.created or time.time(), 3),
            "level": record.levelname.lower(),
            "logger": record.name,
            "msg": record.getMessage(),
        }
        if record.exc_info:
            entry["exc"] = self.formatException(record.exc_info)
        return json.dumps(entry, ensure_ascii=False)


def _pick_formatter() -> logging.Formatter:
    """JSON when M2KT_LOG_JSON asks for it; otherwise the leveled
    formatter, colored only for an interactive stderr that hasn't set
    NO_COLOR (https://no-color.org: any value, even empty, disables)."""
    if os.environ.get("M2KT_LOG_JSON", "").strip().lower() in (
            "1", "true", "yes", "on"):
        return _JsonFormatter()
    use_color = sys.stderr.isatty() and "NO_COLOR" not in os.environ
    return _ColorFormatter(use_color)


def configure(verbose: bool = False) -> None:
    """Configure the root m2kt logger. Idempotent; later calls adjust level."""
    global _CONFIGURED
    logger = logging.getLogger("m2kt")
    logger.setLevel(logging.DEBUG if verbose else logging.INFO)
    if not _CONFIGURED:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(_pick_formatter())
        logger.addHandler(handler)
        logger.propagate = False
        _CONFIGURED = True


def get_logger(name: str | None = None) -> logging.Logger:
    configure_if_needed()
    return logging.getLogger("m2kt" if not name else f"m2kt.{name}")


def configure_if_needed() -> None:
    if not _CONFIGURED:
        configure(verbose=os.environ.get("M2KT_VERBOSE", "") not in ("", "0", "false"))
