"""Leveled logging for the translate engine.

Mirrors the reference's logrus usage (a ``--verbose`` debug flag and
warn-and-continue plugin loops; cmd/move2kube/move2kube.go:41-46) on top of
stdlib logging.
"""

from __future__ import annotations

import logging
import os
import sys

_CONFIGURED = False


class _ColorFormatter(logging.Formatter):
    COLORS = {
        logging.DEBUG: "\x1b[36m",  # cyan
        logging.INFO: "\x1b[32m",  # green
        logging.WARNING: "\x1b[33m",  # yellow
        logging.ERROR: "\x1b[31m",  # red
        logging.CRITICAL: "\x1b[41m",  # red bg
    }
    RESET = "\x1b[0m"

    def __init__(self, use_color: bool) -> None:
        super().__init__("%(levelname)s[%(asctime)s] %(message)s", "%H:%M:%S")
        self.use_color = use_color

    def format(self, record: logging.LogRecord) -> str:
        msg = super().format(record)
        if self.use_color:
            color = self.COLORS.get(record.levelno, "")
            return f"{color}{msg}{self.RESET}"
        return msg


def configure(verbose: bool = False) -> None:
    """Configure the root m2kt logger. Idempotent; later calls adjust level."""
    global _CONFIGURED
    logger = logging.getLogger("m2kt")
    logger.setLevel(logging.DEBUG if verbose else logging.INFO)
    if not _CONFIGURED:
        handler = logging.StreamHandler(sys.stderr)
        use_color = sys.stderr.isatty() and os.environ.get("NO_COLOR") is None
        handler.setFormatter(_ColorFormatter(use_color))
        logger.addHandler(handler)
        logger.propagate = False
        _CONFIGURED = True


def get_logger(name: str | None = None) -> logging.Logger:
    configure_if_needed()
    return logging.getLogger("m2kt" if not name else f"m2kt.{name}")


def configure_if_needed() -> None:
    if not _CONFIGURED:
        configure(verbose=os.environ.get("M2KT_VERBOSE", "") not in ("", "0", "false"))
