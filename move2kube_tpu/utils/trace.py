"""Run tracing: timing spans + machine-readable metrics for a pipeline run.

Net-new vs the reference, which has only leveled logging (SURVEY.md §5
"tracing/profiling: absent"). Every pipeline stage runs under ``span()``;
``write_metrics`` dumps one JSON document per run with wall time and
counters, so headless/CI invocations can be tracked without scraping logs.

Spans nest: a stage's time includes its children, reported with dotted
names (``translate.sources.gpu2tpu``). Thread-safe for the QA REST
engine's server thread (counters take the lock; spans are per-thread).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager

_lock = threading.Lock()
_local = threading.local()

# raw-event ring bound: per-name TOTALS are kept exactly in a dict whose
# cardinality is the span-name set (small and fixed by the pipeline), but
# the raw append-per-call event list must not grow with call count — the
# obs bridge re-mirrors the recorder on every scrape of a long-lived
# process (same grow-forever class as the serving engine's old
# _step_latencies list)
SPAN_RING_MAX = 1024


class Recorder:
    def __init__(self) -> None:
        self.spans: deque[dict] = deque(maxlen=SPAN_RING_MAX)
        self.counters: dict[str, int] = {}
        self.started = time.time()
        self._span_totals: dict[str, float] = {}

    def add_span(self, name: str, seconds: float) -> None:
        with _lock:
            self.spans.append({"name": name, "seconds": round(seconds, 6)})
            self._span_totals[name] = (
                self._span_totals.get(name, 0.0) + seconds)

    def count(self, name: str, n: int = 1) -> None:
        with _lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def to_dict(self) -> dict:
        """Totals come from the persistent accumulator, NOT the ring:
        rolled per-name sums stay exact even after the ring evicts old
        raw events, so ``write_metrics`` output keeps its shape and its
        meaning regardless of run length."""
        with _lock:
            return {
                "wall_seconds": round(time.time() - self.started, 3),
                "spans": {k: round(v, 6)
                          for k, v in sorted(self._span_totals.items())},
                "counters": dict(sorted(self.counters.items())),
            }


_recorder = Recorder()


def reset() -> None:
    global _recorder
    _recorder = Recorder()


def get() -> Recorder:
    return _recorder


@contextmanager
def span(name: str):
    """Time a block; nested spans get dotted names."""
    stack = getattr(_local, "stack", None)
    if stack is None:
        stack = _local.stack = []
    full = ".".join([*stack, name])
    stack.append(name)
    t0 = time.perf_counter()
    try:
        yield
    finally:
        stack.pop()
        _recorder.add_span(full, time.perf_counter() - t0)


def count(name: str, n: int = 1) -> None:
    _recorder.count(name, n)


def write_metrics(out_dir: str, filename: str = "m2kt-metrics.json") -> str:
    path = os.path.join(out_dir, filename)
    os.makedirs(out_dir, exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(_recorder.to_dict(), f, indent=2, sort_keys=True)
        f.write("\n")
    return path
