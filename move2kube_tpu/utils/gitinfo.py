"""Git repository introspection for CI/CD generation.

Parity: ``types/plan/plan.go:194-280`` (GatherGitInfo) and the helpers at
``internal/common/utils.go:636-700`` — find the repo containing a service
directory and its remote URL/branch, preferring the ``upstream`` remote
over ``origin``. The reference uses go-git; we parse ``.git/config`` and
``.git/HEAD`` directly (no subprocess, works in sandboxes without git).
"""

from __future__ import annotations

import configparser
import os
import re
from dataclasses import dataclass

PREFERRED_REMOTES = ["upstream", "origin"]


@dataclass
class GitRepoDetails:
    repo_root: str = ""
    remote_name: str = ""
    url: str = ""
    branch: str = ""


def find_repo_root(path: str) -> str | None:
    """Walk up from path to the directory containing ``.git``."""
    cur = os.path.abspath(path)
    while True:
        if os.path.exists(os.path.join(cur, ".git")):
            return cur
        parent = os.path.dirname(cur)
        if parent == cur:
            return None
        cur = parent


def _git_dir(repo_root: str) -> str | None:
    dotgit = os.path.join(repo_root, ".git")
    if os.path.isdir(dotgit):
        return dotgit
    if os.path.isfile(dotgit):  # worktree / submodule: "gitdir: <path>"
        try:
            with open(dotgit, encoding="utf-8") as f:
                first = f.readline().strip()
        except OSError:
            return None
        if first.startswith("gitdir:"):
            target = first.split(":", 1)[1].strip()
            return os.path.normpath(os.path.join(repo_root, target))
    return None


def _config_path(git_dir: str) -> str:
    """Path of the repo config; linked worktrees (.git/worktrees/<name>)
    keep the shared config in the main .git dir named by ``commondir``."""
    cfg = os.path.join(git_dir, "config")
    if os.path.isfile(cfg):
        return cfg
    commondir = os.path.join(git_dir, "commondir")
    if os.path.isfile(commondir):
        try:
            with open(commondir, encoding="utf-8") as f:
                target = f.read().strip()
        except OSError:
            return cfg
        return os.path.join(os.path.normpath(os.path.join(git_dir, target)),
                            "config")
    return cfg


def get_remotes(repo_root: str) -> dict[str, str]:
    """remote name -> url from .git/config."""
    gd = _git_dir(repo_root)
    if not gd:
        return {}
    # strict=False: duplicate 'url =' lines are legal in git config
    # (remote set-url --add); interpolation=None: URLs may contain '%'
    parser = configparser.ConfigParser(strict=False, interpolation=None)
    remotes: dict[str, str] = {}
    try:
        parser.read(_config_path(gd))
        for section in parser.sections():
            m = re.match(r'remote "(.+)"', section)
            if m and parser.has_option(section, "url"):
                remotes[m.group(1)] = parser.get(section, "url")
    except (OSError, configparser.Error):
        return remotes
    return remotes


def get_branch(repo_root: str) -> str:
    gd = _git_dir(repo_root)
    if not gd:
        return ""
    try:
        with open(os.path.join(gd, "HEAD"), encoding="utf-8") as f:
            head = f.read().strip()
    except OSError:
        return ""
    if head.startswith("ref:"):
        ref = head.split(":", 1)[1].strip()
        # keep '/' in branch names like feature/foo
        return ref.removeprefix("refs/heads/")
    return ""  # detached


def get_git_repo_details(path: str) -> GitRepoDetails | None:
    """Repo info for the service at ``path``, preferring upstream over
    origin (utils.go:653; GetGitRemoteNames:636)."""
    root = find_repo_root(path)
    if not root:
        return None
    remotes = get_remotes(root)
    name, url = "", ""
    for preferred in PREFERRED_REMOTES:
        if preferred in remotes:
            name, url = preferred, remotes[preferred]
            break
    if not url and remotes:
        name = sorted(remotes)[0]
        url = remotes[name]
    return GitRepoDetails(repo_root=root, remote_name=name, url=url,
                          branch=get_branch(root))


def domain_of_git_url(url: str) -> str:
    """Hostname of an ssh/https git remote URL ('' if unparseable)."""
    if "://" in url:  # scheme://[user@]host[:port]/path
        m = re.match(r"\w+://(?:[\w.-]+@)?([\w.-]+)", url)
        return m.group(1) if m else ""
    m = re.match(r"(?:[\w.-]+@)?([\w.-]+):\S", url)  # scp-like git@host:path
    return m.group(1) if m else ""
