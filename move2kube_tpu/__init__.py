"""move2kube-tpu: re-platform applications onto Kubernetes with a TPU-first target.

A ground-up, TPU-native rebuild of the capabilities of Move2Kube
(reference: /root/reference, a pure-Go CLI — see SURVEY.md). The pipeline is:

    source dir -> Plan -> (QA curation) -> IR -> IR passes -> objects -> files

plus the net-new north star: detection of CUDA/NCCL/DeepSpeed GPU training
workloads and their translation into JAX/XLA TPU deployments (JobSet pod
slices with ``google.com/tpu`` resources), backed by a JAX model zoo
(``move2kube_tpu.models``) with real dp/fsdp/tp/sp sharding
(``move2kube_tpu.parallel``) and Pallas TPU kernels (``move2kube_tpu.ops``).
"""

__version__ = "0.1.0"

APP_NAME = "move2kube-tpu"
APP_NAME_SHORT = "m2kt"
GROUP_NAME = "move2kube-tpu.io"
API_VERSION = GROUP_NAME + "/v1alpha1"
