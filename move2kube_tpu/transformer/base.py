"""Shared transformer machinery: container file emission + object writing.

Parity: ``internal/transformer/transformer.go`` — ``write_containers``
dumps every Container's NewFiles under ``<out>/containers/<svc>/`` and
generates buildimages.sh / copysources.sh / pushimages.sh (:59-160);
``write_objects`` serializes k8s objects to YAML files (:162);
``get_transformer`` picks K8s vs Knative by artifact type (:51-56).
"""

from __future__ import annotations

import os

from move2kube_tpu.transformer import templates
from move2kube_tpu.types.ir import IR
from move2kube_tpu.types.plan import TargetArtifactType
from move2kube_tpu.utils import common
from move2kube_tpu.utils.log import get_logger

log = get_logger("transformer")


class Transformer:
    def transform(self, ir: IR) -> None:
        raise NotImplementedError

    def write_objects(self, out_dir: str, ir: IR) -> None:
        raise NotImplementedError


def get_transformer(ir: IR) -> "Transformer":
    from move2kube_tpu.transformer.k8s import K8sTransformer
    from move2kube_tpu.transformer.knative import KnativeTransformer

    if ir.kubernetes.effective_artifact_type() == TargetArtifactType.KNATIVE:
        return KnativeTransformer()
    return K8sTransformer()


def write_containers(out_dir: str, ir: IR, root_dir: str = "") -> None:
    """Emit generated container files + helper scripts (transformer.go:59-160)."""
    containers_dir = os.path.join(out_dir, common.CONTAINERS_DIR)
    build_scripts = []
    copies = []
    images = []
    manual = []
    for container in ir.containers:
        if not container.new:
            continue
        if not container.new_files:
            if container.image_names:
                manual.append(container.image_names[0])
            continue
        image = container.image_names[0] if container.image_names else "app:latest"
        svc_name = common.make_dns_label(image.split("/")[-1].split(":")[0])
        svc_dir = os.path.join(containers_dir, svc_name)
        for rel_path, contents in container.new_files.items():
            mode = 0o755 if rel_path.endswith(".sh") else 0o644
            common.write_file(os.path.join(svc_dir, rel_path), contents, mode)
            if rel_path.endswith("-build.sh") or rel_path.endswith("build.sh"):
                build_scripts.append({
                    "dir": os.path.join(common.CONTAINERS_DIR, svc_name),
                    "name": rel_path,
                })
        # local image name (no registry) for tagging
        local = image.split("/")[-1]
        if container.repo_info.git_repo_dir:
            copies.append({
                "rel_src": container.repo_info.git_repo_dir,
                "dst": os.path.join(common.CONTAINERS_DIR, svc_name),
            })
        else:
            copies.append({
                "rel_src": ".",
                "dst": os.path.join(common.CONTAINERS_DIR, svc_name),
            })
        images.append({"local": local, "remote": local})
    if build_scripts:
        common.write_file(
            os.path.join(out_dir, "buildimages.sh"),
            common.render_template(templates.BUILD_IMAGES_SH,
                                   {"build_scripts": build_scripts}),
            0o755,
        )
        common.write_file(
            os.path.join(out_dir, "copysources.sh"),
            common.render_template(templates.COPY_SOURCES_SH, {"copies": copies}),
            0o755,
        )
    if images:
        common.write_file(
            os.path.join(out_dir, "pushimages.sh"),
            common.render_template(templates.PUSH_IMAGES_SH, {
                "registry_url": ir.kubernetes.registry_url or common.DEFAULT_REGISTRY_URL,
                "registry_namespace": ir.kubernetes.registry_namespace or ir.name,
                "images": images,
            }),
            0o755,
        )
    if manual:
        common.write_file(
            os.path.join(out_dir, "Manualimages.md"),
            common.render_template(templates.MANUAL_IMAGES_MD, {"services": manual}),
        )


def write_objects(objs: list[dict], yaml_dir: str) -> list[str]:
    """One YAML file per object: <name>-<kind>.yaml (transformer.go:162)."""
    os.makedirs(yaml_dir, exist_ok=True)
    written = []
    for obj in objs:
        kind = obj.get("kind", "object").lower()
        name = obj.get("metadata", {}).get("name", "unnamed")
        fname = f"{common.make_dns_label(name)}-{kind}.yaml"
        path = os.path.join(yaml_dir, fname)
        common.write_yaml(path, obj)
        written.append(path)
    return written
