"""K8s transformer: IR -> Kubernetes YAMLs (or Helm chart) on disk.

Parity: ``internal/transformer/k8stransformer.go`` — per-kind version
conversion against the target cluster at write time, YAML files under
``<out>/<proj>/``, Helm mode (Chart.yaml / values.yaml / templates/ /
NOTES.txt + helminstall.sh), deploy.sh and README.
"""

from __future__ import annotations

import os
import shutil

from move2kube_tpu.apiresource.base import convert_objects
from move2kube_tpu.apiresource.deployment import DeploymentAPIResource
from move2kube_tpu.apiresource.imagestream import ImageStreamAPIResource
from move2kube_tpu.apiresource.knative import KnativeServiceAPIResource
from move2kube_tpu.apiresource.networkpolicy import NetworkPolicyAPIResource
from move2kube_tpu.apiresource.rbac import (
    RoleAPIResource,
    RoleBindingAPIResource,
    ServiceAccountAPIResource,
)
from move2kube_tpu.apiresource.service import ServiceAPIResource
from move2kube_tpu.apiresource.storage import StorageAPIResource
from move2kube_tpu.transformer import templates
from move2kube_tpu.transformer.base import Transformer, write_containers, write_objects
from move2kube_tpu.types.ir import IR
from move2kube_tpu.types.plan import TargetArtifactType
from move2kube_tpu.utils import common
from move2kube_tpu.utils.log import get_logger

log = get_logger("transformer.k8s")


def k8s_api_resources() -> list:
    """Parity: K8sAPIResourceSet.getAPIResources (k8sapiresourceset.go:54).

    NetworkPolicy must run before Deployment: it writes network-membership
    labels onto IR services, which the workload creators snapshot into pod
    templates.
    """
    return [
        NetworkPolicyAPIResource(),
        DeploymentAPIResource(),
        StorageAPIResource(),
        ServiceAPIResource(),
        ImageStreamAPIResource(),
        ServiceAccountAPIResource(),
        RoleAPIResource(),
        RoleBindingAPIResource(),
        KnativeServiceAPIResource(),
    ]


class K8sTransformer(Transformer):
    def __init__(self) -> None:
        self.objs: list[dict] = []

    def transform(self, ir: IR) -> None:
        self.objs = convert_objects(ir, k8s_api_resources())

    def write_objects(self, out_dir: str, ir: IR) -> None:
        proj = common.make_dns_label(ir.name)
        write_containers(out_dir, ir)
        helm = ir.kubernetes.effective_artifact_type() == TargetArtifactType.HELM
        if helm:
            self._write_helm(out_dir, ir, proj)
            yaml_dir_rel = os.path.join(proj, "templates")
        else:
            yaml_dir_rel = proj
            write_objects(self.objs, os.path.join(out_dir, proj))
            common.write_file(
                os.path.join(out_dir, "deploy.sh"),
                common.render_template(templates.DEPLOY_SH, {"yaml_dir": proj}),
                0o755,
            )
        has_tpu = any(svc.accelerator is not None for svc in ir.services.values())
        common.write_file(
            os.path.join(out_dir, "README.md"),
            common.render_template(templates.K8S_README_MD, {
                "project": ir.name,
                "yaml_dir": yaml_dir_rel,
                "cluster": ir.kubernetes.target_cluster.type or "Kubernetes",
                "registry": ir.kubernetes.registry_url or common.DEFAULT_REGISTRY_URL,
                "has_tpu": has_tpu,
            }),
        )

    def _write_helm(self, out_dir: str, ir: IR, proj: str) -> None:
        """Helm chart scaffold (k8stransformer.go:157-219) plus a
        helm-operator scaffold (createOperator:219 — the reference execs
        `operator-sdk new --type=helm`; we emit the equivalent files
        directly so no tool is needed)."""
        chart_dir = os.path.join(out_dir, proj)
        common.write_file(
            os.path.join(chart_dir, "Chart.yaml"),
            common.render_template(templates.HELM_CHART_YAML, {"project": proj}),
        )
        common.write_yaml(os.path.join(chart_dir, "values.yaml"), ir.values.to_dict())
        common.write_file(
            os.path.join(chart_dir, "templates", "NOTES.txt"),
            common.render_template(templates.HELM_NOTES_TXT, {"project": proj}),
        )
        # objects go to templates/ with {{ }} refs preserved verbatim
        tmpl_dir = os.path.join(chart_dir, "templates")
        os.makedirs(tmpl_dir, exist_ok=True)
        for obj in self.objs:
            kind = obj.get("kind", "object").lower()
            name = obj.get("metadata", {}).get("name", "unnamed")
            fname = f"{common.make_dns_label(name)}-{kind}.yaml"
            text = common.to_yaml(obj)
            common.write_file(os.path.join(tmpl_dir, fname), text)
        common.write_file(
            os.path.join(out_dir, "helminstall.sh"),
            common.render_template(templates.HELM_INSTALL_SH,
                                   {"release": proj, "chart_dir": proj}),
            0o755,
        )
        self._write_operator(out_dir, proj, chart_dir)

    def _write_operator(self, out_dir: str, proj: str, chart_dir: str) -> None:
        """helm-operator scaffold wrapping the generated chart
        (k8stransformer.go createOperator:219)."""
        op_dir = os.path.join(out_dir, "operator")
        kind = "".join(p.capitalize() for p in proj.split("-"))
        if not kind or not kind[0].isalpha():
            kind = "App" + kind  # Kind must match ^[A-Z][a-zA-Z0-9]*$
        singular = kind.lower()
        params = {
            "project": proj,
            "group": "move2kube-tpu.io",
            "kind": kind,
            "singular": singular,
            "plural": singular + "s",
            "operator_image": f"{proj}-operator:latest",
        }
        files = {
            ("watches.yaml",): templates.OPERATOR_WATCHES_YAML,
            ("Dockerfile",): templates.OPERATOR_DOCKERFILE,
            ("README.md",): templates.OPERATOR_README_MD,
            ("deploy", "crds", f"{singular}_crd.yaml"): templates.OPERATOR_CRD_YAML,
            ("deploy", "samples", f"{singular}_cr.yaml"): templates.OPERATOR_CR_YAML,
            ("deploy", "operator.yaml"): templates.OPERATOR_DEPLOY_YAML,
            ("deploy", "rbac.yaml"): templates.OPERATOR_RBAC_YAML,
        }
        for rel, template in files.items():
            common.write_template_to_file(
                template, params, os.path.join(op_dir, *rel))
        # the operator image embeds the chart: ship a copy beside it
        dest = os.path.join(op_dir, "helm-charts", proj)
        if os.path.isdir(chart_dir):
            shutil.copytree(chart_dir, dest, dirs_exist_ok=True)
