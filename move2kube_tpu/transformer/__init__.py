from move2kube_tpu.transformer.base import get_transformer, write_containers  # noqa: F401
