"""Knative transformer.

Parity: ``internal/transformer/knativetransformer.go:46-100`` +
``internal/apiresourceset/knativeapiresourceset.go`` — one Knative Service
per IR service (built by ``KnativeServiceAPIResource(create=True)``),
routed through the same apiresource engine as the K8s transformer so
cached knative objects merge by name and every emitted object gets the
write-time cluster version fix, then deploy script + README.
"""

from __future__ import annotations

import os

from move2kube_tpu.apiresource.base import convert_objects
from move2kube_tpu.apiresource.knative import KnativeServiceAPIResource
from move2kube_tpu.transformer import templates
from move2kube_tpu.transformer.base import Transformer, write_containers, write_objects
from move2kube_tpu.types.ir import IR
from move2kube_tpu.utils import common


class KnativeTransformer(Transformer):
    def __init__(self) -> None:
        self.objs: list[dict] = []

    def transform(self, ir: IR) -> None:
        self.objs = convert_objects(ir, [KnativeServiceAPIResource(create=True)])

    def write_objects(self, out_dir: str, ir: IR) -> None:
        proj = common.make_dns_label(ir.name)
        write_containers(out_dir, ir)
        write_objects(self.objs, os.path.join(out_dir, proj))
        common.write_file(
            os.path.join(out_dir, "deploy.sh"),
            common.render_template(templates.DEPLOY_SH, {"yaml_dir": proj}),
            0o755,
        )
        common.write_file(
            os.path.join(out_dir, "README.md"),
            common.render_template(templates.KNATIVE_README_MD,
                                   {"project": ir.name, "yaml_dir": proj}),
        )
