"""CI/CD transformer: Tekton pipeline for building the new images.

Parity: ``internal/transformer/cicdtransformer.go`` + ``internal/
apiresourceset/tektonapiresourceset.go`` (setupIR :101-240) + the Tekton
apiresource quad — a git-clone + kaniko Pipeline per project, with the
EventListener / TriggerBinding / TriggerTemplate chain, registry secret,
service account and RBAC, written under ``<out>/cicd/``.
"""

from __future__ import annotations

import os

from move2kube_tpu.apiresource.base import make_obj
from move2kube_tpu.transformer.base import Transformer, write_objects
from move2kube_tpu.types.ir import IR
from move2kube_tpu.utils import common, gitinfo, sshkeys
from move2kube_tpu.utils.log import get_logger

log = get_logger("transformer.cicd")


class CICDTransformer(Transformer):
    def __init__(self) -> None:
        self.objs: list[dict] = []

    def transform(self, ir: IR) -> None:
        proj = common.make_dns_label(ir.name)
        new_containers = [c for c in ir.containers if c.new and c.image_names]
        new_images = [c.image_names[0] for c in new_containers]
        if not new_images:
            self.objs = []
            return
        prefix = proj + "-clone-build-push"
        pipeline_name = prefix + "-pipeline"
        sa_name = prefix + "-sa"
        registry_secret = prefix + "-registry-secret"
        git_event_secret = prefix + "-git-event-secret"

        # detected git remotes: default clone URL + per-domain ssh secrets
        repo_urls = [c.repo_info.git_repo_url for c in new_containers
                     if c.repo_info.git_repo_url]
        # both defaults from the same container — mixing a URL from one
        # repo with a branch from another yields an unclonable revision
        first_with_url = next((c for c in new_containers
                               if c.repo_info.git_repo_url), None)
        default_repo_url = first_with_url.repo_info.git_repo_url \
            if first_with_url else ""
        default_branch = (first_with_url.repo_info.git_repo_branch
                          if first_with_url else "") or "main"

        tasks = []
        for i, image in enumerate(new_images):
            tasks.append({
                "name": f"build-push-{i}",
                "taskRef": {"name": "kaniko"},
                "runAfter": ["clone"] if i == 0 else [f"build-push-{i-1}"],
                "params": [
                    {"name": "IMAGE", "value": image},
                    {"name": "CONTEXT", "value": "."},
                ],
                "workspaces": [{"name": "source", "workspace": "shared-data"}],
            })
        pipeline = make_obj("Pipeline", "tekton.dev/v1beta1", pipeline_name)
        url_param: dict = {"name": "git-repo-url", "type": "string"}
        if default_repo_url:
            url_param["default"] = default_repo_url
        pipeline["spec"] = {
            "params": [
                url_param,
                {"name": "git-revision", "type": "string",
                 "default": default_branch},
            ],
            "workspaces": [{"name": "shared-data"}],
            "tasks": [{
                "name": "clone",
                "taskRef": {"name": "git-clone"},
                "params": [
                    {"name": "url", "value": "$(params.git-repo-url)"},
                    {"name": "revision", "value": "$(params.git-revision)"},
                ],
                "workspaces": [{"name": "output", "workspace": "shared-data"}],
            }] + tasks,
        }

        trigger_template = make_obj("TriggerTemplate", "triggers.tekton.dev/v1alpha1",
                                    prefix + "-triggertemplate")
        trigger_template["spec"] = {
            "params": [{"name": "git-repo-url"}, {"name": "git-revision"}],
            "resourcetemplates": [{
                "apiVersion": "tekton.dev/v1beta1",
                "kind": "PipelineRun",
                "metadata": {"generateName": pipeline_name + "-run-"},
                "spec": {
                    "serviceAccountName": sa_name,
                    "pipelineRef": {"name": pipeline_name},
                    "params": [
                        {"name": "git-repo-url", "value": "$(tt.params.git-repo-url)"},
                        {"name": "git-revision", "value": "$(tt.params.git-revision)"},
                    ],
                    "workspaces": [{
                        "name": "shared-data",
                        "volumeClaimTemplate": {"spec": {
                            "accessModes": ["ReadWriteOnce"],
                            "resources": {"requests": {"storage": "1Gi"}},
                        }},
                    }],
                },
            }],
        }

        trigger_binding = make_obj("TriggerBinding", "triggers.tekton.dev/v1alpha1",
                                   prefix + "-triggerbinding")
        trigger_binding["spec"] = {
            "params": [
                {"name": "git-repo-url", "value": "$(body.repository.clone_url)"},
                {"name": "git-revision", "value": "$(body.head_commit.id)"},
            ],
        }

        event_listener = make_obj("EventListener", "triggers.tekton.dev/v1alpha1",
                                  prefix + "-eventlistener")
        event_listener["spec"] = {
            "serviceAccountName": sa_name,
            "triggers": [{
                "name": prefix + "-trigger",
                "bindings": [{"ref": trigger_binding["metadata"]["name"]}],
                "template": {"ref": trigger_template["metadata"]["name"]}},
            ],
        }

        registry_sec = make_obj("Secret", "v1", registry_secret)
        registry_sec["type"] = "kubernetes.io/dockerconfigjson"
        registry_sec["stringData"] = {".dockerconfigjson": '{"auths": {}}'}
        git_sec = make_obj("Secret", "v1", git_event_secret)
        git_sec["stringData"] = {"secretToken": "m2kt-webhook-token"}

        # per-git-domain SSH auth secrets so git-clone can pull private
        # repos (tektonapiresourceset.go createGitSecret:242, sshkeys.go)
        ssh_secrets: list[dict] = []
        domains = sorted({gitinfo.domain_of_git_url(u) for u in repo_urls}
                         - {""})
        for domain in domains:
            sec = make_obj("Secret", "v1",
                           f"{prefix}-git-ssh-{common.make_dns_label(domain)}")
            sec["type"] = "kubernetes.io/ssh-auth"
            sec["metadata"].setdefault("annotations", {})[
                "tekton.dev/git-0"] = domain
            sec["stringData"] = sshkeys.git_secret_data(domain)
            ssh_secrets.append(sec)

        sa = make_obj("ServiceAccount", "v1", sa_name)
        sa["secrets"] = [{"name": registry_secret}] + [
            {"name": s["metadata"]["name"]} for s in ssh_secrets]
        role = make_obj("Role", "rbac.authorization.k8s.io/v1", prefix + "-role")
        role["rules"] = [
            {"apiGroups": ["triggers.tekton.dev"],
             "resources": ["eventlisteners", "triggerbindings", "triggertemplates"],
             "verbs": ["get"]},
            {"apiGroups": ["tekton.dev"],
             "resources": ["pipelineruns", "pipelineresources", "taskruns"],
             "verbs": ["create"]},
        ]
        binding = make_obj("RoleBinding", "rbac.authorization.k8s.io/v1",
                           prefix + "-rolebinding")
        binding["subjects"] = [{"kind": "ServiceAccount", "name": sa_name}]
        binding["roleRef"] = {"kind": "Role", "name": role["metadata"]["name"],
                              "apiGroup": "rbac.authorization.k8s.io"}

        self.objs = [pipeline, trigger_template, trigger_binding, event_listener,
                     registry_sec, git_sec, *ssh_secrets, sa, role, binding]
        ir.tekton.pipelines = [pipeline]
        ir.tekton.event_listeners = [event_listener]
        ir.tekton.trigger_bindings = [trigger_binding]
        ir.tekton.trigger_templates = [trigger_template]

    def write_objects(self, out_dir: str, ir: IR) -> None:
        if self.objs:
            write_objects(self.objs, os.path.join(out_dir, common.CICD_DIR))
