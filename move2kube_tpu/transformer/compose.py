"""Compose transformer: IR -> docker-compose.yaml for local validation.

Parity: ``internal/transformer/composetransformer.go:48-103`` — v3.5
document, sequential published ports starting at 8080.
"""

from __future__ import annotations

import os

from move2kube_tpu.transformer.base import Transformer
from move2kube_tpu.types.ir import IR
from move2kube_tpu.utils import common


class ComposeTransformer(Transformer):
    def __init__(self) -> None:
        self.doc: dict = {}

    def transform(self, ir: IR) -> None:
        services = {}
        next_port = 8080
        for name, svc in sorted(ir.services.items()):
            if not svc.containers:
                continue
            c = svc.containers[0]
            entry: dict = {"image": c.get("image", name + ":latest")}
            if c.get("command"):
                entry["entrypoint"] = c["command"]
            if c.get("args"):
                entry["command"] = c["args"]
            env = c.get("env")
            if env:
                entry["environment"] = {e["name"]: e.get("value", "") for e in env}
            ports = []
            for pf in svc.port_forwardings:
                ports.append(f"{next_port}:{pf.container_port}")
                next_port += 1
            if ports:
                entry["ports"] = ports
            if svc.restart_policy == "Never":
                entry["restart"] = "no"
            elif svc.restart_policy == "OnFailure":
                entry["restart"] = "on-failure"
            services[name] = entry
        self.doc = {"version": "3.5", "services": services}

    def write_objects(self, out_dir: str, ir: IR) -> None:
        if self.doc.get("services"):
            common.write_yaml(os.path.join(out_dir, "docker-compose.yaml"), self.doc)
