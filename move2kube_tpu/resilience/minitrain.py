"""Tiny real trainer wired through the whole resilience stack.

The fault-injection harness needs a *runnable* training child — real
jit-compiled steps, real orbax checkpoints, real resume — that finishes
in seconds on one CPU device. This module is that child: the CI target
for kill-at-step-N / corrupt-checkpoint proofs (``tests/test_resilience``,
``make fault-smoke``) and the workload behind ``bench.py``'s goodput
phase. It deliberately mirrors the structure of the emitted
``train_tpu.py`` loop (restore → step/fault/save → preempt check →
goodput flush) so what CI proves here is the same control flow the
emitted trainers run on a slice.

Run under the supervisor::

    python -m move2kube_tpu.resilience.supervisor -- \
        python -m move2kube_tpu.resilience.minitrain

Knobs: ``M2KT_STEPS`` (default 8), ``M2KT_CKPT_DIR``/``M2KT_CKPT_EVERY``
(checkpointing off when unset, like the emitted trainers),
``M2KT_STEP_SLEEP_S`` (default 0 — pad steps so goodput numbers have
visible magnitude), plus every ``M2KT_FAULT_*`` / ``M2KT_PREEMPT_*``
knob from :mod:`faults` and :mod:`preemption`.
"""

from __future__ import annotations

import os
import sys
import time


def main() -> None:
    # a CPU harness by definition: never grab a TPU someone is using
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from flax import linen as nn

    from move2kube_tpu.models import checkpoint as m2kt_ckpt
    from move2kube_tpu.models import train as m2kt_train
    from move2kube_tpu.parallel.mesh import MeshConfig, make_mesh
    from move2kube_tpu.resilience import faults, goodput, preemption

    steps = int(os.environ.get("M2KT_STEPS", "8"))
    step_sleep = float(os.environ.get("M2KT_STEP_SLEEP_S", "0"))
    batch, dim = 4, 8

    gp = goodput.GoodputTracker()
    watcher = preemption.from_env()
    if watcher is not None:
        watcher.install()

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(4)(nn.relu(nn.Dense(8)(x)))

    mesh = make_mesh(MeshConfig(data=jax.device_count()))
    sample = {"x": jnp.zeros((batch, dim))}
    state = m2kt_train.create_sharded_state(
        jax.random.PRNGKey(0), Tiny(), sample, optax.sgd(1e-2), mesh)

    def step_fn(state, x):
        def loss_fn(params):
            out = state.apply_fn({"params": params}, x)
            return jnp.mean(out * out)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads=grads), loss

    step_fn = jax.jit(step_fn, donate_argnums=(0,))

    ckpt = m2kt_ckpt.from_env(default_every=1)
    start = 0
    if ckpt is not None:
        with gp.phase("restore"):
            state, start = ckpt.restore_or_init(state)
        if start:
            gp.note_resume(start)
            gp.note_saved(start)
            print(f"[m2kt] resumed from step {start}", flush=True)

    def make_batch(i: int) -> jnp.ndarray:
        return jnp.asarray(
            np.random.default_rng(i).random((batch, dim), np.float32))

    preempted_at = None
    loss = None
    for i in range(start + 1, steps + 1):
        faults.maybe_inject(i)
        t0 = time.perf_counter()
        state, loss = step_fn(state, make_batch(i))
        jax.block_until_ready(loss)
        if step_sleep:
            time.sleep(step_sleep)
        gp.add("compile" if i == start + 1 else "productive",
               time.perf_counter() - t0, steps=1)
        if ckpt is not None and ckpt.maybe_save(i, state):
            # synchronous commit: the fault tests assert resume-from-N, so
            # a save the loop reports must be durable before a kill can land
            ckpt.wait()
            gp.note_saved(i)
            gp.write()
        if watcher is not None and watcher.should_stop(i):
            preempted_at = i
            break
    if ckpt is not None:
        last = preempted_at if preempted_at is not None else steps
        with gp.phase("save"):
            if last >= start + 1:
                ckpt.maybe_save(last, state, force=True)
            ckpt.close()  # block: the last save must land before exit
        gp.note_saved(last)
    if loss is not None:
        print(f"[m2kt] step={gp.steps_done} loss={float(loss):.4f}",
              flush=True)
    gp.write()
    rep = gp.report()
    if preempted_at is not None:
        print(f"[m2kt] preempted: last-chance checkpoint at step "
              f"{preempted_at}; goodput={rep['goodput_fraction']:.2%}",
              flush=True)
        sys.exit(143)
    print(f"[m2kt] done steps={steps} "
          f"goodput={rep['goodput_fraction']:.2%}", flush=True)


if __name__ == "__main__":
    main()
