"""Tiny real trainer wired through the whole resilience stack.

The fault-injection harness needs a *runnable* training child — real
jit-compiled steps, real orbax checkpoints, real resume — that finishes
in seconds on one CPU device. This module is that child: the CI target
for kill-at-step-N / corrupt-checkpoint proofs (``tests/test_resilience``,
``make fault-smoke``), the 2-slice elastic drill (``tests/test_elastic``,
``make elastic-smoke``) and the workload behind ``bench.py``'s goodput
phase. It deliberately mirrors the structure of the emitted
``train_tpu.py`` loop (plan mesh → restore → step/fault/save → preempt
check → goodput flush) so what CI proves here is the same control flow
the emitted trainers run on a slice.

Run under the supervisor::

    python -m move2kube_tpu.resilience.supervisor -- \
        python -m move2kube_tpu.resilience.minitrain

Multislice on CPU: ``M2KT_FORCE_DEVICES=N`` forces an N-device host
platform (rewrites ``XLA_FLAGS`` before jax loads), and
``M2KT_NUM_SLICES=K`` makes the planner lay a ``dcn_dp=K`` outer data
axis over them — a faithful single-process model of K DCN-connected
slices. The elastic supervisor shrinks both after a slice loss, so the
restarted attempt genuinely re-plans for a smaller world.

Batch: global batch = ``M2KT_BATCH_PER_DEVICE`` (default 4) x the
planned data x fsdp extents. Each step's batch is seeded by the step
number alone, so two runs with the same *global* batch see identical
data regardless of how many slices shard it — the loss-continuity
invariant the elastic drill asserts.

Knobs: ``M2KT_STEPS`` (default 8), ``M2KT_CKPT_DIR``/``M2KT_CKPT_EVERY``
(checkpointing off when unset, like the emitted trainers),
``M2KT_STEP_SLEEP_S`` (default 0 — pad steps so goodput numbers have
visible magnitude), plus every ``M2KT_FAULT_*`` / ``M2KT_PREEMPT_*``
knob from :mod:`faults` and :mod:`preemption`.
"""

from __future__ import annotations

import os
import sys
import time

_FORCE_FLAG = "--xla_force_host_platform_device_count"


def apply_forced_devices(environ=None) -> int | None:
    """Honor ``M2KT_FORCE_DEVICES`` by rewriting ``XLA_FLAGS`` in place.

    Must run before jax is imported — the flag is read once at backend
    init. Returns the forced count, or None when the knob is unset or
    malformed (existing flags untouched). Any prior force flag (e.g. the
    test conftest's 8-device default) is replaced, not appended: XLA
    takes the first occurrence, so appending would silently lose.
    """
    env = os.environ if environ is None else environ
    raw = env.get("M2KT_FORCE_DEVICES", "")
    if not raw.isdigit() or int(raw) < 1:
        return None
    n = int(raw)
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if not f.startswith(_FORCE_FLAG)]
    flags.append(f"{_FORCE_FLAG}={n}")
    env["XLA_FLAGS"] = " ".join(flags)
    return n


def main() -> None:
    # a CPU harness by definition: never grab a TPU someone is using
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    apply_forced_devices()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from flax import linen as nn

    from move2kube_tpu.models import checkpoint as m2kt_ckpt
    from move2kube_tpu.models import train as m2kt_train
    from move2kube_tpu.obs import tracing
    from move2kube_tpu.parallel.mesh import make_mesh
    from move2kube_tpu.parallel.topology import resolve_mesh_plan
    from move2kube_tpu.resilience import faults, goodput, preemption

    # runtime tracing: per-step spans into the bounded ring, flushed to
    # <flight>.ring on every teardown-running exit path (incl. the
    # injected sys.exit(83) slice loss) so the supervisor's flight
    # recorder can reconstruct the final seconds of a dead attempt
    tracer = tracing.get() if tracing.enabled() else None
    if tracer is not None:
        tracing.install_ring_flush()

    # same telemetry surface as the emitted trainers when a port is set:
    # /metrics then carries the cost-model gauges (m2kt_train_mfu et al.)
    # the mfu-smoke CI target scrapes off this harness
    from move2kube_tpu.obs import start_telemetry_server

    server = start_telemetry_server()
    if server is not None:
        print(f"[m2kt] metrics on :{server.port}", flush=True)

    steps = int(os.environ.get("M2KT_STEPS", "8"))
    step_sleep = float(os.environ.get("M2KT_STEP_SLEEP_S", "0"))
    bpd = int(os.environ.get("M2KT_BATCH_PER_DEVICE", "4") or 4)
    dim = 8

    gp = goodput.GoodputTracker()
    watcher = preemption.from_env()
    if watcher is not None:
        watcher.install()

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(4)(nn.relu(nn.Dense(8)(x)))

    # same startup as the emitted trainers: plan (num_slices from
    # M2KT_NUM_SLICES — shrunk by the elastic supervisor after a slice
    # loss), then lay the mesh in plan order
    plan = resolve_mesh_plan(jax.device_count())
    mesh = make_mesh(plan)
    straggler = None
    host = ""
    if tracer is not None:
        from move2kube_tpu.obs.bridge import StragglerDetector

        straggler = StragglerDetector()
        host = tracer.host
    batch = bpd * plan.config.data * plan.config.fsdp
    print(f"[m2kt] plan: {plan.describe()} devices={jax.device_count()} "
          f"global_batch={batch}", flush=True)
    sample = {"x": jnp.zeros((batch, dim))}
    state = m2kt_train.create_sharded_state(
        jax.random.PRNGKey(0), Tiny(), sample, optax.sgd(1e-2), mesh)

    def step_fn(state, x):
        def loss_fn(params):
            out = state.apply_fn({"params": params}, x)
            return jnp.mean(out * out)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads=grads), loss

    step_fn = jax.jit(step_fn, donate_argnums=(0,))

    ckpt = m2kt_ckpt.from_env(default_every=1)
    start = 0
    if ckpt is not None:
        with gp.phase("restore"):
            state, start = ckpt.restore_or_init(state)
        if start:
            gp.note_resume(start)
            gp.note_saved(start)
            print(f"[m2kt] resumed from step {start}", flush=True)

    def make_batch(i: int) -> jnp.ndarray:
        # seeded by step alone: the data stream is a function of (step,
        # global batch), never of the mesh — an elastic restart that
        # preserves the global batch sees bit-identical inputs
        return jnp.asarray(
            np.random.default_rng(i).random((batch, dim), np.float32))

    preempted_at = None
    loss = None
    costed = False
    try:
        for i in range(start + 1, steps + 1):
            faults.maybe_inject(i)
            t0 = time.perf_counter()
            state, loss = step_fn(state, make_batch(i))
            jax.block_until_ready(loss)
            if step_sleep:
                time.sleep(step_sleep)
            t1 = time.perf_counter()
            if not costed:
                # compiled-program cost model (obs/costmodel.py): FLOPs /
                # roofline / peak-HBM gauges off the executable that just
                # compiled, MFU from this first measured step; also arms
                # the OOM memory-snapshot sidecar for the flight recorder
                costed = True
                from move2kube_tpu.obs import costmodel

                report = costmodel.analyze_step_fn(
                    step_fn, state, make_batch(i + 1))
                if report is not None:
                    mfu = costmodel.export_train_gauges(
                        report, step_seconds=t1 - t0)
                    costmodel.install_memory_snapshot()
                    ai = report.arithmetic_intensity
                    print(f"[m2kt] costmodel: flops={report.flops} "
                          f"intensity="
                          f"{f'{ai:.2f}' if ai is not None else '-'} "
                          f"mfu={f'{mfu:.3%}' if mfu is not None else '-'}",
                          flush=True)
            if tracer is not None:
                tracer.record(
                    "train.compile" if i == start + 1 else "train.step",
                    t0, t1, attrs={"step": i})
            gp.add("compile" if i == start + 1 else "productive",
                   t1 - t0, steps=1)
            if straggler is not None and i != start + 1:
                # one report per simulated slice: the forced-host drill
                # runs every slice in this process so the dt is shared,
                # but the scoring/gauge path is the same one a per-host
                # reporter feeds on real multislice
                for s in range(max(1, plan.dcn_dp)):
                    straggler.report(f"{host}/s{s}", i, t1 - t0)
            if ckpt is not None and ckpt.maybe_save(i, state):
                # synchronous commit: the fault tests assert resume-from-N,
                # so a save the loop reports must be durable before a kill
                # can land
                ckpt.wait()
                gp.note_saved(i)
                gp.write()
            if watcher is not None and watcher.should_stop(i):
                preempted_at = i
                break
    except SystemExit:
        # injected fault (slice_loss exits 83, exit kind exits N) — an
        # async save still in flight must land before the process dies,
        # or the supervisor's restarted attempt resumes one cadence
        # early. The goodput report is deliberately NOT re-flushed here:
        # post-checkpoint work is the supervisor's "lost" span.
        if ckpt is not None:
            ckpt.wait()
        raise
    if ckpt is not None:
        last = preempted_at if preempted_at is not None else steps
        with gp.phase("save"):
            if last >= start + 1:
                ckpt.maybe_save(last, state, force=True)
            ckpt.close()  # block: the last save must land before exit
        gp.note_saved(last)
    if loss is not None:
        print(f"[m2kt] step={gp.steps_done} loss={float(loss):.6f}",
              flush=True)
    if straggler is not None and straggler.scores():
        worst = max(straggler.scores().items(), key=lambda kv: kv[1])
        print(f"[m2kt] straggler: hosts={len(straggler.scores())} "
              f"worst={worst[0]} score={worst[1]:.3f} "
              f"events={straggler.events}", flush=True)
    gp.write()
    rep = gp.report()
    if preempted_at is not None:
        print(f"[m2kt] preempted: last-chance checkpoint at step "
              f"{preempted_at}; goodput={rep['goodput_fraction']:.2%}",
              flush=True)
        sys.exit(143)
    print(f"[m2kt] done steps={steps} "
          f"goodput={rep['goodput_fraction']:.2%}", flush=True)


if __name__ == "__main__":
    main()
