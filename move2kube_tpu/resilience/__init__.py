"""Preemption-aware resilience for emitted TPU training workloads.

GKE TPU slices are preemptible by design: maintenance events, spot
reclaims and host failures are the normal case, not the exception. This
package makes the emitted training pods survive them cheaply and makes
the cost measurable:

- ``preemption``  — SIGTERM / preStop-sentinel watcher that coordinates a
  multihost last-chance synchronous checkpoint inside the pod's
  termination grace period;
- ``supervisor``  — in-pod retry wrapper around the trainer: classifies
  fatal vs. retryable exits, restarts with exponential backoff, writes a
  structured exit-reason file;
- ``faults``      — deterministic CPU-CI fault injection (die at step N,
  corrupt/truncate the latest checkpoint) so resume paths are provable
  in tier-1 without TPUs;
- ``goodput``     — goodput/badput accounting (productive step time vs.
  compile/restore/save/retry/lost), flushed to a JSON report and
  mirrored into ``utils.trace`` counters;
- ``minitrain``   — a tiny real JAX trainer wired through all of the
  above; the fault-injection harness target for CI and `bench.py`.

Dependency-light on purpose: the jax-xla containerizer vendors this
package into every emitted image (stdlib + lazy jax imports only).
"""
