"""Goodput/badput accounting for training runs.

Goodput — the fraction of wall-clock spent making forward progress — is
the metric that actually decides TPU-vs-GPU cost on preemptible capacity
(PAPERS.md, Gemma-on-TPU comparison): a slice that restarts every hour
with a 10-minute recovery tail has 83% goodput no matter how fast its
steps are. Every emitted trainer owns a :class:`GoodputTracker`; the
supervisor merges per-attempt reports into a pod-level summary with the
lost span (time between the last flushed checkpoint and the death).

Categories:

- ``productive`` — time spent in training steps that were checkpointed
  (or ran to completion);
- ``compile``    — the first step's trace+compile (badput: recurs on
  every uncached restart);
- ``restore``    — checkpoint restore at startup;
- ``save``       — synchronous checkpoint waits (async saves overlap
  compute and cost ~nothing; the last-chance save is synchronous);
- ``retry``      — supervisor backoff sleeps between attempts;
- ``replan``     — elastic slice-loss recovery: the supervisor's pause
  before relaunching with a re-planned (shrunken ``dcn_dp``) mesh —
  kept separate from ``retry`` because it is the price of surviving
  capacity reclaim, not of flaky code;
- ``lost``       — work after the last checkpoint flush that a failure
  threw away (recomputed on resume).

Stdlib-only (vendored into emitted images); mirrors into
``utils.trace`` counters when that module is importable.
"""

from __future__ import annotations

import json
import os
import time
from contextlib import contextmanager

CATEGORIES = ("productive", "compile", "restore", "save", "retry", "replan",
              "lost")

DEFAULT_FILENAME = "m2kt-goodput.json"


def report_path() -> str:
    """Where this process flushes its goodput report (M2KT_GOODPUT_FILE,
    else M2KT_METRICS_DIR, else the working directory)."""
    explicit = os.environ.get("M2KT_GOODPUT_FILE", "")
    if explicit:
        return explicit
    out_dir = os.environ.get("M2KT_METRICS_DIR", "") or "."
    return os.path.join(out_dir, DEFAULT_FILENAME)


class GoodputTracker:
    """Accumulate per-category seconds + step progress for one attempt.

    The tracker is flushed to disk on every checkpoint save (cheap: one
    small JSON dump), so after an abrupt death the supervisor still sees
    the state as of the last checkpoint — exactly the survivable part of
    the run — and can attribute everything after it to ``lost``.
    """

    def __init__(self) -> None:
        self.seconds: dict[str, float] = {c: 0.0 for c in CATEGORIES}
        self.steps_done = 0
        self.last_saved_step = 0
        self.resumed_from = 0
        self.started = time.time()

    def add(self, category: str, seconds: float, steps: int = 0) -> None:
        self.seconds[category] = self.seconds.get(category, 0.0) + seconds
        if steps:
            self.steps_done += steps

    @contextmanager
    def phase(self, category: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(category, time.perf_counter() - t0)

    def note_resume(self, step: int) -> None:
        self.resumed_from = step
        self.steps_done = step

    def note_saved(self, step: int) -> None:
        self.last_saved_step = max(self.last_saved_step, step)

    def report(self) -> dict:
        wall = time.time() - self.started
        accounted = sum(self.seconds.values())
        productive = self.seconds["productive"]
        denom = max(wall, accounted, 1e-9)
        return {
            "wall_seconds": round(wall, 3),
            "seconds": {k: round(v, 3) for k, v in self.seconds.items()},
            "goodput_fraction": round(productive / denom, 4),
            "steps_done": self.steps_done,
            "last_saved_step": self.last_saved_step,
            "resumed_from": self.resumed_from,
        }

    def write(self, path: str | None = None) -> str:
        path = path or report_path()
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(self.report(), f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)  # atomic: a kill mid-dump can't corrupt it
        return path


def read_report(path: str) -> dict | None:
    """Best-effort read of a flushed report (None when absent/corrupt)."""
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def merge_attempts(attempts: list[dict]) -> dict:
    """Pod-level summary across supervisor attempts.

    Each entry: ``{"report": <flushed report or None>, "wall_seconds":
    <attempt wall as measured by the supervisor>, "ok": bool}``. For a
    failed attempt the span between its last flush and its death is
    unrecorded by definition — the supervisor measured the attempt's
    true wall clock, so everything the flushed report doesn't account
    for is ``lost`` (work thrown away + the death tail).
    """
    totals = {c: 0.0 for c in CATEGORIES}
    steps = last_saved = 0
    for att in attempts:
        rep = att.get("report") or {}
        secs = rep.get("seconds", {})
        for c in CATEGORIES:
            totals[c] += float(secs.get(c, 0.0))
        steps = max(steps, int(rep.get("steps_done", 0)))
        last_saved = max(last_saved, int(rep.get("last_saved_step", 0)))
        if not att.get("ok"):
            accounted = sum(float(secs.get(c, 0.0)) for c in CATEGORIES)
            lost = max(0.0, float(att.get("wall_seconds", 0.0)) - accounted)
            totals["lost"] += lost
    wall = sum(float(a.get("wall_seconds", 0.0)) for a in attempts)
    denom = max(wall, sum(totals.values()), 1e-9)
    return {
        "attempts": len(attempts),
        "wall_seconds": round(wall, 3),
        "seconds": {k: round(v, 3) for k, v in totals.items()},
        "goodput_fraction": round(totals["productive"] / denom, 4),
        "steps_done": steps,
        "last_saved_step": last_saved,
    }


def mirror_to_obs(report: dict, registry=None) -> None:
    """Fold a report into an obs metrics registry (gauges: fraction,
    per-category seconds, step watermarks) so a pod's ``/metrics`` scrape
    carries goodput next to the step telemetry. No-op when the vendored
    image doesn't ship obs."""
    try:
        from move2kube_tpu.obs.bridge import mirror_goodput
    except Exception:  # noqa: BLE001 - slim vendored images
        return
    mirror_goodput(report, registry)


def mirror_to_trace(report: dict, prefix: str = "goodput") -> None:
    """Fold a report into ``utils.trace`` counters (milliseconds) so the
    pod metrics file carries goodput next to the pipeline spans. No-op
    when the vendored image doesn't ship trace (it does) or outside a
    recorder context."""
    try:
        from move2kube_tpu.utils import trace
    except Exception:  # noqa: BLE001 - slim vendored images
        return
    for cat, secs in report.get("seconds", {}).items():
        trace.count(f"{prefix}.{cat}_ms", int(secs * 1000))
    trace.count(f"{prefix}.steps_done", int(report.get("steps_done", 0)))
    frac = report.get("goodput_fraction")
    if frac is not None:
        trace.count(f"{prefix}.fraction_bp", int(float(frac) * 10000))
