"""In-pod supervisor: retry the trainer, classify its deaths, keep score.

The JobSet failure policy restarts whole jobs, but a full JobSet restart
re-runs ``jax.distributed`` bootstrap, re-schedules pods and (uncached)
recompiles — tens of badput minutes on big models. Cheap transient
failures (a flaky coordinator connection, an injected test fault, a
spurious crash) are better retried *inside* the pod, where the compile
cache and the mounted checkpoint are warm. This wrapper is the emitted
image's entrypoint::

    python -m move2kube_tpu.resilience.supervisor -- python train_tpu.py

Behavior:

- runs the trainer as a child, streaming its stderr through while
  keeping a tail for exit classification;
- classifies each death as ``ok`` / ``preempted`` / ``slice_lost`` /
  ``retryable`` / ``fatal`` (table below) and restarts retryable ones
  with exponential backoff, up to ``M2KT_RETRY_MAX`` attempts;
- **elastic mode** (``M2KT_ELASTIC=1``): a ``slice_lost`` death does not
  kill the pod — the supervisor re-plans for the survivors by shrinking
  ``M2KT_NUM_SLICES`` in the child's env (the trainer's
  ``resolve_mesh_plan`` reads it back and rebuilds the mesh with a
  smaller ``dcn_dp``), rescales ``M2KT_BATCH_PER_DEVICE`` to preserve
  the global batch when divisible (recording a degraded global batch
  otherwise), and restarts; the child restores from the last checkpoint
  into the smaller mesh. Elastic restarts don't burn the retry budget
  (slice reclaim is capacity weather, not a code bug) — they are bounded
  by ``M2KT_ELASTIC_MIN_SLICES`` (default 1) instead, below which the
  loss is terminal and the JobSet-level failure policy takes over. The
  pause before each elastic relaunch is charged to the goodput ledger's
  ``replan`` category and every event is recorded in the exit file;
- forwards SIGTERM to the child and stops retrying — a preempted pod is
  going away; the last-chance checkpoint already happened in the child;
- merges the per-attempt goodput reports (``resilience.goodput``) into a
  pod-level summary, mirrored into ``utils.trace`` counters and the pod
  metrics file;
- writes a structured exit-reason file (``M2KT_EXIT_FILE``, default
  ``m2kt-exit.json``) so the JobSet controller's restart decision — and
  the human debugging it — sees *why* the pod died, not just the code.

Classification table (first match wins):

====================  ==========  =======================================
signal / pattern      class       rationale
====================  ==========  =======================================
rc 0                  ok          trainer finished
SIGTERM / rc 143      preempted   node reclaim; don't fight the eviction
SIGKILL / rc 137      retryable   OOM-killer or host kill; warm restart
rc 83 / "slice        slice_lost  a whole DCN slice reclaimed; elastic
lost", "slice_loss"               mode re-plans on the survivors
SyntaxError,          fatal       the image is broken; a retry loop
ImportError,                      cannot fix code
ModuleNotFoundError
"exceeds the",        fatal       config rejected at startup (positional
"not divisible"                   table, mesh shape); deterministic
DEADLINE_EXCEEDED,    retryable   transient runtime/collective trouble
UNAVAILABLE,
connection/barrier/
heartbeat, libtpu,
RESOURCE_EXHAUSTED
anything else         retryable   optimistic but bounded by the retry
                                  budget; exhaustion reports the last rc
====================  ==========  =======================================
"""

from __future__ import annotations

import json
import logging
import os
import signal
import subprocess
import sys
import threading
import time
from collections import deque

from move2kube_tpu.resilience import goodput
from move2kube_tpu.resilience.faults import SLICE_LOST_EXIT_CODE

log = logging.getLogger("m2kt.supervisor")

OK = "ok"
PREEMPTED = "preempted"
SLICE_LOST = "slice_lost"
RETRYABLE = "retryable"
FATAL = "fatal"

# slice-loss signatures: the injected fault's stderr line and what a
# surviving slice's processes print when the megascale DCN transport
# loses its peers; checked before the generic fatal/retryable tables
SLICE_LOST_PATTERNS = (
    "FAULT: slice_loss", "slice lost", "SliceUnreachable",
    "megascale slice unreachable",
)

# substring tables over the stderr tail; fatal checked first
FATAL_PATTERNS = (
    "SyntaxError", "ImportError", "ModuleNotFoundError",
    "exceeds the", "not divisible",
)
RETRYABLE_PATTERNS = (
    "DEADLINE_EXCEEDED", "UNAVAILABLE", "RESOURCE_EXHAUSTED",
    "onnection", "Broken pipe", "barrier", "heartbeat",
    "libtpu", "TPU initialization", "FaultInjected", "injected transient",
)

STDERR_TAIL_CHARS = 4000
BACKOFF_CAP_S = 60.0


def classify(returncode: int, stderr_tail: str = "") -> str:
    """Map a child exit to ok / preempted / slice_lost / retryable /
    fatal."""
    if returncode == 0:
        return OK
    if returncode in (-signal.SIGTERM, 128 + signal.SIGTERM):
        return PREEMPTED
    if returncode == SLICE_LOST_EXIT_CODE:
        return SLICE_LOST
    for pat in SLICE_LOST_PATTERNS:
        if pat in stderr_tail:
            return SLICE_LOST
    if returncode in (-signal.SIGKILL, 128 + signal.SIGKILL):
        return RETRYABLE
    for pat in FATAL_PATTERNS:
        if pat in stderr_tail:
            return FATAL
    for pat in RETRYABLE_PATTERNS:
        if pat in stderr_tail:
            return RETRYABLE
    return RETRYABLE


def exit_file_path() -> str:
    explicit = os.environ.get("M2KT_EXIT_FILE", "")
    if explicit:
        return explicit
    out_dir = os.environ.get("M2KT_METRICS_DIR", "") or "."
    return os.path.join(out_dir, "m2kt-exit.json")


class Supervisor:
    def __init__(self, cmd: list[str], max_retries: int | None = None,
                 backoff_s: float | None = None,
                 exit_file: str | None = None):
        if max_retries is None:
            max_retries = int(os.environ.get("M2KT_RETRY_MAX", "3"))
        if backoff_s is None:
            backoff_s = float(os.environ.get("M2KT_RETRY_BACKOFF_S", "5"))
        self.cmd = list(cmd)
        self.max_retries = max(0, max_retries)
        self.backoff_s = max(0.0, backoff_s)
        self.exit_file = exit_file or exit_file_path()
        self.elastic = os.environ.get("M2KT_ELASTIC", "0") == "1"
        try:
            self.min_slices = max(1, int(
                os.environ.get("M2KT_ELASTIC_MIN_SLICES", "1") or 1))
        except ValueError:
            self.min_slices = 1
        self._child: subprocess.Popen | None = None
        self._got_sigterm = False
        self._attempts: list[dict] = []
        self._retry_sleep_total = 0.0
        self._replan_sleep_total = 0.0
        self._replan_events: list[dict] = []
        # env deltas for the NEXT attempt (elastic re-plan shrinks the
        # slice count here rather than mutating this process's environ)
        self._env_overrides: dict[str, str] = {}

    # -- signal forwarding --------------------------------------------------

    def _on_sigterm(self, signum, frame) -> None:
        self._got_sigterm = True
        child = self._child
        if child is not None and child.poll() is None:
            try:
                child.send_signal(signal.SIGTERM)
            except OSError:
                pass

    # -- one attempt --------------------------------------------------------

    def _run_once(self) -> tuple[int, str, float]:
        """Run the child once; returns (rc, stderr_tail, wall_seconds).
        Child stdout passes straight through; stderr is tee'd so the pod
        log is intact AND the tail is available for classification."""
        tail: deque[str] = deque(maxlen=200)
        t0 = time.monotonic()
        env = ({**os.environ, **self._env_overrides}
               if self._env_overrides else None)
        self._child = subprocess.Popen(
            self.cmd, stderr=subprocess.PIPE, text=True, errors="replace",
            env=env)

        def _tee(pipe):
            for line in pipe:
                sys.stderr.write(line)
                tail.append(line)
            pipe.close()

        t = threading.Thread(target=_tee, args=(self._child.stderr,),
                             daemon=True)
        t.start()
        rc = self._child.wait()
        t.join(timeout=10.0)
        self._child = None
        return rc, "".join(tail)[-STDERR_TAIL_CHARS:], time.monotonic() - t0

    # -- the loop -----------------------------------------------------------

    def run(self) -> int:
        prev = signal.signal(signal.SIGTERM, self._on_sigterm)
        try:
            return self._run_supervised()
        finally:
            signal.signal(signal.SIGTERM, prev)

    def _run_supervised(self) -> int:
        gp_path = goodput.report_path()
        attempt = 0
        while True:
            attempt += 1
            # stale report from the previous attempt must not be re-read
            # if this attempt dies before its first flush
            try:
                os.remove(gp_path)
            except OSError:
                pass
            rc, tail, wall = self._run_once()
            clazz = classify(rc, tail)
            if self._got_sigterm:
                clazz = PREEMPTED
            report = goodput.read_report(gp_path)
            self._attempts.append({
                "attempt": attempt, "returncode": rc, "class": clazz,
                "wall_seconds": round(wall, 3),
                "stderr_tail": tail[-2000:],
                "report": report, "ok": clazz == OK,
            })
            log.warning("attempt %d exited rc=%d class=%s", attempt, rc, clazz)
            if clazz in (RETRYABLE, FATAL, SLICE_LOST):
                # flight recorder: capture THIS death's context now —
                # an elastic re-plan or a successful retry will end the
                # pod with class ok, but the flight from the dead
                # attempt is exactly what the postmortem needs
                self._write_flight(clazz, rc, attempt, report)
            if clazz == OK:
                return self._finish(OK, 0)
            if clazz == PREEMPTED:
                return self._finish(PREEMPTED, 128 + signal.SIGTERM)
            if clazz == SLICE_LOST:
                event = self._plan_elastic_restart(attempt) if self.elastic \
                    else None
                if event is None:
                    # not elastic (or survivors below the floor): report
                    # slice_lost so the JobSet failure policy — which
                    # restarts the set without burning maxRestarts on
                    # exit code 83 — makes the scale-level decision
                    return self._finish(SLICE_LOST, SLICE_LOST_EXIT_CODE)
                # small floor so the ledger's replan category is never
                # silently zero even under a zeroed test backoff
                delay = max(0.05, self.backoff_s)
                print(f"[m2kt] supervisor: attempt {attempt} slice_lost; "
                      f"elastic re-plan {event['from_slices']}->"
                      f"{event['to_slices']} slices, restarting in "
                      f"{delay:.1f}s", flush=True)
                time.sleep(delay)
                self._replan_sleep_total += delay
                continue
            if clazz == FATAL:
                return self._finish(FATAL, self._normalize_rc(rc))
            if attempt > self.max_retries:
                return self._finish("retries_exhausted",
                                    self._normalize_rc(rc))
            delay = min(BACKOFF_CAP_S, self.backoff_s * (2 ** (attempt - 1)))
            print(f"[m2kt] supervisor: attempt {attempt} {clazz} (rc={rc}); "
                  f"restarting in {delay:.1f}s "
                  f"({self.max_retries - attempt + 1} retries left)",
                  flush=True)
            time.sleep(delay)
            self._retry_sleep_total += delay

    @staticmethod
    def _normalize_rc(rc: int) -> int:
        return 128 - rc if rc < 0 else (rc or 1)

    # -- elastic re-plan ----------------------------------------------------

    def _plan_elastic_restart(self, attempt: int) -> dict | None:
        """Shrink the next attempt's world to the surviving slices.

        Returns the recorded re-plan event, or None when the survivors
        would fall below ``M2KT_ELASTIC_MIN_SLICES`` (terminal: hand the
        decision back to the JobSet failure policy). The child re-plans
        the mesh itself — ``resolve_mesh_plan`` reads the shrunken
        ``M2KT_NUM_SLICES`` — and orbax restores the last checkpoint
        into the smaller mesh's sharding.

        Global batch: ``M2KT_BATCH_PER_DEVICE`` is scaled up by
        old/new-slice ratio when that stays integral, so the optimizer
        sees identical global batches across the loss; when indivisible
        the per-device batch is kept and the event records the degraded
        global batch instead of silently changing convergence math.
        ``M2KT_FORCE_DEVICES`` (the CPU harness's forced-host device
        count) shrinks proportionally so the drill models the lost
        hardware, not just the lost label."""
        env = {**os.environ, **self._env_overrides}
        try:
            num = max(1, int(env.get("M2KT_NUM_SLICES", "1") or 1))
        except ValueError:
            num = 1
        survivors = num - 1
        if survivors < self.min_slices:
            log.warning(
                "slice lost but %d survivor(s) under the elastic floor "
                "(M2KT_ELASTIC_MIN_SLICES=%d); not re-planning",
                survivors, self.min_slices)
            return None
        overrides = {"M2KT_NUM_SLICES": str(survivors)}
        event: dict = {"attempt": attempt, "from_slices": num,
                       "to_slices": survivors}
        force = env.get("M2KT_FORCE_DEVICES", "")
        if force.isdigit() and int(force) % num == 0:
            overrides["M2KT_FORCE_DEVICES"] = str(
                int(force) // num * survivors)
        bpd = env.get("M2KT_BATCH_PER_DEVICE", "")
        if bpd.isdigit() and (int(bpd) * num) % survivors == 0:
            overrides["M2KT_BATCH_PER_DEVICE"] = str(
                int(bpd) * num // survivors)
            event["batch_per_device"] = int(overrides["M2KT_BATCH_PER_DEVICE"])
            event["global_batch_preserved"] = True
        else:
            # indivisible (or per-device batch unknown to the pod env):
            # keep the per-device batch, record the degradation
            event["global_batch_preserved"] = False
        self._env_overrides.update(overrides)
        self._replan_events.append(event)
        return event

    def _write_flight(self, exit_class: str, rc: int, attempt: int,
                      report: dict | None) -> None:
        """Crash flight recorder: fold the dead child's span ring (the
        child flushes its last ``M2KT_TRACE_RING_SECONDS`` of spans to
        ``<flight>.ring`` on teardown — ``obs.tracing.install_ring_flush``)
        together with its goodput ledger and the stderr tail into
        ``m2kt-flight.json``. A SIGKILL'd child leaves no ring; the
        flight then carries the ledger and classification alone.
        Best-effort: a flight the supervisor cannot write must never
        change the exit path."""
        from move2kube_tpu.obs import tracing

        ring: dict = {}
        ring_file = tracing.ring_path()
        try:
            with open(ring_file, encoding="utf-8") as f:
                ring = json.load(f)
        except (OSError, ValueError):
            pass
        # OOM forensics (obs/costmodel.py): the child's <flight>.mem
        # sidecar carries the memory_analysis of its last compiled step
        # plus a live-buffer summary — exactly what a RESOURCE_EXHAUSTED
        # or OOM-killed (137) postmortem needs. Absent for SIGKILL'd
        # children that never flushed one.
        memory: dict = {}
        try:
            with open(tracing.flight_path() + ".mem",
                      encoding="utf-8") as f:
                memory = json.load(f)
        except (OSError, ValueError):
            pass
        # numerics forensics (obs/numerics.py): the child's
        # <flight>.numerics sidecar names the first layer group that
        # went non-finite plus the per-group tensor health of that step
        # — written by StepTelemetry the moment a NaN/Inf was recorded,
        # so it survives even a child that died before the next sync.
        numerics_doc: dict = {}
        try:
            with open(tracing.flight_path() + ".numerics",
                      encoding="utf-8") as f:
                numerics_doc = json.load(f)
        except (OSError, ValueError):
            pass
        tail = self._attempts[-1].get("stderr_tail", "") \
            if self._attempts else ""
        flight = {
            "exit_class": exit_class,
            "returncode": rc,
            "attempt": attempt,
            "written_unix": time.time(),
            "cmd": self.cmd,
            "stderr_tail": tail[-2000:],
            "goodput": report or {},
            "ring": {k: ring.get(k) for k in
                     ("host", "slice_id", "pid", "written_unix",
                      "ring_seconds", "dropped")} if ring else {},
            "spans": ring.get("spans", []),
            "memory": memory,
            "numerics": numerics_doc,
        }
        path = tracing.flight_path()
        try:
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(flight, f, indent=2, sort_keys=True)
                f.write("\n")
            os.replace(tmp, path)
            log.warning("flight recorder: %s (%d spans) -> %s",
                        exit_class, len(flight["spans"]), path)
        except OSError as e:
            log.warning("could not write flight file %s: %s", path, e)

    def _finish(self, exit_class: str, code: int) -> int:
        merged = goodput.merge_attempts(self._attempts)
        merged["seconds"]["retry"] = round(
            merged["seconds"].get("retry", 0.0) + self._retry_sleep_total, 3)
        merged["seconds"]["replan"] = round(
            merged["seconds"].get("replan", 0.0) + self._replan_sleep_total, 3)
        summary = {
            "exit_class": exit_class,
            "returncode": code,
            "cmd": self.cmd,
            "attempts": [
                {k: v for k, v in a.items() if k != "ok"}
                for a in self._attempts
            ],
            "replan_events": self._replan_events,
            "goodput": merged,
        }
        try:
            d = os.path.dirname(os.path.abspath(self.exit_file))
            os.makedirs(d, exist_ok=True)
            tmp = self.exit_file + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(summary, f, indent=2, sort_keys=True)
                f.write("\n")
            os.replace(tmp, self.exit_file)
        except OSError as e:
            log.warning("could not write exit-reason file %s: %s",
                        self.exit_file, e)
        goodput.mirror_to_trace(merged)
        metrics_dir = os.environ.get("M2KT_METRICS_DIR", "")
        if metrics_dir:
            try:
                from move2kube_tpu.utils import trace

                trace.write_metrics(metrics_dir)
            except Exception as e:  # noqa: BLE001 - metrics are best-effort
                log.warning("could not write pod metrics: %s", e)
        print(f"[m2kt] supervisor: {exit_class} after "
              f"{len(self._attempts)} attempt(s); goodput="
              f"{merged['goodput_fraction']:.2%} "
              f"(lost {merged['seconds']['lost']:.1f}s, "
              f"retry {merged['seconds']['retry']:.1f}s)", flush=True)
        return code


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--" in argv:
        split = argv.index("--")
        opts, cmd = argv[:split], argv[split + 1:]
    else:
        opts, cmd = [], argv
    if not cmd:
        print("usage: python -m move2kube_tpu.resilience.supervisor "
              "[--max-retries N] [--backoff-s S] -- <command...>",
              file=sys.stderr)
        return 2
    max_retries = backoff = None
    it = iter(opts)
    for tok in it:
        if tok == "--max-retries":
            max_retries = int(next(it, "3"))
        elif tok == "--backoff-s":
            backoff = float(next(it, "5"))
        else:
            print(f"unknown supervisor option {tok!r}", file=sys.stderr)
            return 2
    return Supervisor(cmd, max_retries=max_retries, backoff_s=backoff).run()


if __name__ == "__main__":
    sys.exit(main())
