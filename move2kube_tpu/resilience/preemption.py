"""Preemption watcher: turn SIGTERM into a coordinated last-chance save.

When GKE reclaims a TPU node (spot preemption, maintenance event,
scale-down) every pod on it gets SIGTERM and
``terminationGracePeriodSeconds`` to die cleanly. The emitted JobSet
sizes that grace period to the checkpoint budget and adds a preStop hook
that touches a sentinel file (the earliest signal — preStop runs before
SIGTERM is delivered); this watcher notices either and tells the
training loop to take one final synchronous checkpoint and exit.

Multihost rule: a checkpoint is only restorable if **every** host wrote
its shards for the **same** step, but SIGTERM lands on one host first
(often seconds apart across a slice). ``should_stop`` therefore
all-reduces the local flag across processes on a fixed step cadence
(``sync_every``) — a barrier all hosts hit at the same step — so they
unanimously agree on the stop step before any of them saves. Single-
process runs skip the collective entirely.

Env knobs (injected by the TPU apiresources, see
``apiresource/deployment.py``):

- ``M2KT_PREEMPT``         — ``0`` disables the watcher (default on)
- ``M2KT_PREEMPT_GRACE_S`` — grace budget in seconds (default 120);
  mirrored into the JobSet's terminationGracePeriodSeconds
- ``M2KT_PREEMPT_FILE``    — preStop sentinel path
  (default ``/tmp/m2kt-preempt``)
- ``M2KT_PREEMPT_SYNC_EVERY`` — multihost agreement cadence in steps
  (default 10; unused single-process)

Stdlib + lazy jax; vendored into emitted images.
"""

from __future__ import annotations

import logging
import os
import signal
import time

log = logging.getLogger("m2kt.preemption")

DEFAULT_SENTINEL = "/tmp/m2kt-preempt"
DEFAULT_GRACE_S = 120.0
# emitted grace = checkpoint budget + margin for exit/teardown; the
# deployment layer derives terminationGracePeriodSeconds from the same
# numbers so the YAML and the watcher can't drift apart
DEFAULT_CKPT_BUDGET_S = 240
GRACE_MARGIN_S = 60


def grace_period_seconds() -> int:
    """The pod termination grace both the JobSet YAML and the emitted
    env agree on: checkpoint budget + teardown margin, env-overridable."""
    explicit = os.environ.get("M2KT_GRACE_PERIOD_S", "")
    if explicit:
        try:
            return max(1, int(explicit))
        except ValueError:
            log.warning("bad M2KT_GRACE_PERIOD_S=%r; using default", explicit)
    try:
        budget = int(os.environ.get("M2KT_CKPT_BUDGET_S",
                                    str(DEFAULT_CKPT_BUDGET_S)))
    except ValueError:
        budget = DEFAULT_CKPT_BUDGET_S
    return max(1, budget) + GRACE_MARGIN_S


class PreemptionWatcher:
    """SIGTERM/sentinel watcher with multihost stop-step agreement."""

    def __init__(self, grace_seconds: float = DEFAULT_GRACE_S,
                 sentinel: str = DEFAULT_SENTINEL, sync_every: int = 10):
        self.grace_seconds = grace_seconds
        self.sentinel = sentinel
        self.sync_every = max(1, sync_every)
        self._flagged_at: float | None = None
        self._prev_handler = None
        self._installed = False

    # -- local signal plumbing ---------------------------------------------

    def _on_sigterm(self, signum, frame) -> None:
        self._note_flagged("SIGTERM")
        if callable(self._prev_handler):
            self._prev_handler(signum, frame)

    def _note_flagged(self, source: str) -> None:
        if self._flagged_at is None:
            self._flagged_at = time.monotonic()
            log.warning("preemption notice via %s; grace budget %.0fs",
                        source, self.grace_seconds)

    def install(self) -> "PreemptionWatcher":
        """Register the SIGTERM handler (chains to any previous one).
        Main-thread only, like all signal handling in Python."""
        if not self._installed:
            self._prev_handler = signal.signal(signal.SIGTERM, self._on_sigterm)
            self._installed = True
        return self

    def uninstall(self) -> None:
        if self._installed:
            signal.signal(signal.SIGTERM, self._prev_handler or signal.SIG_DFL)
            self._installed = False

    # -- queries ------------------------------------------------------------

    def requested(self) -> bool:
        """Host-local: has this process been told to stop?"""
        if self._flagged_at is None and self.sentinel and \
                os.path.exists(self.sentinel):
            self._note_flagged(f"sentinel {self.sentinel}")
        return self._flagged_at is not None

    def time_left(self) -> float | None:
        """Seconds of grace remaining (None until flagged)."""
        if self._flagged_at is None:
            return None
        return self.grace_seconds - (time.monotonic() - self._flagged_at)

    def should_stop(self, step: int) -> bool:
        """Call once per training step. True means: all hosts have agreed
        this is the stop step — save synchronously now and exit.

        Multihost, this is a collective on the ``sync_every`` cadence and
        MUST be called by every process at every step (the non-cadence
        steps are free)."""
        import jax

        if jax.process_count() <= 1:
            return self.requested()
        if step % self.sync_every:
            return False
        import numpy as np
        from jax.experimental import multihost_utils

        local = np.asarray([1 if self.requested() else 0], dtype=np.int32)
        flagged = multihost_utils.process_allgather(local)
        agreed = bool(flagged.max())
        if agreed and self._flagged_at is None:
            # another host got the signal; adopt its deadline locally
            self._note_flagged("peer host")
        return agreed


def from_env() -> PreemptionWatcher | None:
    """Build the watcher the emitted trainers install at startup; None
    when disabled via M2KT_PREEMPT=0."""
    if os.environ.get("M2KT_PREEMPT", "1") == "0":
        return None
    try:
        grace = float(os.environ.get("M2KT_PREEMPT_GRACE_S",
                                     str(DEFAULT_GRACE_S)))
    except ValueError:
        grace = DEFAULT_GRACE_S
    try:
        sync_every = int(os.environ.get("M2KT_PREEMPT_SYNC_EVERY", "10"))
    except ValueError:
        sync_every = 10
    return PreemptionWatcher(
        grace_seconds=grace,
        sentinel=os.environ.get("M2KT_PREEMPT_FILE", DEFAULT_SENTINEL),
        sync_every=sync_every,
    )
