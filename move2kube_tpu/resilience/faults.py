"""Deterministic fault injection for resume-path testing on CPU.

The resume story ("a pod that dies at step N restarts from N") is only
real if CI can prove it without TPUs or a cluster. This module gives the
training loop one cheap hook — :func:`maybe_inject` — driven entirely by
``M2KT_FAULT_*`` env vars, plus helpers that damage the latest on-disk
checkpoint the way a preempted host does (partial writes, truncation).

Knobs (all inert when unset — production pods never set them):

- ``M2KT_FAULT_STEP``      — step number at which the fault fires
- ``M2KT_FAULT_KIND``      — ``exit`` (sys.exit, default) | ``raise``
  (RuntimeError, reads as a retryable crash) | ``sigkill`` (os.kill
  SIGKILL: the no-cleanup death a host failure produces) |
  ``slice_loss`` (a whole DCN-connected slice reclaimed: exits with
  :data:`SLICE_LOST_EXIT_CODE` after naming the lost slice on stderr,
  which the supervisor classifies as ``slice_lost`` and — in elastic
  mode — answers by re-planning on the survivors)
- ``M2KT_FAULT_EXIT_CODE`` — exit code for ``exit`` (default 1)
- ``M2KT_FAULT_SLICE``     — which slice ``slice_loss`` reclaims
  (default 1, i.e. the last slice of a 2-slice job)
- ``M2KT_FAULT_MARKER``    — path to an exactly-once marker: the fault
  fires only when the file is absent and creates it first, so the
  supervisor's restarted attempt survives. Without a marker the fault
  fires on every attempt (for testing retry exhaustion).

The SERVING fleet has its own injector family built on the same
exactly-once marker primitive (:func:`_marker_fired`):
serving/fleet/chaos.py drives kill-replica-at-token-N, KV-handoff
drop/truncate, slow-replica, and health-flap faults from
``M2KT_CHAOS_*`` env vars — see :func:`serving_chaos`.

Stdlib-only; vendored into emitted images (where it stays dormant).
"""

from __future__ import annotations

import logging
import os
import signal
import sys

log = logging.getLogger("m2kt.faults")

# Exit code for a slice-loss death (EX-range, unused by jax/python/shell
# conventions). The emitted JobSet's podFailurePolicy keys a
# restart-without-burning-maxRestarts rule on it, and the in-pod
# supervisor classifies it as ``slice_lost``.
SLICE_LOST_EXIT_CODE = 83


class FaultInjected(RuntimeError):
    """Raised by the ``raise`` fault kind (classified retryable)."""


def _marker_fired(marker: str) -> bool:
    """True when the exactly-once marker says this fault already fired;
    otherwise claims it (O_EXCL so concurrent hosts race safely)."""
    if not marker:
        return False
    try:
        fd = os.open(marker, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
    except FileExistsError:
        return True
    os.close(fd)
    return False


def maybe_inject(step: int) -> None:
    """Fire the configured fault when ``step`` matches; no-op otherwise.

    Called once per training step — two env reads when unconfigured,
    nothing cached so tests can flip the knobs between runs.
    """
    raw = os.environ.get("M2KT_FAULT_STEP", "")
    if not raw:
        return
    try:
        at = int(raw)
    except ValueError:
        return
    if step != at:
        return
    if _marker_fired(os.environ.get("M2KT_FAULT_MARKER", "")):
        return
    kind = os.environ.get("M2KT_FAULT_KIND", "exit")
    log.warning("injecting fault kind=%s at step %d", kind, step)
    print(f"[m2kt] FAULT: injected {kind} at step {step}", flush=True)
    if kind == "raise":
        raise FaultInjected(f"injected transient fault at step {step}")
    if kind == "sigkill":
        os.kill(os.getpid(), signal.SIGKILL)
    if kind == "slice_loss":
        # a reclaimed slice takes all of its processes with it; survivors
        # see the DCN collectives break. Either way the job dies — with a
        # distinctive exit code plus a stderr line naming the lost slice,
        # so the supervisor's classifier (and a human reading the pod
        # log) sees slice_lost, not a generic crash. stderr, not stdout:
        # the supervisor classifies on the stderr tail.
        lost = os.environ.get("M2KT_FAULT_SLICE", "1")
        print(f"[m2kt] FAULT: slice_loss: slice {lost} reclaimed at step "
              f"{step}; DCN peers unreachable", file=sys.stderr, flush=True)
        sys.exit(SLICE_LOST_EXIT_CODE)
    sys.exit(int(os.environ.get("M2KT_FAULT_EXIT_CODE", "1")))


def serving_chaos():
    """The serving-side injector, armed from ``M2KT_CHAOS_*`` env vars
    (None when nothing is configured). Lazy import: this module stays
    stdlib-only and importable in contexts that never serve."""
    from move2kube_tpu.serving.fleet.chaos import maybe_chaos

    return maybe_chaos()


# -- checkpoint damage (what a preempted host leaves behind) ----------------


def step_dirs(ckpt_dir: str) -> list[tuple[int, str]]:
    """(step, path) for every retained orbax step dir, ascending."""
    out = []
    try:
        entries = os.listdir(ckpt_dir)
    except OSError:
        return []
    for name in entries:
        p = os.path.join(ckpt_dir, name)
        if os.path.isdir(p) and name.isdigit():
            out.append((int(name), p))
    return sorted(out)


def _payload_files(step_dir: str) -> list[str]:
    """Every array-payload replica in an orbax step dir. Ocdbt keeps the
    chunk data twice (merged ``d/`` + per-process ``ocdbt.process_N/d/``)
    and restore transparently falls back between them, so *all* replicas
    must be damaged or the corruption is silently healed. When no ``d/``
    dir exists (layout change), the structure metadata is the victim."""
    payload, metadata = [], []
    for dirpath, _dirs, names in os.walk(step_dir):
        for n in names:
            p = os.path.join(dirpath, n)
            if os.path.basename(dirpath) == "d":
                payload.append(p)
            elif n == "_METADATA":
                metadata.append(p)
    return sorted(payload) or metadata


def corrupt_latest(ckpt_dir: str, mode: str = "truncate") -> int:
    """Damage the newest retained checkpoint; returns the step damaged.

    ``truncate`` halves each payload file (partial write); ``scribble``
    overwrites their heads with garbage (bit rot / torn block);
    ``remove`` deletes them (lost objects). Raises FileNotFoundError
    when there is no checkpoint to damage.
    """
    steps = step_dirs(ckpt_dir)
    if not steps:
        raise FileNotFoundError(f"no checkpoint steps under {ckpt_dir!r}")
    step, sdir = steps[-1]
    victims = _payload_files(sdir)
    if not victims:
        raise FileNotFoundError(f"checkpoint step dir {sdir!r} is empty")
    for victim in victims:
        if mode == "remove":
            os.remove(victim)
        elif mode == "scribble":
            size = os.path.getsize(victim)
            with open(victim, "r+b") as f:
                f.write(b"\xde\xad\xbe\xef" * max(1, min(size, 4096) // 4))
        else:  # truncate
            size = os.path.getsize(victim)
            with open(victim, "r+b") as f:
                f.truncate(size // 2)
    log.warning("corrupted checkpoint step %d (%s x%d: %s ...)",
                step, mode, len(victims), victims[0])
    return step
