{% if build_tool == "maven" %}FROM maven:3.9-eclipse-temurin-17 AS build
WORKDIR /src
COPY pom.xml .
RUN mvn -q dependency:go-offline
COPY . .
RUN mvn -q package -DskipTests
{% elif build_tool == "gradle" %}FROM gradle:8-jdk17 AS build
WORKDIR /src
COPY . .
RUN gradle --no-daemon build -x test && mkdir -p /src/target && cp build/libs/*.war /src/target/
{% elif build_tool == "ant" %}FROM eclipse-temurin:17-jdk AS build
RUN apt-get update && apt-get install -y --no-install-recommends ant && rm -rf /var/lib/apt/lists/*
WORKDIR /src
COPY . .
RUN ant && mkdir -p /src/target && find . -name '*.war' -exec cp {} /src/target/ \;
{% endif %}