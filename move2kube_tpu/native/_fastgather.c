/* Parallel row-gather for the host-sharded input pipeline.
 *
 * The per-batch hot path of models/data.py is `src[take]` — a fancy-index
 * gather that numpy executes single-threaded. On a JobSet host feeding
 * multiple TPU chips, the gather sits between device steps (the device is
 * idle while it runs), so cutting its wall-clock directly raises
 * steps/sec for IO-bound workloads. This module is a dependency-free
 * CPython extension (no numpy C API — plain buffer protocol + memcpy)
 * that splits the row range over pthreads with the GIL released.
 *
 * Reference parity note: the reference implements its performance-
 * critical paths natively (Go); this is the analogous native component
 * for the one hot loop the TPU tool runtime owns (everything else hot
 * runs on-device via XLA/Pallas).
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <pthread.h>
#include <stdint.h>
#include <string.h>

typedef struct {
    const char *src;
    char *dst;
    const int64_t *idx;
    Py_ssize_t row_bytes;
    Py_ssize_t begin; /* first output row (inclusive) */
    Py_ssize_t end;   /* last output row (exclusive) */
} gather_span;

static void *gather_worker(void *arg)
{
    gather_span *s = (gather_span *)arg;
    Py_ssize_t i;
    for (i = s->begin; i < s->end; i++) {
        memcpy(s->dst + i * s->row_bytes,
               s->src + s->idx[i] * s->row_bytes,
               (size_t)s->row_bytes);
    }
    return NULL;
}

/* gather(src: buffer, out: buffer, idx: buffer[int64], row_bytes: int,
 *        n_src_rows: int, threads: int) -> None
 * Bounds are validated here so a bad index can never read/write out of
 * range; raises ValueError instead. */
static PyObject *gather(PyObject *self, PyObject *args)
{
    Py_buffer src, out, idx;
    Py_ssize_t row_bytes, n_src_rows, threads;
    if (!PyArg_ParseTuple(args, "y*w*y*nnn", &src, &out, &idx, &row_bytes,
                          &n_src_rows, &threads)) {
        return NULL;
    }

    PyObject *ret = NULL;
    Py_ssize_t n_idx = idx.len / (Py_ssize_t)sizeof(int64_t);
    const int64_t *indices = (const int64_t *)idx.buf;
    Py_ssize_t i;

    if (row_bytes <= 0 || idx.len % (Py_ssize_t)sizeof(int64_t) != 0) {
        PyErr_SetString(PyExc_ValueError, "bad row_bytes or index buffer");
        goto done;
    }
    if (src.len < n_src_rows * row_bytes || out.len < n_idx * row_bytes) {
        PyErr_SetString(PyExc_ValueError, "buffer too small for rows");
        goto done;
    }
    for (i = 0; i < n_idx; i++) {
        if (indices[i] < 0 || indices[i] >= n_src_rows) {
            PyErr_Format(PyExc_ValueError,
                         "index %lld out of range [0, %lld)",
                         (long long)indices[i], (long long)n_src_rows);
            goto done;
        }
    }

    if (threads < 1) threads = 1;
    if (threads > 16) threads = 16;
    if (threads > n_idx) threads = n_idx > 0 ? n_idx : 1;

    Py_BEGIN_ALLOW_THREADS
    {
        gather_span spans[16];
        pthread_t tids[16]; /* compact: tids[0..spawned) are all live */
        Py_ssize_t per = (n_idx + threads - 1) / threads;
        Py_ssize_t t, spawned = 0;
        for (t = 0; t < threads; t++) {
            spans[t].src = (const char *)src.buf;
            spans[t].dst = (char *)out.buf;
            spans[t].idx = indices;
            spans[t].row_bytes = row_bytes;
            spans[t].begin = t * per;
            spans[t].end = (t + 1) * per < n_idx ? (t + 1) * per : n_idx;
            if (spans[t].begin >= spans[t].end) break;
            if (t + 1 < threads &&
                pthread_create(&tids[spawned], NULL, gather_worker,
                               &spans[t]) == 0) {
                spawned++;
            } else {
                /* last span (or thread creation failed): run inline */
                gather_worker(&spans[t]);
            }
        }
        for (t = 0; t < spawned; t++) {
            pthread_join(tids[t], NULL);
        }
    }
    Py_END_ALLOW_THREADS

    ret = Py_None;
    Py_INCREF(ret);
done:
    PyBuffer_Release(&src);
    PyBuffer_Release(&out);
    PyBuffer_Release(&idx);
    return ret;
}

static PyMethodDef methods[] = {
    {"gather", gather, METH_VARARGS,
     "gather(src, out, idx, row_bytes, n_src_rows, threads): parallel "
     "row memcpy with bounds checking; GIL released during the copy."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_fastgather",
    "Parallel row-gather (see module source header).", -1, methods,
};

PyMODINIT_FUNC PyInit__fastgather(void)
{
    return PyModule_Create(&moduledef);
}
