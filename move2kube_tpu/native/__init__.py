"""Native runtime components (C, built by setup.py; optional).

``gather_rows`` is the public API: a parallel fancy-index row gather for
the input pipeline's per-batch hot path (models/data.py). Falls back to
numpy transparently when the extension isn't built — pure-Python
installs lose speed, never function.
"""

from __future__ import annotations

import os

import numpy as np

try:
    from move2kube_tpu.native import _fastgather
except ImportError:  # extension not built (pure-python install)
    _fastgather = None

_THREADS = int(os.environ.get("M2KT_GATHER_THREADS",
                              str(min(8, os.cpu_count() or 1))))
# below this many bytes the thread spawn costs more than the copy
_MIN_NATIVE_BYTES = 1 << 20


def native_available() -> bool:
    return _fastgather is not None


def gather_rows(src: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """``np.ascontiguousarray(src[idx])`` — via the parallel C gather
    when the layout allows (C-contiguous rows), numpy otherwise."""
    out_shape = (len(idx),) + src.shape[1:]
    if (_fastgather is None or src.ndim < 1
            or not src.flags.c_contiguous
            or src.nbytes < _MIN_NATIVE_BYTES):
        return np.ascontiguousarray(src[idx])
    row_bytes = src.dtype.itemsize
    for dim in src.shape[1:]:
        row_bytes *= dim
    if row_bytes == 0:
        return np.ascontiguousarray(src[idx])
    out = np.empty(out_shape, src.dtype)
    idx64 = np.ascontiguousarray(idx, np.int64)
    # normalize negative indices to numpy's wrapping semantics so the C
    # path (which rejects out-of-range) behaves identically to the numpy
    # fallback regardless of whether the extension is built
    if idx64.size and (idx64 < 0).any():
        idx64 = np.where(idx64 < 0, idx64 + src.shape[0], idx64)
    _fastgather.gather(
        memoryview(src).cast("B"), memoryview(out).cast("B"),
        memoryview(idx64).cast("B"), row_bytes, src.shape[0], _THREADS)
    return out
