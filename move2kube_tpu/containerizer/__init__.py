from move2kube_tpu.containerizer.base import (  # noqa: F401
    Containerizer,
    get_container,
    get_containerization_options,
    init_containerizers,
    reset_containerizers,
)
