from move2kube_tpu.containerizer.base import (  # noqa: F401
    Containerizer,
    get_container,
    get_containerization_options,
    get_containerizers,
    init_containerizers,
    reset_containerizers,
)
