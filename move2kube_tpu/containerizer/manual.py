"""Manual containerizer: a placeholder for services the user containerizes
out of band.

Parity: ``internal/containerizer/manualcontainerizer.go`` — in the
reference this carries the CF-collected buildpack -> containerizer mapping
(``m2k_collect`` CfContainerizers files) and otherwise produces a non-new
container plus an entry in ``Manualimages.md`` telling the user which
images they still have to build by hand.
"""

from __future__ import annotations

import os

from move2kube_tpu.containerizer.base import Containerizer
from move2kube_tpu.types import collection as collecttypes
from move2kube_tpu.types.ir import Container
from move2kube_tpu.types.plan import ContainerBuildType, PlanService
from move2kube_tpu.utils import common
from move2kube_tpu.utils.log import get_logger

log = get_logger("containerizer.manual")


class ManualContainerizer(Containerizer):
    """Offers buildpack-derived options from collected CfContainerizers
    files; emits a no-files container flagged for manual build."""

    def __init__(self) -> None:
        self.cf_containerizers = collecttypes.CfContainerizers()

    def init(self, source_dir: str) -> None:
        """Load collected CfContainerizers yamls (manualcontainerizer.go
        Init). Only files that look like the collect output are parsed —
        a full-tree YAML parse of every manifest would run twice per
        translate for nothing."""
        for path in common.get_files_by_ext(source_dir, [".yaml", ".yml"]):
            base = os.path.basename(path).lower()
            if "cfcontainerizer" not in base and common.COLLECT_OUTPUT_DIR not in path:
                continue
            try:
                other = collecttypes.read_cf_containerizers(path)
            except Exception:  # noqa: BLE001 - not a CfContainerizers file
                continue
            self.cf_containerizers.merge(other)
            log.debug("loaded CF containerizer mapping from %s", path)

    def get_build_type(self) -> str:
        return ContainerBuildType.MANUAL

    def get_target_options(self, plan, directory: str) -> list[str]:
        # Never offered by the directory walk — that would add a Manual
        # option to every any2kube service. CF apps reach Manual through
        # ``options_for_buildpack`` via the collected mapping.
        return []

    def options_for_buildpack(self, buildpack: str) -> list[str]:
        return self.cf_containerizers.options_for(buildpack)

    def get_container(self, plan, service: PlanService) -> Container:
        image = service.image or service.service_name + ":latest"
        log.info("service %s marked for manual containerization (image %s)",
                 service.service_name, image)
        return Container(image_names=[image], new=False,
                         build_type=ContainerBuildType.MANUAL)
