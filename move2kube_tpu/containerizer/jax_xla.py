"""jax-xla containerizer: rewrite GPU training services into JAX TPU images.

The north-star containerizer (net-new vs the reference; see BASELINE.json):
directories whose Python sources use CUDA/NCCL/DeepSpeed are claimed here
and re-emitted as TPU-VM images whose entrypoint is a generated JAX
training program from the model zoo (``move2kube_tpu.models``), with
``jax.distributed.initialize`` bootstrap honoring JobSet env indexing.

Detection lives in ``move2kube_tpu.source.gpu_detect``; emission templates
in ``move2kube_tpu/assets/jax/``.
"""

from __future__ import annotations

from move2kube_tpu.containerizer.base import Containerizer
from move2kube_tpu.types.ir import Container
from move2kube_tpu.types.plan import ContainerBuildType, PlanService
from move2kube_tpu.utils.log import get_logger

log = get_logger("containerizer.jaxxla")


class JaxXlaContainerizer(Containerizer):
    def get_build_type(self) -> str:
        return ContainerBuildType.JAX_XLA

    def get_target_options(self, plan, directory: str) -> list[str]:
        from move2kube_tpu.source import gpu_detect

        report = gpu_detect.analyze_directory(directory)
        if report is None:
            return []
        return [report.model_family or "generic"]

    def get_container(self, plan, service: PlanService) -> Container:
        from move2kube_tpu.containerizer import jax_emit

        return jax_emit.emit_container(service, plan)
