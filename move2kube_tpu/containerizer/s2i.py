"""Source-to-Image containerizer.

Parity: ``internal/containerizer/s2icontainerizer.go:87-170`` — per-stack
builder images; emits an ``<svc>-s2i-build.sh`` script. Custom detectors:
directories containing ``m2kts2idetect.sh`` whose JSON stdout must include
``builder``.
"""

from __future__ import annotations

import json
import os
import subprocess

from move2kube_tpu.containerizer import stacks
from move2kube_tpu.containerizer.base import Containerizer
from move2kube_tpu.containerizer.scripts import S2I_BUILD_SH
from move2kube_tpu.types.ir import Container
from move2kube_tpu.types.plan import ContainerBuildType, PlanService
from move2kube_tpu.utils import common
from move2kube_tpu.utils.log import get_logger

log = get_logger("containerizer.s2i")

CUSTOM_DETECT_SCRIPT = "m2kts2idetect.sh"

# stack id -> s2i builder image (parity: internal/assets/s2i/*)
BUILDERS = {
    "python": "registry.access.redhat.com/ubi8/python-39",
    "django": "registry.access.redhat.com/ubi8/python-39",
    "nodejs": "registry.access.redhat.com/ubi8/nodejs-18",
    "golang": "registry.access.redhat.com/ubi8/go-toolset",
    "java-maven": "registry.access.redhat.com/ubi8/openjdk-17",
    "java-gradle": "registry.access.redhat.com/ubi8/openjdk-17",
    "java-ant": "registry.access.redhat.com/ubi8/openjdk-17",
    "java-war-tomcat": ("registry.access.redhat.com/jboss-webserver-5"
                        "/jws58-openjdk17-openshift-rhel8"),
    "java-war-liberty": "icr.io/appcafe/open-liberty-s2i:23",
    "java-war-jboss": "quay.io/wildfly/wildfly-s2i:latest-jdk17",
    "php": "registry.access.redhat.com/ubi8/php-80",
    "ruby": "registry.access.redhat.com/ubi8/ruby-30",
}


class S2IContainerizer(Containerizer):
    def __init__(self) -> None:
        self.custom_dirs: list[str] = []

    def init(self, source_dir: str) -> None:
        self.custom_dirs = [
            os.path.dirname(p)
            for p in common.get_files_by_name(source_dir, [CUSTOM_DETECT_SCRIPT])
        ]

    def get_build_type(self) -> str:
        return ContainerBuildType.S2I

    def get_target_options(self, plan, directory: str) -> list[str]:
        options = [
            BUILDERS[m.stack]
            for m in stacks.detect_stacks(directory)
            if m.stack in BUILDERS
        ]
        for custom in self.custom_dirs:
            params = self._run_custom_detect(custom, directory)
            if params and params.get("builder"):
                options.append(params["builder"])
        # dedup preserving order
        seen: set[str] = set()
        return [o for o in options if not (o in seen or seen.add(o))]

    def _run_custom_detect(self, custom_dir: str, directory: str) -> dict | None:
        script = os.path.join(custom_dir, CUSTOM_DETECT_SCRIPT)
        try:
            res = subprocess.run(
                ["/bin/sh", script, directory],
                capture_output=True, text=True, timeout=60, check=False,
            )
        except (OSError, subprocess.TimeoutExpired):
            return None
        if res.returncode != 0:
            return None
        try:
            params = json.loads(res.stdout or "{}")
        except json.JSONDecodeError:
            return None
        return params if isinstance(params, dict) else None

    def get_container(self, plan, service: PlanService) -> Container:
        if not service.containerization_target_options:
            raise ValueError(f"{service.service_name}: no s2i builder selected")
        builder = service.containerization_target_options[0]
        name = common.make_dns_label(service.service_name)
        image_name = service.image or f"{name}:latest"
        container = Container(
            image_names=[image_name], new=True, build_type=ContainerBuildType.S2I,
        )
        from move2kube_tpu.containerizer.dockerfile import _record_source_dir

        src_dirs = service.source_artifacts.get(PlanService.SOURCE_DIR_ARTIFACT, [])
        if src_dirs:
            _record_source_dir(container, plan, src_dirs[0])
        container.add_file(
            f"{name}-s2i-build.sh",
            common.render_template(S2I_BUILD_SH, {
                "service_name": name,
                "builder": builder,
                "image_name": image_name,
                "context": ".",
            }),
        )
        container.add_exposed_port(common.DEFAULT_SERVICE_PORT)
        return container
