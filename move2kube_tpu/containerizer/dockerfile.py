"""Dockerfile containerizer: generate a Dockerfile from per-stack templates.

Parity: ``internal/containerizer/dockerfilecontainerizer.go:50-186``. The
reference detects via embedded ``m2kdfdetect.sh`` scripts; built-in stacks
here detect in-process (stacks.py). User-provided detectors still work the
script way: any directory in the source tree containing ``m2ktdfdetect.sh``
plus a ``Dockerfile`` template is registered as a custom option, the script
is run with the service dir as argv[1], and its JSON stdout feeds the
template — the same contract as the reference.
"""

from __future__ import annotations

import json
import os
import subprocess

from move2kube_tpu.containerizer import stacks
from move2kube_tpu.containerizer.base import Containerizer
from move2kube_tpu.containerizer.scripts import DOCKER_BUILD_SH
from move2kube_tpu.types.ir import Container
from move2kube_tpu.types.plan import ContainerBuildType, PlanService
from move2kube_tpu.utils import common
from move2kube_tpu.utils.log import get_logger

log = get_logger("containerizer.dockerfile")

CUSTOM_DETECT_SCRIPT = "m2ktdfdetect.sh"


def _record_source_dir(container, plan, svc_dir: str) -> None:
    """Remember the service's source dir relative to the plan root so
    copysources.sh copies the right subtree next to the build files
    (transformer/base.py reads repo_info.git_repo_dir), plus the git
    remote/branch for CI/CD generation (plan.go GatherGitInfo:194)."""
    from move2kube_tpu.utils import gitinfo

    rel = None
    if plan is not None and getattr(plan, "root_dir", ""):
        rel = common.relpath_under(svc_dir, plan.root_dir)
    container.repo_info.git_repo_dir = rel if rel is not None else "."
    details = gitinfo.get_git_repo_details(svc_dir)
    if details is not None:
        container.repo_info.git_repo_url = details.url
        container.repo_info.git_repo_branch = details.branch


class DockerfileContainerizer(Containerizer):
    def __init__(self) -> None:
        self.custom_dirs: list[str] = []

    def init(self, source_dir: str) -> None:
        """Register custom detector dirs from the source tree
        (dockerfilecontainerizer.go:50)."""
        self.custom_dirs = [
            os.path.dirname(p)
            for p in common.get_files_by_name(source_dir, [CUSTOM_DETECT_SCRIPT])
            if os.path.isfile(os.path.join(os.path.dirname(p), "Dockerfile"))
        ]

    def get_build_type(self) -> str:
        return ContainerBuildType.NEW_DOCKERFILE

    def get_target_options(self, plan, directory: str) -> list[str]:
        options = [m.stack for m in stacks.detect_stacks(directory)]
        for custom in self.custom_dirs:
            if self._run_custom_detect(custom, directory) is not None:
                options.append(custom)
        return options

    def _run_custom_detect(self, custom_dir: str, directory: str) -> dict | None:
        script = os.path.join(custom_dir, CUSTOM_DETECT_SCRIPT)
        try:
            res = subprocess.run(
                ["/bin/sh", script, directory],
                capture_output=True, text=True, timeout=60, check=False,
            )
        except (OSError, subprocess.TimeoutExpired) as e:
            log.debug("custom detect %s failed: %s", script, e)
            return None
        if res.returncode != 0:
            return None
        try:
            params = json.loads(res.stdout or "{}")
        except json.JSONDecodeError:
            params = {}
        return params if isinstance(params, dict) else {}

    def get_container(self, plan, service: PlanService) -> Container:
        """Render the stack template into Container.NewFiles
        (dockerfilecontainerizer.go:86-186)."""
        if not service.containerization_target_options:
            raise ValueError(f"{service.service_name}: no containerization target option")
        option = service.containerization_target_options[0]
        svc_dirs = service.source_artifacts.get(PlanService.SOURCE_DIR_ARTIFACT, [])
        if not svc_dirs:
            raise ValueError(f"{service.service_name}: no source directory artifact")
        svc_dir = svc_dirs[0]

        if option in stacks.available_stacks():
            match = next(
                (m for m in stacks.detect_stacks(svc_dir) if m.stack == option), None
            )
            if match is None:
                raise ValueError(
                    f"{service.service_name}: stack {option!r} no longer detected in {svc_dir}"
                )
            template = stacks.read_template(option)
            params = match.params
        elif os.path.isdir(option):  # custom detector dir
            params = self._run_custom_detect(option, svc_dir)
            if params is None:
                raise ValueError(f"{service.service_name}: custom detect failed in {option}")
            with open(os.path.join(option, "Dockerfile"), encoding="utf-8") as f:
                template = f.read()
        else:
            raise ValueError(f"{service.service_name}: unknown target option {option!r}")

        name = common.make_dns_label(service.service_name)
        image_name = service.image or f"{name}:latest"
        container = Container(
            image_names=[image_name],
            new=True,
            build_type=ContainerBuildType.NEW_DOCKERFILE,
        )
        _record_source_dir(container, plan, svc_dir)
        dockerfile_name = "Dockerfile." + name
        container.add_file(dockerfile_name, common.render_template(template, params))
        container.add_file(
            f"{name}-docker-build.sh",
            common.render_template(DOCKER_BUILD_SH, {
                "service_name": name,
                "dockerfile_name": dockerfile_name,
                "image_name": image_name,
                "context": ".",
            }),
        )
        port = params.get("port")
        if port:
            container.add_exposed_port(int(port))
        # extra files next to a custom template ship too (reference parity)
        if os.path.isdir(option):
            for extra in os.listdir(option):
                if extra in (CUSTOM_DETECT_SCRIPT, "Dockerfile"):
                    continue
                p = os.path.join(option, extra)
                if os.path.isfile(p):
                    with open(p, encoding="utf-8", errors="ignore") as f:
                        container.add_file(
                            extra, common.render_template(f.read(), params)
                        )
        return container
