"""Reuse-Dockerfile containerizer: user already has a Dockerfile.

Parity: ``internal/containerizer/reusedockerfilecontainerizer.go:41-100`` —
emits only the build script wired to the existing Dockerfile's location.
"""

from __future__ import annotations

import os

from move2kube_tpu.containerizer.base import Containerizer
from move2kube_tpu.containerizer.scripts import DOCKER_BUILD_SH
from move2kube_tpu.types.ir import Container
from move2kube_tpu.types.plan import ContainerBuildType, PlanService
from move2kube_tpu.utils import common


class ReuseDockerfileContainerizer(Containerizer):
    def get_build_type(self) -> str:
        return ContainerBuildType.REUSE_DOCKERFILE

    def get_target_options(self, plan, directory: str) -> list[str]:
        if os.path.isfile(os.path.join(directory, "Dockerfile")):
            return [os.path.join(directory, "Dockerfile")]
        return []

    def get_container(self, plan, service: PlanService) -> Container:
        dockerfiles = service.source_artifacts.get(PlanService.DOCKERFILE_ARTIFACT, [])
        if dockerfiles:
            dockerfile = dockerfiles[0]
        elif service.containerization_target_options:
            dockerfile = service.containerization_target_options[0]
        else:
            raise ValueError(f"{service.service_name}: no Dockerfile artifact")
        name = common.make_dns_label(service.service_name)
        image_name = service.image or f"{name}:latest"
        container = Container(
            image_names=[image_name], new=True,
            build_type=ContainerBuildType.REUSE_DOCKERFILE,
        )
        # Build context = the Dockerfile's own directory; the build script is
        # written under containers/<svc>/ and copysources.sh copies the
        # source next to it (transformer parity).
        container.add_file(
            f"{name}-docker-build.sh",
            common.render_template(DOCKER_BUILD_SH, {
                "service_name": name,
                "dockerfile_name": os.path.basename(dockerfile),
                "image_name": image_name,
                "context": ".",
            }),
        )
        return container
