"""Containerizer interface and registry.

Parity: ``internal/containerizer/containerizer.go:37-62`` — each
containerizer detects whether it can build a directory, offers target
options at plan time, and produces a ``Container`` (generated files) at
translate time. The registry is ordered; ``init_containerizers`` wires the
built-ins and lets user-provided detectors in the source tree extend them.
"""

from __future__ import annotations

from move2kube_tpu.types.ir import Container
from move2kube_tpu.types.plan import PlanService
from move2kube_tpu.utils.log import get_logger

log = get_logger("containerizer")


class Containerizer:
    def init(self, source_dir: str) -> None:  # scan for detectors
        pass

    def get_build_type(self) -> str:
        raise NotImplementedError

    def get_target_options(self, plan, directory: str) -> list[str]:
        """Options (e.g. stack template ids) this containerizer offers for
        the directory; empty = cannot containerize it."""
        raise NotImplementedError

    def get_container(self, plan, service: PlanService) -> Container:
        raise NotImplementedError


_containerizers: list[Containerizer] = []


def reset_containerizers() -> None:
    _containerizers.clear()


def init_containerizers(source_dir: str, extra: list[Containerizer] | None = None) -> None:
    """Build the ordered registry (containerizer.go:56-62)."""
    from move2kube_tpu.containerizer.dockerfile import DockerfileContainerizer
    from move2kube_tpu.containerizer.jax_xla import JaxXlaContainerizer
    from move2kube_tpu.containerizer.reuse import ReuseContainerizer
    from move2kube_tpu.containerizer.reuse_dockerfile import ReuseDockerfileContainerizer
    from move2kube_tpu.containerizer.s2i import S2IContainerizer
    from move2kube_tpu.containerizer.cnb import CNBContainerizer
    from move2kube_tpu.containerizer.manual import ManualContainerizer

    reset_containerizers()
    regs: list[Containerizer] = [
        JaxXlaContainerizer(),  # TPU first: GPU training dirs are claimed here
        DockerfileContainerizer(),
        S2IContainerizer(),
        CNBContainerizer(),
        ReuseContainerizer(),
        ReuseDockerfileContainerizer(),
        ManualContainerizer(),  # last resort (manualcontainerizer.go)
    ]
    if extra:
        regs.extend(extra)
    for c in regs:
        try:
            c.init(source_dir)
            _containerizers.append(c)
        except Exception as e:  # noqa: BLE001 - plugin tolerance
            log.warning("containerizer %s failed to init: %s", type(c).__name__, e)


def get_containerizers() -> list[Containerizer]:
    return list(_containerizers)


def get_containerization_options(plan, directory: str) -> dict[str, list[str]]:
    """build-type -> target options for a directory (containerizer.go:64)."""
    out: dict[str, list[str]] = {}
    for c in _containerizers:
        try:
            options = c.get_target_options(plan, directory)
        except Exception as e:  # noqa: BLE001
            log.warning("containerizer %s failed on %s: %s", type(c).__name__, directory, e)
            continue
        if options:
            out[c.get_build_type()] = options
    return out


def get_container(plan, service: PlanService) -> Container:
    """Dispatch to the containerizer matching the service's build type
    (containerizer.go:79)."""
    for c in _containerizers:
        if c.get_build_type() == service.container_build_type:
            return c.get_container(plan, service)
    raise ValueError(
        f"no containerizer for build type {service.container_build_type!r}"
    )
