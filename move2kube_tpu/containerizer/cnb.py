"""Cloud Native Buildpacks containerizer.

Parity: ``internal/containerizer/cnbcontainerizer.go`` + the ``cnb/``
provider chain (cnb_providers.py). The reference probes builder support by
running the CNB lifecycle detector via the docker daemon / pack CLI /
runc; we use the same ordered-provider seam (container runtime CLI → pack
→ static stack heuristic) so planning works with or without a daemon.
Results are memoised per directory (parity: cnbcache).
"""

from __future__ import annotations

from move2kube_tpu.containerizer import cnb_providers
from move2kube_tpu.containerizer.base import Containerizer
from move2kube_tpu.containerizer.scripts import CNB_BUILD_SH
from move2kube_tpu.types.ir import Container
from move2kube_tpu.types.plan import ContainerBuildType, PlanService
from move2kube_tpu.utils import common
from move2kube_tpu.utils.log import get_logger

log = get_logger("containerizer.cnb")

# parity: hardcoded builders, cnbcontainerizer.go:41
BUILDERS = ["gcr.io/buildpacks/builder", "paketobuildpacks/builder-jammy-base"]


class CNBContainerizer(Containerizer):
    def __init__(self) -> None:
        self._cache: dict[str, list[str]] = {}
        self._providers: list | None = None

    def get_build_type(self) -> str:
        return ContainerBuildType.CNB

    @property
    def providers(self) -> list:
        if self._providers is None:
            self._providers = cnb_providers.get_providers()
        return self._providers

    def get_target_options(self, plan, directory: str) -> list[str]:
        if directory in self._cache:
            return self._cache[directory]
        options: list[str] = []
        # cheap stack-heuristic gate first, so directories with no
        # buildpack-shaped stack never cost a docker/pack exec probe
        if cnb_providers.StaticProvider().is_builder_supported(directory, ""):
            live = [
                p for p in self.providers
                if not isinstance(p, cnb_providers.StaticProvider)
                and p.is_available()
            ]
            if live:
                # refine builder list with the first live probe; a probe
                # that denies/errors everywhere falls back to the full
                # list — a broken runtime must not disable CNB
                options = [
                    b for b in BUILDERS
                    if live[0].is_builder_supported(directory, b)
                ] or list(BUILDERS)
            else:
                options = list(BUILDERS)
        self._cache[directory] = options
        return options

    def get_all_buildpacks(self) -> dict[str, list[str]]:
        """Buildpacks baked into the default builders, when a live provider
        can list them (parity: cnb provider.go GetAllBuildpacks:56)."""
        return cnb_providers.get_all_buildpacks(self.providers, BUILDERS)

    def get_container(self, plan, service: PlanService) -> Container:
        if not service.containerization_target_options:
            raise ValueError(f"{service.service_name}: no CNB builder selected")
        builder = service.containerization_target_options[0]
        name = common.make_dns_label(service.service_name)
        image_name = service.image or f"{name}:latest"
        container = Container(
            image_names=[image_name], new=True, build_type=ContainerBuildType.CNB,
        )
        from move2kube_tpu.containerizer.dockerfile import _record_source_dir

        src_dirs = service.source_artifacts.get(PlanService.SOURCE_DIR_ARTIFACT, [])
        if src_dirs:
            _record_source_dir(container, plan, src_dirs[0])
        container.add_file(
            f"{name}-cnb-build.sh",
            common.render_template(CNB_BUILD_SH, {
                "service_name": name,
                "builder": builder,
                "image_name": image_name,
                "context": ".",
            }),
        )
        container.add_exposed_port(common.DEFAULT_SERVICE_PORT)
        return container
