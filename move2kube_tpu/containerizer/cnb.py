"""Cloud Native Buildpacks containerizer.

Parity: ``internal/containerizer/cnbcontainerizer.go`` + the ``cnb/``
provider chain. The reference probes builder support by running the CNB
lifecycle detector via the docker daemon / pack CLI / runc; all of those
are environment-gated. We keep the same provider seam but default to a
static heuristic (stack detection implies buildpack support) so planning
works with no daemon, and shell out to ``pack`` only when available and
``IGNORE_ENVIRONMENT`` is False. Results are memoised per directory
(parity: cnbcache).
"""

from __future__ import annotations

import shutil
import subprocess

from move2kube_tpu.containerizer import stacks
from move2kube_tpu.containerizer.base import Containerizer
from move2kube_tpu.containerizer.scripts import CNB_BUILD_SH
from move2kube_tpu.types.ir import Container
from move2kube_tpu.types.plan import ContainerBuildType, PlanService
from move2kube_tpu.utils import common
from move2kube_tpu.utils.log import get_logger

log = get_logger("containerizer.cnb")

# parity: hardcoded builders, cnbcontainerizer.go:41
BUILDERS = ["gcr.io/buildpacks/builder", "paketobuildpacks/builder-jammy-base"]

# stacks known to be supported by the default builders
_BUILDPACK_STACKS = {
    "python", "django", "nodejs", "golang", "java-maven", "java-gradle",
    "java-ant", "java-war-tomcat", "java-war-liberty", "java-war-jboss",
    "ruby", "php",
}


class CNBContainerizer(Containerizer):
    def __init__(self) -> None:
        self._cache: dict[str, list[str]] = {}
        self._pack = None  # lazily resolved

    def get_build_type(self) -> str:
        return ContainerBuildType.CNB

    def _pack_available(self) -> bool:
        if self._pack is None:
            self._pack = (
                not common.IGNORE_ENVIRONMENT and shutil.which("pack") is not None
            )
        return self._pack

    def get_target_options(self, plan, directory: str) -> list[str]:
        if directory in self._cache:
            return self._cache[directory]
        options: list[str] = []
        matched = {m.stack for m in stacks.detect_stacks(directory)}
        if matched & _BUILDPACK_STACKS:
            if self._pack_available():
                options = [b for b in BUILDERS if self._probe_pack(directory, b)] or list(BUILDERS)
            else:
                options = list(BUILDERS)
        self._cache[directory] = options
        return options

    def _probe_pack(self, directory: str, builder: str) -> bool:
        try:
            res = subprocess.run(
                ["pack", "build", "--dry-run", "--builder", builder, "--path", directory,
                 "m2kt-probe"],
                capture_output=True, timeout=120, check=False,
            )
            return res.returncode == 0
        except (OSError, subprocess.TimeoutExpired):
            return False

    def get_container(self, plan, service: PlanService) -> Container:
        if not service.containerization_target_options:
            raise ValueError(f"{service.service_name}: no CNB builder selected")
        builder = service.containerization_target_options[0]
        name = common.make_dns_label(service.service_name)
        image_name = service.image or f"{name}:latest"
        container = Container(
            image_names=[image_name], new=True, build_type=ContainerBuildType.CNB,
        )
        from move2kube_tpu.containerizer.dockerfile import _record_source_dir

        src_dirs = service.source_artifacts.get(PlanService.SOURCE_DIR_ARTIFACT, [])
        if src_dirs:
            _record_source_dir(container, plan, src_dirs[0])
        container.add_file(
            f"{name}-cnb-build.sh",
            common.render_template(CNB_BUILD_SH, {
                "service_name": name,
                "builder": builder,
                "image_name": image_name,
                "context": ".",
            }),
        )
        container.add_exposed_port(common.DEFAULT_SERVICE_PORT)
        return container
