"""CNB provider chain: probe whether a buildpacks builder supports a dir.

Parity: ``internal/containerizer/cnb/provider.go:31`` — the reference keeps
an ordered chain ``[dockerAPIProvider, containerRuntimeProvider,
packProvider, runcProvider]`` and uses the first available one to (a) run
the CNB lifecycle detector against a source dir (``IsBuilderSupported``,
provider.go:68) and (b) list the buildpacks baked into a builder image
(``GetAllBuildpacks``, provider.go:56).

We keep the same seam with four providers:

- ``DockerAPIProvider`` — talks to the docker daemon REST API directly
  over its unix socket with stdlib ``http.client`` (no docker SDK, no
  CLI binary needed; parity: dockerapiprovider.go:104-300 — daemon-API
  detector run + builder-label buildpack listing).
- ``ContainerRuntimeProvider`` — docker/podman CLI, runs
  ``/cnb/lifecycle/detector`` inside the builder image with the source
  mounted (parity: containerruntimeprovider.go).
- ``PackProvider`` — the ``pack`` CLI (parity: packprovider.go:53).
- ``StaticProvider`` — always-available fallback: a stack match from
  stacks.py implies default-builder support, so planning works with no
  daemon at all (net-new; replaces the reference's hard dependency on a
  container runtime at plan time).

There is no runc provider (runc isn't a dependency of this environment;
the daemon-API and CLI providers cover dockerd/podman setups). Option
lists are memoised per directory by the caller (parity: cnbcache,
cnbcontainerizer.go:41).
"""

from __future__ import annotations

import http.client
import json
import os
import shutil
import socket
import subprocess
import urllib.parse

from move2kube_tpu.utils import common
from move2kube_tpu.utils.log import get_logger

log = get_logger("containerizer.cnb.provider")

_EXEC_TIMEOUT = 120

# builder image label listing the buildpack order (CNB platform spec)
BUILDER_METADATA_LABEL = "io.buildpacks.builder.metadata"


def _run(cmd: list[str], timeout: int = _EXEC_TIMEOUT) -> subprocess.CompletedProcess | None:
    try:
        return subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout, check=False)
    except (OSError, subprocess.TimeoutExpired):
        return None


class _UnixHTTPConnection(http.client.HTTPConnection):
    """HTTP over an AF_UNIX socket (the docker daemon's transport)."""

    def __init__(self, socket_path: str, timeout: float):
        super().__init__("localhost", timeout=timeout)
        self._socket_path = socket_path

    def connect(self) -> None:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(self.timeout)
        sock.connect(self._socket_path)
        self.sock = sock


class DockerAPIProvider:
    """CNB probing straight against the docker Engine API.

    Parity: ``internal/containerizer/cnb/dockerapiprovider.go:104-300`` —
    the reference uses the docker SDK to (a) run the CNB lifecycle
    detector in a container with the source bind-mounted and (b) read the
    builder image's buildpack-order label. This implementation speaks the
    same REST API over the daemon socket with the stdlib, so it works in
    environments that have a dockerd but no docker CLI/SDK.
    """

    API = "/v1.41"

    def __init__(self, socket_path: str | None = None):
        self._socket_path = socket_path
        self._available: bool | None = None

    def _resolve_socket(self) -> str | None:
        if self._socket_path:
            return self._socket_path
        host = os.environ.get("DOCKER_HOST", "")
        if host.startswith("unix://"):
            return host[len("unix://"):]
        if host:
            return None  # tcp daemons: the CLI provider handles those
        return "/var/run/docker.sock"

    def _request(self, method: str, path: str, body: dict | None = None,
                 timeout: float = 30.0) -> tuple[int, bytes]:
        sock_path = self._resolve_socket()
        if sock_path is None:
            return 0, b""
        conn = _UnixHTTPConnection(sock_path, timeout)
        try:
            payload = json.dumps(body).encode() if body is not None else None
            headers = {"Content-Type": "application/json"} if payload else {}
            conn.request(method, self.API + path, body=payload, headers=headers)
            resp = conn.getresponse()
            return resp.status, resp.read()
        except (OSError, http.client.HTTPException) as e:
            log.debug("docker API %s %s failed: %s", method, path, e)
            return 0, b""
        finally:
            conn.close()

    def _json(self, method: str, path: str, body: dict | None = None,
              timeout: float = 30.0) -> tuple[int, dict]:
        status, raw = self._request(method, path, body, timeout)
        try:
            return status, json.loads(raw) if raw else {}
        except json.JSONDecodeError:
            return status, {}

    def is_available(self) -> bool:
        if self._available is None:
            self._available = False
            if not common.IGNORE_ENVIRONMENT:
                sock_path = self._resolve_socket()
                if sock_path and os.path.exists(sock_path):
                    status, _ = self._request("GET", "/_ping", timeout=5.0)
                    self._available = status == 200
        return self._available

    def is_builder_supported(self, directory: str, builder: str) -> bool:
        """create/start/wait a detector container; exit 0 == supported."""
        create_body = {
            "Image": builder,
            "Entrypoint": ["/cnb/lifecycle/detector"],
            "Cmd": ["-app", "/workspace"],
            "HostConfig": {"Binds": [f"{os.path.abspath(directory)}:/workspace:ro"]},
        }
        status, created = self._json("POST", "/containers/create", create_body)
        if status == 404:
            # builder image not present locally; try a daemon-side pull
            # (parity: dockerapiprovider.go isBuilderAvailable pulls first).
            # An explicit tag is required for tag refs — an untagged
            # fromImage pulls EVERY tag — while digest refs (repo@sha256:…)
            # must go through verbatim with no tag param.
            if "@" in builder:
                pull = f"fromImage={urllib.parse.quote(builder, safe='')}"
            else:
                name, _, tag = builder.rpartition(":")
                if not name or "/" in tag:  # no tag, or ':' was a registry port
                    name, tag = builder, "latest"
                pull = (f"fromImage={urllib.parse.quote(name, safe='')}"
                        f"&tag={urllib.parse.quote(tag, safe='')}")
            self._request("POST", f"/images/create?{pull}",
                          timeout=_EXEC_TIMEOUT)
            status, created = self._json("POST", "/containers/create",
                                         create_body)
        cid = created.get("Id")
        if status != 201 or not cid:
            return False
        try:
            status, _ = self._request("POST", f"/containers/{cid}/start")
            if status not in (204, 304):
                return False
            status, result = self._json("POST", f"/containers/{cid}/wait",
                                        timeout=_EXEC_TIMEOUT)
            return status == 200 and result.get("StatusCode") == 0
        finally:
            self._request("DELETE", f"/containers/{cid}?force=true")

    def get_all_buildpacks(self, builders: list[str]) -> dict[str, list[str]]:
        out: dict[str, list[str]] = {}
        for builder in builders:
            quoted = urllib.parse.quote(builder, safe="")
            status, info = self._json("GET", f"/images/{quoted}/json")
            if status != 200:
                continue
            labels = (info.get("Config") or {}).get("Labels") or {}
            try:
                meta = json.loads(labels.get(BUILDER_METADATA_LABEL, ""))
                ids = [bp.get("id", "") for bp in meta.get("buildpacks", [])
                       if bp.get("id")]
            except (json.JSONDecodeError, AttributeError):
                continue
            if ids:
                out[builder] = ids
        return out


class ContainerRuntimeProvider:
    """Run the CNB lifecycle detector via the docker/podman CLI.

    Parity: ``internal/containerizer/cnb/containerruntimeprovider.go``.
    """

    def __init__(self) -> None:
        self._runtime: str | None | bool = False  # False = unresolved

    def _get_runtime(self) -> str | None:
        if self._runtime is False:
            self._runtime = None
            if not common.IGNORE_ENVIRONMENT:
                for cli in ("docker", "podman"):
                    if not shutil.which(cli):
                        continue
                    res = _run([cli, "info"], timeout=15)
                    if res is not None and res.returncode == 0:
                        self._runtime = cli
                        break
        return self._runtime

    def is_available(self) -> bool:
        return self._get_runtime() is not None

    def is_builder_supported(self, directory: str, builder: str) -> bool:
        cli = self._get_runtime()
        if cli is None:
            return False
        # parity: run /cnb/lifecycle/detector with the app mounted at the
        # CNB workspace path; detector exits 0 iff some buildpack group
        # detects the source (containerruntimeprovider.go)
        res = _run([
            cli, "run", "--rm",
            "-v", f"{directory}:/workspace:ro",
            "--entrypoint", "/cnb/lifecycle/detector",
            builder, "-app", "/workspace",
        ])
        return res is not None and res.returncode == 0

    def get_all_buildpacks(self, builders: list[str]) -> dict[str, list[str]]:
        """Builder image label ``io.buildpacks.builder.metadata`` lists its
        buildpacks (parity: dockerapiprovider.go label read)."""
        cli = self._get_runtime()
        out: dict[str, list[str]] = {}
        if cli is None:
            return out
        for builder in builders:
            res = _run([
                cli, "image", "inspect", builder, "--format",
                '{{ index .Config.Labels "io.buildpacks.builder.metadata" }}',
            ], timeout=30)
            if res is None or res.returncode != 0:
                continue
            try:
                meta = json.loads(res.stdout.strip())
                out[builder] = [
                    bp.get("id", "") for bp in meta.get("buildpacks", []) if bp.get("id")
                ]
            except (json.JSONDecodeError, AttributeError):
                continue
        return out


class PackProvider:
    """Probe via the ``pack`` CLI (parity: packprovider.go:53)."""

    def is_available(self) -> bool:
        return not common.IGNORE_ENVIRONMENT and shutil.which("pack") is not None

    def is_builder_supported(self, directory: str, builder: str) -> bool:
        res = _run(["pack", "build", "m2kt-probe", "--dry-run",
                    "--builder", builder, "--path", directory])
        return res is not None and res.returncode == 0

    def get_all_buildpacks(self, builders: list[str]) -> dict[str, list[str]]:
        out: dict[str, list[str]] = {}
        for builder in builders:
            res = _run(["pack", "builder", "inspect", builder,
                        "--output", "json"], timeout=60)
            if res is None or res.returncode != 0:
                continue
            try:
                meta = json.loads(res.stdout)
                bps = (meta.get("remote_info") or meta.get("local_info") or {}
                       ).get("buildpacks", [])
                out[builder] = [bp.get("id", "") for bp in bps if bp.get("id")]
            except json.JSONDecodeError:
                continue
        return out


class StaticProvider:
    """Always-available fallback: stack detection implies support for the
    default builders. Keeps planning runnable with no container runtime."""

    # stacks known to be supported by the default builders' buildpacks
    SUPPORTED_STACKS = {
        "python", "django", "nodejs", "golang", "java-maven", "java-gradle",
        "java-ant", "java-war-tomcat", "java-war-liberty", "java-war-jboss",
        "ruby", "php",
    }

    def is_available(self) -> bool:
        return True

    def is_builder_supported(self, directory: str, builder: str) -> bool:
        from move2kube_tpu.containerizer import stacks

        return bool(
            {m.stack for m in stacks.detect_stacks(directory)} & self.SUPPORTED_STACKS
        )

    def get_all_buildpacks(self, builders: list[str]) -> dict[str, list[str]]:
        return {}


def get_providers() -> list:
    """Ordered chain (provider.go:31: dockerAPI, containerRuntime, pack,
    runc); live providers first, static last (our runc stand-in)."""
    return [DockerAPIProvider(), ContainerRuntimeProvider(), PackProvider(),
            StaticProvider()]


def is_builder_supported(providers: list, directory: str, builder: str) -> bool:
    """True iff any available provider affirms support. A provider that is
    unavailable, errors, or denies falls through to the next one — a
    present-but-broken docker/pack must not disable CNB when the static
    heuristic would have allowed it."""
    return any(
        p.is_available() and p.is_builder_supported(directory, builder)
        for p in providers
    )


def get_all_buildpacks(providers: list, builders: list[str]) -> dict[str, list[str]]:
    for p in providers:
        if p.is_available():
            bps = p.get_all_buildpacks(builders)
            if bps:
                return bps
    return {}
