"""CNB provider chain: probe whether a buildpacks builder supports a dir.

Parity: ``internal/containerizer/cnb/provider.go:31`` — the reference keeps
an ordered chain ``[dockerAPIProvider, containerRuntimeProvider,
packProvider, runcProvider]`` and uses the first available one to (a) run
the CNB lifecycle detector against a source dir (``IsBuilderSupported``,
provider.go:68) and (b) list the buildpacks baked into a builder image
(``GetAllBuildpacks``, provider.go:56).

We keep the same seam with five providers:

- ``DockerAPIProvider`` — talks to the docker daemon REST API directly
  over its unix socket with stdlib ``http.client`` (no docker SDK, no
  CLI binary needed; parity: dockerapiprovider.go:104-300 — daemon-API
  detector run + builder-label buildpack listing).
- ``ContainerRuntimeProvider`` — docker/podman CLI, runs
  ``/cnb/lifecycle/detector`` inside the builder image with the source
  mounted (parity: containerruntimeprovider.go).
- ``PackProvider`` — the ``pack`` CLI (parity: packprovider.go:53).
- ``StaticProvider`` — always-available fallback: a stack match from
  stacks.py implies default-builder support, so planning works with no
  daemon at all (net-new; replaces the reference's hard dependency on a
  container runtime at plan time).

- ``RuncProvider`` — daemon-free: ``skopeo`` fetches the builder image
  into an OCI layout, ``umoci`` unpacks it to a bundle, and ``runc``
  executes the detector with the source bind-mounted (parity:
  runcprovider.go:108-160). For locked-down hosts with no docker/podman
  daemon at all.

Option lists are memoised per directory by the caller (parity: cnbcache,
cnbcontainerizer.go:41).
"""

from __future__ import annotations

import http.client
import json
import os
import shutil
import socket
import subprocess
import urllib.parse

from move2kube_tpu.utils import common
from move2kube_tpu.utils.log import get_logger

log = get_logger("containerizer.cnb.provider")

_EXEC_TIMEOUT = 120

# builder image label listing the buildpack order (CNB platform spec)
BUILDER_METADATA_LABEL = "io.buildpacks.builder.metadata"


def _buildpack_ids_from_labels(labels: dict | None) -> list[str]:
    """Buildpack ids from an image's label map (shared by every provider
    that can reach image labels)."""
    try:
        meta = json.loads((labels or {}).get(BUILDER_METADATA_LABEL, ""))
        return [bp.get("id", "") for bp in meta.get("buildpacks", [])
                if bp.get("id")]
    except (json.JSONDecodeError, AttributeError):
        return []


def _run(cmd: list[str], timeout: int = _EXEC_TIMEOUT) -> subprocess.CompletedProcess | None:
    try:
        return subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout, check=False)
    except (OSError, subprocess.TimeoutExpired):
        return None


class _UnixHTTPConnection(http.client.HTTPConnection):
    """HTTP over an AF_UNIX socket (the docker daemon's transport)."""

    def __init__(self, socket_path: str, timeout: float):
        super().__init__("localhost", timeout=timeout)
        self._socket_path = socket_path

    def connect(self) -> None:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(self.timeout)
        sock.connect(self._socket_path)
        self.sock = sock


class DockerAPIProvider:
    """CNB probing straight against the docker Engine API.

    Parity: ``internal/containerizer/cnb/dockerapiprovider.go:104-300`` —
    the reference uses the docker SDK to (a) run the CNB lifecycle
    detector in a container with the source bind-mounted and (b) read the
    builder image's buildpack-order label. This implementation speaks the
    same REST API over the daemon socket with the stdlib, so it works in
    environments that have a dockerd but no docker CLI/SDK.
    """

    API = "/v1.41"

    def __init__(self, socket_path: str | None = None):
        self._socket_path = socket_path
        self._available: bool | None = None

    def _resolve_socket(self) -> str | None:
        if self._socket_path:
            return self._socket_path
        host = os.environ.get("DOCKER_HOST", "")
        if host.startswith("unix://"):
            return host[len("unix://"):]
        if host:
            return None  # tcp daemons: the CLI provider handles those
        return "/var/run/docker.sock"

    def _request(self, method: str, path: str, body: dict | None = None,
                 timeout: float = 30.0) -> tuple[int, bytes]:
        sock_path = self._resolve_socket()
        if sock_path is None:
            return 0, b""
        conn = _UnixHTTPConnection(sock_path, timeout)
        try:
            payload = json.dumps(body).encode() if body is not None else None
            headers = {"Content-Type": "application/json"} if payload else {}
            conn.request(method, self.API + path, body=payload, headers=headers)
            resp = conn.getresponse()
            return resp.status, resp.read()
        except (OSError, http.client.HTTPException) as e:
            log.debug("docker API %s %s failed: %s", method, path, e)
            return 0, b""
        finally:
            conn.close()

    def _json(self, method: str, path: str, body: dict | None = None,
              timeout: float = 30.0) -> tuple[int, dict]:
        status, raw = self._request(method, path, body, timeout)
        try:
            return status, json.loads(raw) if raw else {}
        except json.JSONDecodeError:
            return status, {}

    def is_available(self) -> bool:
        if self._available is None:
            self._available = False
            if not common.IGNORE_ENVIRONMENT:
                sock_path = self._resolve_socket()
                if sock_path and os.path.exists(sock_path):
                    status, _ = self._request("GET", "/_ping", timeout=5.0)
                    self._available = status == 200
        return self._available

    def is_builder_supported(self, directory: str, builder: str) -> bool:
        """create/start/wait a detector container; exit 0 == supported."""
        create_body = {
            "Image": builder,
            "Entrypoint": ["/cnb/lifecycle/detector"],
            "Cmd": ["-app", "/workspace"],
            "HostConfig": {"Binds": [f"{os.path.abspath(directory)}:/workspace:ro"]},
        }
        status, created = self._json("POST", "/containers/create", create_body)
        if status == 404:
            # builder image not present locally; try a daemon-side pull
            # (parity: dockerapiprovider.go isBuilderAvailable pulls first).
            # An explicit tag is required for tag refs — an untagged
            # fromImage pulls EVERY tag — while digest refs (repo@sha256:…)
            # must go through verbatim with no tag param.
            if "@" in builder:
                pull = f"fromImage={urllib.parse.quote(builder, safe='')}"
            else:
                name, _, tag = builder.rpartition(":")
                if not name or "/" in tag:  # no tag, or ':' was a registry port
                    name, tag = builder, "latest"
                pull = (f"fromImage={urllib.parse.quote(name, safe='')}"
                        f"&tag={urllib.parse.quote(tag, safe='')}")
            self._request("POST", f"/images/create?{pull}",
                          timeout=_EXEC_TIMEOUT)
            status, created = self._json("POST", "/containers/create",
                                         create_body)
        cid = created.get("Id")
        if status != 201 or not cid:
            return False
        try:
            status, _ = self._request("POST", f"/containers/{cid}/start")
            if status not in (204, 304):
                return False
            status, result = self._json("POST", f"/containers/{cid}/wait",
                                        timeout=_EXEC_TIMEOUT)
            return status == 200 and result.get("StatusCode") == 0
        finally:
            self._request("DELETE", f"/containers/{cid}?force=true")

    def get_all_buildpacks(self, builders: list[str]) -> dict[str, list[str]]:
        out: dict[str, list[str]] = {}
        for builder in builders:
            quoted = urllib.parse.quote(builder, safe="")
            status, info = self._json("GET", f"/images/{quoted}/json")
            if status != 200:
                continue
            ids = _buildpack_ids_from_labels(
                (info.get("Config") or {}).get("Labels"))
            if ids:
                out[builder] = ids
        return out


class ContainerRuntimeProvider:
    """Run the CNB lifecycle detector via the docker/podman CLI.

    Parity: ``internal/containerizer/cnb/containerruntimeprovider.go``.
    """

    def __init__(self) -> None:
        self._runtime: str | None | bool = False  # False = unresolved

    def _get_runtime(self) -> str | None:
        if self._runtime is False:
            self._runtime = None
            if not common.IGNORE_ENVIRONMENT:
                for cli in ("docker", "podman"):
                    if not shutil.which(cli):
                        continue
                    res = _run([cli, "info"], timeout=15)
                    if res is not None and res.returncode == 0:
                        self._runtime = cli
                        break
        return self._runtime

    def is_available(self) -> bool:
        return self._get_runtime() is not None

    def is_builder_supported(self, directory: str, builder: str) -> bool:
        cli = self._get_runtime()
        if cli is None:
            return False
        # parity: run /cnb/lifecycle/detector with the app mounted at the
        # CNB workspace path; detector exits 0 iff some buildpack group
        # detects the source (containerruntimeprovider.go)
        res = _run([
            cli, "run", "--rm",
            "-v", f"{directory}:/workspace:ro",
            "--entrypoint", "/cnb/lifecycle/detector",
            builder, "-app", "/workspace",
        ])
        return res is not None and res.returncode == 0

    def get_all_buildpacks(self, builders: list[str]) -> dict[str, list[str]]:
        """Builder image label ``io.buildpacks.builder.metadata`` lists its
        buildpacks (parity: dockerapiprovider.go label read)."""
        cli = self._get_runtime()
        out: dict[str, list[str]] = {}
        if cli is None:
            return out
        for builder in builders:
            res = _run([
                cli, "image", "inspect", builder, "--format",
                '{{ index .Config.Labels "io.buildpacks.builder.metadata" }}',
            ], timeout=30)
            if res is None or res.returncode != 0:
                continue
            ids = _buildpack_ids_from_labels(
                {BUILDER_METADATA_LABEL: res.stdout.strip()})
            if ids:
                out[builder] = ids
        return out


class PackProvider:
    """Probe via the ``pack`` CLI (parity: packprovider.go:53)."""

    def is_available(self) -> bool:
        return not common.IGNORE_ENVIRONMENT and shutil.which("pack") is not None

    def is_builder_supported(self, directory: str, builder: str) -> bool:
        res = _run(["pack", "build", "m2kt-probe", "--dry-run",
                    "--builder", builder, "--path", directory])
        return res is not None and res.returncode == 0

    def get_all_buildpacks(self, builders: list[str]) -> dict[str, list[str]]:
        out: dict[str, list[str]] = {}
        for builder in builders:
            res = _run(["pack", "builder", "inspect", builder,
                        "--output", "json"], timeout=60)
            if res is None or res.returncode != 0:
                continue
            try:
                meta = json.loads(res.stdout)
                bps = (meta.get("remote_info") or meta.get("local_info") or {}
                       ).get("buildpacks", [])
                out[builder] = [bp.get("id", "") for bp in bps if bp.get("id")]
            except json.JSONDecodeError:
                continue
        return out


class RuncProvider:
    """Daemon-free CNB probing: skopeo + umoci + runc.

    Parity: ``internal/containerizer/cnb/runcprovider.go:108-160`` —
    the builder image is fetched into an OCI layout (skopeo), unpacked
    into a runtime bundle (umoci), the bundle's ``config.json`` patched
    to bind-mount the source at ``/workspace`` and run
    ``/cnb/lifecycle/detector``, then executed with runc. Buildpack
    listing goes through ``skopeo inspect`` labels without pulling.
    """

    def __init__(self, cache_dir: str | None = None):
        self._cache = cache_dir or os.path.join(
            os.path.expanduser("~"), ".m2kt", "cnb")
        # builders whose fetch failed this process: don't re-pay the
        # skopeo/umoci timeouts on every probe of an offline host
        self._fetch_failed: set[str] = set()
        self._run_seq = 0

    def is_available(self) -> bool:
        return (not common.IGNORE_ENVIRONMENT
                and all(shutil.which(b) for b in ("runc", "skopeo", "umoci")))

    def _safe_key(self, builder: str) -> str:
        # lossless: distinct refs (tag vs digest vs path) stay distinct
        return urllib.parse.quote(builder, safe="")

    def _bundle_dir(self, builder: str) -> str:
        return os.path.join(self._cache, "bundles", self._safe_key(builder))

    def _layout_dir(self, builder: str) -> str:
        return os.path.join(self._cache, "images", self._safe_key(builder))

    def _read_config(self, bundle: str) -> dict | None:
        try:
            with open(os.path.join(bundle, "config.json"),
                      encoding="utf-8") as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    def _ensure_bundle(self, builder: str) -> str | None:
        if builder in self._fetch_failed:
            return None
        bundle = self._bundle_dir(builder)
        if self._read_config(bundle) is not None:
            return bundle
        # a dir without a parseable config is a partial fetch: re-fetch
        # from scratch (umoci refuses to unpack over a non-empty dir)
        oci_layout = self._layout_dir(builder)
        shutil.rmtree(bundle, ignore_errors=True)
        shutil.rmtree(oci_layout, ignore_errors=True)
        os.makedirs(os.path.dirname(bundle), exist_ok=True)
        os.makedirs(os.path.dirname(oci_layout), exist_ok=True)
        res = _run(["skopeo", "copy", f"docker://{builder}",
                    f"oci:{oci_layout}:builder"], timeout=600)
        if res is None or res.returncode != 0:
            log.debug("skopeo copy failed for %s", builder)
            self._fetch_failed.add(builder)
            shutil.rmtree(oci_layout, ignore_errors=True)
            return None
        res = _run(["umoci", "unpack", "--image", f"{oci_layout}:builder",
                    bundle], timeout=600)
        if res is None or res.returncode != 0 \
                or self._read_config(bundle) is None:
            log.debug("umoci unpack failed for %s", builder)
            self._fetch_failed.add(builder)
            shutil.rmtree(bundle, ignore_errors=True)
            return None
        return bundle

    def is_builder_supported(self, directory: str, builder: str) -> bool:
        bundle = self._ensure_bundle(builder)
        if bundle is None:
            return False
        spec = self._read_config(bundle)
        if spec is None:
            return False
        # the detector writes group.toml/plan.toml under /layers; the
        # rootfs stays read-only (it is shared by concurrent probes), so
        # /layers and /tmp get private tmpfs mounts instead
        mounts = [
            {"source": os.path.abspath(directory),
             "destination": "/workspace", "type": "bind",
             "options": ["rbind", "ro"]},
            {"source": "tmpfs", "destination": "/layers", "type": "tmpfs",
             "options": ["nosuid", "nodev", "mode=1777"]},
            {"source": "tmpfs", "destination": "/tmp", "type": "tmpfs",
             "options": ["nosuid", "nodev", "mode=1777"]},
        ]
        taken = {m["destination"] for m in mounts}
        spec["mounts"] = [m for m in spec.get("mounts", [])
                          if m.get("destination") not in taken] + mounts
        spec.setdefault("process", {})
        spec["process"]["args"] = ["/cnb/lifecycle/detector", "-app", "/workspace"]
        spec["process"]["terminal"] = False
        # the rootfs is shared read-only; the patched config goes into a
        # private per-call bundle so concurrent probes of the same builder
        # (different source dirs) can't race on one config.json
        root = spec.setdefault("root", {})
        root["path"] = os.path.join(bundle, root.get("path") or "rootfs") \
            if not os.path.isabs(root.get("path") or "rootfs") \
            else root["path"]
        root.setdefault("readonly", True)
        self._run_seq += 1
        name = f"m2kt-cnb-{os.getpid()}-{self._run_seq}"
        run_bundle = os.path.join(self._cache, "runs", name)
        os.makedirs(run_bundle, exist_ok=True)
        try:
            with open(os.path.join(run_bundle, "config.json"), "w",
                      encoding="utf-8") as f:
                json.dump(spec, f)
            res = _run(["runc", "run", "--bundle", run_bundle, name],
                       timeout=_EXEC_TIMEOUT)
            if res is None or res.returncode != 0:
                return False
            return "No buildpack groups passed detection" not in (
                res.stdout + res.stderr)
        except OSError as e:
            log.debug("cannot stage run bundle for %s: %s", builder, e)
            return False
        finally:
            # a timed-out run can leave container state behind; clear it
            # so the name space and disk don't accumulate
            _run(["runc", "delete", "--force", name], timeout=30)
            shutil.rmtree(run_bundle, ignore_errors=True)

    def get_all_buildpacks(self, builders: list[str]) -> dict[str, list[str]]:
        out: dict[str, list[str]] = {}
        for builder in builders:
            # prefer the cached OCI layout (offline-friendly); fall back
            # to a registry inspect
            layout = self._layout_dir(builder)
            if os.path.exists(os.path.join(layout, "index.json")):
                res = _run(["skopeo", "inspect", f"oci:{layout}:builder"],
                           timeout=60)
            else:
                res = _run(["skopeo", "inspect", f"docker://{builder}"],
                           timeout=60)
            if res is None or res.returncode != 0:
                continue
            try:
                info = json.loads(res.stdout)
            except json.JSONDecodeError:
                continue
            ids = _buildpack_ids_from_labels(info.get("Labels"))
            if ids:
                out[builder] = ids
        return out


class StaticProvider:
    """Always-available fallback: stack detection implies support for the
    default builders. Keeps planning runnable with no container runtime."""

    # stacks known to be supported by the default builders' buildpacks
    SUPPORTED_STACKS = {
        "python", "django", "nodejs", "golang", "java-maven", "java-gradle",
        "java-ant", "java-war-tomcat", "java-war-liberty", "java-war-jboss",
        "ruby", "php",
    }

    def is_available(self) -> bool:
        return True

    def is_builder_supported(self, directory: str, builder: str) -> bool:
        from move2kube_tpu.containerizer import stacks

        return bool(
            {m.stack for m in stacks.detect_stacks(directory)} & self.SUPPORTED_STACKS
        )

    def get_all_buildpacks(self, builders: list[str]) -> dict[str, list[str]]:
        return {}


def get_providers() -> list:
    """Ordered chain (provider.go:31: dockerAPI, containerRuntime, pack,
    runc); live providers first, the always-available static heuristic
    last so planning works with no runtime at all."""
    return [DockerAPIProvider(), ContainerRuntimeProvider(), PackProvider(),
            RuncProvider(), StaticProvider()]


def is_builder_supported(providers: list, directory: str, builder: str) -> bool:
    """True iff any available provider affirms support. A provider that is
    unavailable, errors, or denies falls through to the next one — a
    present-but-broken docker/pack must not disable CNB when the static
    heuristic would have allowed it."""
    return any(
        p.is_available() and p.is_builder_supported(directory, builder)
        for p in providers
    )


def get_all_buildpacks(providers: list, builders: list[str]) -> dict[str, list[str]]:
    for p in providers:
        if p.is_available():
            bps = p.get_all_buildpacks(builders)
            if bps:
                return bps
    return {}
