"""CNB provider chain: probe whether a buildpacks builder supports a dir.

Parity: ``internal/containerizer/cnb/provider.go:31`` — the reference keeps
an ordered chain ``[dockerAPIProvider, containerRuntimeProvider,
packProvider, runcProvider]`` and uses the first available one to (a) run
the CNB lifecycle detector against a source dir (``IsBuilderSupported``,
provider.go:68) and (b) list the buildpacks baked into a builder image
(``GetAllBuildpacks``, provider.go:56).

We keep the same seam with three providers:

- ``ContainerRuntimeProvider`` — docker/podman CLI, runs
  ``/cnb/lifecycle/detector`` inside the builder image with the source
  mounted (parity: containerruntimeprovider.go).
- ``PackProvider`` — the ``pack`` CLI (parity: packprovider.go:53).
- ``StaticProvider`` — always-available fallback: a stack match from
  stacks.py implies default-builder support, so planning works with no
  daemon at all (net-new; replaces the reference's hard dependency on a
  container runtime at plan time).

There is no dockerAPI/runc provider because neither the docker SDK nor
runc is a dependency of this environment; the CLI provider covers both
docker and podman. Option lists are memoised per directory by the caller
(parity: cnbcache, cnbcontainerizer.go:41).
"""

from __future__ import annotations

import json
import shutil
import subprocess

from move2kube_tpu.utils import common
from move2kube_tpu.utils.log import get_logger

log = get_logger("containerizer.cnb.provider")

_EXEC_TIMEOUT = 120


def _run(cmd: list[str], timeout: int = _EXEC_TIMEOUT) -> subprocess.CompletedProcess | None:
    try:
        return subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout, check=False)
    except (OSError, subprocess.TimeoutExpired):
        return None


class ContainerRuntimeProvider:
    """Run the CNB lifecycle detector via the docker/podman CLI.

    Parity: ``internal/containerizer/cnb/containerruntimeprovider.go``.
    """

    def __init__(self) -> None:
        self._runtime: str | None | bool = False  # False = unresolved

    def _get_runtime(self) -> str | None:
        if self._runtime is False:
            self._runtime = None
            if not common.IGNORE_ENVIRONMENT:
                for cli in ("docker", "podman"):
                    if not shutil.which(cli):
                        continue
                    res = _run([cli, "info"], timeout=15)
                    if res is not None and res.returncode == 0:
                        self._runtime = cli
                        break
        return self._runtime

    def is_available(self) -> bool:
        return self._get_runtime() is not None

    def is_builder_supported(self, directory: str, builder: str) -> bool:
        cli = self._get_runtime()
        if cli is None:
            return False
        # parity: run /cnb/lifecycle/detector with the app mounted at the
        # CNB workspace path; detector exits 0 iff some buildpack group
        # detects the source (containerruntimeprovider.go)
        res = _run([
            cli, "run", "--rm",
            "-v", f"{directory}:/workspace:ro",
            "--entrypoint", "/cnb/lifecycle/detector",
            builder, "-app", "/workspace",
        ])
        return res is not None and res.returncode == 0

    def get_all_buildpacks(self, builders: list[str]) -> dict[str, list[str]]:
        """Builder image label ``io.buildpacks.builder.metadata`` lists its
        buildpacks (parity: dockerapiprovider.go label read)."""
        cli = self._get_runtime()
        out: dict[str, list[str]] = {}
        if cli is None:
            return out
        for builder in builders:
            res = _run([
                cli, "image", "inspect", builder, "--format",
                '{{ index .Config.Labels "io.buildpacks.builder.metadata" }}',
            ], timeout=30)
            if res is None or res.returncode != 0:
                continue
            try:
                meta = json.loads(res.stdout.strip())
                out[builder] = [
                    bp.get("id", "") for bp in meta.get("buildpacks", []) if bp.get("id")
                ]
            except (json.JSONDecodeError, AttributeError):
                continue
        return out


class PackProvider:
    """Probe via the ``pack`` CLI (parity: packprovider.go:53)."""

    def is_available(self) -> bool:
        return not common.IGNORE_ENVIRONMENT and shutil.which("pack") is not None

    def is_builder_supported(self, directory: str, builder: str) -> bool:
        res = _run(["pack", "build", "m2kt-probe", "--dry-run",
                    "--builder", builder, "--path", directory])
        return res is not None and res.returncode == 0

    def get_all_buildpacks(self, builders: list[str]) -> dict[str, list[str]]:
        out: dict[str, list[str]] = {}
        for builder in builders:
            res = _run(["pack", "builder", "inspect", builder,
                        "--output", "json"], timeout=60)
            if res is None or res.returncode != 0:
                continue
            try:
                meta = json.loads(res.stdout)
                bps = (meta.get("remote_info") or meta.get("local_info") or {}
                       ).get("buildpacks", [])
                out[builder] = [bp.get("id", "") for bp in bps if bp.get("id")]
            except json.JSONDecodeError:
                continue
        return out


class StaticProvider:
    """Always-available fallback: stack detection implies support for the
    default builders. Keeps planning runnable with no container runtime."""

    # stacks known to be supported by the default builders' buildpacks
    SUPPORTED_STACKS = {
        "python", "django", "nodejs", "golang", "java-maven", "java-gradle",
        "java-ant", "java-war-tomcat", "java-war-liberty", "java-war-jboss",
        "ruby", "php",
    }

    def is_available(self) -> bool:
        return True

    def is_builder_supported(self, directory: str, builder: str) -> bool:
        from move2kube_tpu.containerizer import stacks

        return bool(
            {m.stack for m in stacks.detect_stacks(directory)} & self.SUPPORTED_STACKS
        )

    def get_all_buildpacks(self, builders: list[str]) -> dict[str, list[str]]:
        return {}


def get_providers() -> list:
    """Ordered chain (provider.go:31); live providers first, static last."""
    return [ContainerRuntimeProvider(), PackProvider(), StaticProvider()]


def is_builder_supported(providers: list, directory: str, builder: str) -> bool:
    """True iff any available provider affirms support. A provider that is
    unavailable, errors, or denies falls through to the next one — a
    present-but-broken docker/pack must not disable CNB when the static
    heuristic would have allowed it."""
    return any(
        p.is_available() and p.is_builder_supported(directory, builder)
        for p in providers
    )


def get_all_buildpacks(providers: list, builders: list[str]) -> dict[str, list[str]]:
    for p in providers:
        if p.is_available():
            bps = p.get_all_buildpacks(builders)
            if bps:
                return bps
    return {}
