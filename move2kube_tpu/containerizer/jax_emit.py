"""Emission logic of the jax-xla containerizer.

Builds the Container for a detected GPU training service:

- ``train_tpu.py``: a complete JAX training program for the detected model
  family, rendered from ``assets/jax/train_tpu.py`` with the TPU mesh that
  maps the workload's GPU parallelism (DDP->data, ZeRO->fsdp, TP->tensor);
  detected inference servers emit ``serve_tpu.py`` instead — the
  continuous-batching decode server over the vendored serving engine
  (paged KV cache, bucketed prefill);
- the **vendored model zoo**: ``move2kube_tpu/{models,parallel,ops}`` source
  files are copied verbatim into the image, so the emitted program uses the
  exact code this repo tests (single source of truth, no pip dependency on
  move2kube-tpu itself);
- a TPU-VM ``Dockerfile`` + ``requirements.txt`` (jax[tpu], flax, optax);
- the usual ``<svc>-docker-build.sh``.
"""

from __future__ import annotations

import os

from move2kube_tpu.containerizer.scripts import DOCKER_BUILD_SH
from move2kube_tpu.parallel.mesh import infer_mesh_config
from move2kube_tpu.types.ir import Container
from move2kube_tpu.types.plan import AcceleratorInfo, ContainerBuildType, PlanService
from move2kube_tpu.utils import common
from move2kube_tpu.utils.log import get_logger

log = get_logger("containerizer.jaxemit")

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_ASSETS = os.path.join(_PKG_ROOT, "assets", "jax")

# subpackages vendored into every emitted image
# "native" ships its .py fallback AND the C source: the vendored tree is
# copied, not pip-installed, so the emitted Dockerfile best-effort-builds
# the extension (transient gcc install, `|| true`); when that fails
# gather_rows degrades to the numpy fallback. "resilience" is the
# preemption/supervisor/goodput stack the image's entrypoint runs under.
# "obs" is the stdlib-only telemetry plane (Prometheus exposition +
# /profile endpoint) both entrypoints serve on M2KT_METRICS_PORT.
# "serving/fleet" rides along explicitly — the vendoring walk below is a
# flat listdir per entry, not recursive.
VENDORED_SUBPACKAGES = ("models", "parallel", "ops", "native", "resilience",
                        "serving", "serving/fleet", "serving/sched", "obs")

REQUIREMENTS = """jax[tpu]>=0.4.35
flax
optax
numpy
orbax-checkpoint
"""

# families accepted as containerization target options; "gpt2" may also
# be chosen explicitly during curation (detection reports "gpt" and the
# no-model-parallelism refinement below picks gpt2 automatically)
KNOWN_FAMILIES = ("resnet", "bert", "llama", "gpt", "gpt2", "unet")


def _vendor_package(container: Container) -> None:
    container.add_file(
        "move2kube_tpu/__init__.py",
        '"""Vendored move2kube-tpu model zoo (generated image payload)."""\n'
        '__version__ = "vendored"\n',
    )
    for sub in VENDORED_SUBPACKAGES:
        sub_dir = os.path.join(_PKG_ROOT, sub)
        for fname in sorted(os.listdir(sub_dir)):
            if not fname.endswith((".py", ".c")):
                continue
            with open(os.path.join(sub_dir, fname), encoding="utf-8") as f:
                container.add_file(f"move2kube_tpu/{sub}/{fname}", f.read())
    # models/data.py and parallel/sharding.py log through utils.log, and
    # resilience/goodput.py mirrors its ledger into utils.trace counters;
    # ship just those two stdlib-only modules under a stub __init__ — the
    # full utils package would drag yaml and the QA engine into the image
    container.add_file("move2kube_tpu/utils/__init__.py", "")
    for mod in ("log.py", "trace.py"):
        with open(os.path.join(_PKG_ROOT, "utils", mod),
                  encoding="utf-8") as f:
            container.add_file(f"move2kube_tpu/utils/{mod}", f.read())


TPU_ACCELERATOR_OPTIONS = [
    "tpu-v5-lite-podslice", "tpu-v5p-slice", "tpu-v4-podslice",
    "tpu-v6e-slice",
]


# memoised per (type, path): emission resolves the same target cluster
# once per run, not once per service (and warns once on unreadable paths)
_cluster_acc_cache: dict = {}


def _cluster_tpu_accelerators(plan) -> list[str]:
    """Accelerator types the plan's target cluster actually has (collected
    metadata or builtin profile); empty when unknown."""
    if plan is None:
        return []
    try:
        target = plan.kubernetes.target_cluster
    except AttributeError:
        return []
    key = (getattr(target, "type", ""), getattr(target, "path", ""))
    if not any(key):
        return []
    if key not in _cluster_acc_cache:
        from move2kube_tpu.metadata.clusters import resolve_target_cluster

        _cluster_acc_cache[key] = list(
            resolve_target_cluster(target).tpu_accelerators)
    return list(_cluster_acc_cache[key])


def _ask_tpu_slice(name: str, acc: AcceleratorInfo, plan=None) -> None:
    """TPU slice choice is a QA problem like every other decision
    (reference philosophy: all runtime decisions are Problems —
    engine.go fetch chain). Defaults keep headless runs identical to
    detection; interactive/REST/cache answers override the slice,
    resize the host count, and rescale the chip count the emitted
    trainer's mesh is derived from (callers must ask BEFORE computing
    the mesh). The target cluster's collected TPU node-pool types rank
    first in the options (collect -> QA default flow)."""
    from move2kube_tpu import qa
    from move2kube_tpu.source.gpu_detect import (
        CHIPS_PER_HOST, MAX_SLICES, topology_chip_count)

    detected_acc = acc.tpu_accelerator or "tpu-v5-lite-podslice"
    detected_topo = acc.tpu_topology or "1x1"
    cluster_accs = _cluster_tpu_accelerators(plan)
    # cluster-supported types first, then the generic list
    options = cluster_accs + [a for a in TPU_ACCELERATOR_OPTIONS
                              if a not in cluster_accs]
    if detected_acc not in options:
        options.insert(0, detected_acc)
    chosen_acc = qa.fetch_select(
        f"m2kt.services.{name}.tpu.accelerator",
        f"Select the TPU accelerator for GPU service [{name}]",
        ["Detected from the workload's GPU parallelism; override to retarget"],
        detected_acc, options)
    chosen_topo = qa.fetch_input(
        f"m2kt.services.{name}.tpu.topology",
        f"Enter the TPU topology for [{name}] (e.g. 2x4, 4x4x4)",
        ["chips = product of the dims; one host per 4 chips"],
        detected_topo)
    if chosen_acc == detected_acc and chosen_topo == detected_topo:
        return
    try:
        chips = topology_chip_count(chosen_topo)
    except ValueError:
        log.warning("invalid TPU topology answer %r for %s; keeping "
                    "detected %s/%s", chosen_topo, name, detected_acc,
                    detected_topo)
        return
    acc.tpu_accelerator = chosen_acc
    acc.tpu_topology = chosen_topo
    acc.num_hosts = max(1, chips // CHIPS_PER_HOST)
    # the answer describes ONE slice; the detected chip need is preserved
    # by re-deriving the slice count against the chosen per-slice size
    # (round-3 verdict: a 4096-chip detection answered with a smaller
    # slice used to silently collapse to that single slice)
    total_need = max(1, acc.gpu_count)
    slices_needed = -(-total_need // chips)
    acc.num_slices = min(slices_needed, MAX_SLICES)
    if slices_needed > MAX_SLICES:
        log.warning(
            "detected %d chips for %s needs %d slices of the chosen %s "
            "(%d chips) but the emitter caps at %d slices; scale the "
            "JobSet replicas up manually for the full footprint",
            total_need, name, slices_needed, chosen_topo, chips, MAX_SLICES)
    elif acc.num_slices > 1:
        log.info("%s: chosen slice %s/%s covers the detected %d chips "
                 "with %d DCN-connected slices", name, chosen_acc,
                 chosen_topo, total_need, acc.num_slices)
    # the emitted trainer's mesh covers all slices (data parallelism
    # rides DCN between them, everything else stays on ICI)
    acc.gpu_count = acc.num_slices * chips


def _ask_training_knobs(name: str, family: str) -> tuple[str, int, str]:
    """Precision, gradient-accumulation and the fused-CE dispatch are QA
    problems with cached defaults, same engine as the slice choice. The
    IDs are shared with ``passes/optimize.py``'s tpu_training_optimizer —
    one logical knob, asked once, cached answer reused by both the
    emitted trainer template and the JobSet env injection."""
    from move2kube_tpu import qa
    from move2kube_tpu.models.precision import PRECISION_OPTIONS

    default_precision = "bf16" if family in ("llama", "gpt", "gpt2",
                                             "bert") else "fp32"
    precision = qa.fetch_select(
        f"m2kt.services.{name}.tpu.precision",
        f"Select the training precision policy for [{name}]",
        ["bf16 compute + fp32 master weights; bf16-scaled adds loss "
         "scaling; fp32 for conv nets / numerics debugging"],
        default_precision, list(PRECISION_OPTIONS))
    if precision not in PRECISION_OPTIONS:
        log.warning("unknown precision answer %r for %s; keeping %s",
                    precision, name, default_precision)
        precision = default_precision
    raw = qa.fetch_input(
        f"m2kt.services.{name}.tpu.gradaccum",
        f"Enter gradient accumulation microbatches for [{name}]",
        ["1 disables accumulation; k>1 folds k microbatches into one "
         "optimizer update (overlapped ring reduction on pure-DP meshes)"],
        "1")
    try:
        grad_accum = max(1, int(raw))
    except (TypeError, ValueError):
        log.warning("invalid grad-accum answer %r for %s; using 1",
                    raw, name)
        grad_accum = 1
    raw = qa.fetch_select(
        f"m2kt.services.{name}.train.fusedce",
        f"Select the fused LM-head cross-entropy mode for [{name}]",
        ["auto fuses the chunked online-logsumexp loss when the vocab "
         "spans multiple chunks (the [B,T,V] logit tensor never "
         "materializes); on forces it; off keeps the jnp reference loss"],
        "auto", ["auto", "on", "off"])
    fused_ce = raw if raw in ("auto", "on", "off") else "auto"
    return precision, grad_accum, fused_ce


def _ask_elastic_knobs(name: str, num_slices: int) -> tuple[bool, int]:
    """Elastic slice-loss behavior as QA problems, for multislice
    trainers only. Delegates to ``apiresource.deployment.elastic_knobs``
    — the SAME ids (``m2kt.services.<name>.elastic`` /
    ``.elastic.minslices``) the JobSet emitter and the elastic optimizer
    pass ask, so the template's baked-in defaults and the pod env agree
    through the QA cache. Single-slice services never ask: with no
    survivor to re-plan onto, the knob is meaningless."""
    if num_slices < 2:
        return False, 1
    from move2kube_tpu.apiresource.deployment import elastic_knobs

    return elastic_knobs(name)


def _ask_serving_knobs(name: str) -> dict:
    """Serving capacity knobs (max in-flight batch, context length, KV
    page size) as QA problems. IDs are shared with
    ``passes/optimize.py``'s tpu_serving_optimizer — asked once here,
    cached answers reused for the Knative env injection."""
    from move2kube_tpu import qa

    knobs = {}
    for key, qid, desc, default in (
        ("max_batch", "serve.maxbatch",
         "Enter the max concurrent decode batch for [{name}]", "8"),
        ("max_seq", "serve.maxseq",
         "Enter the max context length (prompt + generation) for [{name}]",
         "2048"),
        ("kv_block", "serve.kvblock",
         "Enter the paged KV cache block size (tokens/page) for [{name}]",
         "16"),
    ):
        raw = qa.fetch_input(
            f"m2kt.services.{name}.{qid}", desc.format(name=name),
            ["bounds compiled shapes and HBM footprint of the serving "
             "engine's paged KV cache"],
            default)
        try:
            knobs[key] = max(1, int(raw))
        except (TypeError, ValueError):
            log.warning("invalid %s answer %r for %s; using %s",
                        qid, raw, name, default)
            knobs[key] = int(default)
    # low-precision policy is a select (three valid spellings, not a
    # number); spec_k rides the numeric loop's conventions but allows 0
    raw = qa.fetch_select(
        f"m2kt.services.{name}.serve.quant",
        f"Select the serving quantization policy for [{name}]",
        ["int8 halves weight (and optionally KV-cache) HBM traffic — "
         "decode is bandwidth-bound, so bytes are tokens/s"],
        "off", ["off", "int8", "int8-kv"])
    knobs["quant"] = raw if raw in ("off", "int8", "int8-kv") else "off"
    raw = qa.fetch_select(
        f"m2kt.services.{name}.serve.kernels",
        f"Select the fused serving-kernel mode for [{name}]",
        ["auto enables the fused Pallas paged-decode kernel and "
         "collective-overlapped decode matmul on TPU backends only; "
         "on forces them (interpreter off-TPU); off keeps the jnp "
         "reference path"],
        "auto", ["auto", "on", "off"])
    knobs["kernels"] = raw if raw in ("auto", "on", "off") else "auto"
    raw = qa.fetch_input(
        f"m2kt.services.{name}.serve.speck",
        f"Enter the speculative-decoding proposal length for [{name}]",
        ["tokens the draft model proposes per verify step; 0 disables "
         "speculative decoding"],
        "0")
    try:
        knobs["spec_k"] = max(0, int(raw))
    except (TypeError, ValueError):
        log.warning("invalid serve.speck answer %r for %s; using 0",
                    raw, name)
        knobs["spec_k"] = 0
    raw = qa.fetch_select(
        f"m2kt.services.{name}.serve.async",
        f"Select the async decode pipeline mode for [{name}]",
        ["auto overlaps host-side token consumption with the next "
         "device decode step whenever spec decoding is off; off "
         "keeps the synchronous reference loop"],
        "auto", ["auto", "on", "off"])
    knobs["async"] = raw if raw in ("auto", "on", "off") else "auto"
    raw = qa.fetch_input(
        f"m2kt.services.{name}.serve.substeps",
        f"Enter the in-graph decode substeps for [{name}]",
        ["decode micro-steps fused into one dispatch (fori_loop); "
         "the host touches the device once per N tokens — needs the "
         "async pipeline, 1 = one token per dispatch"],
        "1")
    try:
        knobs["substeps"] = max(1, int(raw))
    except (TypeError, ValueError):
        log.warning("invalid serve.substeps answer %r for %s; using 1",
                    raw, name)
        knobs["substeps"] = 1
    return knobs


def _ask_slo_knobs(name: str) -> dict:
    """Per-tenant SLO targets (obs/slo.py) as QA problems: the TTFT p95
    target, the availability objective, and the tenant-label cardinality
    cap. Baked into the serve template's env defaults and lifted into
    Helm values by ``passes/parameterize.py``'s tpu_slo_parameterizer."""
    from move2kube_tpu import qa

    knobs = {}
    for key, qid, desc, extra, default in (
        ("ttft_p95", "obs.slo.ttftp95",
         "Enter the TTFT p95 SLO target in seconds for [{name}]",
         "requests whose time-to-first-token exceeds this count against "
         "the error budget; burn-rate alerts fire on budget spend", "0.5"),
        ("availability", "obs.slo.availability",
         "Enter the availability SLO objective for [{name}]",
         "fraction of requests that must complete AND meet latency "
         "targets (e.g. 0.99 = 1% error budget)", "0.99"),
        ("max_tenants", "obs.slo.maxtenants",
         "Enter the max distinct tenant labels for [{name}]",
         "bounded metric cardinality: tenants beyond this collapse into "
         "the 'other' series", "8"),
    ):
        raw = qa.fetch_input(
            f"m2kt.services.{name}.{qid}", desc.format(name=name),
            [extra], default)
        try:
            knobs[key] = (max(1, int(raw)) if key == "max_tenants"
                          else float(raw))
        except (TypeError, ValueError):
            log.warning("invalid %s answer %r for %s; using %s",
                        qid, raw, name, default)
            knobs[key] = (int(default) if key == "max_tenants"
                          else float(default))
    return knobs


def _ask_sched_knobs(name: str) -> dict:
    """Scheduler-plane knobs (serving/sched) as QA problems: tenant
    priority classes, token-bucket quotas, the chunked-prefill chunk
    size, and the resident multi-LoRA adapter cap. IDs are shared with
    ``passes/optimize.py``'s tpu_sched_optimizer — asked once here,
    cached answers reused for the pod env injection, so the serve
    template's baked-in defaults and the workload env agree. The spec
    strings are passed through verbatim: serving/sched's parser is the
    tolerant one (malformed entries warn and are skipped at runtime)."""
    from move2kube_tpu import qa

    knobs = {}
    for key, qid, desc, extra, default in (
        ("priorities", "serve.sched.priorities",
         "Enter the tenant priority classes for [{name}]",
         "tenant:class pairs ('gold:high;free:besteffort'); higher "
         "classes may preempt lower under slot/page pressure — empty "
         "keeps the flat, never-preempt default", ""),
        ("quotas", "serve.sched.quotas",
         "Enter the tenant admission quotas for [{name}]",
         "tenant:rate/burst token buckets ('gold:50/100'); over-quota "
         "requests are refused 429 at the router front — empty means "
         "unlimited", ""),
        ("chunkprefill", "serve.sched.chunkprefill",
         "Enter the chunked-prefill chunk size in tokens for [{name}]",
         "prompts longer than this prefill in chunks interleaved with "
         "decode steps, bounding decode stalls; 0 disables chunking", "0"),
        ("maxloras", "serve.sched.maxloras",
         "Enter the max resident LoRA adapters for [{name}]",
         "paged adapter slots served from one engine (S-LoRA style); "
         "0 disables multi-LoRA serving", "0"),
    ):
        raw = qa.fetch_input(
            f"m2kt.services.{name}.{qid}", desc.format(name=name),
            [extra], default)
        if key in ("priorities", "quotas"):
            knobs[key] = str(raw) if raw is not None else ""
            continue
        try:
            knobs[key] = max(0, int(raw))
        except (TypeError, ValueError):
            log.warning("invalid %s answer %r for %s; using %s",
                        qid, raw, name, default)
            knobs[key] = int(default)
    return knobs


def _ask_numerics_knobs(name: str, serving: bool) -> dict:
    """Numerics-plane knobs, via the SAME cached QA ids
    ``passes/optimize.py``'s tpu_numerics_optimizer asks
    (``apiresource.obs_wiring.numerics_enabled`` / ``_audit_rate``) —
    the template's baked-in default and the pod env always agree."""
    from move2kube_tpu.apiresource.obs_wiring import (
        numerics_audit_rate,
        numerics_enabled,
    )

    knobs = {"numerics": "1" if numerics_enabled(name) else "0"}
    knobs["quant_audit_rate"] = (numerics_audit_rate(name)
                                 if serving else "0")
    return knobs


def _ask_autoscale_interval(name: str) -> int:
    """Predictive-autoscaler control-loop period as a QA problem. Only
    the baked template default — the enable knob, lead time, ceiling
    and utilization live in ``fleet_wiring.fleet_knobs`` (the
    ``serve.fleet.autoscale.*`` ids) because they shape the emitted
    objects, not just the runtime env."""
    from move2kube_tpu import qa

    raw = qa.fetch_input(
        f"m2kt.services.{name}.serve.fleet.autoscale.interval",
        f"Predictive-autoscaler loop period (seconds) for [{name}]",
        ["How often the controller re-forecasts and re-decides; "
         "override via M2KT_AUTOSCALE_INTERVAL_S"], "15")
    try:
        return max(1, int(raw))
    except (TypeError, ValueError):
        log.warning("invalid autoscale.interval answer %r for %s; "
                    "using 15", raw, name)
        return 15


def _ask_obs_port(name: str) -> int:
    """Telemetry (/metrics) port as a QA problem. Same ID as
    ``passes/optimize.py``'s tpu_observability_optimizer — asked once,
    cached: the template's baked-in default and the workload YAML's
    ``M2KT_METRICS_PORT`` env always agree. 0 disables telemetry."""
    from move2kube_tpu import qa

    raw = qa.fetch_input(
        f"m2kt.services.{name}.obs.port",
        f"Enter the telemetry (/metrics) port for [{name}]",
        ["Prometheus exposition + on-demand XLA profiling; 0 disables"],
        "9090")
    try:
        return int(raw)
    except (TypeError, ValueError):
        log.warning("invalid obs.port answer %r for %s; using 9090",
                    raw, name)
        return 9090


def emit_container(service: PlanService, plan=None) -> Container:
    acc = service.accelerator or AcceleratorInfo()
    family = (service.containerization_target_options[0]
              if service.containerization_target_options
              else acc.model_family) or "generic"
    if family not in KNOWN_FAMILIES:
        family = "generic"

    name = common.make_dns_label(service.service_name)
    # ask for the slice BEFORE sizing the mesh: an override rescales
    # acc.gpu_count so the emitted mesh covers the chosen topology
    _ask_tpu_slice(name, acc, plan)

    # inference services emit the decode server instead of a trainer;
    # only the decoder-LM families have a serving engine (the paged KV
    # cache is a decoder structure). Anything else falls back to the
    # training path — and clears the serving flag so the apiresources
    # classify the service to match what the image actually runs.
    serving = bool(acc.serving)
    if serving and family not in ("llama", "gpt", "gpt2"):
        log.warning(
            "%s is an inference server but family %r has no serving "
            "engine (decoder LMs only); emitting the training path",
            name, family)
        serving = False
        acc.serving = False
        acc.serving_port = 0

    # MoE only exists in the decoder-LM family; elsewhere detected expert
    # settings would shape a mesh the trainer can't use
    moe_experts = (acc.parallelism.get("experts", 0)
                   if family in ("llama", "gpt") else 0)
    # Detected GPU pipeline parallelism: when the workload also uses
    # ZeRO>=2, the pp degree folds into fsdp — on a TPU slice the ICI
    # makes FSDP strictly better than a GPipe bubble at the sizes pp is
    # used at on GPUs. WITHOUT ZeRO sharding (classic Megatron/GPipe
    # decoder runs whose model is too deep to data-shard), the staged
    # execution is kept: the mesh gets a real pipe axis and the emitted
    # trainer runs the compiled GPipe schedule (models/llama_pipe.py).
    pp = acc.parallelism.get("pp", 1)
    zero = acc.parallelism.get("zero_stage", 0)
    # pp must divide the device count, or infer_mesh_config would drop the
    # pipe axis and (with zero<2 passed through) leave a fully replicated
    # pure-DP trainer for a model the pipe path exists for because it is
    # too deep to replicate — fold into ZeRO/fsdp instead in that case
    use_pipe = (family in ("llama", "gpt", "gpt2") and pp > 1 and zero < 2
                and not moe_experts and max(1, acc.gpu_count) % pp == 0)
    # On the pipe path detected tp/sp fold into data parallelism: inside
    # the GPipe shard_map the mesh axes are manual, so block-level TP
    # would need hand-written collective matmuls rather than GSPMD
    # annotations; every device still does useful (data-parallel) work.
    # (gpt2 no longer folds: models/gpt2.py carries the same logical-axis
    # sharding annotations as llama.py, so detected tp/sp map straight
    # onto the tensor/seq mesh axes.)
    fold_tp_sp = use_pipe
    # the emitted trainer re-derives the mesh AT RUNTIME from the actual
    # device count + M2KT_TPU_TOPOLOGY (parallel/topology.py planner), so
    # the same parallelism degrees are both resolved here (for logging /
    # plan inspection) and baked into the template as planner arguments
    degrees = {
        "zero_stage": zero if use_pipe else max(zero, 2 if pp > 1 else 0),
        "tensor_parallel": 1 if fold_tp_sp else acc.parallelism.get("tp", 1),
        "seq_parallel": 1 if fold_tp_sp else acc.parallelism.get("sp", 1),
        "pipeline_parallel": pp if use_pipe else 1,
        "expert_parallel": acc.parallelism.get("ep", 1) if moe_experts else 1,
    }
    mesh = infer_mesh_config(max(1, acc.gpu_count), **degrees)
    if serving:
        # decode server: no train knobs
        precision, grad_accum, fused_ce = "bf16", 1, "auto"
    else:
        precision, grad_accum, fused_ce = _ask_training_knobs(name, family)

    image_name = service.image or f"{name}:latest"
    # HF GPT-2 fine-tunes (family gpt) emit the true GPT-2 architecture
    # so port_weights can load real GPT2LMHeadModel checkpoints; detected
    # tp/sp map straight onto the tensor/seq mesh axes (models/gpt2.py
    # carries the same logical-axis sharding annotations as llama.py) and
    # detected Megatron pipeline parallelism runs the staged GPT-2
    # trainer (models/gpt2_pipe.py — VERDICT r4 #7). Only MoE gpt
    # workloads keep the Llama-class trainer: expert layers exist only
    # there (architecture fidelity is irrelevant for a from-scratch
    # pretrain, the parallelism mapping is not).
    emit_family = family
    if family == "gpt" and not moe_experts:
        emit_family = "gpt2"

    container = Container(
        image_names=[image_name],
        new=True,
        build_type=ContainerBuildType.JAX_XLA,
        accelerator=acc,
    )
    src_dirs = service.source_artifacts.get(PlanService.SOURCE_DIR_ARTIFACT, [])
    if src_dirs:
        from move2kube_tpu.containerizer.dockerfile import _record_source_dir

        _record_source_dir(container, plan, src_dirs[0])

    entry_rel = acc.entrypoint
    if entry_rel and os.path.isabs(entry_rel):
        src_dirs = service.source_artifacts.get(PlanService.SOURCE_DIR_ARTIFACT, [])
        if src_dirs:
            rel = common.relpath_under(entry_rel, src_dirs[0])
            entry_rel = rel if rel is not None else os.path.basename(entry_rel)
    serve_port = acc.serving_port or 8080
    metrics_port = _ask_obs_port(name)
    num_slices = max(1, acc.num_slices)
    elastic, elastic_min_slices = (
        (False, 1) if serving else _ask_elastic_knobs(name, num_slices))
    numerics_knobs = _ask_numerics_knobs(name, serving)
    if serving:
        acc.serving_port = serve_port
        serve_knobs = _ask_serving_knobs(name)
        slo_knobs = _ask_slo_knobs(name)
        sched_knobs = _ask_sched_knobs(name)
        with open(os.path.join(_ASSETS, "serve_tpu.py"),
                  encoding="utf-8") as f:
            container.add_file(
                "serve_tpu.py",
                common.render_template(f.read(), {
                    "source_entrypoint": entry_rel or "(unknown)",
                    "family": emit_family,
                    "tpu_accelerator": (acc.tpu_accelerator
                                        or "tpu-v5-lite-podslice"),
                    "tpu_topology": acc.tpu_topology or "1x1",
                    "serve_port": serve_port,
                    "serve_max_batch": serve_knobs["max_batch"],
                    "serve_max_seq": serve_knobs["max_seq"],
                    "serve_kv_block": serve_knobs["kv_block"],
                    "serve_quant": serve_knobs["quant"],
                    "serve_kernels": serve_knobs["kernels"],
                    "spec_k": serve_knobs["spec_k"],
                    "serve_async": serve_knobs["async"],
                    "serve_substeps": serve_knobs["substeps"],
                    "slo_ttft_p95": slo_knobs["ttft_p95"],
                    "slo_availability": slo_knobs["availability"],
                    "slo_max_tenants": slo_knobs["max_tenants"],
                    "sched_priorities": sched_knobs["priorities"],
                    "sched_quotas": sched_knobs["quotas"],
                    "sched_chunk_prefill": sched_knobs["chunkprefill"],
                    "sched_max_loras": sched_knobs["maxloras"],
                    "autoscale_interval": _ask_autoscale_interval(name),
                    "numerics": numerics_knobs["numerics"],
                    "quant_audit_rate": numerics_knobs["quant_audit_rate"],
                    "compile_cache_dir": "/app/.jax-cache",
                    "metrics_port": metrics_port,
                    # weight-plane listener default; the fleet wiring
                    # overrides per-pod via M2KT_WEIGHTS_PORT
                    "weights_port": 8981,
                }))
    else:
        with open(os.path.join(_ASSETS, "train_tpu.py"),
                  encoding="utf-8") as f:
            train_template = f.read()
        container.add_file(
            "train_tpu.py",
            common.render_template(train_template, {
                "source_entrypoint": entry_rel or "(unknown)",
                "frameworks": ",".join(acc.frameworks) or "unknown",
                "backend": acc.distributed_backend,
                "gpu_count": acc.gpu_count,
                "family": emit_family,
                "tpu_accelerator": (acc.tpu_accelerator
                                    or "tpu-v5-lite-podslice"),
                "tpu_topology": acc.tpu_topology or "1x1",
                "num_hosts": acc.num_hosts,
                "num_slices": num_slices,
                "elastic": elastic,
                "elastic_min_slices": elastic_min_slices,
                "mesh": mesh,
                "zero_stage": degrees["zero_stage"],
                "tensor_parallel": degrees["tensor_parallel"],
                "seq_parallel": degrees["seq_parallel"],
                "pipeline_parallel": degrees["pipeline_parallel"],
                "expert_parallel": degrees["expert_parallel"],
                "precision": precision,
                "grad_accum": grad_accum,
                "fused_ce": fused_ce,
                "moe_experts": moe_experts,
                "numerics": numerics_knobs["numerics"],
                # in-image default; pods that mount a durable volume point
                # M2KT_COMPILE_CACHE_DIR at it to survive restarts
                "compile_cache_dir": "/app/.jax-cache",
                "metrics_port": metrics_port,
                "steps": 100,
                "lr": (3e-4 if family in ("llama", "gpt", "gpt2")
                       else 1e-4 if family == "unet" else 1e-3),
            }),
        )
    with open(os.path.join(_ASSETS, "port_weights.py"), encoding="utf-8") as f:
        container.add_file(
            "port_weights.py",
            common.render_template(f.read(), {"family": emit_family}),
        )
    _vendor_package(container)
    with open(os.path.join(_ASSETS, "Dockerfile"), encoding="utf-8") as f:
        container.add_file(
            "Dockerfile",
            common.render_template(f.read(), {
                "serve": serving, "serve_port": serve_port,
            }))
    container.add_file("requirements.txt", REQUIREMENTS)
    container.add_file(
        f"{name}-docker-build.sh",
        common.render_template(DOCKER_BUILD_SH, {
            "service_name": name,
            "dockerfile_name": "Dockerfile",
            "image_name": image_name,
            "context": ".",
        }),
    )
    log.info("jax-xla: %s -> family=%s %s mesh=%s on %s/%s",
             name, family, "serve" if serving else "train", mesh.dims(),
             acc.tpu_accelerator, acc.tpu_topology)
    return container
