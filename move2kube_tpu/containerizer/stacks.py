"""Built-in per-stack detection for the Dockerfile containerizer.

Parity with the reference's embedded asset tree (``internal/assets/
dockerfiles/*/m2kdfdetect.sh`` + template pairs): each stack has a detect
function that inspects a directory and returns template parameters (or
None), plus a Jinja2 Dockerfile template shipped as package data under
``move2kube_tpu/assets/dockerfiles/<stack>/Dockerfile``. The reference
shells out to ``/bin/sh m2kdfdetect.sh``; we detect in-process but keep the
same contract (JSON-able params feeding a template).
"""

from __future__ import annotations

import json
import os
import re
from dataclasses import dataclass
from typing import Callable

from move2kube_tpu.utils import common

ASSETS_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "assets")


@dataclass
class StackMatch:
    stack: str  # template id, e.g. "python"
    params: dict  # template parameters


def _list_files(directory: str) -> list[str]:
    try:
        return sorted(os.listdir(directory))
    except OSError:
        return []


# --- detectors (each: dir -> StackMatch | None) ----------------------------

def detect_django(d: str) -> StackMatch | None:
    if not os.path.isfile(os.path.join(d, "manage.py")):
        return None
    app_name = os.path.basename(d.rstrip(os.sep)) or "app"
    return StackMatch("django", {
        "app_name": common.make_dns_label(app_name),
        "port": common.DEFAULT_SERVICE_PORT,
        "has_requirements": os.path.isfile(os.path.join(d, "requirements.txt")),
    })


def detect_python(d: str) -> StackMatch | None:
    files = _list_files(d)
    py_files = [f for f in files if f.endswith(".py")]
    if not py_files:
        return None
    main_script = ""
    for candidate in ("main.py", "app.py", "server.py", "run.py", "wsgi.py"):
        if candidate in files:
            main_script = candidate
            break
    if not main_script:
        # any .py that looks like an entrypoint
        for f in py_files:
            try:
                with open(os.path.join(d, f), encoding="utf-8", errors="ignore") as fh:
                    if "__main__" in fh.read():
                        main_script = f
                        break
            except OSError:
                continue
    if not main_script:
        return None
    port = common.DEFAULT_SERVICE_PORT
    try:
        with open(os.path.join(d, main_script), encoding="utf-8", errors="ignore") as fh:
            m = re.search(r"port\s*=\s*(\d{2,5})", fh.read(), re.IGNORECASE)
            if m:
                port = int(m.group(1))
    except OSError:
        pass
    return StackMatch("python", {
        "main_script": main_script,
        "app_name": common.make_dns_label(os.path.basename(d.rstrip(os.sep)) or "app"),
        "port": port,
        "has_requirements": "requirements.txt" in files,
    })


def detect_nodejs(d: str) -> StackMatch | None:
    pkg_path = os.path.join(d, "package.json")
    if not os.path.isfile(pkg_path):
        return None
    node_version = "20"
    port = common.DEFAULT_SERVICE_PORT
    try:
        pkg = json.load(open(pkg_path, encoding="utf-8"))
        engines = pkg.get("engines", {})
        m = re.search(r"(\d+)", str(engines.get("node", "")))
        if m:
            node_version = m.group(1)
    except (OSError, json.JSONDecodeError):
        pkg = {}
    return StackMatch("nodejs", {
        "node_version": node_version,
        "port": port,
        "has_start": bool(pkg.get("scripts", {}).get("start")),
        "main": pkg.get("main", "index.js") or "index.js",
    })


def detect_golang(d: str) -> StackMatch | None:
    gomod = os.path.join(d, "go.mod")
    if not os.path.isfile(gomod):
        return None
    module = "app"
    try:
        for line in open(gomod, encoding="utf-8"):
            if line.startswith("module"):
                module = line.split()[-1].rsplit("/", 1)[-1]
                break
    except OSError:
        pass
    return StackMatch("golang", {
        "app_name": common.make_dns_label(module),
        "port": common.DEFAULT_SERVICE_PORT,
    })


def detect_java_maven(d: str) -> StackMatch | None:
    pom = os.path.join(d, "pom.xml")
    if not os.path.isfile(pom):
        return None
    artifact_id, packaging = "app", "jar"
    try:
        text = open(pom, encoding="utf-8", errors="ignore").read()
        m = re.search(r"<artifactId>([^<]+)</artifactId>", text)
        if m:
            artifact_id = m.group(1)
        m = re.search(r"<packaging>([^<]+)</packaging>", text)
        if m:
            packaging = m.group(1).strip()
    except OSError:
        pass
    if packaging == "war":
        return None  # handled by the war app-server variants
    return StackMatch("java-maven", {
        "artifact_id": artifact_id,
        "packaging": packaging,
        "port": common.DEFAULT_SERVICE_PORT,
    })


def detect_java_gradle(d: str) -> StackMatch | None:
    if not (os.path.isfile(os.path.join(d, "build.gradle"))
            or os.path.isfile(os.path.join(d, "build.gradle.kts"))):
        return None
    return StackMatch("java-gradle", {
        "app_name": common.make_dns_label(os.path.basename(d.rstrip(os.sep)) or "app"),
        "port": common.DEFAULT_SERVICE_PORT,
    })


def detect_java_ant(d: str) -> StackMatch | None:
    """Ant builds (parity: internal/assets/dockerfiles/java ant detect)."""
    build_xml = os.path.join(d, "build.xml")
    if not os.path.isfile(build_xml):
        return None
    app_name = "app"
    try:
        m = re.search(r'<project[^>]*\sname="([^"]+)"',
                      open(build_xml, encoding="utf-8", errors="ignore").read())
        if m:
            app_name = m.group(1)
    except OSError:
        pass
    return StackMatch("java-ant", {
        "app_name": common.make_dns_label(app_name),
        "port": common.DEFAULT_SERVICE_PORT,
    })


def _war_build_info(d: str) -> dict | None:
    """Detect a WAR-producing java build: maven <packaging>war</packaging>,
    gradle war plugin, an ant build, or a prebuilt .war in the tree."""
    files = _list_files(d)
    pom = os.path.join(d, "pom.xml")
    if os.path.isfile(pom):
        try:
            text = open(pom, encoding="utf-8", errors="ignore").read()
        except OSError:
            text = ""
        if re.search(r"<packaging>\s*war\s*</packaging>", text):
            # mvn package names the war artifactId-VERSION.war (or
            # <finalName>); glob instead of guessing
            return {"build_tool": "maven", "war_name": "*.war"}
    for gradle in ("build.gradle", "build.gradle.kts"):
        path = os.path.join(d, gradle)
        if os.path.isfile(path):
            try:
                text = open(path, encoding="utf-8", errors="ignore").read()
            except OSError:
                text = ""
            if re.search(r"""(apply\s+plugin|id)\s*[:(]?\s*['"]war['"]""", text):
                return {"build_tool": "gradle", "war_name": "*.war"}
    if os.path.isfile(os.path.join(d, "build.xml")):
        try:
            text = open(os.path.join(d, "build.xml"),
                        encoding="utf-8", errors="ignore").read()
        except OSError:
            text = ""
        if re.search(r"<war[\s>]", text):  # an actual <war> task element
            return {"build_tool": "ant", "war_name": "*.war"}
    wars = [f for f in files if f.endswith(".war")]
    if wars:
        return {"build_tool": "none", "war_name": wars[0]}
    return None


# app-server stack -> port it serves on
WAR_SERVERS = {"java-war-tomcat": 8080, "java-war-liberty": 9080,
               "java-war-jboss": 8080}


def _war_build_stage(info: dict) -> str:
    """Render the shared maven/gradle/ant build stage used by every
    app-server template ('' for a prebuilt war)."""
    path = os.path.join(ASSETS_DIR, "dockerfiles", "_java_war_buildstage.Dockerfile")
    with open(path, encoding="utf-8") as f:
        return common.render_template(f.read(), info).strip()


def detect_java_war(d: str) -> list[StackMatch]:
    """All app-server variants for a WAR-producing build, one scan
    (parity: internal/assets/dockerfiles/java/war-{tomcat,liberty,jboss});
    tomcat first = preferred default."""
    info = _war_build_info(d)
    if info is None:
        return []
    info["build_stage"] = _war_build_stage(info)
    app_name = common.make_dns_label(os.path.basename(d.rstrip(os.sep)) or "app")
    return [
        StackMatch(stack, {"app_name": app_name, "port": port, **info})
        for stack, port in WAR_SERVERS.items()
    ]


def detect_php(d: str) -> StackMatch | None:
    files = _list_files(d)
    if "composer.json" not in files and not any(f.endswith(".php") for f in files):
        return None
    return StackMatch("php", {"port": common.DEFAULT_SERVICE_PORT})


def detect_ruby(d: str) -> StackMatch | None:
    files = _list_files(d)
    if "Gemfile" not in files:
        return None
    rackup = "config.ru" in files
    main_script = ""
    if not rackup:
        rb = [f for f in files if f.endswith(".rb")]
        main_script = "app.rb" if "app.rb" in files else (rb[0] if rb else "")
        if not main_script:
            return None
    return StackMatch("ruby", {
        "rackup": rackup,
        "main_script": main_script,
        "port": common.DEFAULT_SERVICE_PORT,
    })


# Order matters: specific before generic (django before python; war
# app-server variants before plain jar builds). A detector may return a
# single StackMatch, a list of them, or None.
DETECTORS: list[Callable[[str], StackMatch | list[StackMatch] | None]] = [
    detect_django,
    detect_golang,
    detect_nodejs,
    detect_java_war,
    detect_java_maven,
    detect_java_gradle,
    detect_java_ant,
    detect_ruby,
    detect_php,
    detect_python,
]


def detect_stacks(directory: str) -> list[StackMatch]:
    """All stacks matching a directory, most specific first."""
    out: list[StackMatch] = []
    for det in DETECTORS:
        m = det(directory)
        if isinstance(m, list):
            out.extend(m)
        elif m is not None:
            out.append(m)
    return out


def template_path(stack: str) -> str:
    return os.path.join(ASSETS_DIR, "dockerfiles", stack, "Dockerfile")


def read_template(stack: str) -> str:
    with open(template_path(stack), encoding="utf-8") as f:
        return f.read()


def available_stacks() -> list[str]:
    root = os.path.join(ASSETS_DIR, "dockerfiles")
    try:
        return sorted(
            d for d in os.listdir(root)
            if os.path.isfile(os.path.join(root, d, "Dockerfile"))
        )
    except OSError:
        return []
