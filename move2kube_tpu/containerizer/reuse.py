"""Reuse containerizer: the image already exists, nothing to build.

Parity: ``internal/containerizer/reusecontainerizer.go:45``.
"""

from __future__ import annotations

from move2kube_tpu.containerizer.base import Containerizer
from move2kube_tpu.types.ir import Container
from move2kube_tpu.types.plan import ContainerBuildType, PlanService


class ReuseContainerizer(Containerizer):
    def get_build_type(self) -> str:
        return ContainerBuildType.REUSE

    def get_target_options(self, plan, directory: str) -> list[str]:
        return []  # offered by translators that know an image exists, not by scan

    def get_container(self, plan, service: PlanService) -> Container:
        image = service.image or service.service_name + ":latest"
        return Container(image_names=[image], new=False,
                         build_type=ContainerBuildType.REUSE)
