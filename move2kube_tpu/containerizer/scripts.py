"""Build-script templates emitted alongside generated Dockerfiles.

Parity: ``internal/containerizer/scripts/constants.go:23-75``.
"""

DOCKER_BUILD_SH = """#!/bin/sh
# Build the container image for service {{ service_name }}.
# Run from the directory containing this script.
cd "$(dirname "$0")"
docker build -f {{ dockerfile_name }} -t {{ image_name }} {{ context }}
"""

S2I_BUILD_SH = """#!/bin/sh
# Source-to-Image build for service {{ service_name }}.
cd "$(dirname "$0")"
s2i build {{ context }} {{ builder }} {{ image_name }}
"""

CNB_BUILD_SH = """#!/bin/sh
# Cloud Native Buildpack build for service {{ service_name }}.
cd "$(dirname "$0")"
pack build {{ image_name }} --builder {{ builder }} --path {{ context }}
"""
