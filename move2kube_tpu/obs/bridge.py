"""Bridges: fold goodput reports and translate-trace totals into an obs
registry, so one scrape carries step metrics, goodput, and span totals.

Both mirrors are idempotent gauge writes, so they compose with
:meth:`Registry.add_collect_hook` — the registry refreshes them on every
scrape instead of the workload polling on a timer.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from move2kube_tpu.obs.metrics import Registry, default_registry


def mirror_trace(registry: Registry | None = None, recorder=None) -> None:
    """Mirror ``utils.trace`` span totals + counters into gauges
    (``m2kt_trace_span_seconds_total{span=...}``). No-op when the image
    doesn't ship trace."""
    reg = registry if registry is not None else default_registry()
    try:
        from move2kube_tpu.utils import trace
    except Exception:  # noqa: BLE001 - slim vendored images
        return
    snap = (recorder or trace.get()).to_dict()
    spans = reg.gauge(
        "m2kt_trace_span_seconds_total",
        "Cumulative wall seconds per pipeline span", labels=("span",))
    for name, seconds in snap.get("spans", {}).items():
        spans.labels(span=name).set(seconds)
    counters = reg.gauge(
        "m2kt_trace_counter", "utils.trace counters", labels=("name",))
    for name, value in snap.get("counters", {}).items():
        counters.labels(name=name).set(value)


def mirror_goodput(report: dict, registry: Registry | None = None) -> None:
    """Mirror a :func:`resilience.goodput` report into gauges: fraction,
    per-category seconds, and step watermarks."""
    reg = registry if registry is not None else default_registry()
    frac = report.get("goodput_fraction")
    if frac is not None:
        reg.gauge("m2kt_goodput_fraction",
                  "Fraction of wall-clock spent on productive steps"
                  ).set(float(frac))
    secs = reg.gauge("m2kt_goodput_seconds",
                     "Wall seconds per goodput category",
                     labels=("category",))
    for cat, val in report.get("seconds", {}).items():
        secs.labels(category=cat).set(float(val))
    for key, name in (("steps_done", "m2kt_goodput_steps_done"),
                      ("last_saved_step", "m2kt_goodput_last_saved_step")):
        if key in report:
            reg.gauge(name, f"Goodput watermark: {key}"
                      ).set(float(report[key]))


class StragglerDetector:
    """MegaScale-style slow-host identification from per-host step-time
    reports.

    Each host (or simulated slice in the forced-host drill) reports its
    wall time for every step; the detector keeps a bounded window per
    host and scores each host as ``median(host window) / median(fleet
    medians)`` — 1.0 means in line with the fleet, 1.5 means this host's
    steps take 50% longer than the typical host. Synchronous data-
    parallel training runs at the speed of the slowest participant, so a
    single straggling host taxes every step of every other host; the
    score makes the guilty one visible *before* anyone stares at 256
    per-host dashboards.

    Scores are exported as ``m2kt_straggler_score{host=...}`` gauges and
    crossing ``threshold`` increments
    ``m2kt_straggler_events_total{host=...}`` once per excursion (hyst:
    re-arms only after the score drops back under) — alertable without
    firing once per step while a host stays slow.
    """

    def __init__(self, registry: Registry | None = None,
                 threshold: float = 1.5, window: int = 32):
        reg = registry if registry is not None else default_registry()
        self.threshold = float(threshold)
        self.window = max(2, int(window))
        self._lock = threading.Lock()
        self._times: dict[str, deque[float]] = {}
        self._over: set[str] = set()
        self.events = 0
        self._score_gauge = reg.gauge(
            "m2kt_straggler_score",
            "Per-host median step time / fleet median (1.0 = in line)",
            labels=("host",))
        self._event_counter = reg.counter(
            "m2kt_straggler_events_total",
            "Straggler threshold crossings", labels=("host",))

    @staticmethod
    def _median(values) -> float:
        vals = sorted(values)
        n = len(vals)
        mid = n // 2
        return vals[mid] if n % 2 else (vals[mid - 1] + vals[mid]) / 2.0

    def report(self, host: str, step: int, seconds: float) -> None:
        """Fold one (host, step time) observation in and refresh that
        host's score + event state."""
        with self._lock:
            times = self._times.get(host)
            if times is None:
                times = self._times[host] = deque(maxlen=self.window)
            times.append(max(0.0, float(seconds)))
            scores = self._scores_locked()
        score = scores.get(host)
        if score is None:
            return
        self._score_gauge.labels(host=host).set(round(score, 6))
        with self._lock:
            if score >= self.threshold and host not in self._over:
                self._over.add(host)
                self.events += 1
                fire = True
            else:
                if score < self.threshold:
                    self._over.discard(host)
                fire = False
        if fire:
            self._event_counter.labels(host=host).inc()

    def _scores_locked(self) -> dict[str, float]:
        medians = {h: self._median(t)
                   for h, t in self._times.items() if t}
        if not medians:
            return {}
        fleet = self._median(medians.values())
        if fleet <= 0:
            return {h: 1.0 for h in medians}
        return {h: m / fleet for h, m in medians.items()}

    def scores(self) -> dict[str, float]:
        """Current per-host scores (host median / fleet median)."""
        with self._lock:
            return self._scores_locked()


DIAG_ENV = "M2KT_DIAG"
DIAG_DIR_ENV = "M2KT_DIAG_DIR"
DIAG_MIN_INTERVAL_ENV = "M2KT_DIAG_MIN_INTERVAL_S"
DIAG_PROFILE_SECONDS_ENV = "M2KT_DIAG_PROFILE_S"
DIAG_MAX_CAPTURES_ENV = "M2KT_DIAG_MAX_CAPTURES"

DEFAULT_DIAG_MIN_INTERVAL_S = 600.0
DEFAULT_DIAG_PROFILE_S = 1.0
DEFAULT_DIAG_MAX_CAPTURES = 8


def diag_enabled() -> bool:
    return os.environ.get(DIAG_ENV, "1").lower() not in ("0", "false", "off")


def diag_dir() -> str:
    d = os.environ.get(DIAG_DIR_ENV, "")
    if d:
        return d
    return os.path.join(os.environ.get("M2KT_METRICS_DIR", "") or ".",
                        "m2kt-diag")


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    try:
        val = float(raw) if raw.strip() else default
    except (TypeError, ValueError):
        return default
    return val if val >= 0 else default


class DiagWatchdog:
    """Anomaly-triggered auto-profiling: arm on trouble, capture once.

    The expensive diagnostics (a jax.profiler trace, the span ring, the
    usage-ledger window) are exactly the data an engineer asks for
    *after* an incident — and by then the interesting window has rolled
    out of every ring. The watchdog watches three cheap signals and
    freezes a one-shot bundle the moment one fires:

    - **SLO fast-burn**: the tracker's paired-window burn-rate alarm
      (``slo.fast_burn_firing()``), checked on every :meth:`check`.
    - **Step-time regression**: p95 of the last ``short_window`` step
      times exceeds ``factor`` × the rolling-median baseline of the
      preceding window (fed via :meth:`observe_step`).
    - **Non-finite steps**: edge-triggered via :meth:`note_nonfinite`
      from the numerics guard.

    Level-triggered reasons use StragglerDetector-style hysteresis —
    fire once per excursion, re-arm only after the condition clears —
    and every capture passes a rate limiter
    (``M2KT_DIAG_MIN_INTERVAL_S``, default 600s) plus a lifetime cap
    (``M2KT_DIAG_MAX_CAPTURES``) so a flapping SLO cannot fill the disk
    with profiles. Captures are counted in
    ``m2kt_diag_captures_total{reason=...}`` (suppressions in
    ``m2kt_diag_suppressed_total{reason=...}``).

    Bundles land under ``M2KT_DIAG_DIR`` as ``diag-<reason>-<seq>/``
    with ``traces.json`` (span-ring drain), ``usage.json`` (trailing
    ledger window), a ``profile/`` jax trace, and ``manifest.json`` —
    written *last*, so a manifest's presence means the bundle is
    complete. The heavy work runs on a daemon thread: arming must cost
    the serve loop microseconds, not a profiler pause.
    """

    REASONS = ("slo_fast_burn", "step_regression", "nonfinite")

    def __init__(self, registry: Registry | None = None,
                 slo=None, tracer=None, ledger=None,
                 out_dir: str | None = None,
                 min_interval_s: float | None = None,
                 profile_seconds: float | None = None,
                 max_captures: int | None = None,
                 factor: float = 2.0, short_window: int = 16,
                 baseline_window: int = 128, min_baseline: int = 32,
                 ledger_window_s: float = 300.0,
                 clock=time.monotonic) -> None:
        reg = registry if registry is not None else default_registry()
        self.slo = slo
        self.tracer = tracer
        self.ledger = ledger
        self.out_dir = out_dir or diag_dir()
        self.min_interval_s = (min_interval_s if min_interval_s is not None
                               else _env_float(DIAG_MIN_INTERVAL_ENV,
                                               DEFAULT_DIAG_MIN_INTERVAL_S))
        self.profile_seconds = (profile_seconds
                                if profile_seconds is not None
                                else _env_float(DIAG_PROFILE_SECONDS_ENV,
                                                DEFAULT_DIAG_PROFILE_S))
        self.max_captures = (max_captures if max_captures is not None
                             else int(_env_float(DIAG_MAX_CAPTURES_ENV,
                                                 DEFAULT_DIAG_MAX_CAPTURES)))
        self.factor = float(factor)
        self.short_window = max(2, int(short_window))
        self.min_baseline = max(2, int(min_baseline))
        self.ledger_window_s = float(ledger_window_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._steps: deque[float] = deque(
            maxlen=self.short_window + max(self.short_window,
                                           int(baseline_window)))
        self._over: set[str] = set()
        self._last_capture_t: float | None = None
        self._seq = 0
        self._threads: list[threading.Thread] = []
        self.captures: list[str] = []
        self._c_captures = reg.counter(
            "m2kt_diag_captures_total",
            "Diagnostic bundles captured by the anomaly watchdog",
            labels=("reason",))
        self._c_suppressed = reg.counter(
            "m2kt_diag_suppressed_total",
            "Watchdog triggers suppressed by the capture rate limit",
            labels=("reason",))

    # -- signal feeds ------------------------------------------------------

    def observe_step(self, seconds: float) -> str | None:
        """Fold one step wall time in and run the trigger check."""
        with self._lock:
            self._steps.append(max(0.0, float(seconds)))
        return self.check()

    def note_nonfinite(self) -> str | None:
        """Edge trigger from the numerics guard (non-finite loss/grad)."""
        return self._request("nonfinite")

    # -- trigger evaluation ------------------------------------------------

    def _step_regressed(self) -> bool:
        with self._lock:
            steps = list(self._steps)
        short = steps[-self.short_window:]
        baseline = steps[:-self.short_window]
        if len(short) < self.short_window or len(baseline) < self.min_baseline:
            return False
        base = StragglerDetector._median(baseline)
        if base <= 0:
            return False
        p95 = sorted(short)[min(len(short) - 1,
                                int(0.95 * (len(short) - 1)))]
        return p95 >= self.factor * base

    def check(self) -> str | None:
        """Evaluate the level-triggered reasons; returns the bundle dir
        when this call captured one. Cheap — safe to call per step or
        per scrape."""
        fired = None
        for reason, live in (("slo_fast_burn", self._slo_firing),
                             ("step_regression", self._step_regressed)):
            try:
                now_firing = bool(live())
            except Exception:  # noqa: BLE001 - watchdog must not throw
                continue
            with self._lock:
                if now_firing and reason not in self._over:
                    self._over.add(reason)
                    edge = True
                else:
                    if not now_firing:
                        self._over.discard(reason)
                    edge = False
            if edge:
                fired = self._request(reason) or fired
        return fired

    def _slo_firing(self) -> bool:
        return self.slo is not None and self.slo.fast_burn_firing()

    # -- capture -----------------------------------------------------------

    def _request(self, reason: str) -> str | None:
        now = self._clock()
        with self._lock:
            if self._seq >= self.max_captures or (
                    self._last_capture_t is not None
                    and now - self._last_capture_t < self.min_interval_s):
                suppressed = True
            else:
                suppressed = False
                self._last_capture_t = now
                self._seq += 1
                seq = self._seq
        if suppressed:
            self._c_suppressed.labels(reason=reason).inc()
            return None
        bundle = os.path.join(self.out_dir, f"diag-{reason}-{seq:03d}")
        self._c_captures.labels(reason=reason).inc()
        self.captures.append(bundle)
        t = threading.Thread(target=self._capture, args=(reason, bundle),
                             name="m2kt-diag-capture", daemon=True)
        self._threads.append(t)
        t.start()
        return bundle

    def _capture(self, reason: str, bundle: str) -> None:
        manifest = {
            "schema": "m2kt-diag/v1",
            "reason": reason,
            "captured_unix": time.time(),
            "parts": [],
        }
        try:
            os.makedirs(bundle, exist_ok=True)
        except OSError:
            return
        if self.tracer is not None:
            try:
                doc = self.tracer.ring_doc()
                with open(os.path.join(bundle, "traces.json"), "w",
                          encoding="utf-8") as f:
                    json.dump(doc, f)
                manifest["parts"].append("traces.json")
            except Exception as e:  # noqa: BLE001 - best-effort bundle
                manifest["errors"] = manifest.get("errors", []) + [str(e)]
        if self.ledger is not None:
            try:
                doc = self.ledger.doc(window_s=self.ledger_window_s)
                with open(os.path.join(bundle, "usage.json"), "w",
                          encoding="utf-8") as f:
                    json.dump(doc, f)
                manifest["parts"].append("usage.json")
            except Exception as e:  # noqa: BLE001
                manifest["errors"] = manifest.get("errors", []) + [str(e)]
        if self.profile_seconds > 0:
            try:
                self._profile(os.path.join(bundle, "profile"))
                manifest["parts"].append("profile")
            except Exception as e:  # noqa: BLE001 - jax may be absent
                manifest["errors"] = manifest.get("errors", []) + [str(e)]
        # manifest last: its presence marks the bundle complete
        try:
            tmp = os.path.join(bundle, ".manifest.tmp")
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(manifest, f, sort_keys=True, indent=1)
            os.replace(tmp, os.path.join(bundle, "manifest.json"))
        except OSError:
            pass

    def _profile(self, profile_dir: str) -> None:
        import jax  # lazy: watchdog must import in slim images

        os.makedirs(profile_dir, exist_ok=True)
        jax.profiler.start_trace(profile_dir)
        try:
            time.sleep(self.profile_seconds)
        finally:
            jax.profiler.stop_trace()

    def wait(self, timeout_s: float = 10.0) -> None:
        """Join outstanding capture threads (tests / orderly shutdown)."""
        deadline = time.monotonic() + timeout_s
        for t in list(self._threads):
            t.join(max(0.0, deadline - time.monotonic()))


def install_trace_hook(registry: Registry | None = None) -> None:
    """Refresh the trace mirror on every scrape."""
    reg = registry if registry is not None else default_registry()
    reg.add_collect_hook(lambda: mirror_trace(reg))


def install_goodput_hook(tracker, registry: Registry | None = None) -> None:
    """Refresh the goodput mirror from a live tracker on every scrape."""
    reg = registry if registry is not None else default_registry()
    reg.add_collect_hook(lambda: mirror_goodput(tracker.report(), reg))
