"""Bridges: fold goodput reports and translate-trace totals into an obs
registry, so one scrape carries step metrics, goodput, and span totals.

Both mirrors are idempotent gauge writes, so they compose with
:meth:`Registry.add_collect_hook` — the registry refreshes them on every
scrape instead of the workload polling on a timer.
"""

from __future__ import annotations

from move2kube_tpu.obs.metrics import Registry, default_registry


def mirror_trace(registry: Registry | None = None, recorder=None) -> None:
    """Mirror ``utils.trace`` span totals + counters into gauges
    (``m2kt_trace_span_seconds_total{span=...}``). No-op when the image
    doesn't ship trace."""
    reg = registry if registry is not None else default_registry()
    try:
        from move2kube_tpu.utils import trace
    except Exception:  # noqa: BLE001 - slim vendored images
        return
    snap = (recorder or trace.get()).to_dict()
    spans = reg.gauge(
        "m2kt_trace_span_seconds_total",
        "Cumulative wall seconds per pipeline span", labels=("span",))
    for name, seconds in snap.get("spans", {}).items():
        spans.labels(span=name).set(seconds)
    counters = reg.gauge(
        "m2kt_trace_counter", "utils.trace counters", labels=("name",))
    for name, value in snap.get("counters", {}).items():
        counters.labels(name=name).set(value)


def mirror_goodput(report: dict, registry: Registry | None = None) -> None:
    """Mirror a :func:`resilience.goodput` report into gauges: fraction,
    per-category seconds, and step watermarks."""
    reg = registry if registry is not None else default_registry()
    frac = report.get("goodput_fraction")
    if frac is not None:
        reg.gauge("m2kt_goodput_fraction",
                  "Fraction of wall-clock spent on productive steps"
                  ).set(float(frac))
    secs = reg.gauge("m2kt_goodput_seconds",
                     "Wall seconds per goodput category",
                     labels=("category",))
    for cat, val in report.get("seconds", {}).items():
        secs.labels(category=cat).set(float(val))
    for key, name in (("steps_done", "m2kt_goodput_steps_done"),
                      ("last_saved_step", "m2kt_goodput_last_saved_step")):
        if key in report:
            reg.gauge(name, f"Goodput watermark: {key}"
                      ).set(float(report[key]))


def install_trace_hook(registry: Registry | None = None) -> None:
    """Refresh the trace mirror on every scrape."""
    reg = registry if registry is not None else default_registry()
    reg.add_collect_hook(lambda: mirror_trace(reg))


def install_goodput_hook(tracker, registry: Registry | None = None) -> None:
    """Refresh the goodput mirror from a live tracker on every scrape."""
    reg = registry if registry is not None else default_registry()
    reg.add_collect_hook(lambda: mirror_goodput(tracker.report(), reg))
