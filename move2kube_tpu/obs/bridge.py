"""Bridges: fold goodput reports and translate-trace totals into an obs
registry, so one scrape carries step metrics, goodput, and span totals.

Both mirrors are idempotent gauge writes, so they compose with
:meth:`Registry.add_collect_hook` — the registry refreshes them on every
scrape instead of the workload polling on a timer.
"""

from __future__ import annotations

import threading
from collections import deque

from move2kube_tpu.obs.metrics import Registry, default_registry


def mirror_trace(registry: Registry | None = None, recorder=None) -> None:
    """Mirror ``utils.trace`` span totals + counters into gauges
    (``m2kt_trace_span_seconds_total{span=...}``). No-op when the image
    doesn't ship trace."""
    reg = registry if registry is not None else default_registry()
    try:
        from move2kube_tpu.utils import trace
    except Exception:  # noqa: BLE001 - slim vendored images
        return
    snap = (recorder or trace.get()).to_dict()
    spans = reg.gauge(
        "m2kt_trace_span_seconds_total",
        "Cumulative wall seconds per pipeline span", labels=("span",))
    for name, seconds in snap.get("spans", {}).items():
        spans.labels(span=name).set(seconds)
    counters = reg.gauge(
        "m2kt_trace_counter", "utils.trace counters", labels=("name",))
    for name, value in snap.get("counters", {}).items():
        counters.labels(name=name).set(value)


def mirror_goodput(report: dict, registry: Registry | None = None) -> None:
    """Mirror a :func:`resilience.goodput` report into gauges: fraction,
    per-category seconds, and step watermarks."""
    reg = registry if registry is not None else default_registry()
    frac = report.get("goodput_fraction")
    if frac is not None:
        reg.gauge("m2kt_goodput_fraction",
                  "Fraction of wall-clock spent on productive steps"
                  ).set(float(frac))
    secs = reg.gauge("m2kt_goodput_seconds",
                     "Wall seconds per goodput category",
                     labels=("category",))
    for cat, val in report.get("seconds", {}).items():
        secs.labels(category=cat).set(float(val))
    for key, name in (("steps_done", "m2kt_goodput_steps_done"),
                      ("last_saved_step", "m2kt_goodput_last_saved_step")):
        if key in report:
            reg.gauge(name, f"Goodput watermark: {key}"
                      ).set(float(report[key]))


class StragglerDetector:
    """MegaScale-style slow-host identification from per-host step-time
    reports.

    Each host (or simulated slice in the forced-host drill) reports its
    wall time for every step; the detector keeps a bounded window per
    host and scores each host as ``median(host window) / median(fleet
    medians)`` — 1.0 means in line with the fleet, 1.5 means this host's
    steps take 50% longer than the typical host. Synchronous data-
    parallel training runs at the speed of the slowest participant, so a
    single straggling host taxes every step of every other host; the
    score makes the guilty one visible *before* anyone stares at 256
    per-host dashboards.

    Scores are exported as ``m2kt_straggler_score{host=...}`` gauges and
    crossing ``threshold`` increments
    ``m2kt_straggler_events_total{host=...}`` once per excursion (hyst:
    re-arms only after the score drops back under) — alertable without
    firing once per step while a host stays slow.
    """

    def __init__(self, registry: Registry | None = None,
                 threshold: float = 1.5, window: int = 32):
        reg = registry if registry is not None else default_registry()
        self.threshold = float(threshold)
        self.window = max(2, int(window))
        self._lock = threading.Lock()
        self._times: dict[str, deque[float]] = {}
        self._over: set[str] = set()
        self.events = 0
        self._score_gauge = reg.gauge(
            "m2kt_straggler_score",
            "Per-host median step time / fleet median (1.0 = in line)",
            labels=("host",))
        self._event_counter = reg.counter(
            "m2kt_straggler_events_total",
            "Straggler threshold crossings", labels=("host",))

    @staticmethod
    def _median(values) -> float:
        vals = sorted(values)
        n = len(vals)
        mid = n // 2
        return vals[mid] if n % 2 else (vals[mid - 1] + vals[mid]) / 2.0

    def report(self, host: str, step: int, seconds: float) -> None:
        """Fold one (host, step time) observation in and refresh that
        host's score + event state."""
        with self._lock:
            times = self._times.get(host)
            if times is None:
                times = self._times[host] = deque(maxlen=self.window)
            times.append(max(0.0, float(seconds)))
            scores = self._scores_locked()
        score = scores.get(host)
        if score is None:
            return
        self._score_gauge.labels(host=host).set(round(score, 6))
        with self._lock:
            if score >= self.threshold and host not in self._over:
                self._over.add(host)
                self.events += 1
                fire = True
            else:
                if score < self.threshold:
                    self._over.discard(host)
                fire = False
        if fire:
            self._event_counter.labels(host=host).inc()

    def _scores_locked(self) -> dict[str, float]:
        medians = {h: self._median(t)
                   for h, t in self._times.items() if t}
        if not medians:
            return {}
        fleet = self._median(medians.values())
        if fleet <= 0:
            return {h: 1.0 for h in medians}
        return {h: m / fleet for h, m in medians.items()}

    def scores(self) -> dict[str, float]:
        """Current per-host scores (host median / fleet median)."""
        with self._lock:
            return self._scores_locked()


def install_trace_hook(registry: Registry | None = None) -> None:
    """Refresh the trace mirror on every scrape."""
    reg = registry if registry is not None else default_registry()
    reg.add_collect_hook(lambda: mirror_trace(reg))


def install_goodput_hook(tracker, registry: Registry | None = None) -> None:
    """Refresh the goodput mirror from a live tracker on every scrape."""
    reg = registry if registry is not None else default_registry()
    reg.add_collect_hook(lambda: mirror_goodput(tracker.report(), reg))
