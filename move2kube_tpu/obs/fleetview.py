"""Fleet trace collector: stitch per-role span rings into one timeline.

Each fleet process (router, prefill, decode) keeps its own bounded span
ring and serves it at ``/traces`` (obs/server.py) in the flight-recorder
document shape. This module pulls those rings — over HTTP, from live
``SpanRecorder`` objects, or from already-parsed docs — and merges them
into one cross-process view, keyed by the W3C trace ids the router
propagated on every hop.

Network-gap synthesis, and why the decomposition is *exact*: process
clocks are not synchronized, so absolute cross-host timestamps cannot be
trusted — but differences of the SAME parent/child pair's endpoints can.
For every cross-process edge (a replica span whose parent span lives in
another process) the collector synthesizes two ``net.hop`` spans as
residuals of the client span around the server span:

    hop_send = server.start - client.start
    hop_recv = client.end   - server.end

so ``client.dur == hop_send + server.dur + hop_recv`` holds to float
rounding *by construction*, whatever the skew (skew shifts the two gaps
in opposite directions; their sum is skew-free). Likewise router-local
idle between a parent's consecutive child spans becomes ``local.gap``
spans, extending PR 7's exact-decomposition invariant (TTFT == queue +
prefill from shared clock readings) across processes: the
router-observed e2e equals the sum of its decomposed parts, and
``decompose()`` asserts the residual.

Stdlib-only (urllib for the pulls): vendored into emitted images with
the rest of ``obs/``.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request

SYNTH_HOP = "net.hop"
SYNTH_GAP = "local.gap"


def _src_key(span: dict) -> tuple:
    # role is part of the identity: in-process fleets (tests, the bench
    # probe) run router and replica recorders under one pid
    return (span.get("host", ""), span.get("pid", 0), span.get("role", ""))


class FleetTraceCollector:
    """Pulls span rings from fleet roles and stitches one timeline.

    Sources may be mixed:

    - ``str`` — base URL of a role's telemetry server; pulled from
      ``<url>/traces`` (append ``clear`` at collect time to drain);
    - objects with ``ring_doc()`` — live in-process recorders;
    - ``dict`` — an already-parsed ring document (e.g. a flight file).

    A source that fails to answer is skipped, not raised: the collector
    runs against fleets where replicas die — that is the point.
    """

    def __init__(self, sources=(), timeout_s: float = 2.0) -> None:
        self.sources = list(sources)
        self.timeout_s = timeout_s

    def add_source(self, source) -> None:
        self.sources.append(source)

    # -- collection --------------------------------------------------------

    def _pull(self, source, clear: bool) -> dict | None:
        if isinstance(source, dict):
            return source
        ring_doc = getattr(source, "ring_doc", None)
        if callable(ring_doc):
            return ring_doc()
        url = str(source).rstrip("/") + "/traces"
        if clear:
            url += "?clear=1"
        try:
            with urllib.request.urlopen(url, timeout=self.timeout_s) as r:
                return json.loads(r.read().decode("utf-8"))
        except (urllib.error.URLError, OSError, ValueError):
            return None

    def collect(self, clear: bool = False) -> list[dict]:
        docs = []
        for source in self.sources:
            doc = self._pull(source, clear)
            if doc and isinstance(doc.get("spans"), list):
                docs.append(doc)
        return docs

    # -- stitching ---------------------------------------------------------

    def stitch(self, docs: list[dict] | None = None) -> dict:
        """Merge ring docs into ``{"spans": [...], "traces": {tid: [...]}}``
        with per-hop ``net.hop`` spans synthesized on every cross-process
        parent/child edge. Every span gains ``host``/``pid``/``role``
        from its source doc and a ``synthetic`` flag."""
        if docs is None:
            docs = self.collect()
        spans: list[dict] = []
        by_id: dict[str, dict] = {}
        for doc in docs:
            for s in doc.get("spans", []):
                t = dict(s)
                t.setdefault("host", doc.get("host", ""))
                t["pid"] = doc.get("pid", 0)
                t["role"] = s.get("role") or doc.get("role", "")
                t["synthetic"] = False
                spans.append(t)
                by_id[t["span_id"]] = t
        synth: list[dict] = []
        for s in spans:
            parent = by_id.get(s.get("parent_id", ""))
            if parent is None or _src_key(parent) == _src_key(s):
                continue
            synth.extend(self._hops(parent, s))
        spans = spans + synth
        traces: dict[str, list[dict]] = {}
        for s in spans:
            traces.setdefault(s["trace_id"], []).append(s)
        for tid in traces:
            traces[tid].sort(key=lambda x: x["ts_unix"])
        return {"spans": spans, "traces": traces}

    @staticmethod
    def _hops(client: dict, server: dict) -> list[dict]:
        """The two residual gap spans around one cross-process edge.
        Durations may come out negative under extreme skew — they are
        residuals, and keeping them is what keeps the sum exact."""
        c0 = client["ts_unix"]
        s0 = server["ts_unix"]
        # send is the one genuine cross-clock difference; recv is the
        # residual closing the client span, computed as small-number
        # arithmetic (NOT as a difference of epoch-anchored endpoints,
        # whose float ulp is ~0.5µs) so send + server + recv equals the
        # client duration to float rounding
        send = s0 - c0
        recv = client["dur_s"] - server["dur_s"] - send
        common = {
            "trace_id": client["trace_id"],
            "parent_id": client["span_id"],
            "in_flight": False,
            "synthetic": True,
            "host": client.get("host", ""),
            "pid": client.get("pid", 0),
            "role": client.get("role", ""),
        }
        return [
            {**common, "name": SYNTH_HOP,
             "span_id": f"syn-{server['span_id']}-send",
             "ts_unix": c0, "dur_s": send,
             "attrs": {"direction": "send",
                       "from_role": client.get("role", ""),
                       "to_role": server.get("role", ""),
                       "over": server["span_id"]}},
            {**common, "name": SYNTH_HOP,
             "span_id": f"syn-{server['span_id']}-recv",
             "ts_unix": s0 + server["dur_s"], "dur_s": recv,
             "attrs": {"direction": "recv",
                       "from_role": server.get("role", ""),
                       "to_role": client.get("role", ""),
                       "over": server["span_id"]}},
        ]

    # -- exact decomposition ----------------------------------------------

    def decompose(self, trace_id: str, root_name: str = "router.request",
                  docs: list[dict] | None = None) -> dict:
        """Flatten one stitched trace into the exact parts of the root
        span's observed latency: local child spans, synthesized local
        idle gaps, and — for every child that crossed a process — the
        hop-send gap, the remote span, and the hop-recv gap in place of
        the client span's own duration.

        Returns ``{"e2e_s", "parts": [{name, dur_s, kind}, ...],
        "residual_s"}`` where ``residual_s == e2e_s - sum(parts)`` is
        zero up to float rounding — the acceptance invariant."""
        merged = self.stitch(docs)
        trace = merged["traces"].get(trace_id, [])
        real = [s for s in trace if not s["synthetic"]]
        roots = [s for s in real if s["name"] == root_name]
        if not roots:
            raise ValueError(f"no {root_name!r} span in trace {trace_id}")
        root = roots[0]
        children = sorted(
            (s for s in real
             if s.get("parent_id") == root["span_id"]
             and _src_key(s) == _src_key(root)),
            key=lambda s: s["ts_unix"])
        remote_by_parent: dict[str, dict] = {}
        for s in real:
            parent = s.get("parent_id", "")
            if parent and _src_key(s) != _src_key(root):
                remote_by_parent.setdefault(parent, s)
        # all arithmetic is rebased to the root's start (epoch-anchored
        # endpoints cancel at ~0.5µs float ulp; differences of small
        # numbers telescope exactly), and closing residuals are computed
        # from durations, not endpoint subtraction — exactness by
        # construction
        parts: list[dict] = []
        root_t0 = root["ts_unix"]
        cursor = 0.0  # elapsed-from-root already accounted for
        for child in children:
            rel = child["ts_unix"] - root_t0
            parts.append({"name": SYNTH_GAP, "dur_s": rel - cursor,
                          "kind": "gap"})
            remote = remote_by_parent.get(child["span_id"])
            if remote is not None:
                send = remote["ts_unix"] - child["ts_unix"]
                recv = child["dur_s"] - remote["dur_s"] - send
                parts.append({"name": SYNTH_HOP, "dur_s": send,
                              "kind": "hop",
                              "to_role": remote.get("role", "")})
                parts.append({"name": remote["name"],
                              "dur_s": remote["dur_s"], "kind": "remote",
                              "role": remote.get("role", "")})
                parts.append({"name": SYNTH_HOP, "dur_s": recv,
                              "kind": "hop",
                              "to_role": root.get("role", "")})
            else:
                parts.append({"name": child["name"],
                              "dur_s": child["dur_s"], "kind": "child"})
            cursor = rel + child["dur_s"]
        parts.append({"name": SYNTH_GAP, "dur_s": root["dur_s"] - cursor,
                      "kind": "gap"})
        e2e = root["dur_s"]
        residual = e2e - sum(p["dur_s"] for p in parts)
        return {"e2e_s": e2e, "parts": parts, "residual_s": residual,
                "trace_id": trace_id}

    # -- export ------------------------------------------------------------

    def chrome_trace(self, docs: list[dict] | None = None) -> dict:
        """One merged Chrome trace: every role's spans on its own
        process row (metadata-named ``role@host``), synthesized hops
        included so the timeline shows the wire time between rows."""
        merged = self.stitch(docs)
        spans = merged["spans"]
        if not spans:
            return {"traceEvents": [], "displayTimeUnit": "ms",
                    "otherData": {"sources": 0}}
        anchor = min(s["ts_unix"] for s in spans)
        events: list[dict] = []
        named: set = set()
        for s in spans:
            pid = s.get("pid", 0)
            if pid not in named:
                named.add(pid)
                events.append({
                    "name": "process_name", "ph": "M", "pid": pid,
                    "args": {"name": (f"{s.get('role', '?')}"
                                      f"@{s.get('host', '?')}")},
                })
            events.append({
                "name": s["name"],
                "ph": "X",
                "ts": round((s["ts_unix"] - anchor) * 1e6, 3),
                "dur": round(max(0.0, s["dur_s"]) * 1e6, 3),
                "pid": pid,
                "tid": 0 if s["synthetic"] else 1,
                "cat": "m2kt.synthetic" if s["synthetic"] else "m2kt",
                "args": {**s.get("attrs", {}), "trace_id": s["trace_id"],
                         "span_id": s["span_id"],
                         "parent_id": s.get("parent_id", ""),
                         "role": s.get("role", "")},
            })
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "otherData": {"anchor_unix": anchor,
                              "sources": len(named)}}

    def otlp_lines(self, docs: list[dict] | None = None) -> list[str]:
        """OTLP/JSON lines over the merged view — synthetic hop spans
        ride along flagged ``m2kt.synthetic`` so a real collector can
        drop or keep them."""
        merged = self.stitch(docs)
        lines = []
        for s in merged["spans"]:
            start_ns = int(s["ts_unix"] * 1e9)
            attrs = [{"key": "m2kt.role",
                      "value": {"stringValue": s.get("role", "")}},
                     {"key": "m2kt.synthetic",
                      "value": {"boolValue": bool(s["synthetic"])}}]
            for k, v in (s.get("attrs") or {}).items():
                attrs.append({"key": str(k),
                              "value": {"stringValue": str(v)}})
            span_id = s["span_id"]
            if s["synthetic"]:
                # synthetic ids are not 16-hex; derive a stable one
                span_id = format(abs(hash(span_id)) % (1 << 64), "016x")
            lines.append(json.dumps({"resourceSpans": [{
                "resource": {"attributes": [
                    {"key": "host.name",
                     "value": {"stringValue": s.get("host", "")}},
                    {"key": "service.name",
                     "value": {"stringValue": "move2kube-tpu"}},
                ]},
                "scopeSpans": [{
                    "scope": {"name": "m2kt.obs.fleetview"},
                    "spans": [{
                        "traceId": s["trace_id"],
                        "spanId": span_id,
                        "parentSpanId": s.get("parent_id", ""),
                        "name": s["name"],
                        "kind": 1,
                        "startTimeUnixNano": str(start_ns),
                        "endTimeUnixNano": str(
                            start_ns + int(max(0.0, s["dur_s"]) * 1e9)),
                        "attributes": attrs,
                    }],
                }],
            }]}, separators=(",", ":")))
        return lines
