"""Alerting + dashboard manifest builders for emitted TPU workloads.

The telemetry plane (PR 5) made the metrics scrapeable; this module
makes them *actionable* at emission time: every JobSet/Deployment/
Knative target can carry a ``monitoring.coreos.com/v1`` PrometheusRule
encoding the fleet's operational contract — goodput fraction, step-time
p95 regression, restart storms (the exit-83 slice-loss signature), and
serving queue depth — plus a Grafana dashboard ConfigMap (the standard
``grafana_dashboard: "1"`` sidecar-discovery label) so the first scrape
lands on a dashboard instead of a blank Explore tab.

Builders return plain manifest dicts and keep this module stdlib-only
(it is vendored into emitted images with the rest of ``obs/``; nothing
imports it at runtime there, but an import must not drag the QA engine
in). The QA gating and cluster-support warnings live in
``apiresource/obs_wiring.py``; Helm parameterization of the thresholds
lives in ``passes/parameterize.py`` keyed off :data:`THRESHOLDS`.
"""

from __future__ import annotations

import json

# alert thresholds, single source of truth: builders bake the default
# into the PromQL expr, the parameterizer lifts exactly these literals
# into chart values (value key -> default). Keys double as .Values names.
THRESHOLDS = {
    "tpugoodputmin": "0.5",        # goodput fraction alarm floor
    "tpustepp95factor": "1.5",     # p95 step time vs 1h-ago baseline
    "tpurestartstormcount": "3",   # restarts per window before alarm
    "tpuservequeuemax": "64",      # queued requests before alarm
    "tpumfumin": "0.05",           # achieved-MFU alarm floor
    "tpuhbmheadroomfrac": "0.92",  # peak-HBM fraction of chip capacity
    # SLO burn-rate multiples (obs/slo.py exports the gauges; the SRE
    # 1h/5m + 6h/30m window pairing lives in the gauge's window label)
    "tpuslofastburn": "14.4",      # fast-burn page threshold
    "tpusloslowburn": "6",         # slow-burn ticket threshold
    "tpuslottftp95": "0.5",        # per-tenant TTFT p95 objective, s
    # numerics plane (obs/numerics.py): max-rel logit error the serving
    # quant-drift auditor may report before alarming — matches the
    # int8 tier of the build-time logit gates
    "tpunumdriftmax": "0.05",
    # scheduler plane (serving/sched): a tenant whose TTFT p95 sits at
    # this multiple of the objective WHILE preemption is active is being
    # starved by higher classes, not by its own quota
    "tpuschedstarvefactor": "4",
    # usage/diag plane (obs/ledger.py, obs/bridge.py DiagWatchdog):
    # diag bundles captured per hour before the watchdog itself is the
    # anomaly — each bundle is a profiler pause plus disk, so a storm
    # means a flapping trigger or a mis-set rate limit
    "tpudiagstormcount": "4",
}


def prometheus_rule(name: str, selector_label: str,
                    serving: bool = False,
                    thresholds: dict | None = None) -> dict:
    """A PrometheusRule for one emitted service. Training targets get
    the goodput/step-time/restart rules; serving targets get the queue
    rule as well (their engine exports ``m2kt_serve_*``).

    ``thresholds`` overrides the baked-in defaults per key — in Helm
    output the caller passes ``{{ .Values.<key> }}`` refs so the chart
    retunes alert floors without touching the manifests."""
    th = dict(THRESHOLDS)
    th.update(thresholds or {})
    sel = f'{{{selector_label.replace("/", "_").replace(".", "_")}="{name}"}}'
    # the relabeled pod-label selector: annotation-driven scrapes expose
    # pod labels through labelmap relabeling with / and . sanitized
    rules = [
        {
            "alert": "M2KTGoodputLow",
            "expr": (f"m2kt_goodput_fraction{sel} "
                     f"< {th['tpugoodputmin']}"),
            "for": "15m",
            "labels": {"severity": "warning", "m2kt_service": name},
            "annotations": {
                "summary": f"{name}: goodput fraction below floor",
                "description": (
                    "Productive step time is a low fraction of wall "
                    "clock — the pod is spending its life in restarts, "
                    "restores, or retry backoff."),
            },
        },
        {
            "alert": "M2KTStepTimeP95Regression",
            "expr": (
                "histogram_quantile(0.95, sum(rate("
                f"m2kt_train_step_seconds_bucket{sel}[10m])) by (le)) > "
                f"{th['tpustepp95factor']} * "
                "histogram_quantile(0.95, sum(rate("
                f"m2kt_train_step_seconds_bucket{sel}[1h] offset 1h)) "
                "by (le))"),
            "for": "10m",
            "labels": {"severity": "warning", "m2kt_service": name},
            "annotations": {
                "summary": f"{name}: step-time p95 regressed",
                "description": (
                    "p95 step wall time exceeds its 1h-ago baseline by "
                    "the configured factor — check the straggler scores "
                    "(m2kt_straggler_score) and the flight recorder of "
                    "any recent restarts."),
            },
        },
        {
            "alert": "M2KTRestartStorm",
            "expr": (
                "sum(increase(kube_pod_container_status_restarts_total"
                f'{{pod=~"{name}.*"}}[30m])) > '
                f"{th['tpurestartstormcount']}"),
            "for": "0m",
            "labels": {"severity": "critical", "m2kt_service": name},
            "annotations": {
                "summary": f"{name}: restart storm",
                "description": (
                    "Container restarts are above budget for the "
                    "window. Exit code 83 means slice loss "
                    "(capacity weather — check the elastic re-plan "
                    "events in m2kt-exit.json); anything else, read "
                    "m2kt-flight.json from the pod volume."),
            },
        },
        {
            "alert": "M2KTMFULow",
            # the > 0 guard keeps the alert quiet when the cost model
            # could not derive flops (gauge pinned at 0 = unknown)
            "expr": (f"(m2kt_train_mfu{sel} > 0) and "
                     f"(m2kt_train_mfu{sel} < {th['tpumfumin']})"),
            "for": "30m",
            "labels": {"severity": "warning", "m2kt_service": name},
            "annotations": {
                "summary": f"{name}: achieved MFU below floor",
                "description": (
                    "The compiled step's FLOPs over measured wall time "
                    "is far from the chip peak. Check "
                    "m2kt_roofline_bound (0 = bandwidth-bound: no "
                    "kernel tuning will help, re-shard or grow batch) "
                    "and the straggler scores."),
            },
        },
        {
            "alert": "M2KTHBMHeadroomLow",
            "expr": (
                f'm2kt_hbm_peak_bytes{{category="total",'
                f'{sel[1:-1]}}} > '
                f"{th['tpuhbmheadroomfrac']} * m2kt_chip_hbm_bytes{sel}"),
            "for": "5m",
            "labels": {"severity": "critical", "m2kt_service": name},
            "annotations": {
                "summary": f"{name}: compiled peak HBM near capacity",
                "description": (
                    "The executable's argument+output+temp footprint is "
                    "within the fragmentation margin of chip HBM — the "
                    "next recompile (longer bucket, bigger batch) OOMs. "
                    "Read the memory block of m2kt-flight.json / the "
                    "plan report's fsdp re-split suggestion."),
            },
        },
        {
            "alert": "M2KTNonFiniteSteps",
            # any skipped update or recorded non-finite step in the
            # window: apply_if_finite absorbs a handful silently, but a
            # training run producing NaNs is diverging — read the
            # numerics block of m2kt-flight.json for the first bad
            # layer group. No threshold knob: zero is the budget.
            "expr": (
                f"increase(m2kt_train_skipped_steps_total{sel}[30m]) > 0 "
                f"or increase(m2kt_train_nonfinite_steps_total{sel}"
                "[30m]) > 0"),
            "for": "0m",
            "labels": {"severity": "warning", "m2kt_service": name},
            "annotations": {
                "summary": f"{name}: non-finite training steps",
                "description": (
                    "Gradients, parameters, or the loss went NaN/Inf. "
                    "m2kt_train_tensor_nonfinite names the layer group; "
                    "the <flight>.numerics sidecar (folded into "
                    "m2kt-flight.json) holds the full per-group tensor "
                    "health of the bad step. Check the loss scale "
                    "(m2kt_train_loss_scale) before blaming the data."),
            },
        },
        {
            "alert": "M2KTDiagCaptureStorm",
            # the watchdog is rate-limited and capped in-process; this
            # alert is the out-of-process backstop — a pod repeatedly
            # arming means a flapping trigger (SLO oscillating around
            # the burn threshold, a bimodal step time) or an operator
            # who set M2KT_DIAG_MIN_INTERVAL_S to zero. The reason
            # label on m2kt_diag_captures_total names the trigger.
            "expr": (
                f"sum(increase(m2kt_diag_captures_total{sel}[1h])) "
                f"> {th['tpudiagstormcount']}"),
            "for": "0m",
            "labels": {"severity": "warning", "m2kt_service": name},
            "annotations": {
                "summary": f"{name}: diagnostic captures storming",
                "description": (
                    "The anomaly watchdog has captured more diagnostic "
                    "bundles this hour than the storm budget — each one "
                    "pauses the workload for a profiler trace and "
                    "writes a bundle to M2KT_DIAG_DIR. Read the reason "
                    "label (slo_fast_burn / step_regression / "
                    "nonfinite) and the newest bundle's manifest; fix "
                    "the underlying flap or raise "
                    "M2KT_DIAG_MIN_INTERVAL_S."),
            },
        },
    ]
    if serving:
        rules.append({
            "alert": "M2KTQuantDriftHigh",
            "expr": (f"m2kt_serve_quant_drift{sel} "
                     f"> {th['tpunumdriftmax']}"),
            "for": "5m",
            "labels": {"severity": "critical", "m2kt_service": name},
            "annotations": {
                "summary": f"{name}: quantized logits drifting from fp",
                "description": (
                    "The runtime quant-drift audit (sampled cold "
                    "prefills replayed through the fp reference "
                    "weights) exceeds the build-time logit-gate "
                    "budget — an int8 scale pool is corrupted or a "
                    "weight swap installed a damaged shard. Roll back "
                    "the weights generation (m2kt_weights_version) or "
                    "disable quantization."),
            },
        })
        rules.append({
            "alert": "M2KTServeQueueDeep",
            "expr": (f"m2kt_serve_queue_depth{sel} "
                     f"> {th['tpuservequeuemax']}"),
            "for": "5m",
            "labels": {"severity": "warning", "m2kt_service": name},
            "annotations": {
                "summary": f"{name}: serving admission queue is deep",
                "description": (
                    "Requests are waiting longer than the decode slots "
                    "can absorb — TTFT is queue-dominated. Scale "
                    "replicas or raise the max decode batch."),
            },
        })
        inner = sel[1:-1]
        rules.append({
            "alert": "M2KTSLOFastBurn",
            # the SRE multi-window pairing: page only while BOTH the
            # long and the short fast window burn over threshold, so
            # the page stops as soon as the short window recovers
            "expr": (
                f'm2kt_slo_burn_rate{{window="fast_long",{inner}}} '
                f"> {th['tpuslofastburn']} and "
                f'm2kt_slo_burn_rate{{window="fast_short",{inner}}} '
                f"> {th['tpuslofastburn']}"),
            "for": "2m",
            "labels": {"severity": "critical", "m2kt_service": name},
            "annotations": {
                "summary": f"{name}: SLO error budget burning fast",
                "description": (
                    "At this burn rate the monthly error budget is gone "
                    "in hours — a flood or a latency regression is "
                    "failing the TTFT/availability objective right now. "
                    "Check per-tenant attainment "
                    "(m2kt_slo_tenant_attainment) to see who is "
                    "affected and the router reason-labeled retry "
                    "counters for the cause."),
            },
        })
        rules.append({
            "alert": "M2KTSLOSlowBurn",
            "expr": (
                f'm2kt_slo_burn_rate{{window="slow_long",{inner}}} '
                f"> {th['tpusloslowburn']} and "
                f'm2kt_slo_burn_rate{{window="slow_short",{inner}}} '
                f"> {th['tpusloslowburn']}"),
            "for": "15m",
            "labels": {"severity": "warning", "m2kt_service": name},
            "annotations": {
                "summary": f"{name}: SLO error budget burning steadily",
                "description": (
                    "A sustained moderate burn: not page-worthy, but at "
                    "this rate the budget is exhausted before the SLO "
                    "period ends. Ticket and trend the per-tenant TTFT "
                    "p95 gauges."),
            },
        })
        rules.append({
            "alert": "M2KTPriorityStarvation",
            # fires only while the scheduler is actively preempting: a
            # tenant far over its TTFT objective during preemption churn
            # is losing its slots to higher classes — quota throttling
            # shows up as 429s (m2kt_sched_throttled_total), never here
            "expr": (
                f"m2kt_slo_tenant_ttft_p95_seconds{sel} "
                f"> {th['tpuschedstarvefactor']} * {th['tpuslottftp95']} "
                f"and on() (sum(increase("
                f"m2kt_sched_preempted_total{sel}[10m])) > 0)"),
            "for": "10m",
            "labels": {"severity": "warning", "m2kt_service": name},
            "annotations": {
                "summary": f"{name}: a low-priority tenant is starving "
                           "under preemption",
                "description": (
                    "A tenant's TTFT p95 has sat at a multiple of the "
                    "objective while the scheduler kept preempting — "
                    "best-effort work is being evicted faster than it "
                    "can finish. Raise the tenant's priority class, add "
                    "capacity, or quota the high-priority flood "
                    "(m2kt_sched_preempted_total / _resumed_total show "
                    "the churn; the tenant label on this alert shows "
                    "who is starving)."),
            },
        })
        rules.append({
            "alert": "M2KTAutoscaleActuationStalled",
            # the predictive controller wants capacity it is not
            # getting: target held above actual for 10m means scale
            # patches are failing (RBAC, quota) or new pods cannot
            # schedule (no TPU nodes) — either way the forecasted
            # demand will land on a fleet that never grew. No threshold
            # knob: any sustained gap is wrong (M2KTNonFiniteSteps
            # precedent).
            "expr": (f"m2kt_autoscale_target_replicas{sel} "
                     f"> m2kt_autoscale_actual_replicas{sel}"),
            "for": "10m",
            "labels": {"severity": "warning", "m2kt_service": name},
            "annotations": {
                "summary": f"{name}: predictive autoscaler cannot "
                           "actuate",
                "description": (
                    "The autoscaler's target replica count has stayed "
                    "above what the fleet actually runs. Check the "
                    "controller pod's logs for scale-subresource patch "
                    "failures (RBAC), the decode Deployment's events "
                    "for unschedulable pods (TPU node pool at quota), "
                    "and m2kt_autoscale_forecast_tps for whether the "
                    "demand it is provisioning for is real."),
            },
        })
        rules.append({
            "alert": "M2KTSLOTenantTTFTHigh",
            "expr": (f"m2kt_slo_tenant_ttft_p95_seconds{sel} "
                     f"> {th['tpuslottftp95']}"),
            "for": "10m",
            "labels": {"severity": "warning", "m2kt_service": name},
            "annotations": {
                "summary": f"{name}: a tenant's TTFT p95 is over target",
                "description": (
                    "One tenant is missing the TTFT objective while the "
                    "aggregate may still look healthy — check the "
                    "tenant label on this alert, their prefix-cache "
                    "affinity, and whether their traffic is landing on "
                    "a spilled replica."),
            },
        })
    return {
        "apiVersion": "monitoring.coreos.com/v1",
        "kind": "PrometheusRule",
        "metadata": {
            "name": f"{name}-alerts",
            "labels": {selector_label: name, "role": "alert-rules"},
        },
        "spec": {"groups": [{"name": f"m2kt-{name}", "rules": rules}]},
    }


def _panel(panel_id: int, title: str, expr: str, x: int, y: int,
           unit: str = "short") -> dict:
    return {
        "id": panel_id,
        "title": title,
        "type": "timeseries",
        "gridPos": {"h": 8, "w": 12, "x": x, "y": y},
        "fieldConfig": {"defaults": {"unit": unit}},
        "targets": [{"expr": expr, "refId": "A"}],
    }


def grafana_dashboard(name: str, selector_label: str,
                      serving: bool = False) -> dict:
    """The Grafana dashboard JSON model for one service: goodput, step
    time p50/p95, straggler scores, restarts — plus the serving TTFT/
    queue panels for serving targets."""
    sel = f'{{{selector_label.replace("/", "_").replace(".", "_")}="{name}"}}'
    panels = [
        _panel(1, "Goodput fraction",
               f"m2kt_goodput_fraction{sel}", 0, 0, "percentunit"),
        _panel(2, "Step time p50 / p95",
               "histogram_quantile(0.95, sum(rate("
               f"m2kt_train_step_seconds_bucket{sel}[5m])) by (le))",
               12, 0, "s"),
        _panel(3, "Straggler score by host",
               f"m2kt_straggler_score{sel}", 0, 8),
        _panel(4, "Container restarts (30m)",
               "sum(increase(kube_pod_container_status_restarts_total"
               f'{{pod=~"{name}.*"}}[30m]))', 12, 8),
        # cost-model row (obs/costmodel.py): how close to the hardware
        # ceiling, and how close to the HBM cliff
        _panel(7, "Achieved MFU",
               f"m2kt_train_mfu{sel}", 0, 16, "percentunit"),
        _panel(8, "Peak HBM by category",
               f"m2kt_hbm_peak_bytes{sel}", 12, 16, "bytes"),
        # numerics row (obs/numerics.py): per-layer-group tensor health
        # and what apply_if_finite is doing with the loss scale
        _panel(16, "Gradient rms by layer group",
               f'm2kt_train_tensor_rms{{kind="grad",{sel[1:-1]}}}',
               0, 64),
        _panel(17, "Non-finite entries by layer group",
               f"m2kt_train_tensor_nonfinite{sel}", 12, 64),
        _panel(18, "Skipped / non-finite steps (30m)",
               f"increase(m2kt_train_skipped_steps_total{sel}[30m]) "
               f"or increase(m2kt_train_nonfinite_steps_total{sel}"
               "[30m])", 0, 72),
        _panel(19, "Loss scale",
               f"m2kt_train_loss_scale{sel}", 12, 72),
    ]
    if serving:
        panels.append(_panel(
            5, "TTFT p95",
            "histogram_quantile(0.95, sum(rate("
            f"m2kt_serve_ttft_seconds_bucket{sel}[5m])) by (le))",
            0, 24, "s"))
        panels.append(_panel(
            6, "Serving queue depth",
            f"m2kt_serve_queue_depth{sel}", 12, 24))
        panels.append(_panel(
            9, "Serving roofline class by executable",
            f"m2kt_serve_roofline_bound{sel}", 0, 32))
        # SLO row (obs/slo.py): budget burn + who is missing the target
        panels.append(_panel(
            10, "SLO burn rate by window",
            f"m2kt_slo_burn_rate{sel}", 12, 32))
        panels.append(_panel(
            11, "SLO attainment by window",
            f"m2kt_slo_attainment{sel}", 0, 40, "percentunit"))
        panels.append(_panel(
            12, "Tenant TTFT p95",
            f"m2kt_slo_tenant_ttft_p95_seconds{sel}", 12, 40, "s"))
        panels.append(_panel(
            13, "Tenant attainment",
            f"m2kt_slo_tenant_attainment{sel}", 0, 48, "percentunit"))
        # weight-plane row (serving/fleet/weights.py): the generation
        # every replica is decoding with (a swap shows as a fleet-wide
        # step; a straggler stuck on the old generation stands out), and
        # the fetch outcomes — digest_mismatch / store fallback spikes
        # mean peers are serving damaged shards or nobody is healthy
        panels.append(_panel(
            14, "Weights generation by replica",
            f"m2kt_weights_version{sel}", 12, 48))
        panels.append(_panel(
            15, "Weight fetches by source / reason",
            "sum(rate("
            f"m2kt_weights_fetch_total{sel}[5m])) by (source, reason)",
            0, 56))
        panels.append(_panel(
            20, "Quant drift (max-rel logit error, audited prefills)",
            f"m2kt_serve_quant_drift{sel}", 12, 56))
        # scheduler row (serving/sched): preemption/resume churn, who is
        # being throttled at admission, and how much prefill is riding
        # the chunked executable — the starvation alert reads the same
        # series, so the panel is the alert's debugging view
        panels.append(_panel(
            21, "Scheduler preemptions / resumes by reason",
            f"sum(rate(m2kt_sched_preempted_total{sel}[5m])) by (reason) "
            f"or sum(rate(m2kt_sched_resumed_total{sel}[5m])) by (reason)",
            0, 80))
        panels.append(_panel(
            22, "Admission throttles (429s) by reason",
            f"sum(rate(m2kt_sched_throttled_total{sel}[5m])) by (reason)",
            12, 80))
        panels.append(_panel(
            23, "Chunked prefill rate by reason",
            f"sum(rate(m2kt_sched_chunked_total{sel}[5m])) by (reason)",
            0, 88))
        # autoscaling row (serving/fleet/autoscaler.py): the
        # controller's plan vs what the fleet actually runs (the
        # ActuationStalled alert is the gap between these two lines),
        # and its forecast vs the admitted-token demand it predicts —
        # a forecast tracking above demand by more than the lead
        # time's trend is over-provisioning money away
        panels.append(_panel(
            24, "Autoscale target vs actual replicas",
            f"m2kt_autoscale_target_replicas{sel} "
            f"or m2kt_autoscale_actual_replicas{sel}", 12, 88))
        panels.append(_panel(
            25, "Forecast vs admitted token demand (tok/s)",
            f"m2kt_autoscale_forecast_tps{sel} or sum(rate("
            f"m2kt_router_admitted_tokens_total{sel}[5m]))", 0, 96))
        # async-pipeline row (serving/engine.py PR 19): the host gap
        # between consuming step k and dispatching k+1 — the tax the
        # double-buffered pipeline exists to erase — and the fraction
        # of wall time it still eats. Overlap working = gap p95 near
        # zero and the ratio flat near zero under load.
        panels.append(_panel(
            26, "Decode dispatch gap p95",
            "histogram_quantile(0.95, sum(rate("
            f"m2kt_serve_dispatch_gap_seconds_bucket{sel}[5m])) by (le))",
            12, 96, "s"))
        panels.append(_panel(
            27, "Host overhead ratio (gap / wall)",
            f"m2kt_serve_host_overhead_ratio{sel}", 0, 104,
            "percentunit"))
        # usage/cost row (obs/ledger.py + serving/fleet/capture.py):
        # who the fleet's TPU-seconds are billed to (attainment-
        # weighted, from the aggregator), each tenant's net token rate,
        # and the two self-health series of the plane itself — diag
        # bundles by reason and label-cardinality drops by family
        panels.append(_panel(
            28, "Tenant TPU-seconds rate (attainment-weighted)",
            f"sum(rate(m2kt_tenant_tpu_seconds_total{sel}[5m])) "
            "by (tenant)", 12, 104))
        panels.append(_panel(
            29, "Tenant net token rate (tok/s)",
            f"sum(rate(m2kt_router_admitted_tokens_total{sel}[5m])) "
            "by (tenant) - sum(rate("
            f"m2kt_router_admitted_tokens_unused_total{sel}[5m])) "
            "by (tenant)", 0, 112))
        panels.append(_panel(
            30, "Diag captures by reason / series drops by family",
            f"sum(increase(m2kt_diag_captures_total{sel}[1h])) "
            "by (reason) or "
            f"sum(increase(m2kt_obs_series_dropped_total{sel}[1h])) "
            "by (family)", 12, 112))
    return {
        "title": f"move2kube-tpu: {name}",
        "uid": f"m2kt-{name}",
        "tags": ["move2kube-tpu", name],
        "timezone": "browser",
        "schemaVersion": 39,
        "panels": panels,
    }


def dashboard_configmap(name: str, selector_label: str,
                        serving: bool = False) -> dict:
    """The dashboard wrapped in a ConfigMap the standard Grafana sidecar
    discovers via the ``grafana_dashboard: "1"`` label."""
    return {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": {
            "name": f"{name}-dashboard",
            "labels": {selector_label: name, "grafana_dashboard": "1"},
        },
        "data": {
            f"{name}-dashboard.json": json.dumps(
                grafana_dashboard(name, selector_label, serving=serving),
                indent=2, sort_keys=True) + "\n",
        },
    }
