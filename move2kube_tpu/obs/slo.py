"""Per-tenant SLO plane: declarative objectives, sliding-window
attainment, and Google-SRE multi-window multi-burn-rate accounting.

An ``SLOSpec`` names the contract the serving fleet is held to — TTFT
p95 target, per-token latency target, availability — resolved from QA
knobs/env at emission time (the optimizer bakes ``M2KT_SLO_*`` into the
pod env; a Helm install retunes them). The ``SLOTracker`` turns the
engine's per-request outcomes into that contract's ledger:

- a request is *good* when it completed AND met the latency targets;
  the good fraction over a sliding window is the attainment;
- burn rate = (1 - attainment) / error_budget, the SRE workbook's
  unit: burn 1.0 spends the budget exactly over the SLO period, 14.4
  spends 2% of a 30-day budget in one hour;
- alerts use *paired* windows (long AND short over threshold) so a
  fast burn fires in minutes while a recovered incident stops alerting
  as soon as the short window clears — the multi-window multi-burn-rate
  recipe, with the canonical 1h/5m (14.4x) and 6h/30m (6x) pairs,
  scalable via ``M2KT_SLO_WINDOW_SCALE`` so drills and tests need not
  wait an hour for a synthetic flood to register.

Everything exports as ``m2kt_slo_*`` gauges refreshed on scrape (a
collect hook — same pull-model shape as the goodput tracker), including
per-tenant p95 TTFT and attainment under the bounded ``tenant`` label
(``M2KT_OBS_MAX_TENANTS`` seats + ``other`` overflow).

Stdlib-only: vendored into emitted images with the rest of ``obs/``.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass

from move2kube_tpu.obs.metrics import OVERFLOW_LABEL, Registry, TimedWindow

TTFT_P95_ENV = "M2KT_SLO_TTFT_P95_S"
TOKEN_P95_ENV = "M2KT_SLO_TOKEN_P95_S"
AVAILABILITY_ENV = "M2KT_SLO_AVAILABILITY"
WINDOW_SCALE_ENV = "M2KT_SLO_WINDOW_SCALE"
MAX_TENANTS_ENV = "M2KT_OBS_MAX_TENANTS"

DEFAULT_TTFT_P95_S = 0.5
DEFAULT_TOKEN_P95_S = 0.05
DEFAULT_AVAILABILITY = 0.99
DEFAULT_MAX_TENANTS = 8
DEFAULT_TENANT = "default"

# the header tenant identity rides on, router -> replica -> engine
TENANT_HEADER = "X-M2KT-Tenant"

# canonical SRE-workbook pairs: (long_window_s, short_window_s) and the
# burn-rate multiple that must hold over BOTH for the alert to fire
FAST_BURN = 14.4
SLOW_BURN = 6.0
FAST_WINDOWS = (3600.0, 300.0)
SLOW_WINDOWS = (21600.0, 1800.0)

# hard cap on retained request outcomes regardless of window length — a
# flooded server must not hold the flood in memory to account for it
DEFAULT_MAX_EVENTS = 65536


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    try:
        val = float(raw) if raw.strip() else default
    except (TypeError, ValueError):
        return default
    return val if val > 0 else default


def max_tenants() -> int:
    """How many tenants get their own label seat before overflow
    collapses into ``other`` (``M2KT_OBS_MAX_TENANTS``, default 8)."""
    raw = os.environ.get(MAX_TENANTS_ENV, "")
    try:
        val = int(raw) if raw.strip() else DEFAULT_MAX_TENANTS
    except (TypeError, ValueError):
        return DEFAULT_MAX_TENANTS
    return max(1, val)


def clean_tenant(raw: str | None) -> str:
    """Normalize an untrusted tenant header value into a label-safe id:
    printable, bounded length, never empty. The cardinality cap bounds
    the series count; this bounds each value."""
    t = (raw or "").strip()
    if not t:
        return DEFAULT_TENANT
    t = "".join(c if c.isprintable() else "_" for c in t)
    return t[:64]


@dataclass(frozen=True)
class SLOSpec:
    """The declarative serving contract. Zero/negative targets disable
    that dimension (a request cannot miss a target that is off)."""

    ttft_p95_s: float = DEFAULT_TTFT_P95_S
    token_p95_s: float = DEFAULT_TOKEN_P95_S
    availability: float = DEFAULT_AVAILABILITY
    # scales every burn window: 1.0 = the canonical 1h/5m + 6h/30m
    # pairs; a drill sets it tiny so floods register in seconds
    window_scale: float = 1.0

    @property
    def error_budget(self) -> float:
        return max(1e-9, 1.0 - self.availability)

    @property
    def fast_windows(self) -> tuple[float, float]:
        return (FAST_WINDOWS[0] * self.window_scale,
                FAST_WINDOWS[1] * self.window_scale)

    @property
    def slow_windows(self) -> tuple[float, float]:
        return (SLOW_WINDOWS[0] * self.window_scale,
                SLOW_WINDOWS[1] * self.window_scale)

    @classmethod
    def from_env(cls) -> "SLOSpec":
        avail = _env_float(AVAILABILITY_ENV, DEFAULT_AVAILABILITY)
        if not 0 < avail < 1:
            avail = DEFAULT_AVAILABILITY
        return cls(
            ttft_p95_s=_env_float(TTFT_P95_ENV, DEFAULT_TTFT_P95_S),
            token_p95_s=_env_float(TOKEN_P95_ENV, DEFAULT_TOKEN_P95_S),
            availability=avail,
            window_scale=_env_float(WINDOW_SCALE_ENV, 1.0),
        )


def _p95(values: list[float]) -> float:
    if not values:
        return 0.0
    ordered = sorted(values)
    return ordered[min(len(ordered) - 1, int(0.95 * len(ordered)))]


class SLOTracker:
    """Sliding-window request-outcome ledger + burn-rate arithmetic.

    ``clock`` is injectable (tests feed synthetic timelines; production
    uses ``time.monotonic``). Thread-safe: the engine records from its
    step loop while the telemetry thread exports on scrape.
    """

    def __init__(self, spec: SLOSpec | None = None,
                 registry: Registry | None = None,
                 clock=time.monotonic,
                 max_events: int = DEFAULT_MAX_EVENTS,
                 tenant_cap: int | None = None) -> None:
        self.spec = spec or SLOSpec.from_env()
        self._clock = clock
        self._horizon = max(self.spec.fast_windows[0],
                            self.spec.slow_windows[0])
        # (t, tenant, good, ttft_s or None) events; the shared
        # TimedWindow (obs/metrics.py) owns horizon/cap pruning and the
        # trailing-window queries — same math the demand forecaster uses
        self._events = TimedWindow(self._horizon,
                                   max_items=max(1, int(max_events)),
                                   clock=clock)
        self.tenant_cap = tenant_cap if tenant_cap is not None else (
            max_tenants())
        self._registry = registry
        if registry is not None:
            self._init_metrics(registry)
            registry.add_collect_hook(self.export)

    def _init_metrics(self, reg: Registry) -> None:
        self._g_attain = reg.gauge(
            "m2kt_slo_attainment",
            "good-request fraction over each burn window",
            labels=("window",))
        self._g_burn = reg.gauge(
            "m2kt_slo_burn_rate",
            "error-budget burn rate over each burn window "
            "(1.0 spends the budget exactly over the SLO period)",
            labels=("window",))
        self._g_fast = reg.gauge(
            "m2kt_slo_fast_burn_firing",
            "1 when burn rate exceeds the fast threshold over BOTH "
            "paired fast windows")
        self._g_slow = reg.gauge(
            "m2kt_slo_slow_burn_firing",
            "1 when burn rate exceeds the slow threshold over BOTH "
            "paired slow windows")
        self._g_budget = reg.gauge(
            "m2kt_slo_error_budget",
            "1 - availability target: the bad fraction the SLO tolerates")
        self._g_ttft_target = reg.gauge(
            "m2kt_slo_ttft_p95_target_seconds",
            "the TTFT p95 objective requests are judged against")
        cap = self.tenant_cap
        self._g_tenant_ttft = reg.gauge(
            "m2kt_slo_tenant_ttft_p95_seconds",
            "observed TTFT p95 per tenant over the long fast window",
            labels=("tenant",), max_series=cap)
        self._g_tenant_attain = reg.gauge(
            "m2kt_slo_tenant_attainment",
            "good-request fraction per tenant over the long fast window",
            labels=("tenant",), max_series=cap)

    # -- recording ---------------------------------------------------------

    def judge(self, ok: bool, ttft_s: float | None = None,
              token_s: float | None = None) -> bool:
        """One request against the contract: completed AND within every
        enabled latency target."""
        if not ok:
            return False
        if (self.spec.ttft_p95_s > 0 and ttft_s is not None
                and ttft_s > self.spec.ttft_p95_s):
            return False
        if (self.spec.token_p95_s > 0 and token_s is not None
                and token_s > self.spec.token_p95_s):
            return False
        return True

    def record(self, tenant: str = DEFAULT_TENANT, ok: bool = True,
               ttft_s: float | None = None,
               token_s: float | None = None) -> bool:
        """Record one request outcome; returns its good/bad verdict."""
        good = self.judge(ok, ttft_s, token_s)
        now = self._clock()
        self._events.append((now, clean_tenant(tenant), good, ttft_s),
                            t=now)
        return good

    # -- windows -----------------------------------------------------------

    def _window(self, window_s: float,
                tenant: str | None = None) -> list[tuple]:
        events = self._events.window(window_s)
        if tenant is None:
            return events
        return [e for e in events if e[1] == tenant]

    def attainment(self, window_s: float | None = None,
                   tenant: str | None = None) -> float:
        """Good fraction over the window; 1.0 when empty (no traffic
        spends no budget)."""
        if window_s is None:
            window_s = self.spec.fast_windows[0]
        events = self._window(window_s, tenant)
        if not events:
            return 1.0
        return sum(1 for e in events if e[2]) / len(events)

    def burn_rate(self, window_s: float | None = None,
                  tenant: str | None = None) -> float:
        return ((1.0 - self.attainment(window_s, tenant))
                / self.spec.error_budget)

    def fast_burn_firing(self) -> bool:
        long_w, short_w = self.spec.fast_windows
        return (self.burn_rate(long_w) >= FAST_BURN
                and self.burn_rate(short_w) >= FAST_BURN)

    def slow_burn_firing(self) -> bool:
        long_w, short_w = self.spec.slow_windows
        return (self.burn_rate(long_w) >= SLOW_BURN
                and self.burn_rate(short_w) >= SLOW_BURN)

    def tenants(self) -> list[str]:
        """Distinct tenants inside the long fast window, first-seen
        order, capped to the label budget (+ ``other`` when truncated)."""
        seen: dict[str, None] = {}
        for e in self._window(self.spec.fast_windows[0]):
            seen.setdefault(e[1])
        names = list(seen)
        if len(names) > self.tenant_cap:
            names = names[:self.tenant_cap] + [OVERFLOW_LABEL]
        return names

    def tenant_ttft_p95(self, tenant: str) -> float:
        events = self._window(self.spec.fast_windows[0])
        if tenant == OVERFLOW_LABEL:
            # overflow aggregates every tenant beyond the first cap seats
            kept: dict[str, None] = {}
            for e in events:
                kept.setdefault(e[1])
            inside = set(list(kept)[:self.tenant_cap])
            vals = [e[3] for e in events
                    if e[1] not in inside and e[3] is not None]
        else:
            vals = [e[3] for e in events
                    if e[1] == tenant and e[3] is not None]
        return _p95([float(v) for v in vals])

    # -- exposition --------------------------------------------------------

    def export(self) -> None:
        """Refresh every ``m2kt_slo_*`` gauge (collect hook: runs on
        scrape, outside the registry lock)."""
        if self._registry is None:
            return
        spec = self.spec
        windows = {
            "fast_long": spec.fast_windows[0],
            "fast_short": spec.fast_windows[1],
            "slow_long": spec.slow_windows[0],
            "slow_short": spec.slow_windows[1],
        }
        for label, w in windows.items():
            att = self.attainment(w)
            self._g_attain.labels(label).set(att)
            self._g_burn.labels(label).set(
                (1.0 - att) / spec.error_budget)
        self._g_fast.set(1.0 if self.fast_burn_firing() else 0.0)
        self._g_slow.set(1.0 if self.slow_burn_firing() else 0.0)
        self._g_budget.set(spec.error_budget)
        self._g_ttft_target.set(spec.ttft_p95_s)
        for tenant in self.tenants():
            if tenant == OVERFLOW_LABEL:
                self._g_tenant_ttft.labels(tenant).set(
                    self.tenant_ttft_p95(tenant))
                continue
            events = self._window(spec.fast_windows[0], tenant)
            vals = [e[3] for e in events if e[3] is not None]
            self._g_tenant_ttft.labels(tenant).set(
                _p95([float(v) for v in vals]))
            good = sum(1 for e in events if e[2])
            self._g_tenant_attain.labels(tenant).set(
                good / len(events) if events else 1.0)
