"""Tensor-health plane: in-graph numerics telemetry + non-finite forensics.

The repo could observe everything about a run except its numbers: one
global grad-norm gauge, ``apply_if_finite`` silently swallowing
non-finite updates, and no way to say *where* a NaN was born. This
module computes cheap per-layer-group summaries (rms / max-abs /
non-finite counts) **inside the jitted step** — the reductions fuse into
the compiled program and only ``O(groups)`` scalars ever cross to host —
and turns them into first-class signals:

- :func:`health_recorder` is an optax identity transform (the
  ``grad_norm_recorder`` idiom) that stows grouped gradient and
  parameter stats in the optimizer state; ``StepTelemetry`` reads them
  back at sync points into the ``m2kt_train_tensor_*`` gauges, bounded
  by the registry's ``max_series`` label cap.
- On a NaN/Inf step, :func:`first_bad_group` binary-searches the
  cumulative per-group non-finite counts (tree order == forward module
  order for the zoo's flax models) to name the first bad layer group,
  and :func:`write_sidecar` dumps a ``<flight>.numerics`` JSON the
  supervisor folds into ``m2kt-flight.json`` — post-mortem forensics
  that survive the process.

Grouping is static (derived from the pytree paths at trace time), so
the per-leaf scatter-adds compile to fixed index updates. Like every
obs module this file imports only the stdlib at module scope — it is
vendored into emitted images and must not pull jax before the runtime
configures it.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any, NamedTuple

_OFF = ("0", "false", "off", "no")

# gauge fields exported per layer group, in TensorHealthState order
HEALTH_FIELDS = ("grad_rms", "grad_max_abs", "grad_nonfinite",
                 "param_rms", "param_max_abs", "param_nonfinite")


def enabled(env=None) -> bool:
    """``M2KT_NUMERICS`` gates the tensor-health plane (default on — the
    bench ``numerics`` phase bounds the in-graph cost at <= 3%)."""
    env = os.environ if env is None else env
    return str(env.get("M2KT_NUMERICS", "1")).strip().lower() not in _OFF


def max_groups(env=None) -> int:
    """Label-cardinality cap for the per-group gauges
    (``M2KT_NUMERICS_MAX_GROUPS``); groups beyond it collapse into the
    registry's shared overflow series, same contract as tenant caps."""
    env = os.environ if env is None else env
    try:
        return max(1, int(env.get("M2KT_NUMERICS_MAX_GROUPS", "") or 16))
    except ValueError:
        return 16


def audit_rate(env=None) -> float:
    """Serving quant-drift audit rate (``M2KT_QUANT_AUDIT_RATE``):
    fraction of cold admissions re-run through the fp reference path.
    0 (the default) disables the auditor and keeps no fp weight copy."""
    env = os.environ if env is None else env
    try:
        rate = float(env.get("M2KT_QUANT_AUDIT_RATE", "") or 0.0)
    except ValueError:
        return 0.0
    return min(1.0, max(0.0, rate))


class TensorHealthState(NamedTuple):
    """Opt-state slot the health recorder writes each update: per-group
    vectors (shape ``[num_groups]``) in :func:`group_index` order."""

    grad_rms: Any
    grad_max_abs: Any
    grad_nonfinite: Any
    param_rms: Any
    param_max_abs: Any
    param_nonfinite: Any


def _key_name(entry) -> str:
    for attr in ("key", "name", "idx"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry)


def group_index(tree) -> tuple[list[str], list[int]]:
    """Static grouping of a pytree's leaves by top-level module path
    component (``blocks_0``, ``embed``, ...), skipping flax collection
    wrappers (``params``). Returns ``(ordered group names, per-leaf
    group index)`` in tree-flatten order — the model's forward order for
    the zoo's flax param dicts."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    names: list[str] = []
    index: dict[str, int] = {}
    leaf_groups: list[int] = []
    for path, _leaf in flat:
        parts = [_key_name(p) for p in path]
        while len(parts) > 1 and parts[0] in ("params", "batch_stats"):
            parts = parts[1:]
        group = parts[0] if parts else "root"
        if group not in index:
            index[group] = len(names)
            names.append(group)
        leaf_groups.append(index[group])
    return names, leaf_groups


def summarize_tree(tree, leaf_groups=None, num_groups=None):
    """In-graph per-group ``(rms, max_abs, nonfinite)`` of a pytree's
    inexact leaves — pure jnp reductions, safe inside jit. ``rms`` is
    computed over the *finite* entries (a single Inf must not erase the
    magnitude signal); ``max_abs`` maps any non-finite entry to +Inf, so
    an overflow OR a NaN is visible in the gauge (a raw NaN would
    otherwise poison the max into NaN, which Prometheus renders as a
    gap). Integer leaves are skipped."""
    import jax
    import jax.numpy as jnp

    if leaf_groups is None or num_groups is None:
        names, leaf_groups = group_index(tree)
        num_groups = len(names)
    n = max(1, int(num_groups))
    # one concatenated vector per group, then one fused stats pass over
    # it: per-LEAF reductions with scatter-adds compiled to ~20 tiny CPU
    # kernels per leaf and measured at +60% step time on the bench host
    # (launch overhead, not FLOPs); per-GROUP passes keep the whole
    # plane inside the <= 3% budget
    buckets: list[list] = [[] for _ in range(n)]
    for g, leaf in zip(leaf_groups, jax.tree_util.tree_leaves(tree)):
        if not hasattr(leaf, "dtype") or not jnp.issubdtype(
                jnp.asarray(leaf).dtype, jnp.inexact):
            continue
        flat = jnp.ravel(jnp.asarray(leaf))
        if flat.size:
            buckets[g].append(flat.astype(jnp.float32))
    rms, max_abs, nonfinite = [], [], []
    zero_f = jnp.zeros((), jnp.float32)
    zero_i = jnp.zeros((), jnp.int32)
    for vecs in buckets:
        if not vecs:
            rms.append(zero_f)
            max_abs.append(zero_f)
            nonfinite.append(zero_i)
            continue
        x = jnp.concatenate(vecs) if len(vecs) > 1 else vecs[0]
        finite = jnp.isfinite(x)
        safe = jnp.where(finite, x, 0.0)
        rms.append(jnp.sqrt(jnp.sum(safe * safe) / x.size))
        max_abs.append(jnp.max(jnp.where(finite, jnp.abs(x), jnp.inf)))
        nonfinite.append(jnp.sum(~finite).astype(jnp.int32))
    return jnp.stack(rms), jnp.stack(max_abs), jnp.stack(nonfinite)


def health_recorder(record: bool | None = None):
    """Identity optax transform recording grouped tensor health of the
    updates (gradients) and parameters into a :class:`TensorHealthState`
    slot. Chained UNCONDITIONALLY by ``instrument_optimizer`` — the
    state shape is identical whether recording is on or off (``record``
    defaults to the ``M2KT_NUMERICS`` env), so toggling telemetry never
    changes the opt-state pytree and checkpoints stay restorable.

    Sits OUTSIDE ``apply_if_finite``: a skipped non-finite update still
    flows through this transform, so the forensics see exactly the
    gradients that poisoned the step."""
    import jax.numpy as jnp
    import optax

    on = enabled() if record is None else bool(record)

    def _zeros(params):
        names, _ = group_index(params)
        n = max(1, len(names))
        # distinct buffers per field: a shared zeros array would be
        # donated twice by the compiled train step (same buffer at two
        # flattened argument positions -> XLA INVALID_ARGUMENT)
        return TensorHealthState(*(
            jnp.zeros((n,), dt) for dt in (
                jnp.float32, jnp.float32, jnp.int32,
                jnp.float32, jnp.float32, jnp.int32)))

    def init(params):
        return _zeros(params)

    def update(updates, state, params=None):
        if not on:
            return updates, state
        names, leaf_groups = group_index(updates)
        n = max(1, len(names))
        g_rms, g_max, g_nf = summarize_tree(updates, leaf_groups, n)
        if params is not None:
            p_rms, p_max, p_nf = summarize_tree(params, leaf_groups, n)
        else:
            p_rms, p_max, p_nf = (state.param_rms, state.param_max_abs,
                                  state.param_nonfinite)
        return updates, TensorHealthState(g_rms, g_max, g_nf,
                                          p_rms, p_max, p_nf)

    return optax.GradientTransformation(init, update)


def health_from_state(state) -> TensorHealthState | None:
    """Latest :class:`TensorHealthState` recorded by
    :func:`health_recorder`, walking the (arbitrarily nested) optimizer
    state; None when the optimizer wasn't instrumented."""

    def find(node):
        if isinstance(node, TensorHealthState):
            return node
        if isinstance(node, (tuple, list)):
            for item in node:
                hit = find(item)
                if hit is not None:
                    return hit
        inner = getattr(node, "inner_state", None)
        if inner is not None:
            return find(inner)
        return None

    return find(getattr(state, "opt_state", state))


def summary(names: list[str], state: TensorHealthState) -> dict:
    """Host-side ``{group: {field: float}}`` view of a health state —
    the ONLY device->host transfer of the plane: six ``[num_groups]``
    vectors."""
    import numpy as np

    cols = [np.asarray(v) for v in state]
    out: dict[str, dict[str, float]] = {}
    for i, name in enumerate(names):
        if i >= len(cols[0]):
            break
        out[name] = {field: float(col[i])
                     for field, col in zip(HEALTH_FIELDS, cols)}
    return out


def first_bad_group(summary_doc: dict) -> str | None:
    """First layer group (forward order) with a non-finite gradient or
    parameter entry — a binary search over the cumulative per-group
    non-finite counts — or None when the step is clean."""
    import numpy as np

    names = list(summary_doc)
    counts = np.asarray(
        [summary_doc[n]["grad_nonfinite"] + summary_doc[n]["param_nonfinite"]
         for n in names], np.float64)
    if counts.size == 0 or not counts.sum():
        return None
    cum = np.cumsum(counts)
    return names[int(np.searchsorted(cum, 1.0))]


def sidecar_path() -> str:
    """``<flight>.numerics`` — rides next to the crash flight recorder
    so the supervisor can fold it into ``m2kt-flight.json``."""
    from move2kube_tpu.obs import tracing

    return tracing.flight_path() + ".numerics"


def write_sidecar(doc: dict, path: str | None = None) -> str | None:
    """Atomically dump the forensics document. Best-effort: telemetry
    must never kill a training run over a full disk."""
    path = path or sidecar_path()
    try:
        fd, tmp = tempfile.mkstemp(
            dir=os.path.dirname(path) or ".", suffix=".numerics.tmp")
        with os.fdopen(fd, "w") as fh:
            json.dump(doc, fh, indent=2, default=str)
        os.replace(tmp, path)
        return path
    except OSError:
        return None


def read_sidecar(path: str | None = None) -> dict | None:
    path = path or sidecar_path()
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None
