"""Usage ledger: a bounded time-series ring of per-pod usage snapshots.

The telemetry plane so far is scrape-or-lose: /metrics shows the
counters *now*, and a missed scrape window is history nobody can bill.
This module is the durable half — every fleet role keeps a small ring
of periodic snapshots (per-tenant admitted/unused tokens and request
counts, TTFT / token-latency / length histogram snapshots, slot
occupancy, preempt/throttle counters, ``weights_version``), serves it
at ``GET /usage``, and flushes it to ``m2kt-usage.jsonl`` on exit via
the same ``threading._register_atexit`` flight-recorder path as the
span ring — so a pod that dies between scrapes still leaves its usage
trail on disk for the aggregator.

The consumer is ``serving/fleet/capture.py``: it joins these snapshots
with the ``obs/costmodel`` chip specs into per-tenant TPU-seconds and
$-proxy cost per token (chargeback), and re-bins the per-tenant token
deltas into the versioned capture schema the fleet simulator replays.

Data sources are duck-typed zero-arg callables (``add_source``) so the
ledger stays stdlib-only and engine-agnostic: :func:`engine_source` /
:func:`router_source` build the standard adapters with tolerant
``getattr`` reads — a source raising or a field missing degrades that
snapshot, never the workload.

Determinism: ``clock`` is injectable and :meth:`UsageLedger.snapshot`
takes an explicit ``t``, so tests drive a synthetic timeline and get
bit-identical rings. Stdlib-only: vendored into emitted images with
the rest of ``obs/``.
"""

from __future__ import annotations

import json
import math
import os
import socket
import threading
import time
from collections import deque

from move2kube_tpu.obs import tracing
from move2kube_tpu.obs.metrics import HistogramSnapshot, Registry

USAGE_ENV = "M2KT_USAGE"
USAGE_INTERVAL_ENV = "M2KT_USAGE_INTERVAL_S"
USAGE_RING_ENV = "M2KT_USAGE_RING"
USAGE_PATH_ENV = "M2KT_USAGE_PATH"

SCHEMA = "m2kt-usage/v1"

DEFAULT_INTERVAL_S = 10.0
# 360 snapshots at the 10s default = one hour of history per pod,
# ~O(100KB) — bounded no matter how long the pod lives
DEFAULT_RING = 360


def enabled() -> bool:
    """Ledger defaults ON (same rationale as tracing: a periodic dict
    merge is gated ≤1% by the bench usage phase, and an off-by-default
    ledger bills no one)."""
    return os.environ.get(USAGE_ENV, "1").lower() not in ("0", "false", "off")


def usage_interval() -> float:
    raw = os.environ.get(USAGE_INTERVAL_ENV, "")
    try:
        val = float(raw) if raw.strip() else DEFAULT_INTERVAL_S
    except (TypeError, ValueError):
        return DEFAULT_INTERVAL_S
    return val if val > 0 else DEFAULT_INTERVAL_S


def usage_ring() -> int:
    raw = os.environ.get(USAGE_RING_ENV, "")
    try:
        val = int(raw) if raw.strip() else DEFAULT_RING
    except (TypeError, ValueError):
        return DEFAULT_RING
    return val if val > 0 else DEFAULT_RING


def usage_path() -> str:
    """Where the exit flush lands — next to the flight recorder's
    artifacts, derived from the same env so the aggregator and the
    dying pod agree without a handshake."""
    p = os.environ.get(USAGE_PATH_ENV, "")
    if p:
        return p
    return os.path.join(os.environ.get("M2KT_METRICS_DIR", "") or ".",
                        "m2kt-usage.jsonl")


# ---------------------------------------------------------------------------
# histogram (de)serialization — +Inf has no JSON literal
# ---------------------------------------------------------------------------


def hist_doc(snap: HistogramSnapshot) -> dict:
    """One histogram snapshot as a JSON-safe dict (the +Inf edge is
    serialized as null)."""
    return {
        "buckets": [None if b == math.inf else float(b)
                    for b in snap.buckets],
        "counts": [int(c) for c in snap.bucket_counts],
        "sum": float(snap.sum),
        "count": int(snap.count),
    }


def hist_from_doc(doc: dict) -> HistogramSnapshot:
    """The inverse of :func:`hist_doc` — a real
    :class:`HistogramSnapshot`, so replay code can ``.sample()`` /
    ``.quantile()`` a recorded distribution directly."""
    buckets = tuple(math.inf if b is None else float(b)
                    for b in doc.get("buckets", ()))
    counts = tuple(int(c) for c in doc.get("counts", ()))
    return HistogramSnapshot(buckets, counts,
                             float(doc.get("sum", 0.0)),
                             int(doc.get("count", 0)))


# ---------------------------------------------------------------------------
# the ledger
# ---------------------------------------------------------------------------


class UsageLedger:
    """Bounded snapshot ring + periodic ticker + exit flush.

    Thread-safe: the ticker thread (or the engine's step loop) appends
    while the telemetry thread serves ``doc()`` and the atexit hook
    flushes. Snapshot content comes from registered sources — each a
    zero-arg callable returning a partial dict; ``tenants`` and
    ``counters`` keys deep-merge so one snapshot can combine an engine
    source and a router source."""

    def __init__(self, clock=time.monotonic,
                 interval_s: float | None = None,
                 max_snapshots: int | None = None,
                 registry: Registry | None = None,
                 role: str | None = None, host: str | None = None) -> None:
        self._clock = clock
        self.interval_s = float(interval_s) if interval_s else (
            usage_interval())
        self.max_snapshots = int(max_snapshots) if max_snapshots else (
            usage_ring())
        self.role = (role or tracing.fleet_role()).strip().lower()
        self.host = host or socket.gethostname()
        self._lock = threading.Lock()
        self._ring: deque[dict] = deque(maxlen=max(1, self.max_snapshots))
        self._sources: list[tuple[str, object]] = []
        self._seq = 0
        self._last_t: float | None = None
        # wall-clock anchor: snapshots carry both clocks so synthetic
        # monotonic timelines still export sensible unix stamps
        self._t0_mono = self._clock()
        self._t0_unix = time.time()
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._c_snapshots = None
        if registry is not None:
            self._c_snapshots = registry.counter(
                "m2kt_usage_snapshots_total",
                "Usage-ledger snapshots taken by this pod")

    # -- sources -----------------------------------------------------------

    def add_source(self, fn, name: str = "") -> "UsageLedger":
        """Register one snapshot source (zero-arg callable returning a
        partial snapshot dict). Returns self for chaining."""
        self._sources.append((name or getattr(fn, "__name__", "source"),
                              fn))
        return self

    # -- recording ---------------------------------------------------------

    def _unix(self, t_mono: float) -> float:
        return self._t0_unix + (t_mono - self._t0_mono)

    def snapshot(self, t: float | None = None) -> dict:
        """Take one snapshot unconditionally: merge every source into
        the base record and append it to the ring. A raising source is
        skipped (noted under ``errors``) — billing must degrade, never
        take the workload down."""
        now = self._clock() if t is None else float(t)
        with self._lock:
            self._seq += 1
            seq = self._seq
        snap: dict = {
            "seq": seq,
            "t_mono": now,
            "t_unix": round(self._unix(now), 6),
            "role": self.role,
            "host": self.host,
            "pid": os.getpid(),
            "tenants": {},
            "counters": {},
        }
        errors = []
        for name, fn in list(self._sources):
            try:
                part = fn() or {}
            except Exception as e:  # noqa: BLE001 - degrade, don't die
                errors.append(f"{name}: {e}")
                continue
            for key, value in part.items():
                if key == "tenants" and isinstance(value, dict):
                    for tenant, fields in value.items():
                        snap["tenants"].setdefault(
                            str(tenant), {}).update(fields)
                elif key == "counters" and isinstance(value, dict):
                    snap["counters"].update(value)
                else:
                    snap[key] = value
        if errors:
            snap["errors"] = errors
        with self._lock:
            self._ring.append(snap)
            self._last_t = now
        if self._c_snapshots is not None:
            self._c_snapshots.inc()
        return snap

    def maybe_snapshot(self, t: float | None = None) -> dict | None:
        """Snapshot iff at least ``interval_s`` has passed since the
        last one — the idempotent tick the serve loop (or the ticker
        thread) calls as often as it likes."""
        now = self._clock() if t is None else float(t)
        with self._lock:
            due = (self._last_t is None
                   or now - self._last_t >= self.interval_s)
        return self.snapshot(t=now) if due else None

    # -- queries -----------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def snapshots(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    def window(self, window_s: float,
               now: float | None = None) -> list[dict]:
        """Snapshots whose monotonic stamp falls inside the trailing
        window — the slice a diagnostic bundle freezes."""
        if now is None:
            now = self._clock()
        floor = now - float(window_s)
        with self._lock:
            return [s for s in self._ring if s["t_mono"] >= floor]

    def doc(self, window_s: float | None = None) -> dict:
        """The ring as one self-describing JSON document — what
        ``GET /usage`` serves and what the aggregator scrapes."""
        snaps = (self.window(window_s) if window_s is not None
                 else self.snapshots())
        return {
            "schema": SCHEMA,
            "host": self.host,
            "role": self.role,
            "pid": os.getpid(),
            "written_unix": time.time(),
            "interval_s": self.interval_s,
            "max_snapshots": self.max_snapshots,
            "snapshots": snaps,
        }

    # -- flush -------------------------------------------------------------

    def flush(self, path: str | None = None) -> str | None:
        """Atomic JSONL dump: one header line (the doc sans snapshots)
        then one line per snapshot — greppable, streamable, and the
        whole file still lands or doesn't (tmp + rename). Best-effort:
        this runs on dying-process paths."""
        path = path or usage_path()
        doc = self.doc()
        snaps = doc.pop("snapshots")
        try:
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            tmp = path + f".tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(json.dumps(doc, separators=(",", ":")) + "\n")
                for snap in snaps:
                    f.write(json.dumps(snap, separators=(",", ":")) + "\n")
            os.replace(tmp, path)
            return path
        except OSError:
            return None

    # -- ticker ------------------------------------------------------------

    def start(self) -> "UsageLedger":
        """Spawn the daemon ticker (one snapshot per interval). Safe to
        call once; tests drive :meth:`snapshot` directly instead."""
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._run, name="m2kt-usage-ledger", daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.snapshot()
            except Exception:  # noqa: BLE001 - ticker must never die noisy
                pass

    def close(self) -> None:
        self._stop.set()


def load_jsonl(path: str) -> dict:
    """Read one ``m2kt-usage.jsonl`` flush back into the ``doc()``
    shape (header + snapshots). Tolerates a missing header (plain
    snapshot lines) and skips unparsable lines."""
    header: dict = {}
    snaps: list[dict] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if not isinstance(rec, dict):
                continue
            if rec.get("schema") == SCHEMA and "snapshots" not in rec:
                header = rec
            else:
                snaps.append(rec)
    doc = dict(header) if header else {"schema": SCHEMA}
    doc["snapshots"] = snaps
    return doc


# ---------------------------------------------------------------------------
# standard sources
# ---------------------------------------------------------------------------


def _samples_by_tenant(family) -> dict[str, float]:
    """{tenant: value} off a single-label family's samples()."""
    out: dict[str, float] = {}
    if family is None:
        return out
    try:
        for values, value in family.samples():
            if values:
                out[values[0]] = out.get(values[0], 0.0) + value
    except Exception:  # noqa: BLE001 - source reads are best-effort
        pass
    return out


def _hists_by_tenant(family) -> dict[str, dict]:
    out: dict[str, dict] = {}
    if family is None:
        return out
    try:
        for values, snap in family.snapshots().items():
            if values:
                out[values[0]] = hist_doc(snap)
    except Exception:  # noqa: BLE001 - source reads are best-effort
        pass
    return out


def engine_source(engine):
    """Snapshot adapter over a ServingEngine: occupancy gauges,
    ``weights_version``, scheduler counters, and the per-tenant request
    counts, attainment, and latency/length histogram snapshots. Every
    read is ``getattr``-tolerant so an engine predating a field (or a
    non-engine stand-in in tests) degrades instead of raising."""

    def read() -> dict:
        gauge_snap = dict(getattr(engine, "_gauge_snapshot", {}) or {})
        out: dict = {
            "weights_version": int(getattr(engine, "weights_version", 0)),
            "slot_occupancy": float(gauge_snap.get("slot_occupancy", 0.0)),
            "queue_depth": float(gauge_snap.get("queue_depth", 0.0)),
            "active_slots": float(gauge_snap.get("active_slots", 0.0)),
            "counters": {},
            "tenants": {},
        }
        for attr, key in (("_sched_preempted", "preempted"),
                          ("_sched_chunked", "chunked"),
                          ("_sched_throttled", "throttled"),
                          ("_admitted", "admitted"),
                          ("_rejected", "rejected"),
                          ("_decode_tokens", "decode_tokens")):
            fam = getattr(engine, attr, None)
            if fam is not None:
                try:
                    out["counters"][key] = fam.total()
                except Exception:  # noqa: BLE001
                    pass
        tenants = out["tenants"]
        for attr, field in (("_tenant_admitted", "requests"),
                            ("_tenant_rejected", "rejected")):
            for tenant, value in _samples_by_tenant(
                    getattr(engine, attr, None)).items():
                tenants.setdefault(tenant, {})[field] = value
        for attr, field in (("_tenant_ttft", "ttft"),
                            ("_tenant_lat", "token_latency"),
                            ("_tenant_prompt_tokens", "prompt_tokens"),
                            ("_tenant_decode_tokens", "decode_tokens")):
            for tenant, doc in _hists_by_tenant(
                    getattr(engine, attr, None)).items():
                tenants.setdefault(tenant, {})[field] = doc
        slo = getattr(engine, "slo", None)
        if slo is not None:
            try:
                for tenant in slo.tenants():
                    tenants.setdefault(tenant, {})["attainment"] = (
                        slo.attainment(tenant=tenant))
            except Exception:  # noqa: BLE001
                pass
        return out

    return read


def router_source(router):
    """Snapshot adapter over a fleet Router: the per-tenant net token
    demand (admitted minus unused corrections) that chargeback
    allocates TPU-seconds by."""

    def read() -> dict:
        admitted = _samples_by_tenant(
            getattr(router, "_admitted_tokens", None))
        unused = _samples_by_tenant(
            getattr(router, "_admitted_unused", None))
        tenants: dict[str, dict] = {}
        for tenant, value in admitted.items():
            tenants.setdefault(tenant, {})["admitted_tokens"] = value
        for tenant, value in unused.items():
            tenants.setdefault(tenant, {})["unused_tokens"] = value
        out: dict = {"tenants": tenants, "counters": {}}
        try:
            out["counters"]["admitted_tokens_net"] = float(
                router.admitted_tokens())
        except Exception:  # noqa: BLE001
            pass
        return out

    return read


# ---------------------------------------------------------------------------
# exit flush (the flight-recorder path)
# ---------------------------------------------------------------------------

_flush_installed = False


def install_usage_flush(ledger: UsageLedger,
                        path: str | None = None) -> None:
    """Flush the ledger on every teardown-running exit path — the same
    ``threading._register_atexit`` trick as ``tracing.install_ring_flush``
    (plain atexit runs after thread joins, too late for a dying serve
    loop), so a pod killed between scrapes still leaves
    ``m2kt-usage.jsonl`` for the aggregator. A final snapshot is taken
    first so the file includes the counters at death."""
    global _flush_installed
    if _flush_installed or not enabled():
        return
    _flush_installed = True

    def _flush() -> None:
        try:
            ledger.snapshot()
            ledger.flush(path)
        except Exception:  # noqa: BLE001 - dying process, best effort
            pass

    register = getattr(threading, "_register_atexit", None)
    if register is None:
        import atexit

        atexit.register(_flush)
    else:
        register(_flush)
