"""Runtime telemetry plane for emitted TPU workloads.

Net-new vs the reference (SURVEY §5 "tracing/profiling: absent") and
complementary to ``utils/trace.py``, which only covers the *offline*
translate pipeline: once a translated workload lands on a slice, this
package is what makes it observable — a dependency-free Prometheus
registry (:mod:`metrics`), a stdlib HTTP server exposing ``/metrics`` /
``/healthz`` / on-demand ``/profile`` XLA captures (:mod:`server`), and
bridges folding translate-trace spans and goodput reports into the same
registry (:mod:`bridge`).

PR 7 adds the distributed-runtime tracing plane: a bounded span ring
with Chrome-trace / OTLP-lines export (:mod:`tracing`), MegaScale-style
straggler scoring (:class:`bridge.StragglerDetector`), and the alert/
dashboard manifest builders the emitters attach to workloads
(:mod:`rules`).

PR 8 adds the compiled-program cost model (:mod:`costmodel`): MFU and
roofline accounting from ``cost_analysis``/``memory_analysis``, the
preflight plan report (``m2kt-plan-report.{json,md}``), and the OOM
memory-snapshot sidecar the flight recorder folds in.

PR 12 extends the plane across the fleet: W3C traceparent propagation
and role tagging (:mod:`tracing`), the ``/traces`` drain endpoint
(:mod:`server`), the cross-role trace collector with exact hop-gap
stitching (:mod:`fleetview`), and the per-tenant SLO/burn-rate ledger
(:mod:`slo`).

PR 15 adds the numerics plane (:mod:`numerics`): in-graph per-layer-
group tensor-health summaries recorded into the optimizer state,
non-finite forensics with a first-bad-layer sidecar the flight recorder
folds in, and the serving quant-drift audit knobs.

PR 20 adds the usage plane: the per-pod usage ledger (:mod:`ledger`) —
bounded snapshot rings of per-tenant tokens/latency/occupancy served at
``GET /usage`` and exit-flushed to ``m2kt-usage.jsonl`` — and the
anomaly watchdog (:class:`bridge.DiagWatchdog`) that freezes a one-shot
diagnostic bundle (profiler trace + span ring + ledger window) on SLO
fast-burn, step-time regression, or non-finite steps. The fleet-side
consumers (chargeback, capture→replay) live in
``serving/fleet/capture.py``.

Stdlib-only on import (jax is loaded lazily, only for profiling and
device-memory reads) so the whole package vendors into emitted images.
"""

from move2kube_tpu.obs.bridge import (
    DiagWatchdog,
    StragglerDetector,
    diag_dir,
    diag_enabled,
    install_goodput_hook,
    install_trace_hook,
    mirror_goodput,
    mirror_trace,
)
from move2kube_tpu.obs.costmodel import (
    CHIP_SPECS,
    ChipSpec,
    CostReport,
    analyze_compiled,
    analyze_step_fn,
    build_plan_report,
    chip_spec,
    export_drift_gauge,
    export_serving_gauges,
    export_train_gauges,
    install_memory_snapshot,
    normalize_accelerator,
    write_memory_snapshot,
    write_plan_report,
)
from move2kube_tpu.obs.fleetview import FleetTraceCollector
from move2kube_tpu.obs.metrics import (
    OVERFLOW_LABEL,
    Counter,
    Gauge,
    Histogram,
    Registry,
    default_registry,
)
from move2kube_tpu.obs.numerics import (
    HEALTH_FIELDS,
    TensorHealthState,
    first_bad_group,
    group_index,
    health_from_state,
    health_recorder,
    read_sidecar,
    sidecar_path,
    summarize_tree,
    write_sidecar,
)
from move2kube_tpu.obs.numerics import audit_rate as quant_audit_rate
from move2kube_tpu.obs.numerics import enabled as numerics_enabled
from move2kube_tpu.obs.numerics import summary as numerics_summary
from move2kube_tpu.obs.ledger import (
    UsageLedger,
    engine_source,
    install_usage_flush,
    router_source,
    usage_path,
)
from move2kube_tpu.obs.ledger import enabled as usage_enabled
from move2kube_tpu.obs.slo import (
    SLOSpec,
    SLOTracker,
    TENANT_HEADER,
    clean_tenant,
    max_tenants,
)
from move2kube_tpu.obs.server import (
    DEFAULT_METRICS_PORT,
    METRICS_PORT_ENV,
    PROFILE_DIR_ENV,
    TelemetryServer,
    metrics_port_from_env,
    start_telemetry_server,
)
from move2kube_tpu.obs.tracing import (
    Span,
    SpanRecorder,
    TRACEPARENT_HEADER,
    fleet_role,
    install_ring_flush,
    parse_traceparent,
)
from move2kube_tpu.obs.tracing import enabled as tracing_enabled
from move2kube_tpu.obs.tracing import get as get_tracer

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "default_registry",
    "TelemetryServer",
    "start_telemetry_server",
    "metrics_port_from_env",
    "DEFAULT_METRICS_PORT",
    "METRICS_PORT_ENV",
    "PROFILE_DIR_ENV",
    "mirror_trace",
    "mirror_goodput",
    "install_trace_hook",
    "install_goodput_hook",
    "StragglerDetector",
    "DiagWatchdog",
    "diag_dir",
    "diag_enabled",
    "UsageLedger",
    "engine_source",
    "router_source",
    "install_usage_flush",
    "usage_enabled",
    "usage_path",
    "Span",
    "SpanRecorder",
    "get_tracer",
    "tracing_enabled",
    "install_ring_flush",
    "parse_traceparent",
    "fleet_role",
    "TRACEPARENT_HEADER",
    "FleetTraceCollector",
    "SLOSpec",
    "SLOTracker",
    "TENANT_HEADER",
    "clean_tenant",
    "max_tenants",
    "OVERFLOW_LABEL",
    "CHIP_SPECS",
    "ChipSpec",
    "CostReport",
    "analyze_compiled",
    "analyze_step_fn",
    "build_plan_report",
    "chip_spec",
    "export_drift_gauge",
    "export_serving_gauges",
    "export_train_gauges",
    "install_memory_snapshot",
    "normalize_accelerator",
    "write_memory_snapshot",
    "write_plan_report",
    "HEALTH_FIELDS",
    "TensorHealthState",
    "first_bad_group",
    "group_index",
    "health_from_state",
    "health_recorder",
    "numerics_enabled",
    "numerics_summary",
    "quant_audit_rate",
    "read_sidecar",
    "sidecar_path",
    "summarize_tree",
    "write_sidecar",
]
