"""Stdlib-HTTP telemetry server: /metrics, /healthz, /readyz, /profile,
/traces.

One daemon thread per process (ThreadingHTTPServer: a slow profiler
capture must not block a concurrent scrape). ``/profile`` drives
``jax.profiler`` trace capture into ``M2KT_PROFILE_DIR`` on demand —
the operator curls the pod, waits N seconds, and pulls the trace from
the volume, no workload restart. jax is imported lazily so the server
(and the whole obs package) stays importable in slim images.

Liveness vs readiness are distinct probes: ``/healthz`` answers 200
whenever the process (and this thread) is alive — restarting a pod
because its model is still compiling would be self-inflicted crashloop —
while ``/readyz`` reports the workload's actual state (``starting`` /
``serving`` / ``draining``) via a caller-supplied provider and returns
503 until it says ``serving``, so a serving pod takes no traffic before
warm-up and is drained from endpoints before shutdown.

``/traces`` serves the process's span ring as the same JSON document the
flight recorder dumps (host/role/slice + spans with unix-anchored
endpoints) — the fleet trace collector polls it on every role to stitch
one cross-process timeline. ``/traces?clear=1`` drains: snapshot, then
reset the ring, so repeated collector pulls do not double-count.
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from move2kube_tpu.obs.metrics import Registry, default_registry

METRICS_PORT_ENV = "M2KT_METRICS_PORT"
PROFILE_DIR_ENV = "M2KT_PROFILE_DIR"
DEFAULT_METRICS_PORT = 9090
DEFAULT_PROFILE_DIR = "/tmp/m2kt-profile"
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
MAX_PROFILE_SECONDS = 120.0

# default /traces response bound: the ring window at a generous span
# rate. A decode engine emits a handful of spans per request, so 64/s
# covers a busy replica; an operator chasing more passes ?limit=N.
TRACE_SPANS_PER_SECOND = 64


def default_trace_limit() -> int:
    """Span cap for an unqualified ``/traces`` pull, derived from the
    ring window (``M2KT_TRACE_RING_SECONDS``) so the default response
    stays proportional to what the ring can hold."""
    from move2kube_tpu.obs import tracing

    return max(1, int(tracing.ring_seconds() * TRACE_SPANS_PER_SECOND))


def metrics_port_from_env(default: int = 0) -> int:
    """Resolve the telemetry port: env wins, else the baked-in default;
    0 (or garbage) means disabled."""
    raw = os.environ.get(METRICS_PORT_ENV, "")
    try:
        return int(raw) if raw.strip() else int(default)
    except (TypeError, ValueError):
        return 0


class TelemetryServer:
    """Owns the HTTP listener + its serve thread. ``port=0`` binds an
    OS-assigned port (tests); ``.port`` is the bound port either way."""

    def __init__(self, port: int = 0, registry: Registry | None = None,
                 profile_dir: str | None = None,
                 readiness=None, tracer=None, ledger=None) -> None:
        self.registry = registry if registry is not None else default_registry()
        # span recorder served by /traces; None falls back to the
        # process-wide recorder iff tracing is enabled
        self._tracer = tracer
        # usage ledger served by /usage (set_ledger post-construction:
        # the serve template builds the server before the engine exists)
        self._ledger = ledger
        self.profile_dir = (profile_dir
                            or os.environ.get(PROFILE_DIR_ENV, "")
                            or DEFAULT_PROFILE_DIR)
        # readiness provider: a zero-arg callable returning "starting" /
        # "serving" / "draining". None keeps /readyz always-ready for
        # back-compat (trainers have no warm-up gate to report).
        self._readiness = readiness
        self._profile_lock = threading.Lock()
        server = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 - http.server API
                server._route(self)

            def log_message(self, fmt, *args) -> None:
                pass  # scrapes every 15s would spam stderr

        self._httpd = ThreadingHTTPServer(("0.0.0.0", int(port)), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="m2kt-telemetry",
            daemon=True)

    def start(self) -> "TelemetryServer":
        self._thread.start()
        return self

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    # -- routing ----------------------------------------------------------

    def _route(self, req: BaseHTTPRequestHandler) -> None:
        parsed = urlparse(req.path)
        if parsed.path == "/metrics":
            self._send(req, 200, self.registry.render(), CONTENT_TYPE)
        elif parsed.path == "/healthz":
            # liveness only: reachable == alive; workload state belongs
            # to /readyz (a compiling model must not be restart-killed)
            self._send(req, 200, "ok\n")
        elif parsed.path == "/readyz":
            self._handle_readyz(req)
        elif parsed.path == "/profile":
            self._handle_profile(req, parse_qs(parsed.query))
        elif parsed.path == "/traces":
            self._handle_traces(req, parse_qs(parsed.query))
        elif parsed.path == "/usage":
            self._handle_usage(req, parse_qs(parsed.query))
        else:
            self._send(req, 404, "not found\n")

    def set_readiness(self, readiness) -> None:
        """Install/replace the readiness provider after construction (the
        serve template builds the server before the engine exists)."""
        self._readiness = readiness

    def set_tracer(self, tracer) -> None:
        """Install/replace the span recorder served by ``/traces`` (same
        post-construction shape as ``set_readiness``)."""
        self._tracer = tracer

    def set_ledger(self, ledger) -> None:
        """Install/replace the usage ledger served by ``/usage``."""
        self._ledger = ledger

    def _handle_traces(self, req, query: dict) -> None:
        from move2kube_tpu.obs import tracing

        tracer = self._tracer
        if tracer is None and tracing.enabled():
            tracer = tracing.get()
        if tracer is None:
            self._send(req, 404, "tracing disabled\n")
            return
        try:
            limit = int(query.get("limit", [""])[0] or default_trace_limit())
        except (TypeError, ValueError):
            self._send(req, 400, "limit must be an integer\n")
            return
        doc = tracer.ring_doc(limit=max(0, limit))
        if query.get("clear", ["0"])[0] not in ("0", "", "false"):
            tracer.clear()
        self._send(req, 200, json.dumps(doc) + "\n", "application/json")

    def _handle_usage(self, req, query: dict) -> None:
        ledger = self._ledger
        if ledger is None:
            self._send(req, 404, "usage ledger disabled\n")
            return
        try:
            window = float(query.get("window", ["0"])[0] or 0)
        except (TypeError, ValueError):
            self._send(req, 400, "window must be a number\n")
            return
        try:
            doc = ledger.doc(window_s=window if window > 0 else None)
        except Exception as e:  # noqa: BLE001 - probe must not 500
            self._send(req, 422, f"usage ledger errored: {e}\n")
            return
        self._send(req, 200, json.dumps(doc) + "\n", "application/json")

    def _handle_readyz(self, req) -> None:
        state = "serving"
        if self._readiness is not None:
            try:
                state = str(self._readiness())
            except Exception as e:  # noqa: BLE001 - probe must not 500
                self._send(req, 503, f"readiness probe errored: {e}\n")
                return
        self._send(req, 200 if state == "serving" else 503, state + "\n")

    def _handle_profile(self, req, query: dict) -> None:
        # every failure here is fail-open and non-5xx: a bad or unlucky
        # /profile request must degrade to a handled client-error reply,
        # never to a 5xx that trips alerting on the workload itself
        try:
            seconds = float(query.get("seconds", ["1"])[0])
        except (TypeError, ValueError):
            self._send(req, 400, "seconds must be a number\n")
            return
        if not 0 < seconds <= MAX_PROFILE_SECONDS:
            self._send(req, 400,
                       f"seconds must be in (0, {MAX_PROFILE_SECONDS:g}]\n")
            return
        try:
            os.makedirs(self.profile_dir, exist_ok=True)
            writable = os.access(self.profile_dir, os.W_OK)
        except OSError:
            writable = False
        if not writable:
            self._send(req, 403,
                       f"profile dir {self.profile_dir} is not writable\n")
            return
        if not self._profile_lock.acquire(blocking=False):
            self._send(req, 409, "a profile capture is already running\n")
            return
        try:
            result = self._capture(seconds)
        except Exception as e:  # noqa: BLE001 - surface, don't kill the server
            self._send(req, 422, f"profiler unavailable: {e}\n")
            return
        finally:
            self._profile_lock.release()
        self._send(req, 200, json.dumps(result, sort_keys=True) + "\n",
                   "application/json")

    def _capture(self, seconds: float) -> dict:
        import jax  # lazy: /metrics must work even where jax is absent

        os.makedirs(self.profile_dir, exist_ok=True)
        jax.profiler.start_trace(self.profile_dir)
        try:
            time.sleep(seconds)
        finally:
            jax.profiler.stop_trace()
        return {"profile_dir": self.profile_dir, "seconds": seconds}

    @staticmethod
    def _send(req, code: int, body: str,
              content_type: str = "text/plain; charset=utf-8") -> None:
        payload = body.encode("utf-8")
        req.send_response(code)
        req.send_header("Content-Type", content_type)
        req.send_header("Content-Length", str(len(payload)))
        req.end_headers()
        req.wfile.write(payload)


def start_telemetry_server(port: int | None = None,
                           registry: Registry | None = None,
                           profile_dir: str | None = None,
                           readiness=None,
                           tracer=None, ledger=None) -> TelemetryServer | None:
    """Start the telemetry server. ``port=None`` resolves from
    ``M2KT_METRICS_PORT`` and returns None when that says disabled (0 /
    unset) — the shape the emitted templates use. An explicit ``port=0``
    means "any free port" (tests)."""
    if port is None:
        port = metrics_port_from_env(0)
        if port <= 0:
            return None
    try:
        return TelemetryServer(port=port, registry=registry,
                               profile_dir=profile_dir,
                               readiness=readiness, tracer=tracer,
                               ledger=ledger).start()
    except OSError:
        # never kill a training run over a busy metrics port
        return None
