"""Runtime distributed tracing: bounded span ring + flight-recorder dump.

Distinct from ``utils/trace.py`` on purpose: that module times *pipeline*
stages of one translate run (seconds-scale, rolled up by name into one
JSON document). This one traces the *emitted runtime's* hot paths —
per-training-step and per-serving-request spans at µs resolution — and
must therefore be (a) cheap enough to leave on (a dict append under a
lock; the bench obs phase gates the cost at ≤3% of step time), (b)
bounded (a ring holding the last ``M2KT_TRACE_RING_SECONDS`` of spans,
with a hard entry cap — a month-long trainer must not grow a month-long
span list), and (c) crash-useful: the ring is exactly what the flight
recorder dumps when the supervisor sees a retryable/fatal/slice-lost
death, so the last seconds before an exit-83 are reconstructable.

Clocks: span endpoints are ``time.perf_counter()`` (monotonic — a wall
clock stepped by NTP mid-span would corrupt durations); one wall-clock
anchor captured at recorder construction maps them back to unix time for
export. Identity: every span carries a 16-hex trace id and 8-hex span id
(W3C-sized), plus ``M2KT_SLICE_ID``/hostname/pid resource tags so rings
flushed by different hosts of a multislice job can be merged and still
attributed.

Exports:

- ``chrome_trace()`` — Chrome trace-event JSON (``ph: "X"`` complete
  events, µs timestamps), loadable directly in Perfetto / chrome://tracing;
- ``otlp_lines()`` — OTLP/JSON-shaped lines (one ``resourceSpans`` object
  per line) for a collector's filelog receiver, without taking an
  opentelemetry dependency;
- ``flush_ring(path)`` — the crash-flight half: atomic JSON dump of the
  ring for the supervisor to fold into ``m2kt-flight.json``.

Stdlib-only: this module is vendored into emitted images next to
``obs/metrics.py``.
"""

from __future__ import annotations

import contextvars
import json
import os
import socket
import threading
import time
from collections import deque
from contextlib import contextmanager

TRACE_ENV = "M2KT_TRACE"
RING_SECONDS_ENV = "M2KT_TRACE_RING_SECONDS"
FLIGHT_PATH_ENV = "M2KT_FLIGHT_PATH"
ROLE_ENV = "M2KT_FLEET_ROLE"

# the W3C header name, and the fleet roles a recorder may claim
TRACEPARENT_HEADER = "traceparent"
FLEET_ROLES = ("router", "prefill", "decode", "train")

DEFAULT_RING_SECONDS = 120.0
# hard cap regardless of ring_seconds: a serving engine decoding 1k
# steps/s must not hold 120k span dicts because the window says so
DEFAULT_MAX_SPANS = 8192


def enabled() -> bool:
    """Tracing defaults ON: the recorder is a bounded dict-append whose
    cost the bench obs phase gates at ≤3% of step time, and a flight
    recorder that is off by default records no flights."""
    return os.environ.get(TRACE_ENV, "1").lower() not in ("0", "false", "off")


def ring_seconds() -> float:
    raw = os.environ.get(RING_SECONDS_ENV, "")
    try:
        val = float(raw) if raw else DEFAULT_RING_SECONDS
    except ValueError:
        return DEFAULT_RING_SECONDS
    return val if val > 0 else DEFAULT_RING_SECONDS


def flight_path() -> str:
    """Where the supervisor writes ``m2kt-flight.json`` (and, derived,
    where the dying child flushes its span ring for the supervisor to
    pick up). Defaults next to the goodput/metrics artifacts."""
    p = os.environ.get(FLIGHT_PATH_ENV, "")
    if p:
        return p
    return os.path.join(os.environ.get("M2KT_METRICS_DIR", "") or ".",
                        "m2kt-flight.json")


def ring_path() -> str:
    """Child-side ring dump path: the supervisor and the supervised
    process compute the same name from the same env, no handshake."""
    return flight_path() + ".ring"


def fleet_role() -> str:
    """The role this process plays in the fleet (``M2KT_FLEET_ROLE``);
    defaults to ``train`` — the workload every pre-fleet emitter ran."""
    role = os.environ.get(ROLE_ENV, "").strip().lower()
    return role if role else "train"


def parse_traceparent(header: str | None) -> tuple[str, str] | None:
    """Validate a W3C ``traceparent`` header and return
    ``(trace_id, parent_span_id)``, or None for anything malformed —
    request headers are untrusted input and a bad one must degrade to
    "start a fresh trace", never to an exception on the serve path."""
    if not header or not isinstance(header, str):
        return None
    parts = header.strip().lower().split("-")
    if len(parts) < 4:
        return None
    version, trace_id, span_id, flags = parts[0], parts[1], parts[2], parts[3]
    if (len(version), len(trace_id), len(span_id)) != (2, 32, 16):
        return None
    if len(flags) != 2:
        return None
    try:
        int(version, 16)
        int(trace_id, 16)
        int(span_id, 16)
        int(flags, 16)
    except ValueError:
        return None
    # version ff is reserved-invalid; all-zero ids mean "no parent"
    if version == "ff" or trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id


def _new_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


class Span:
    """One timed operation. ``t0``/``t1`` are perf_counter readings of
    the owning recorder's clock; ``t1 is None`` while in flight."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "t0", "t1",
                 "attrs", "_token")

    def __init__(self, name: str, trace_id: str, span_id: str,
                 parent_id: str, t0: float, attrs: dict | None = None):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.t0 = t0
        self.t1: float | None = None
        self.attrs: dict = dict(attrs) if attrs else {}
        self._token = None

    def traceparent(self) -> str:
        """This span's identity as a W3C ``traceparent`` header value —
        what the router injects on every cross-process hop so the
        replica's root span lands in the router's trace. Ids are already
        W3C-sized (32-hex trace, 16-hex span), sampled flag always set:
        the ring is the sampler."""
        return f"00-{self.trace_id}-{self.span_id}-01"


class SpanRecorder:
    """Thread-safe bounded ring of completed spans + in-flight set.

    Completed spans older than ``ring_seconds`` (or beyond ``max_spans``)
    are evicted on append — memory is O(window), not O(run length).
    In-flight spans are tracked separately so a crash dump still shows
    what was executing when the process died.
    """

    def __init__(self, ring_seconds: float | None = None,
                 max_spans: int = DEFAULT_MAX_SPANS,
                 host: str | None = None, slice_id: int | None = None,
                 role: str | None = None):
        self._lock = threading.Lock()
        self._ring: deque[Span] = deque()
        self._active: dict[str, Span] = {}
        self.max_spans = max(1, int(max_spans))
        self.ring_seconds = float(ring_seconds) if ring_seconds else (
            globals()["ring_seconds"]())
        # wall-clock anchor for export; all span math stays monotonic
        self._t0_perf = time.perf_counter()
        self._t0_unix = time.time()
        self.host = host or socket.gethostname()
        if slice_id is None:
            try:
                slice_id = int(os.environ.get("M2KT_SLICE_ID", "0") or 0)
            except ValueError:
                slice_id = 0
        self.slice_id = slice_id
        # fleet role rides every span and the flight-recorder header, so
        # a ring flushed by a dead prefill replica is distinguishable
        # from a router's or a trainer's at a glance
        self.role = (role or fleet_role()).strip().lower()
        self.dropped = 0
        # per-recorder context: nested start() calls parent automatically
        # within one thread/task without threading ids through call sites
        self._current: contextvars.ContextVar[Span | None] = (
            contextvars.ContextVar(f"m2kt_span_{id(self)}", default=None))

    # -- recording ---------------------------------------------------------

    def start(self, name: str, attrs: dict | None = None,
              parent: Span | None = None, trace_id: str | None = None,
              detached: bool = False,
              remote_parent: str | None = None) -> Span:
        """Open a span. Parent/trace identity comes from (in order) a
        ``remote_parent`` W3C traceparent header (cross-process: the
        span adopts the remote trace id and parents under the remote
        span), the explicit args, the calling context's current span, or
        a fresh root trace. The new span becomes the context's current
        span — unless ``detached``, which neither inherits nor sets the
        context (the serving engine interleaves many live request traces
        in one thread and threads identity explicitly instead). A
        malformed ``remote_parent`` is ignored, not raised: headers are
        untrusted."""
        remote = parse_traceparent(remote_parent) if remote_parent else None
        if remote is not None:
            trace_id, parent_id = remote
        else:
            if parent is None and not detached:
                parent = self._current.get()
            if parent is not None:
                trace_id = trace_id or parent.trace_id
                parent_id = parent.span_id
            else:
                trace_id = trace_id or _new_id(16)
                parent_id = ""
        span = Span(name, trace_id, _new_id(8), parent_id,
                    time.perf_counter(), attrs)
        if not detached:
            span._token = self._current.set(span)
        with self._lock:
            self._active[span.span_id] = span
        return span

    def end(self, span: Span, attrs: dict | None = None) -> Span:
        span.t1 = time.perf_counter()
        if attrs:
            span.attrs.update(attrs)
        if span._token is not None:
            try:
                self._current.reset(span._token)
            except ValueError:
                self._current.set(None)  # ended from another context
            span._token = None
        with self._lock:
            self._active.pop(span.span_id, None)
            self._append_locked(span)
        return span

    @contextmanager
    def span(self, name: str, attrs: dict | None = None,
             parent: Span | None = None, trace_id: str | None = None):
        s = self.start(name, attrs, parent=parent, trace_id=trace_id)
        try:
            yield s
        finally:
            self.end(s)

    def record(self, name: str, t0: float, t1: float,
               attrs: dict | None = None, trace_id: str | None = None,
               parent_id: str = "") -> Span:
        """Append an already-timed span from explicit perf_counter
        endpoints — the serving engine times prefill/decode itself and
        must hand the *same* readings to both the TTFT histogram and the
        trace, so the two decompositions agree exactly."""
        span = Span(name, trace_id or _new_id(16), _new_id(8), parent_id,
                    t0, attrs)
        span.t1 = t1
        with self._lock:
            self._append_locked(span)
        return span

    @staticmethod
    def annotate(span: Span, **attrs) -> None:
        span.attrs.update(attrs)

    def current(self) -> Span | None:
        return self._current.get()

    def _append_locked(self, span: Span) -> None:
        self._ring.append(span)
        horizon = time.perf_counter() - self.ring_seconds
        while self._ring and (
                len(self._ring) > self.max_spans
                or (self._ring[0].t1 is not None
                    and self._ring[0].t1 < horizon)):
            self._ring.popleft()
            self.dropped += 1

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._active.clear()
            self.dropped = 0

    # -- export ------------------------------------------------------------

    def _unix(self, t: float) -> float:
        return self._t0_unix + (t - self._t0_perf)

    def snapshot(self) -> list[dict]:
        """Completed + in-flight spans as plain dicts (oldest first);
        in-flight spans report the duration so far and ``in_flight``."""
        now = time.perf_counter()
        with self._lock:
            spans = list(self._ring) + sorted(
                self._active.values(), key=lambda s: s.t0)
        out = []
        for s in spans:
            end = s.t1 if s.t1 is not None else now
            out.append({
                "name": s.name,
                "trace_id": s.trace_id,
                "span_id": s.span_id,
                "parent_id": s.parent_id,
                "ts_unix": round(self._unix(s.t0), 6),
                "dur_s": round(end - s.t0, 9),
                "in_flight": s.t1 is None,
                "role": self.role,
                "attrs": dict(s.attrs),
            })
        return out

    def chrome_trace(self) -> dict:
        """Chrome trace-event JSON: ``ph: "X"`` complete events with µs
        timestamps, pid = host process, tid = slice id — drop the file
        in Perfetto and the per-step/per-request timeline renders."""
        pid = os.getpid()
        events = []
        for s in self.snapshot():
            events.append({
                "name": s["name"],
                "ph": "X",
                "ts": round((s["ts_unix"] - self._t0_unix) * 1e6, 3),
                "dur": round(s["dur_s"] * 1e6, 3),
                "pid": pid,
                "tid": self.slice_id,
                "cat": "m2kt",
                "args": {**s["attrs"], "trace_id": s["trace_id"],
                         "span_id": s["span_id"],
                         "parent_id": s["parent_id"],
                         "role": s["role"]},
            })
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"host": self.host, "slice_id": self.slice_id,
                          "role": self.role,
                          "anchor_unix": self._t0_unix},
        }

    def otlp_lines(self) -> list[str]:
        """OTLP/JSON-shaped lines: one ``resourceSpans`` object per line
        (filelog-receiver friendly), string/int attributes only."""
        resource_attrs = [
            {"key": "host.name", "value": {"stringValue": self.host}},
            {"key": "m2kt.slice_id",
             "value": {"intValue": str(self.slice_id)}},
            {"key": "m2kt.role", "value": {"stringValue": self.role}},
            {"key": "service.name", "value": {"stringValue": "move2kube-tpu"}},
        ]
        lines = []
        for s in self.snapshot():
            attrs = []
            for k, v in s["attrs"].items():
                if isinstance(v, bool):
                    attrs.append({"key": k, "value": {"boolValue": v}})
                elif isinstance(v, int):
                    attrs.append({"key": k, "value": {"intValue": str(v)}})
                elif isinstance(v, float):
                    attrs.append({"key": k, "value": {"doubleValue": v}})
                else:
                    attrs.append({"key": k,
                                  "value": {"stringValue": str(v)}})
            start_ns = int(s["ts_unix"] * 1e9)
            lines.append(json.dumps({"resourceSpans": [{
                "resource": {"attributes": resource_attrs},
                "scopeSpans": [{
                    "scope": {"name": "m2kt.obs.tracing"},
                    "spans": [{
                        "traceId": s["trace_id"],
                        "spanId": s["span_id"],
                        "parentSpanId": s["parent_id"],
                        "name": s["name"],
                        "kind": 1,
                        "startTimeUnixNano": str(start_ns),
                        "endTimeUnixNano": str(
                            start_ns + int(s["dur_s"] * 1e9)),
                        "attributes": attrs,
                    }],
                }],
            }]}, separators=(",", ":")))
        return lines

    # -- flight-recorder half ---------------------------------------------

    def ring_doc(self, limit: int | None = None) -> dict:
        """The ring as one self-describing JSON document — the shape the
        flight recorder dumps and the ``/traces`` drain endpoint serves,
        so the fleet collector and the supervisor parse the same thing.

        ``limit`` bounds the span list to the NEWEST ``limit`` entries
        (the ones a diagnosis wants); ``truncated`` counts what the
        bound cut and ``dropped`` what ring eviction already lost, so a
        reader always knows how much history is missing."""
        spans = self.snapshot()
        truncated = 0
        if limit is not None and limit >= 0 and len(spans) > limit:
            truncated = len(spans) - limit
            spans = spans[len(spans) - limit:]
        return {
            "host": self.host,
            "slice_id": self.slice_id,
            "role": self.role,
            "pid": os.getpid(),
            "written_unix": time.time(),
            "ring_seconds": self.ring_seconds,
            "dropped": self.dropped,
            "truncated": truncated,
            "spans": spans,
        }

    def flush_ring(self, path: str | None = None) -> str | None:
        """Atomically dump the ring for the supervisor's flight recorder.
        Best-effort by design — this runs on dying-process paths and must
        never mask the original exit code."""
        path = path or ring_path()
        doc = self.ring_doc()
        try:
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            tmp = path + f".tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f)
                f.write("\n")
            os.replace(tmp, path)
            return path
        except OSError:
            return None


_recorder: SpanRecorder | None = None
_recorder_lock = threading.Lock()


def get() -> SpanRecorder:
    """Process-wide recorder (lazy: env knobs are read at first use)."""
    global _recorder
    if _recorder is None:
        with _recorder_lock:
            if _recorder is None:
                _recorder = SpanRecorder()
    return _recorder


def reset() -> None:
    global _recorder
    with _recorder_lock:
        _recorder = None


_flush_installed = False


def install_ring_flush(path: str | None = None) -> None:
    """Flush the span ring on every interpreter exit path that runs
    teardown — the same ``threading._register_atexit`` trick as
    ``checkpoint.install_exit_flush`` (see that docstring for why plain
    atexit is too late), so a ``sys.exit(83)`` from an injected
    slice-loss fault still leaves the ring on disk for the supervisor's
    flight recorder. SIGKILL skips teardown; that flight is simply the
    goodput ledger alone."""
    global _flush_installed
    if _flush_installed or not enabled():
        return
    _flush_installed = True

    def _flush() -> None:
        try:
            if _recorder is not None:
                _recorder.flush_ring(path)
        except Exception:  # noqa: BLE001 - dying process, best effort
            pass

    register = getattr(threading, "_register_atexit", None)
    if register is None:
        import atexit

        atexit.register(_flush)
    else:
        register(_flush)
